"""S3-compatible REST gateway backed by the filer.

Reference: weed/s3api/s3api_server.go:44 (router), s3api_bucket_handlers.go,
s3api_object_handlers.go (put/get proxy through the filer),
s3api_objects_list_handlers.go (V1/V2 listing over the directory tree),
filer_multipart.go (multipart complete = chunk-list splice, no data copy),
s3api_object_tagging_handlers.go (tags in entry.extended).

Buckets are directories under /buckets/<name>; object keys map to nested
directories; multipart uploads stage parts under
/buckets/<bucket>/.uploads/<uploadId>/.
"""

from __future__ import annotations

import hashlib
import os
import re
import threading
import time
import urllib.parse
import xml.etree.ElementTree as ET
from email.utils import formatdate
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from ..util.httpd import FrameworkHTTPServer

import shutil
import urllib.error

from ..filer.fleet.tenant import QuotaExceededError, SlowDownError
from ..pb import filer_pb2
from ..stats.metrics import S3_REJECT
from ..util.http_util import read_chunked_body
from .auth import (
    ACTION_ADMIN,
    ACTION_LIST,
    ACTION_READ,
    ACTION_TAGGING,
    ACTION_WRITE,
    STREAMING_PAYLOAD,
    AuthError,
    IdentityAccessManagement,
    S3HttpRequest,
    decode_streaming_body,
)
from .filer_client import FilerClient, FilerUnavailable
from .policy import (
    ALLOW,
    DENY,
    BucketPolicy,
    PolicyError,
    PostPolicy,
    resource_arn,
    s3_action,
)

XMLNS = "http://s3.amazonaws.com/doc/2006-03-01/"

# extended-attribute key bucket policies are stored under on the bucket
# entry (was referenced undefined — a latent NameError on any bucket that
# actually carried a policy, caught by the ruff F821 gate)
POLICY_KEY = b"seaweedfs.s3.policy"
BUCKETS_DIR = "/buckets"
UPLOADS_DIR = ".uploads"
TAG_PREFIX = "Seaweed-X-Amz-Tagging-"
META_PREFIX = "X-Amz-Meta-"
ETAG_KEY = "Seaweed-ETag"
OWNER_ID = "seaweedfs-tpu"
MAX_DIR_PAGE = 10000


class S3ApiServer:
    def __init__(
        self,
        filer: str = "127.0.0.1:8888",
        port: int = 8333,
        config_path: str = "",
        domain: str = "",
        iam_config_filer_path: str = "",
        iam_refresh_seconds: float = 3.0,
        masters: str | list[str] = "",
        geo_masters: str | list[str] = "",  # remote-cluster failover
    ):
        self.port = port
        master_list = (masters.split(",") if isinstance(masters, str)
                       else list(masters))
        master_list = [m.strip() for m in master_list if m.strip()]
        filer_list = [f.strip() for f in filer.split(",") if f.strip()]
        geo_list = (geo_masters.split(",")
                    if isinstance(geo_masters, str) else list(geo_masters))
        geo_list = [m.strip() for m in geo_list if m.strip()]
        if master_list or len(filer_list) > 1 or geo_list:
            # fleet mode: stateless gateway over the sharded filer
            # plane — membership from the master's filer registrations
            # (or the static list), routing by consistent hash; with
            # geo masters the gateway fails over to the remote cluster
            # when the local fleet is entirely unreachable (ISSUE 12)
            from ..filer.fleet import FleetRouter
            from ..filer.fleet.fleet_client import FleetFilerClient

            self.client = FleetFilerClient(FleetRouter(
                masters=master_list,
                filers=filer_list if not master_list else None,
                remote_masters=geo_list or None))
        else:
            self.client = FilerClient(filer_list[0] if filer_list
                                      else filer)
        self.iam = IdentityAccessManagement(config_path, domain)
        self._httpd: ThreadingHTTPServer | None = None
        # parsed-bucket-policy cache: bucket -> (expires_at, policy|None)
        self._policy_cache: dict[str, tuple[float, BucketPolicy | None]] = {}
        self._policy_lock = threading.Lock()
        # identities shared with the IAM API through the filer
        # (iamapi writes /etc/iam/identity.json; the gateway re-reads it)
        self.iam_config_filer_path = iam_config_filer_path
        self.iam_refresh_seconds = iam_refresh_seconds
        self._iam_stop = threading.Event()

    def start(self) -> None:
        from ..util import glog
        from ..util import profiler as _profiler

        # flight-recorder plane: always-on low-hz stack sampler feeding
        # /debug/profile/history (kill-switch + hz env knobs respected)
        _profiler.ensure_continuous()
        handler = type("BoundS3Handler", (S3Handler,), {"s3": self})
        self._httpd = FrameworkHTTPServer(("0.0.0.0", self.port), handler)
        threading.Thread(target=self._httpd.serve_forever, daemon=True).start()
        if self.iam_config_filer_path:
            self.refresh_iam_from_filer()
            threading.Thread(target=self._iam_refresh_loop,
                             daemon=True).start()
        glog.info("s3 gateway started port=%d filer=%s auth=%s",
                  self.port, self.client.http_address, self.iam.enabled)

    def stop(self) -> None:
        self._iam_stop.set()
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()

    # -- IAM config via filer ------------------------------------------------

    def refresh_iam_from_filer(self) -> None:
        import json as _json

        try:
            status, _hdrs, body = self.client.get_object(
                self.iam_config_filer_path
            )
        except Exception:
            return
        if status == 200 and body:
            try:
                self.iam.load_config(_json.loads(body))
            except (ValueError, KeyError):
                pass

    def _iam_refresh_loop(self) -> None:
        while not self._iam_stop.wait(self.iam_refresh_seconds):
            self.refresh_iam_from_filer()

    # -- bucket policy -------------------------------------------------------

    def bucket_policy(self, bucket: str) -> BucketPolicy | None:
        now = time.monotonic()
        with self._policy_lock:
            hit = self._policy_cache.get(bucket)
            if hit and now < hit[0]:
                return hit[1]
        entry = self.client.find_entry(BUCKETS_DIR, bucket)
        pol = None
        if entry is not None and POLICY_KEY in entry.extended:
            try:
                pol = BucketPolicy.parse(bytes(entry.extended[POLICY_KEY]))
            except PolicyError:
                pol = None
        with self._policy_lock:
            self._policy_cache[bucket] = (now + 5.0, pol)
        return pol

    def invalidate_policy(self, bucket: str) -> None:
        with self._policy_lock:
            self._policy_cache.pop(bucket, None)

    # -- path helpers --------------------------------------------------------

    def bucket_dir(self, bucket: str) -> str:
        return f"{BUCKETS_DIR}/{bucket}"

    def object_path(self, bucket: str, key: str) -> str:
        return f"{BUCKETS_DIR}/{bucket}/{key}"


# -- XML helpers --------------------------------------------------------------


_CT_PREFIX = "ct-"  # marks this gateway's base64 continuation tokens


def _encode_ct(key: str) -> str:
    import base64

    return _CT_PREFIX + base64.urlsafe_b64encode(
        key.encode()).decode().rstrip("=")


def _decode_ct(token: str) -> str:
    """Inverse of _encode_ct; a foreign/legacy token passes through as a
    raw start key."""
    if not token.startswith(_CT_PREFIX):
        return token
    import base64

    raw = token[len(_CT_PREFIX):]
    try:
        return base64.urlsafe_b64decode(
            raw + "=" * (-len(raw) % 4)).decode()
    except Exception:
        return token


_BUCKET_NAME_RE = re.compile(r"^[a-z0-9][a-z0-9.-]{1,61}[a-z0-9]$")
_IPV4_RE = re.compile(r"^\d+\.\d+\.\d+\.\d+$")


def _valid_bucket_name(name: str) -> bool:
    """AWS bucket naming rules (the subset s3-tests pins): 3-63 chars of
    lowercase/digits/dot/hyphen, alphanumeric ends, no '..'/'.-'/'-.'
    runs, not formatted like an IPv4 address."""
    if not _BUCKET_NAME_RE.match(name):
        return False
    if ".." in name or ".-" in name or "-." in name:
        return False
    return not _IPV4_RE.match(name)


def _el(parent, tag: str, text: str | None = None):
    e = ET.SubElement(parent, tag)
    if text is not None:
        e.text = text
    return e


def _xml_bytes(root: ET.Element) -> bytes:
    return b'<?xml version="1.0" encoding="UTF-8"?>' + ET.tostring(root)


def _error_xml(code: str, message: str, resource: str) -> bytes:
    root = ET.Element("Error")
    _el(root, "Code", code)
    _el(root, "Message", message)
    _el(root, "Resource", resource)
    _el(root, "RequestId", "")
    return _xml_bytes(root)


def _iso(ts: int) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%S.000Z", time.gmtime(ts or 0))


class S3Error(Exception):
    def __init__(self, status: int, code: str, message: str):
        super().__init__(message)
        self.status = status
        self.code = code


NO_SUCH_BUCKET = ("NoSuchBucket", "the specified bucket does not exist", 404)
NO_SUCH_KEY = ("NoSuchKey", "the specified key does not exist", 404)


class _TeeReader:
    """File-like over a source stream, limited to ``length`` bytes, feeding
    md5 (the ETag) and sha256 (signed-payload verification) as it goes —
    lets object bodies stream gateway-through without buffering."""

    def __init__(self, src, length: int):
        self.src = src
        self.remaining = length
        self.md5 = hashlib.md5()
        self.sha = hashlib.sha256()

    def read(self, n: int = -1) -> bytes:
        if self.remaining <= 0:
            return b""
        n = self.remaining if n is None or n < 0 else min(n, self.remaining)
        b = self.src.read(n)
        self.remaining -= len(b)
        self.md5.update(b)
        self.sha.update(b)
        return b


class S3Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "seaweedfs-tpu-s3"
    s3: S3ApiServer = None  # injected

    def log_message(self, fmt, *args):
        pass

    # -- plumbing ------------------------------------------------------------

    def _send(self, status: int, body: bytes = b"",
              content_type: str = "application/xml",
              extra: dict | None = None):
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.send_header("x-amz-request-id", "")
        for k, v in (extra or {}).items():
            self.send_header(k, v)
        self.end_headers()
        if body and self.command != "HEAD":
            self.wfile.write(body)

    def _send_error(self, status: int, code: str, message: str):
        self._send(status, _error_xml(code, message, self.path))

    def _read_body(self) -> bytes:
        te = (self.headers.get("Transfer-Encoding") or "").lower()
        if "chunked" in te:
            try:
                return read_chunked_body(self.rfile)
            except ValueError as e:
                # client framing error, not a server fault
                raise S3Error(400, "IncompleteBody", str(e))
        length = int(self.headers.get("Content-Length") or 0)
        return self.rfile.read(length) if length else b""

    def _route(self):
        from ..telemetry import http_request, serve_debug_http

        u = urllib.parse.urlsplit(self.path)
        path = urllib.parse.unquote(u.path)
        self.query = {
            k: v[0]
            for k, v in urllib.parse.parse_qs(
                u.query, keep_blank_values=True
            ).items()
        }
        parts = path.lstrip("/").split("/", 1)
        bucket = parts[0]
        key = parts[1] if len(parts) > 1 else ""
        self.auth_req = S3HttpRequest(
            method=self.command,
            raw_path=u.path,
            raw_query=u.query,
            headers={k.lower(): v for k, v in self.headers.items()},
        )
        with http_request(self, "s3", self.command.lower()):
            try:
                self.identity = self.s3.iam.authenticate(self.auth_req)
                # debug/observability surface: authenticated (traces
                # carry object keys and internal volume URLs), exact
                # paths, ahead of the bucket namespace — a bucket
                # literally named "metrics" is shadowed (see METRICS.md)
                if (self.command in ("GET", "HEAD")
                        and serve_debug_http(self, u.path)):
                    return
                self._dispatch(bucket, key)
            except AuthError as e:
                self._send(e.status, _error_xml(e.code, str(e), self.path))
            except S3Error as e:
                self._send_error(e.status, e.code, str(e))
            except SlowDownError as e:
                # WFQ admission on the owning filer shard said no —
                # proper S3 throttle semantics so SDK clients back off
                S3_REJECT.labels("slowdown").inc()
                self._send(503, _error_xml(
                    "SlowDown", "Please reduce your request rate.",
                    self.path),
                    extra={"Retry-After": str(e.retry_after)})
            except QuotaExceededError as e:
                S3_REJECT.labels("quota").inc()
                self._send(403, _error_xml(
                    "QuotaExceeded", str(e), self.path))
            except FilerUnavailable as e:
                # never report an outage as NoSuchKey — sync clients would
                # mirror the "deletion"
                self._send_error(503, "ServiceUnavailable", str(e))
            except IOError as e:
                if str(e).startswith("quota exceeded"):
                    # the gRPC CreateEntry path carries the rejection as
                    # an error string (see filer grpc_handlers)
                    S3_REJECT.labels("quota").inc()
                    self._send(403, _error_xml(
                        "QuotaExceeded", str(e), self.path))
                else:
                    self._send_error(500, "InternalError",
                                     f"{type(e).__name__}: {e}")
            except BrokenPipeError:
                pass
            except Exception as e:  # internal
                self._send_error(500, "InternalError",
                                 f"{type(e).__name__}: {e}")

    do_GET = do_PUT = do_POST = do_DELETE = do_HEAD = _route

    def _authz(self, action: str, bucket: str) -> None:
        self.s3.iam.authorize(self.identity, action, bucket)

    def _dispatch(self, bucket: str, key: str) -> None:
        # heavy-hitter attribution: runs after the debug-surface check,
        # so "/metrics" etc. never pollute the bucket sketch
        if bucket:
            from ..telemetry import hotkeys

            hotkeys.record("bucket", bucket)
        m, q = self.command, self.query
        if not bucket:
            if m in ("GET", "HEAD"):
                return self.list_buckets()
            raise S3Error(405, "MethodNotAllowed", "bad root request")
        if not key:
            if m == "GET":
                if "uploads" in q:
                    return self.list_multipart_uploads(bucket)
                if "location" in q:
                    return self.bucket_location(bucket)
                if "acl" in q:
                    return self.canned_acl(bucket)
                if "versioning" in q:
                    return self.bucket_versioning(bucket)
                if "lifecycle" in q:
                    raise S3Error(404, "NoSuchLifecycleConfiguration",
                                  "no lifecycle configured")
                if "policy" in q:
                    raise S3Error(404, "NoSuchBucketPolicy", "no policy")
                if "tagging" in q:
                    raise S3Error(404, "NoSuchTagSet", "no tags")
                return self.list_objects(bucket, v2="list-type" in q)
            if m == "HEAD":
                return self.head_bucket(bucket)
            if m == "PUT":
                return self.put_bucket(bucket)
            if m == "DELETE":
                return self.delete_bucket(bucket)
            if m == "POST":
                if "delete" in q:
                    return self.delete_multiple(bucket)
                raise S3Error(501, "NotImplemented", "POST uploads unsupported")
            raise S3Error(405, "MethodNotAllowed", m)
        # object-level
        if m == "GET":
            if "uploadId" in q:
                return self.list_parts(bucket, key)
            if "tagging" in q:
                return self.get_tagging(bucket, key)
            if "acl" in q:
                return self.canned_acl(bucket)
            return self.get_object(bucket, key)
        if m == "HEAD":
            return self.head_object(bucket, key)
        if m == "PUT":
            if "partNumber" in q and "uploadId" in q:
                return self.upload_part(bucket, key)
            if "tagging" in q:
                return self.put_tagging(bucket, key)
            if "acl" in q:
                self._authz(ACTION_WRITE, bucket)
                return self._send(200)
            if self.headers.get("x-amz-copy-source"):
                return self.copy_object(bucket, key)
            return self.put_object(bucket, key)
        if m == "POST":
            if "uploads" in q:
                return self.create_multipart(bucket, key)
            if "uploadId" in q:
                return self.complete_multipart(bucket, key)
            raise S3Error(501, "NotImplemented", "bad object POST")
        if m == "DELETE":
            if "uploadId" in q:
                return self.abort_multipart(bucket, key)
            if "tagging" in q:
                return self.delete_tagging(bucket, key)
            return self.delete_object(bucket, key)
        raise S3Error(405, "MethodNotAllowed", m)

    # -- service / bucket ----------------------------------------------------

    def list_buckets(self):
        client = self.s3.client
        root = ET.Element("ListAllMyBucketsResult", xmlns=XMLNS)
        owner = _el(root, "Owner")
        _el(owner, "ID", OWNER_ID)
        _el(owner, "DisplayName", OWNER_ID)
        buckets = _el(root, "Buckets")
        for e in client.list_entries(BUCKETS_DIR, limit=MAX_DIR_PAGE):
            if not e.is_directory:
                continue
            if self.s3.iam.enabled and self.identity and not any(
                self.identity.can_do(a, e.name)
                for a in (ACTION_ADMIN, ACTION_READ, ACTION_LIST)
            ):
                continue
            b = _el(buckets, "Bucket")
            _el(b, "Name", e.name)
            _el(b, "CreationDate", _iso(e.attributes.crtime))
        self._send(200, _xml_bytes(root))

    def _require_bucket(self, bucket: str) -> filer_pb2.Entry:
        entry = self.s3.client.find_entry(BUCKETS_DIR, bucket)
        if entry is None or not entry.is_directory:
            raise S3Error(NO_SUCH_BUCKET[2], NO_SUCH_BUCKET[0], NO_SUCH_BUCKET[1])
        return entry

    def put_bucket(self, bucket: str):
        self._authz(ACTION_ADMIN, bucket)
        if not _valid_bucket_name(bucket):
            raise S3Error(400, "InvalidBucketName",
                          "bucket names are 3-63 chars of [a-z0-9.-], "
                          "starting/ending alphanumeric")
        if self.s3.client.find_entry(BUCKETS_DIR, bucket) is not None:
            raise S3Error(409, "BucketAlreadyExists", "duplicate bucket")
        self.s3.client.mkdir(BUCKETS_DIR, bucket)
        self._send(200, extra={"Location": f"/{bucket}"})

    def delete_bucket(self, bucket: str):
        self._authz(ACTION_ADMIN, bucket)
        self._require_bucket(bucket)
        entries = [
            e for e in self.s3.client.list_entries(
                self.s3.bucket_dir(bucket), limit=3
            )
            if e.name != UPLOADS_DIR
        ]
        if entries:
            raise S3Error(409, "BucketNotEmpty", "the bucket is not empty")
        err = self.s3.client.delete_entry(
            BUCKETS_DIR, bucket, is_delete_data=True, is_recursive=True
        )
        if err:
            raise S3Error(500, "InternalError", err)
        self._send(204)

    def head_bucket(self, bucket: str):
        self._authz(ACTION_READ, bucket)
        self._require_bucket(bucket)
        self._send(200)

    def bucket_location(self, bucket: str):
        self._require_bucket(bucket)
        root = ET.Element("LocationConstraint", xmlns=XMLNS)
        self._send(200, _xml_bytes(root))

    def bucket_versioning(self, bucket: str):
        self._require_bucket(bucket)
        self._send(200, _xml_bytes(ET.Element("VersioningConfiguration",
                                              xmlns=XMLNS)))

    def canned_acl(self, bucket: str):
        self._authz(ACTION_READ, bucket)
        root = ET.Element("AccessControlPolicy", xmlns=XMLNS)
        owner = _el(root, "Owner")
        _el(owner, "ID", OWNER_ID)
        acl = _el(root, "AccessControlList")
        grant = _el(acl, "Grant")
        grantee = _el(grant, "Grantee")
        grantee.set("xmlns:xsi", "http://www.w3.org/2001/XMLSchema-instance")
        grantee.set("xsi:type", "CanonicalUser")
        _el(grantee, "ID", OWNER_ID)
        _el(grant, "Permission", "FULL_CONTROL")
        self._send(200, _xml_bytes(root))

    # -- listing -------------------------------------------------------------

    def list_objects(self, bucket: str, v2: bool):
        self._authz(ACTION_LIST, bucket)
        self._require_bucket(bucket)
        q = self.query
        prefix = q.get("prefix", "")
        delimiter = q.get("delimiter", "")
        try:
            max_keys = min(int(q.get("max-keys", "1000") or "1000"), 1000)
        except ValueError:
            raise S3Error(400, "InvalidArgument",
                          "max-keys must be an integer")
        if max_keys < 0:
            raise S3Error(400, "InvalidArgument",
                          "max-keys must be non-negative")
        encoding = q.get("encoding-type", "")
        if encoding and encoding != "url":
            raise S3Error(400, "InvalidArgument",
                          "encoding-type must be 'url'")

        def enc(s: str) -> str:
            # AWS url-encodes Key/Prefix values, '/' kept literal
            return urllib.parse.quote(s, safe="/") if encoding else s

        if v2:
            marker = (_decode_ct(q.get("continuation-token", ""))
                      or q.get("start-after", ""))
        else:
            marker = q.get("marker", "")
        contents, prefixes, truncated, next_marker = self._list(
            bucket, prefix, delimiter, marker, max_keys
        )
        tag = "ListBucketResult"
        root = ET.Element(tag, xmlns=XMLNS)
        _el(root, "Name", bucket)
        _el(root, "Prefix", enc(prefix))
        if delimiter:
            _el(root, "Delimiter", enc(delimiter))
        _el(root, "MaxKeys", str(max_keys))
        _el(root, "IsTruncated", "true" if truncated else "false")
        # paging markers are keys too: they must be encoded with the same
        # rule as Contents/Key or pagination breaks on the exact keys
        # encoding-type exists for (bytes illegal in XML 1.0)
        if v2:
            _el(root, "KeyCount", str(len(contents)))
            # v2 continuation tokens are OPAQUE: clients echo them back
            # verbatim without decoding (AWS never applies EncodingType to
            # them), so they are base64-wrapped — XML-safe for any key
            # bytes AND immune to double-encoding on the resume path
            if truncated:
                _el(root, "NextContinuationToken", _encode_ct(next_marker))
            if q.get("continuation-token"):
                _el(root, "ContinuationToken", q["continuation-token"])
        else:
            _el(root, "Marker", enc(marker))
            if truncated and delimiter:
                _el(root, "NextMarker", enc(next_marker))
        if encoding:
            _el(root, "EncodingType", "url")
        for key, entry in contents:
            c = _el(root, "Contents")
            _el(c, "Key", enc(key))
            _el(c, "LastModified", _iso(entry.attributes.mtime))
            _el(c, "ETag", f'"{_entry_etag(entry)}"')
            _el(c, "Size", str(_entry_size(entry)))
            _el(c, "StorageClass", "STANDARD")
            owner = _el(c, "Owner")
            _el(owner, "ID", OWNER_ID)
        for p in prefixes:
            cp = _el(root, "CommonPrefixes")
            _el(cp, "Prefix", enc(p))
        self._send(200, _xml_bytes(root))

    def _list(self, bucket: str, prefix: str, delimiter: str,
              marker: str, max_keys: int):
        """-> (contents, common_prefixes, is_truncated, next_marker).

        delimiter "/" lists one directory level (dirs -> CommonPrefixes);
        empty delimiter walks the tree recursively in key order
        (s3api_objects_list_handlers.go).
        """
        client = self.s3.client
        base = self.s3.bucket_dir(bucket)
        contents: list[tuple[str, filer_pb2.Entry]] = []
        prefixes: list[str] = []

        if delimiter == "/":
            dir_part, _, name_prefix = prefix.rpartition("/")
            directory = f"{base}/{dir_part}" if dir_part else base
            start = ""
            if marker.startswith(dir_part):
                start = marker[len(dir_part):].lstrip("/").split("/", 1)[0]
            entries = client.list_entries(
                directory, prefix=name_prefix, start_from=start,
                limit=max_keys + 2,
            )
            for e in entries:
                if e.name == UPLOADS_DIR and not dir_part:
                    continue
                rel = f"{dir_part}/{e.name}" if dir_part else e.name
                if rel <= marker.rstrip("/") and not e.is_directory:
                    continue
                if len(contents) + len(prefixes) >= max_keys:
                    last = (contents[-1][0] if contents else "")
                    lastp = prefixes[-1] if prefixes else ""
                    return contents, prefixes, True, max(last, lastp)
                if e.is_directory:
                    if rel + "/" > marker:
                        prefixes.append(rel + "/")
                else:
                    contents.append((rel, e))
            return contents, prefixes, False, ""

        # recursive walk (no delimiter, or a non-"/" delimiter grouped below)
        truncated = [False]

        def walk(directory: str, rel: str, after: str):
            head = after.split("/", 1)[0] if after else ""
            entries = client.list_entries(
                directory, start_from=head, inclusive=True,
                limit=MAX_DIR_PAGE,
            )
            for e in entries:
                if e.name == UPLOADS_DIR and not rel:
                    continue
                key = f"{rel}{e.name}"
                full_prefix = prefix
                if e.is_directory:
                    subtree = key + "/"
                    # prune subtrees that cannot contain the prefix
                    if not (subtree.startswith(full_prefix)
                            or full_prefix.startswith(subtree)):
                        continue
                    sub_after = ""
                    if head and e.name == head and "/" in after:
                        sub_after = after.split("/", 1)[1]
                    yield from walk(f"{directory}/{e.name}", subtree, sub_after)
                else:
                    if not key.startswith(full_prefix):
                        continue
                    if key <= marker:
                        continue
                    yield key, e

        gen = walk(base, "", marker)
        for key, e in gen:
            if len(contents) >= max_keys:
                truncated[0] = True
                break
            contents.append((key, e))
        next_marker = contents[-1][0] if contents else ""
        if delimiter and delimiter != "/":
            grouped: dict[str, None] = {}
            kept = []
            for key, e in contents:
                tail = key[len(prefix):]
                if delimiter in tail:
                    grouped[prefix + tail.split(delimiter, 1)[0] + delimiter] = None
                else:
                    kept.append((key, e))
            contents, prefixes = kept, list(grouped)
        return contents, prefixes, truncated[0], next_marker

    # -- objects -------------------------------------------------------------

    def _save_meta(self, directory: str, name: str, etag: str,
                   extra: dict[str, str] | None = None,
                   request_meta: bool = True):
        """`request_meta=False` skips harvesting x-amz-meta-* request
        headers — a COPY-directive copy takes metadata from the SOURCE
        only (AWS ignores request metadata unless REPLACE)."""
        client = self.s3.client
        entry = client.find_entry(directory, name)
        if entry is None:
            # the object was just written; losing the ETag/meta silently
            # would break client integrity checks later
            raise S3Error(500, "InternalError",
                          f"{directory}/{name} vanished after write")
        entry.extended[ETAG_KEY] = etag.encode()
        if request_meta:
            self._harvest_request_meta(entry)
        for k, v in (extra or {}).items():
            entry.extended[k] = v.encode()
        client.update_entry(directory, entry)

    def _harvest_request_meta(self, entry) -> None:
        """Copy this request's x-amz-meta-* headers onto the entry under
        the stored META_PREFIX convention (lower-cased suffixes)."""
        for hk, hv in self.headers.items():
            if hk.lower().startswith("x-amz-meta-"):
                entry.extended[
                    META_PREFIX + hk[len("x-amz-meta-"):].lower()
                ] = hv.encode()

    def put_object(self, bucket: str, key: str):
        self._authz(ACTION_WRITE, bucket)
        self._require_bucket(bucket)
        if key.endswith("/"):
            # directory-marker object: the reference mkdirs instead of
            # storing a needle (filer_server_handlers_write.go mkdir
            # branch).  The ETag is the REAL body md5 so client-side
            # integrity checks hold, and a non-empty body rides the
            # directory entry's inline content (served back by GET/HEAD
            # of the marker key)
            body = self._read_body()
            path = self.s3.object_path(bucket, key.rstrip("/"))
            directory, name = path.rsplit("/", 1)
            entry = self.s3.client.find_entry(directory, name)
            if entry is not None and not entry.is_directory:
                # a FILE occupies the slashless name; the filer cannot
                # hold a file and a directory under one name, so the
                # marker write must fail loudly rather than pretend
                raise S3Error(
                    409, "InvalidRequest",
                    "a regular object exists at this key's directory "
                    "name; delete it before creating the folder marker")
            if entry is None:
                self.s3.client.mkdir(directory, name)
                entry = self.s3.client.find_entry(directory, name)
            etag = hashlib.md5(body).hexdigest()
            if entry is not None:
                # ALWAYS overwrite: a re-PUT with an empty body must
                # clear previous marker content, and the stored ETag
                # must match the one returned here (AWS overwrites)
                entry.content = body
                entry.extended[ETAG_KEY] = etag.encode()
                self.s3.client.update_entry(directory, entry)
            return self._send(200, extra={"ETag": f'"{etag}"'})
        path = self.s3.object_path(bucket, key)
        etag = self._put_body_to(path, self.headers.get("Content-Type", ""))
        directory, name = path.rsplit("/", 1)
        self._save_meta(directory, name, etag)
        self._send(200, extra={"ETag": f'"{etag}"'})

    def _put_body_to(self, path: str, mime: str = "") -> str:
        """Write the request body to the filer, streaming when possible;
        returns the content md5 (the ETag).  Verifies the signed
        x-amz-content-sha256 — after upload on the streamed path (the
        object is removed again on mismatch, like AWS rejects the write)."""
        te = (self.headers.get("Transfer-Encoding") or "").lower()
        aws_chunked = (
            self.auth_req.headers.get("x-amz-content-sha256")
            == STREAMING_PAYLOAD
        )
        expected = self.auth_req.expected_sha256
        if "chunked" in te or aws_chunked:
            body = self._read_body()
            if aws_chunked:
                body = decode_streaming_body(body, self.auth_req)
            if expected and hashlib.sha256(body).hexdigest() != expected:
                raise AuthError("XAmzContentSHA256Mismatch",
                                "payload hash mismatch", status=400)
            self.s3.client.put_object(path, body, mime=mime)
            return hashlib.md5(body).hexdigest()
        length = int(self.headers.get("Content-Length") or 0)
        reader = _TeeReader(self.rfile, length)
        self.s3.client.put_object_stream(path, reader, length, mime=mime)
        if expected and reader.sha.hexdigest() != expected:
            directory, name = path.rsplit("/", 1)
            self.s3.client.delete_entry(directory, name, is_delete_data=True)
            raise AuthError("XAmzContentSHA256Mismatch",
                            "payload hash mismatch", status=400)
        return reader.md5.hexdigest()

    def _find_object(self, bucket: str, key: str) -> filer_pb2.Entry:
        if key.endswith("/"):
            # directory-marker key: resolves to the directory entry
            path = self.s3.object_path(bucket, key.rstrip("/"))
            directory, name = path.rsplit("/", 1)
            entry = self.s3.client.find_entry(directory, name)
            if entry is None or not entry.is_directory:
                raise S3Error(NO_SUCH_KEY[2], NO_SUCH_KEY[0],
                              NO_SUCH_KEY[1])
            return entry
        path = self.s3.object_path(bucket, key)
        directory, name = path.rsplit("/", 1)
        entry = self.s3.client.find_entry(directory, name)
        if entry is None or entry.is_directory:
            raise S3Error(NO_SUCH_KEY[2], NO_SUCH_KEY[0], NO_SUCH_KEY[1])
        return entry

    def _object_headers(self, entry: filer_pb2.Entry) -> dict:
        h = {
            "ETag": f'"{_entry_etag(entry)}"',
            "Last-Modified": formatdate(entry.attributes.mtime, usegmt=True),
            "Accept-Ranges": "bytes",
        }
        for k, v in entry.extended.items():
            if k.startswith(META_PREFIX):
                h["x-amz-meta-" + k[len(META_PREFIX):]] = v.decode()
        return h

    def _check_conditionals(self, entry) -> bool:
        """If-Match / If-None-Match (RFC 7232 as S3 applies it):
        mismatched If-Match -> 412 PreconditionFailed; matching
        If-None-Match -> True (caller answers 304).  ETags compare
        without quotes; '*' matches any existing entry."""
        etag = _entry_etag(entry)
        if_match = self.headers.get("If-Match")
        if if_match is not None and if_match != "*" and all(
            t.strip().strip('"') != etag
            for t in if_match.split(",")
        ):
            raise S3Error(412, "PreconditionFailed",
                          "If-Match condition failed")
        inm = self.headers.get("If-None-Match")
        if inm is not None and (inm == "*" or any(
            t.strip().strip('"') == etag for t in inm.split(","))):
            return True
        return False

    def get_object(self, bucket: str, key: str):
        self._authz(ACTION_READ, bucket)
        entry = self._find_object(bucket, key)
        if self._check_conditionals(entry):
            self.send_response(304)
            self.send_header("ETag", f'"{_entry_etag(entry)}"')
            self.end_headers()
            return
        if entry.is_directory:
            # directory-marker key: serve the (usually empty) inline body
            body = bytes(entry.content)
            self.send_response(200)
            self.send_header("Content-Type", "application/octet-stream")
            self.send_header("Content-Length", str(len(body)))
            for k, v in self._object_headers(entry).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)
            return
        try:
            resp = self.s3.client.open_object(
                self.s3.object_path(bucket, key),
                range_header=self.headers.get("Range", ""),
            )
        except urllib.error.HTTPError as e:
            e.read()
            raise S3Error(e.code, "InvalidRange" if e.code == 416 else
                          "InternalError", "read failed")
        with resp:
            self.send_response(resp.status)
            self.send_header(
                "Content-Type",
                entry.attributes.mime
                or resp.headers.get("Content-Type", "application/octet-stream"),
            )
            self.send_header("Content-Length",
                             resp.headers.get("Content-Length", "0"))
            if resp.headers.get("Content-Range"):
                self.send_header("Content-Range", resp.headers["Content-Range"])
            for k, v in self._object_headers(entry).items():
                self.send_header(k, v)
            self.send_header("x-amz-request-id", "")
            self.end_headers()
            # stream filer -> client; no gateway-side buffering
            shutil.copyfileobj(resp, self.wfile, 256 * 1024)

    def head_object(self, bucket: str, key: str):
        self._authz(ACTION_READ, bucket)
        entry = self._find_object(bucket, key)
        if self._check_conditionals(entry):
            self.send_response(304)
            self.send_header("ETag", f'"{_entry_etag(entry)}"')
            self.end_headers()
            return
        extra = self._object_headers(entry)
        extra["Content-Length"] = str(
            len(entry.content) if entry.is_directory
            else _entry_size(entry))
        self.send_response(200)
        self.send_header("Content-Type",
                         entry.attributes.mime or "application/octet-stream")
        for k, v in extra.items():
            self.send_header(k, v)
        self.end_headers()

    def delete_object(self, bucket: str, key: str):
        self._authz(ACTION_WRITE, bucket)
        if key.endswith("/"):
            # marker delete: only a DIRECTORY entry is a marker — a plain
            # file under the slashless name is a DIFFERENT key on AWS and
            # must never be destroyed by a marker cleanup.  Drop the
            # directory only when it has no children (children keep the
            # prefix alive on AWS too — there it exists purely through
            # them); anything else is a 204 no-op.
            path = self.s3.object_path(bucket, key.rstrip("/"))
            directory, name = path.rsplit("/", 1)
            entry = self.s3.client.find_entry(directory, name)
            if (entry is not None and entry.is_directory
                    and not list(self.s3.client.list_entries(
                        path, limit=1))):
                self.s3.client.delete_entry(
                    directory, name, is_delete_data=True,
                    is_recursive=True)
            return self._send(204)
        path = self.s3.object_path(bucket, key)
        directory, name = path.rsplit("/", 1)
        self.s3.client.delete_entry(directory, name, is_delete_data=True,
                                    is_recursive=True)
        self._send(204)

    def delete_multiple(self, bucket: str):
        self._authz(ACTION_WRITE, bucket)
        self._require_bucket(bucket)
        body = self._read_body()
        try:
            tree = ET.fromstring(body)
        except ET.ParseError:
            raise S3Error(400, "MalformedXML", "bad delete request")
        quiet = (
            tree.findtext("Quiet") or tree.findtext(f"{{{XMLNS}}}Quiet") or ""
        ).lower() == "true"
        root = ET.Element("DeleteResult", xmlns=XMLNS)
        for obj in tree.iter():
            if not obj.tag.endswith("Object"):
                continue
            key = obj.findtext("Key") or obj.findtext(
                f"{{{XMLNS}}}Key"
            )
            if not key:
                continue
            path = self.s3.object_path(bucket, key)
            directory, name = path.rsplit("/", 1)
            err = self.s3.client.delete_entry(
                directory, name, is_delete_data=True, is_recursive=True
            )
            # AWS semantics: deleting a nonexistent key reports Deleted
            # (the filer marks missing entries with a "not found:" prefix)
            if err and not err.startswith("not found"):
                e = _el(root, "Error")
                _el(e, "Key", key)
                _el(e, "Code", "InternalError")
                _el(e, "Message", err)
            elif not quiet:
                d = _el(root, "Deleted")
                _el(d, "Key", key)
        self._send(200, _xml_bytes(root))

    def copy_object(self, bucket: str, key: str):
        self._authz(ACTION_WRITE, bucket)
        src = urllib.parse.unquote(self.headers["x-amz-copy-source"])
        src_bucket, _, src_key = src.lstrip("/").partition("/")
        self._authz(ACTION_READ, src_bucket)
        directive = (self.headers.get("x-amz-metadata-directive")
                     or "COPY").upper()
        if (src_bucket, src_key) == (bucket, key) and directive != "REPLACE":
            # AWS: copying onto itself is only valid as the canonical
            # metadata-rewrite (s3tests test_object_copy_to_itself)
            raise S3Error(
                400, "InvalidRequest",
                "This copy request is illegal because it is copying an "
                "object to itself without changing the object's "
                "metadata.")
        src_entry = self._find_object(src_bucket, src_key)
        if (src_bucket, src_key) == (bucket, key):
            # REPLACE onto itself = the canonical metadata rewrite: no
            # data movement, just swap the user-metadata keys in place
            directory, name = self.s3.object_path(
                bucket, key).rsplit("/", 1)
            for k in [k for k in src_entry.extended
                      if k.startswith(META_PREFIX)]:
                del src_entry.extended[k]
            self._harvest_request_meta(src_entry)
            src_entry.attributes.mtime = int(time.time())
            self.s3.client.update_entry(directory, src_entry)
            etag = _entry_etag(src_entry)
            root = ET.Element("CopyObjectResult", xmlns=XMLNS)
            _el(root, "ETag", f'"{etag}"')
            _el(root, "LastModified", _iso(int(time.time())))
            return self._send(200, _xml_bytes(root))
        dst = self.s3.object_path(bucket, key)
        try:
            resp = self.s3.client.open_object(
                self.s3.object_path(src_bucket, src_key)
            )
        except urllib.error.HTTPError as e:
            e.read()
            raise S3Error(e.code, "NoSuchKey", "source unreadable")
        with resp:  # stream source -> destination through the gateway
            length = int(resp.headers.get("Content-Length") or 0)
            reader = _TeeReader(resp, length)
            self.s3.client.put_object_stream(
                dst, reader, length, mime=src_entry.attributes.mime
            )
        etag = reader.md5.hexdigest()
        directory, name = dst.rsplit("/", 1)
        if directive == "REPLACE":
            # user metadata comes from THIS request's x-amz-meta headers
            # (harvested by _save_meta), not the source entry
            self._save_meta(directory, name, etag)
        else:
            meta = {
                k: v.decode()
                for k, v in src_entry.extended.items()
                if k.startswith(META_PREFIX)
            }
            self._save_meta(directory, name, etag, extra=meta,
                            request_meta=False)
        root = ET.Element("CopyObjectResult", xmlns=XMLNS)
        _el(root, "ETag", f'"{etag}"')
        _el(root, "LastModified", _iso(int(time.time())))
        self._send(200, _xml_bytes(root))

    # -- multipart -----------------------------------------------------------

    def _uploads_dir(self, bucket: str) -> str:
        return f"{self.s3.bucket_dir(bucket)}/{UPLOADS_DIR}"

    def create_multipart(self, bucket: str, key: str):
        self._authz(ACTION_WRITE, bucket)
        self._require_bucket(bucket)
        upload_id = os.urandom(16).hex()
        client = self.s3.client
        if client.find_entry(self.s3.bucket_dir(bucket), UPLOADS_DIR) is None:
            client.mkdir(self.s3.bucket_dir(bucket), UPLOADS_DIR)
        entry = filer_pb2.Entry(name=upload_id, is_directory=True)
        entry.attributes.file_mode = 0o40777
        entry.attributes.mtime = int(time.time())
        entry.extended["key"] = key.encode()
        entry.extended["Content-Type"] = (
            self.headers.get("Content-Type") or ""
        ).encode()
        self._harvest_request_meta(entry)
        client.create_entry(self._uploads_dir(bucket), entry)
        root = ET.Element("InitiateMultipartUploadResult", xmlns=XMLNS)
        _el(root, "Bucket", bucket)
        _el(root, "Key", key)
        _el(root, "UploadId", upload_id)
        self._send(200, _xml_bytes(root))

    def _upload_entry(self, bucket: str, upload_id: str) -> filer_pb2.Entry:
        entry = self.s3.client.find_entry(self._uploads_dir(bucket), upload_id)
        if entry is None:
            raise S3Error(404, "NoSuchUpload", "upload id not found")
        return entry

    def upload_part(self, bucket: str, key: str):
        self._authz(ACTION_WRITE, bucket)
        upload_id = self.query["uploadId"]
        part_num = int(self.query["partNumber"])
        self._upload_entry(bucket, upload_id)
        part_name = f"{part_num:04d}.part"
        path = f"{self._uploads_dir(bucket)}/{upload_id}/{part_name}"
        etag = self._put_body_to(path)
        directory, name = path.rsplit("/", 1)
        self._save_meta(directory, name, etag)
        self._send(200, extra={"ETag": f'"{etag}"'})

    def complete_multipart(self, bucket: str, key: str):
        self._authz(ACTION_WRITE, bucket)
        upload_id = self.query["uploadId"]
        upload_entry = self._upload_entry(bucket, upload_id)
        body = self._read_body()
        wanted: list[tuple[int, str]] = []
        if body:
            try:
                tree = ET.fromstring(body)
                for part in tree.iter():
                    if not part.tag.endswith("Part"):
                        continue
                    num = part.findtext("PartNumber") or part.findtext(
                        f"{{{XMLNS}}}PartNumber"
                    )
                    tag = part.findtext("ETag") or part.findtext(
                        f"{{{XMLNS}}}ETag"
                    ) or ""
                    wanted.append((int(num), tag.strip('"')))
            except ET.ParseError:
                raise S3Error(400, "MalformedXML", "bad complete request")
        updir = f"{self._uploads_dir(bucket)}/{upload_id}"
        parts = {
            int(e.name.split(".", 1)[0]): e
            for e in self.s3.client.list_entries(updir, limit=MAX_DIR_PAGE)
            if e.name.endswith(".part")
        }
        if not wanted:
            wanted = [(n, "") for n in sorted(parts)]
        elif [n for n, _ in wanted] != sorted(n for n, _ in wanted):
            # AWS requires ascending part order in the complete request
            raise S3Error(400, "InvalidPartOrder",
                          "parts must be listed in ascending order")
        chunks: list[filer_pb2.FileChunk] = []
        offset = 0
        digests = b""
        for num, want_etag in sorted(wanted):
            part = parts.get(num)
            if part is None:
                raise S3Error(400, "InvalidPart", f"part {num} missing")
            etag = _entry_etag(part)
            if want_etag and etag != want_etag:
                raise S3Error(400, "InvalidPart", f"part {num} etag mismatch")
            digests += bytes.fromhex(etag) if len(etag) == 32 else b""
            for c in part.chunks:
                nc = filer_pb2.FileChunk()
                nc.CopyFrom(c)
                nc.offset = offset + c.offset
                chunks.append(nc)
            offset += _entry_size(part)
        final_etag = f"{hashlib.md5(digests).hexdigest()}-{len(wanted)}"
        path = self.s3.object_path(bucket, key)
        directory, name = path.rsplit("/", 1)
        entry = filer_pb2.Entry(name=name)
        entry.chunks.extend(chunks)
        entry.attributes.file_size = offset
        entry.attributes.mime = (
            upload_entry.extended.get("Content-Type", b"").decode()
        )
        entry.attributes.mtime = int(time.time())
        entry.attributes.crtime = int(time.time())
        entry.attributes.file_mode = 0o644
        entry.extended[ETAG_KEY] = final_etag.encode()
        for k, v in upload_entry.extended.items():
            if k.startswith(META_PREFIX):
                entry.extended[k] = v
        # the filer's create_entry mkdir -p's the ancestor chain
        self.s3.client.create_entry(directory, entry)
        # parts' chunks now belong to the object: delete metadata only
        self.s3.client.delete_entry(
            self._uploads_dir(bucket), upload_id,
            is_delete_data=False, is_recursive=True,
        )
        root = ET.Element("CompleteMultipartUploadResult", xmlns=XMLNS)
        _el(root, "Location", f"/{bucket}/{key}")
        _el(root, "Bucket", bucket)
        _el(root, "Key", key)
        _el(root, "ETag", f'"{final_etag}"')
        self._send(200, _xml_bytes(root))

    def abort_multipart(self, bucket: str, key: str):
        self._authz(ACTION_WRITE, bucket)
        upload_id = self.query["uploadId"]
        if self.s3.client.find_entry(
                self._uploads_dir(bucket), upload_id) is None:
            raise S3Error(404, "NoSuchUpload", "upload id not found")
        self.s3.client.delete_entry(
            self._uploads_dir(bucket), upload_id,
            is_delete_data=True, is_recursive=True,
        )
        self._send(204)

    def list_multipart_uploads(self, bucket: str):
        self._authz(ACTION_LIST, bucket)
        self._require_bucket(bucket)
        root = ET.Element("ListMultipartUploadsResult", xmlns=XMLNS)
        _el(root, "Bucket", bucket)
        _el(root, "IsTruncated", "false")
        for e in self.s3.client.list_entries(self._uploads_dir(bucket),
                                             limit=MAX_DIR_PAGE):
            if not e.is_directory:
                continue
            u = _el(root, "Upload")
            _el(u, "Key", e.extended.get("key", b"").decode())
            _el(u, "UploadId", e.name)
            _el(u, "Initiated", _iso(e.attributes.mtime))
        self._send(200, _xml_bytes(root))

    def list_parts(self, bucket: str, key: str):
        self._authz(ACTION_LIST, bucket)
        upload_id = self.query["uploadId"]
        self._upload_entry(bucket, upload_id)
        updir = f"{self._uploads_dir(bucket)}/{upload_id}"
        root = ET.Element("ListPartsResult", xmlns=XMLNS)
        _el(root, "Bucket", bucket)
        _el(root, "Key", key)
        _el(root, "UploadId", upload_id)
        _el(root, "IsTruncated", "false")
        for e in self.s3.client.list_entries(updir, limit=MAX_DIR_PAGE):
            if not e.name.endswith(".part"):
                continue
            p = _el(root, "Part")
            _el(p, "PartNumber", str(int(e.name.split(".", 1)[0])))
            _el(p, "LastModified", _iso(e.attributes.mtime))
            _el(p, "ETag", f'"{_entry_etag(e)}"')
            _el(p, "Size", str(_entry_size(e)))
        self._send(200, _xml_bytes(root))

    # -- tagging -------------------------------------------------------------

    def put_tagging(self, bucket: str, key: str):
        self._authz(ACTION_TAGGING, bucket)
        entry = self._find_object(bucket, key)
        try:
            tree = ET.fromstring(self._read_body())
        except ET.ParseError:
            raise S3Error(400, "MalformedXML", "bad tagging request")
        for k in list(entry.extended):
            if k.startswith(TAG_PREFIX):
                del entry.extended[k]
        for tag in tree.iter():
            if not tag.tag.endswith("Tag"):
                continue
            k = tag.findtext("Key") or tag.findtext(f"{{{XMLNS}}}Key")
            v = tag.findtext("Value") or tag.findtext(f"{{{XMLNS}}}Value") or ""
            if k:
                entry.extended[TAG_PREFIX + k] = v.encode()
        directory, _ = self.s3.object_path(bucket, key).rsplit("/", 1)
        self.s3.client.update_entry(directory, entry)
        self._send(200)

    def get_tagging(self, bucket: str, key: str):
        self._authz(ACTION_READ, bucket)
        entry = self._find_object(bucket, key)
        root = ET.Element("Tagging", xmlns=XMLNS)
        tagset = _el(root, "TagSet")
        for k, v in entry.extended.items():
            if k.startswith(TAG_PREFIX):
                t = _el(tagset, "Tag")
                _el(t, "Key", k[len(TAG_PREFIX):])
                _el(t, "Value", v.decode())
        self._send(200, _xml_bytes(root))

    def delete_tagging(self, bucket: str, key: str):
        self._authz(ACTION_TAGGING, bucket)
        entry = self._find_object(bucket, key)
        for k in list(entry.extended):
            if k.startswith(TAG_PREFIX):
                del entry.extended[k]
        directory, _ = self.s3.object_path(bucket, key).rsplit("/", 1)
        self.s3.client.update_entry(directory, entry)
        self._send(204)


# -- entry helpers ------------------------------------------------------------


def _entry_size(entry: filer_pb2.Entry) -> int:
    size = 0
    for c in entry.chunks:
        size = max(size, c.offset + c.size)
    return size or entry.attributes.file_size or len(entry.content)


def _entry_etag(entry: filer_pb2.Entry) -> str:
    stored = entry.extended.get(ETAG_KEY)
    if stored:
        return stored.decode()
    ids = ",".join(c.file_id for c in entry.chunks)
    return hashlib.md5(ids.encode()).hexdigest()
