"""S3 bucket-policy engine + POST-policy (browser form upload) checks.

Reference: weed/s3api/policy/ (post-policy condition evaluation) and the
AWS bucket-policy document semantics the reference's policy package
implements: explicit Deny wins, then explicit Allow, else fall through
to identity-based authorization.

Implemented from the public AWS policy-language specification; pinned by
tests/test_s3_policy.py.
"""

from __future__ import annotations

import base64
import datetime
import fnmatch
import json
from dataclasses import dataclass

ALLOW = "Allow"
DENY = "Deny"
DEFAULT = ""  # no statement matched: fall through to identity auth

# internal action + key -> s3:* action names
_ACTION_MAP = {
    "Read": "s3:GetObject",
    "Write": "s3:PutObject",
    "List": "s3:ListBucket",
    "Tagging": "s3:PutObjectTagging",
    "Delete": "s3:DeleteObject",
}


def s3_action(internal: str, key: str = "") -> str:
    return _ACTION_MAP.get(internal, f"s3:{internal}")


def resource_arn(bucket: str, key: str = "") -> str:
    return f"arn:aws:s3:::{bucket}/{key}" if key else f"arn:aws:s3:::{bucket}"


class PolicyError(ValueError):
    pass


@dataclass
class Statement:
    effect: str
    principals: list[str]  # "*" or AWS principal strings
    actions: list[str]
    not_actions: list[str]
    resources: list[str]

    def matches(self, principal: str, action: str, resource: str) -> bool:
        if not any(_wild(p, principal) or p == "*" for p in self.principals):
            return False
        if self.not_actions:
            if any(_wild(a, action) for a in self.not_actions):
                return False
        elif not any(_wild(a, action) for a in self.actions):
            return False
        return any(_wild(r, resource) for r in self.resources)


def _wild(pattern: str, value: str) -> bool:
    """AWS wildcard match: * and ? only ([ stays literal)."""
    pattern = pattern.replace("[", "[[]")
    return fnmatch.fnmatchcase(value, pattern)


class BucketPolicy:
    def __init__(self, statements: list[Statement]):
        self.statements = statements

    @classmethod
    def parse(cls, doc: "str | bytes | dict") -> "BucketPolicy":
        if isinstance(doc, (str, bytes)):
            try:
                doc = json.loads(doc)
            except json.JSONDecodeError as e:
                raise PolicyError(f"malformed policy JSON: {e}")
        if not isinstance(doc, dict):
            raise PolicyError("policy must be a JSON object")
        statements = []
        for raw in _as_list(doc.get("Statement")):
            effect = raw.get("Effect")
            if effect not in (ALLOW, DENY):
                raise PolicyError(f"bad Effect {effect!r}")
            principal = raw.get("Principal", "*")
            if isinstance(principal, dict):
                principals = _as_list(principal.get("AWS", []))
            else:
                principals = _as_list(principal)
            actions = _as_list(raw.get("Action", []))
            not_actions = _as_list(raw.get("NotAction", []))
            if not actions and not not_actions:
                raise PolicyError("statement needs Action or NotAction")
            resources = _as_list(raw.get("Resource", []))
            if not resources:
                raise PolicyError("statement needs Resource")
            statements.append(
                Statement(effect, [str(p) for p in principals],
                          actions, not_actions, resources)
            )
        if not statements:
            raise PolicyError("policy has no statements")
        return cls(statements)

    def evaluate(self, principal: str, action: str, resource: str) -> str:
        """-> DENY | ALLOW | DEFAULT (explicit deny wins)."""
        verdict = DEFAULT
        for s in self.statements:
            if not s.matches(principal, action, resource):
                continue
            if s.effect == DENY:
                return DENY
            verdict = ALLOW
        return verdict


def _as_list(x) -> list:
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


# -- POST policy (browser form uploads) --------------------------------------


@dataclass
class PostPolicy:
    expiration: datetime.datetime
    conditions: list

    @classmethod
    def parse(cls, b64: str) -> "PostPolicy":
        try:
            doc = json.loads(base64.b64decode(b64))
        except (ValueError, json.JSONDecodeError) as e:
            raise PolicyError(f"bad post policy: {e}")
        exp = doc.get("expiration")
        if not exp:
            raise PolicyError("post policy missing expiration")
        try:
            expiration = datetime.datetime.strptime(
                exp, "%Y-%m-%dT%H:%M:%S.%fZ"
            ).replace(tzinfo=datetime.timezone.utc)
        except ValueError:
            expiration = datetime.datetime.strptime(
                exp, "%Y-%m-%dT%H:%M:%SZ"
            ).replace(tzinfo=datetime.timezone.utc)
        return cls(expiration, _as_list(doc.get("conditions")))

    def check(self, form: dict[str, str], content_length: int) -> None:
        """Validate form fields against the signed conditions
        (policy/post-policy condition kinds: eq, starts-with,
        content-length-range)."""
        now = datetime.datetime.now(datetime.timezone.utc)
        if now > self.expiration:
            raise PolicyError("post policy expired")
        for cond in self.conditions:
            if isinstance(cond, dict):
                for k, v in cond.items():
                    got = form.get(k.lower(), "")
                    if k.lower().startswith("x-ignore-"):
                        continue
                    if got != str(v):
                        raise PolicyError(f"condition {k}={v!r} not met")
            elif isinstance(cond, list) and len(cond) == 3:
                op, name, want = cond
                if op == "eq":
                    name = str(name).lstrip("$").lower()
                    if form.get(name, "") != str(want):
                        raise PolicyError(f"eq condition on {name} not met")
                elif op == "starts-with":
                    name = str(name).lstrip("$").lower()
                    if not form.get(name, "").startswith(str(want)):
                        raise PolicyError(
                            f"starts-with condition on {name} not met"
                        )
                elif op == "content-length-range":
                    lo, hi = int(name), int(want)
                    if not lo <= content_length <= hi:
                        raise PolicyError("content-length out of range")
                else:
                    raise PolicyError(f"unknown condition op {op!r}")
            else:
                raise PolicyError("malformed condition")
