"""S3 request authentication: AWS Signature V4 (header + presigned),
legacy V2, and the aws-chunked streaming payload decoder.

Reference behavior: weed/s3api/auth_signature_v4.go (canonical request /
string-to-sign / signing-key chain, seed signature for streaming uploads),
auth_signature_v2.go, and auth_credentials.go (identities + actions from
the s3 config json; anonymous access when no identities are configured).

Implemented from the public AWS SigV4 specification; the signing primitive
is pinned against the documented AWS example vector in tests/test_s3.py.
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import json
import urllib.parse
from dataclasses import dataclass, field

ACTION_ADMIN = "Admin"
ACTION_READ = "Read"
ACTION_WRITE = "Write"
ACTION_LIST = "List"
ACTION_TAGGING = "Tagging"

UNSIGNED_PAYLOAD = "UNSIGNED-PAYLOAD"
STREAMING_PAYLOAD = "STREAMING-AWS4-HMAC-SHA256-PAYLOAD"

_EMPTY_SHA256 = hashlib.sha256(b"").hexdigest()


class AuthError(Exception):
    """Maps to an S3 error code + HTTP status."""

    def __init__(self, code: str, message: str, status: int = 403):
        super().__init__(message)
        self.code = code
        self.status = status


# -- signing primitives ------------------------------------------------------


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def signing_key(secret: str, date: str, region: str, service: str) -> bytes:
    """AWS4 signing-key derivation chain (date is YYYYMMDD)."""
    k = _hmac(("AWS4" + secret).encode(), date)
    k = _hmac(k, region)
    k = _hmac(k, service)
    return _hmac(k, "aws4_request")


def _uri_encode(s: str, encode_slash: bool = True) -> str:
    safe = "-._~" if encode_slash else "-._~/"
    return urllib.parse.quote(s, safe=safe)


def canonical_query(query: str, drop: set[str] = frozenset()) -> str:
    """Sorted, URI-encoded query string (values re-encoded per the spec)."""
    pairs = []
    for part in query.split("&"):
        if not part:
            continue
        k, _, v = part.partition("=")
        k = urllib.parse.unquote_plus(k)
        v = urllib.parse.unquote_plus(v)
        if k in drop:
            continue
        pairs.append((_uri_encode(k), _uri_encode(v)))
    pairs.sort()
    return "&".join(f"{k}={v}" for k, v in pairs)


def canonical_request(
    method: str,
    raw_path: str,
    query: str,
    headers: dict[str, str],
    signed_headers: list[str],
    payload_hash: str,
    drop_query: set[str] = frozenset(),
) -> str:
    canon_headers = "".join(
        f"{h}:{' '.join(headers.get(h, '').split())}\n" for h in signed_headers
    )
    # S3 does NOT normalize paths: SDKs sign the raw (still percent-encoded)
    # request path verbatim, so keys containing %2F etc. must reach the
    # canonical request untouched (AWS SigV4 spec, "do not normalize URI
    # paths for Amazon S3").
    return "\n".join(
        [
            method,
            raw_path or "/",
            canonical_query(query, drop_query),
            canon_headers,
            ";".join(signed_headers),
            payload_hash,
        ]
    )


def string_to_sign(amz_date: str, scope: str, canon_req: str) -> str:
    return "\n".join(
        [
            "AWS4-HMAC-SHA256",
            amz_date,
            scope,
            hashlib.sha256(canon_req.encode()).hexdigest(),
        ]
    )


def sign_v4(secret: str, date: str, region: str, service: str,
            amz_date: str, canon_req: str) -> str:
    scope = f"{date}/{region}/{service}/aws4_request"
    sts = string_to_sign(amz_date, scope, canon_req)
    return hmac.new(
        signing_key(secret, date, region, service), sts.encode(), hashlib.sha256
    ).hexdigest()


# -- identities --------------------------------------------------------------


@dataclass
class Identity:
    name: str
    credentials: list[tuple[str, str]] = field(default_factory=list)
    actions: list[str] = field(default_factory=list)

    def secret_for(self, access_key: str) -> str | None:
        for ak, sk in self.credentials:
            if ak == access_key:
                return sk
        return None

    def can_do(self, action: str, bucket: str) -> bool:
        if ACTION_ADMIN in self.actions:
            return True
        for a in self.actions:
            base, _, scope = a.partition(":")
            if base != action:
                continue
            if not scope or scope == bucket:
                return True
        return False


class IdentityAccessManagement:
    """Access-key registry + per-request authentication/authorization.

    When no identities are configured, every request is allowed (the
    reference's behavior without an s3 config: auth disabled).
    """

    def __init__(self, config_path: str = "", domain: str = ""):
        self.domain = domain
        self.identities: list[Identity] = []
        if config_path:
            self.load_config_file(config_path)

    @property
    def enabled(self) -> bool:
        return bool(self.identities)

    def load_config_file(self, path: str) -> None:
        with open(path) as f:
            self.load_config(json.load(f))

    def load_config(self, conf: dict) -> None:
        self.identities = []
        for ident in conf.get("identities", []):
            self.identities.append(
                Identity(
                    name=ident.get("name", ""),
                    credentials=[
                        (c["accessKey"], c["secretKey"])
                        for c in ident.get("credentials", [])
                    ],
                    actions=list(ident.get("actions", [])),
                )
            )

    def lookup(self, access_key: str) -> tuple[Identity, str] | None:
        for ident in self.identities:
            secret = ident.secret_for(access_key)
            if secret is not None:
                return ident, secret
        return None

    # -- request authentication ---------------------------------------------

    def authenticate(self, req: "S3HttpRequest") -> Identity | None:
        """Raises AuthError on bad signatures; returns the Identity (or None
        when auth is disabled / anonymous)."""
        if not self.enabled:
            return None
        auth_header = req.headers.get("authorization", "")
        if auth_header.startswith("AWS4-HMAC-SHA256"):
            return self._auth_v4_header(req, auth_header)
        if auth_header.startswith("AWS "):
            return self._auth_v2_header(req, auth_header)
        q = req.query_params
        if q.get("X-Amz-Algorithm") == "AWS4-HMAC-SHA256":
            return self._auth_v4_presigned(req)
        if "Signature" in q and "AWSAccessKeyId" in q:
            raise AuthError("AccessDenied", "presigned v2 not supported")
        raise AuthError("AccessDenied", "no credentials provided")

    def _auth_v4_header(self, req: "S3HttpRequest", header: str) -> Identity:
        fields: dict[str, str] = {}
        for item in header[len("AWS4-HMAC-SHA256"):].split(","):
            k, _, v = item.strip().partition("=")
            fields[k] = v
        try:
            cred_parts = fields["Credential"].split("/")
            access_key, date, region, service, terminal = cred_parts
            signed_headers = fields["SignedHeaders"].split(";")
            got_sig = fields["Signature"]
        except (KeyError, ValueError):
            raise AuthError("AuthorizationHeaderMalformed", "bad v4 header")
        # "iam" scope: the IAM gateway (iamapi/) shares this authenticator,
        # and AWS SDK/CLI IAM clients sign with service=iam
        if terminal != "aws4_request" or service not in ("s3", "iam"):
            raise AuthError("AuthorizationHeaderMalformed", "bad scope")
        found = self.lookup(access_key)
        if not found:
            raise AuthError("InvalidAccessKeyId", f"unknown key {access_key}")
        ident, secret = found
        amz_date = req.headers.get("x-amz-date") or req.headers.get("date", "")
        self._check_freshness(amz_date)
        payload_hash = req.headers.get("x-amz-content-sha256") or _EMPTY_SHA256
        canon = canonical_request(
            req.method, req.raw_path, req.raw_query, req.headers,
            signed_headers, payload_hash,
        )
        want = sign_v4(secret, date, region, service, amz_date, canon)
        if not hmac.compare_digest(want, got_sig):
            raise AuthError("SignatureDoesNotMatch",
                            "the computed signature does not match")
        req.seed_signature = got_sig
        req.sig_date, req.sig_region, req.sig_secret = date, region, secret
        req.sig_amz_date = amz_date
        if len(payload_hash) == 64:
            # a concrete content hash was signed: the body handler MUST
            # verify it, or signed bodies are swappable in flight
            req.expected_sha256 = payload_hash
        return ident

    @staticmethod
    def _check_freshness(amz_date: str, window_s: int = 900) -> None:
        """Reject requests whose signed timestamp is >15min from now —
        bounds the replay window of a captured signed request."""
        try:
            t0 = datetime.datetime.strptime(
                amz_date, "%Y%m%dT%H%M%SZ"
            ).replace(tzinfo=datetime.timezone.utc)
        except ValueError:
            raise AuthError("AccessDenied", "bad x-amz-date")
        now = datetime.datetime.now(datetime.timezone.utc)
        if abs((now - t0).total_seconds()) > window_s:
            raise AuthError("RequestTimeTooSkewed",
                            "request timestamp too far from server time")

    def _auth_v4_presigned(self, req: "S3HttpRequest") -> Identity:
        q = req.query_params
        try:
            access_key, date, region, service, terminal = q[
                "X-Amz-Credential"
            ].split("/")
            signed_headers = q["X-Amz-SignedHeaders"].split(";")
            got_sig = q["X-Amz-Signature"]
            amz_date = q["X-Amz-Date"]
            expires = int(q.get("X-Amz-Expires", "604800"))
        except (KeyError, ValueError):
            raise AuthError("AuthorizationQueryParametersError", "bad presign")
        if terminal != "aws4_request" or service != "s3":
            raise AuthError("AuthorizationQueryParametersError", "bad scope")
        t0 = datetime.datetime.strptime(amz_date, "%Y%m%dT%H%M%SZ").replace(
            tzinfo=datetime.timezone.utc
        )
        now = datetime.datetime.now(datetime.timezone.utc)
        if now > t0 + datetime.timedelta(seconds=expires):
            raise AuthError("AccessDenied", "request has expired")
        found = self.lookup(access_key)
        if not found:
            raise AuthError("InvalidAccessKeyId", f"unknown key {access_key}")
        ident, secret = found
        canon = canonical_request(
            req.method, req.raw_path, req.raw_query, req.headers,
            signed_headers, UNSIGNED_PAYLOAD,
            drop_query={"X-Amz-Signature"},
        )
        want = sign_v4(secret, date, region, "s3", amz_date, canon)
        if not hmac.compare_digest(want, got_sig):
            raise AuthError("SignatureDoesNotMatch",
                            "the computed signature does not match")
        return ident

    def _auth_v2_header(self, req: "S3HttpRequest", header: str) -> Identity:
        try:
            access_key, got_sig = header[len("AWS "):].split(":", 1)
        except ValueError:
            raise AuthError("AuthorizationHeaderMalformed", "bad v2 header")
        self._check_v2_freshness(req)
        found = self.lookup(access_key)
        if not found:
            raise AuthError("InvalidAccessKeyId", f"unknown key {access_key}")
        ident, secret = found
        sts = self._v2_string_to_sign(req)
        want = hmac.new(secret.encode(), sts.encode(), hashlib.sha1).digest()
        import base64

        if not hmac.compare_digest(base64.b64encode(want).decode(), got_sig):
            raise AuthError("SignatureDoesNotMatch", "v2 signature mismatch")
        return ident

    @staticmethod
    def _check_v2_freshness(req: "S3HttpRequest", window_s: int = 900) -> None:
        """V2 replay bound: like V4's 15-minute skew window, a captured
        V2-signed request must not verify forever.  x-amz-date overrides
        Date when both are present (the signed one wins, per the V2 spec)."""
        import email.utils

        raw = req.headers.get("x-amz-date") or req.headers.get("date", "")
        if not raw:
            raise AuthError("AccessDenied", "v2 request missing Date")
        try:
            t0 = email.utils.parsedate_to_datetime(raw)
        except (TypeError, ValueError):
            raise AuthError("AccessDenied", "bad v2 Date header")
        if t0.tzinfo is None:
            t0 = t0.replace(tzinfo=datetime.timezone.utc)
        now = datetime.datetime.now(datetime.timezone.utc)
        if abs((now - t0).total_seconds()) > window_s:
            raise AuthError("RequestTimeTooSkewed",
                            "request timestamp too far from server time")

    _V2_SUBRESOURCES = (
        "acl", "delete", "lifecycle", "location", "logging", "notification",
        "partNumber", "policy", "requestPayment", "tagging", "torrent",
        "uploadId", "uploads", "versionId", "versioning", "versions",
        "website",
    )

    def _v2_string_to_sign(self, req: "S3HttpRequest") -> str:
        amz_headers = sorted(
            (k, v) for k, v in req.headers.items() if k.startswith("x-amz-")
        )
        canon_amz = "".join(f"{k}:{v}\n" for k, v in amz_headers)
        sub = [
            f"{k}={v}" if v else k
            for k, v in sorted(req.query_params.items())
            if k in self._V2_SUBRESOURCES
        ]
        resource = urllib.parse.unquote(req.raw_path)
        if sub:
            resource += "?" + "&".join(sub)
        return "\n".join(
            [
                req.method,
                req.headers.get("content-md5", ""),
                req.headers.get("content-type", ""),
                req.headers.get("date", ""),
                canon_amz + resource,
            ]
        )

    # -- authorization -------------------------------------------------------

    def authorize(self, ident: Identity | None, action: str, bucket: str) -> None:
        if not self.enabled:
            return
        if ident is None or not ident.can_do(action, bucket):
            raise AuthError("AccessDenied", f"not allowed to {action} {bucket}")


@dataclass
class S3HttpRequest:
    """The subset of the HTTP request the authenticator needs.

    headers keys must be lower-cased; raw_path/raw_query are as received
    (still percent-encoded).
    """

    method: str
    raw_path: str
    raw_query: str
    headers: dict[str, str]
    seed_signature: str = ""
    sig_date: str = ""
    sig_region: str = ""
    sig_secret: str = ""
    sig_amz_date: str = ""
    expected_sha256: str = ""  # signed content hash the body must match

    @property
    def query_params(self) -> dict[str, str]:
        return {
            k: v[0]
            for k, v in urllib.parse.parse_qs(
                self.raw_query, keep_blank_values=True
            ).items()
        }


# -- aws-chunked streaming payload -------------------------------------------


def decode_streaming_body(body: bytes, req: S3HttpRequest | None = None) -> bytes:
    """Decode (and when req carries a seed signature, verify) an
    aws-chunked body: hex-size;chunk-signature=sig CRLF data CRLF ...

    Verification follows the spec: each chunk signature signs
    AWS4-HMAC-SHA256-PAYLOAD / date / scope / prev-sig / sha256("") /
    sha256(chunk-data), chained from the seed (header) signature.

    A stream is only complete once the signed terminal 0-size chunk has been
    seen (and verified) — a body truncated at any chunk boundary otherwise
    passes every per-chunk check (reference: chunked_reader_v4.go fails such
    streams with ErrUnexpectedEOF).  When the client signed an
    x-amz-decoded-content-length header, the decoded size must match it too.
    """
    out = bytearray()
    pos = 0
    prev_sig = req.seed_signature if req else ""
    verify = bool(req and req.seed_signature and req.sig_secret)
    saw_final_chunk = False
    while pos < len(body):
        nl = body.find(b"\r\n", pos)
        if nl < 0:
            raise AuthError("IncompleteBody", "bad chunk header", status=400)
        header = body[pos:nl].decode("latin-1")
        size_hex, _, ext = header.partition(";")
        try:
            size = int(size_hex, 16)
        except ValueError:
            raise AuthError("IncompleteBody", "bad chunk size", status=400)
        data = body[nl + 2 : nl + 2 + size]
        if len(data) != size:
            raise AuthError("IncompleteBody", "short chunk", status=400)
        if verify:
            sig = ""
            for kv in ext.split(";"):
                k, _, v = kv.partition("=")
                if k == "chunk-signature":
                    sig = v
            scope = f"{req.sig_date}/{req.sig_region}/s3/aws4_request"
            sts = "\n".join(
                [
                    "AWS4-HMAC-SHA256-PAYLOAD",
                    req.sig_amz_date,
                    scope,
                    prev_sig,
                    _EMPTY_SHA256,
                    hashlib.sha256(bytes(data)).hexdigest(),
                ]
            )
            want = hmac.new(
                signing_key(req.sig_secret, req.sig_date, req.sig_region, "s3"),
                sts.encode(),
                hashlib.sha256,
            ).hexdigest()
            if not hmac.compare_digest(want, sig):
                raise AuthError("SignatureDoesNotMatch", "bad chunk signature")
            prev_sig = sig
        out += data
        pos = nl + 2 + size + 2  # skip trailing CRLF
        if size == 0:
            saw_final_chunk = True
            break
    if not saw_final_chunk:
        raise AuthError("IncompleteBody",
                        "stream ended before the terminal chunk", status=400)
    declared = (req.headers.get("x-amz-decoded-content-length") if req else None)
    if declared is not None:
        try:
            if int(declared) != len(out):
                raise AuthError("IncompleteBody",
                                "decoded length != x-amz-decoded-content-length",
                                status=400)
        except ValueError:
            raise AuthError("IncompleteBody",
                            "bad x-amz-decoded-content-length", status=400)
    return bytes(out)


def sign_request(method: str, host: str, path: str, service: str,
                 region: str, access_key: str, secret: str,
                 body: bytes = b"", query: str = "") -> dict:
    """Build the signed header set for an outbound SigV4 request (the
    client-side counterpart of this module's verifier; shared by the SQS
    publisher and the signed replication sinks)."""
    import hashlib
    import time as _time

    amz_date = _time.strftime("%Y%m%dT%H%M%SZ", _time.gmtime())
    headers = {
        "host": host,
        "x-amz-date": amz_date,
        "x-amz-content-sha256": hashlib.sha256(body).hexdigest(),
    }
    canon = canonical_request(method, path, query, headers,
                              sorted(headers),
                              headers["x-amz-content-sha256"])
    signature = sign_v4(secret, amz_date[:8], region, service, amz_date,
                        canon)
    headers["Authorization"] = (
        f"AWS4-HMAC-SHA256 Credential={access_key}/{amz_date[:8]}/"
        f"{region}/{service}/aws4_request, "
        f"SignedHeaders={';'.join(sorted(headers))}, "
        f"Signature={signature}"
    )
    return headers
