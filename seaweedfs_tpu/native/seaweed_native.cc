// Native byte-path for seaweedfs_tpu: hardware CRC32C and a SIMD GF(2^8)
// codec.  This plays the role the reference delegates to SIMD assembly
// (klauspost/crc32 for needle checksums, klauspost/reedsolomon for the
// RS(10,4) hot loop): the host-side fast path for per-needle work where a TPU
// dispatch would dominate the latency.  Bulk encode/rebuild runs on TPU.
//
// Build: g++ -O3 -shared -fPIC (see build.py).  x86 SIMD paths are guarded so
// the file also compiles on other architectures.

#include <cstddef>
#include <cstdint>
#include <cstring>
#include <initializer_list>

#if defined(__SSE4_2__)
#include <nmmintrin.h>
#endif
#if defined(__SSSE3__)
#include <tmmintrin.h>
#endif
#if defined(__GFNI__) && defined(__AVX512F__) && defined(__AVX512BW__)
#define SW_HAVE_GFNI 1
#include <immintrin.h>
#endif

extern "C" {

// ---------------------------------------------------------------------------
// CRC32C (Castagnoli).  Unmasked; callers apply the LevelDB-style mask.
// ---------------------------------------------------------------------------

static uint32_t crc32c_table[8][256];
static bool crc32c_init_done = false;

static void crc32c_init() {
  if (crc32c_init_done) return;
  const uint32_t poly = 0x82F63B78u;
  for (int i = 0; i < 256; i++) {
    uint32_t crc = (uint32_t)i;
    for (int j = 0; j < 8; j++) crc = (crc >> 1) ^ ((crc & 1) ? poly : 0);
    crc32c_table[0][i] = crc;
  }
  for (int k = 1; k < 8; k++)
    for (int i = 0; i < 256; i++)
      crc32c_table[k][i] =
          (crc32c_table[k - 1][i] >> 8) ^ crc32c_table[0][crc32c_table[k - 1][i] & 0xFF];
  crc32c_init_done = true;
}

uint32_t sw_crc32c_update(uint32_t crc, const uint8_t* data, size_t n) {
  crc = ~crc;
#if defined(__SSE4_2__)
  while (n >= 8) {
    uint64_t chunk;
    memcpy(&chunk, data, 8);
    crc = (uint32_t)_mm_crc32_u64(crc, chunk);
    data += 8;
    n -= 8;
  }
  while (n--) crc = _mm_crc32_u8(crc, *data++);
#else
  crc32c_init();
  while (n >= 8) {
    uint32_t low = crc ^ ((uint32_t)data[0] | (uint32_t)data[1] << 8 |
                          (uint32_t)data[2] << 16 | (uint32_t)data[3] << 24);
    crc = crc32c_table[7][low & 0xFF] ^ crc32c_table[6][(low >> 8) & 0xFF] ^
          crc32c_table[5][(low >> 16) & 0xFF] ^ crc32c_table[4][(low >> 24) & 0xFF] ^
          crc32c_table[3][data[4]] ^ crc32c_table[2][data[5]] ^
          crc32c_table[1][data[6]] ^ crc32c_table[0][data[7]];
    data += 8;
    n -= 8;
  }
  while (n--) crc = (crc >> 8) ^ crc32c_table[0][(crc ^ *data++) & 0xFF];
#endif
  return ~crc;
}

// ---------------------------------------------------------------------------
// GF(2^8) codec, field polynomial 0x11D.  outputs[r] = XOR_s M[r][s]*in[s].
// Per-constant low/high-nibble tables; SSSE3 pshufb path processes 16 bytes
// per step (the same trick the reference's SIMD assembly uses).
// ---------------------------------------------------------------------------

static uint8_t gf_mul_table[256][256];
static bool gf_init_done = false;

static void gf_init() {
  if (gf_init_done) return;
  uint8_t exp_t[512];
  int log_t[256];
  int x = 1;
  for (int i = 0; i < 255; i++) {
    exp_t[i] = (uint8_t)x;
    log_t[x] = i;
    x <<= 1;
    if (x & 0x100) x ^= 0x11D;
  }
  for (int i = 255; i < 512; i++) exp_t[i] = exp_t[i - 255];
  for (int a = 0; a < 256; a++)
    for (int b = 0; b < 256; b++)
      gf_mul_table[a][b] =
          (a == 0 || b == 0) ? 0 : exp_t[log_t[a] + log_t[b]];
  gf_init_done = true;
}

static void gf_mul_acc_scalar(uint8_t c, const uint8_t* in, uint8_t* out,
                              size_t n, bool first) {
  const uint8_t* row = gf_mul_table[c];
  if (first) {
    for (size_t i = 0; i < n; i++) out[i] = row[in[i]];
  } else {
    for (size_t i = 0; i < n; i++) out[i] ^= row[in[i]];
  }
}

#if defined(__SSSE3__)
static void gf_mul_acc_ssse3(uint8_t c, const uint8_t* in, uint8_t* out,
                             size_t n, bool first) {
  // Build 16-entry nibble tables for constant c.
  alignas(16) uint8_t lo_tbl[16], hi_tbl[16];
  for (int i = 0; i < 16; i++) {
    lo_tbl[i] = gf_mul_table[c][i];
    hi_tbl[i] = gf_mul_table[c][i << 4];
  }
  __m128i lo = _mm_load_si128((const __m128i*)lo_tbl);
  __m128i hi = _mm_load_si128((const __m128i*)hi_tbl);
  __m128i mask = _mm_set1_epi8(0x0F);
  size_t i = 0;
  for (; i + 16 <= n; i += 16) {
    __m128i v = _mm_loadu_si128((const __m128i*)(in + i));
    __m128i vl = _mm_and_si128(v, mask);
    __m128i vh = _mm_and_si128(_mm_srli_epi64(v, 4), mask);
    __m128i r = _mm_xor_si128(_mm_shuffle_epi8(lo, vl), _mm_shuffle_epi8(hi, vh));
    if (!first) r = _mm_xor_si128(r, _mm_loadu_si128((const __m128i*)(out + i)));
    _mm_storeu_si128((__m128i*)(out + i), r);
  }
  if (i < n) gf_mul_acc_scalar(c, in + i, out + i, n - i, first);
}
#endif

#if defined(SW_HAVE_GFNI)
// GFNI path: multiply-by-constant in ANY GF(2^8) representation is a
// GF(2)-linear map on the byte's bits, so it is one vgf2p8affineqb with a
// per-constant 8x8 bit matrix — 64 bytes per instruction under AVX512,
// no table lookups.  (The same technique modern klauspost/reedsolomon
// and ISA-L use; the reference pins v1.9.2, which predates it.)
static uint64_t gf_affine_matrix[256];
static int gfni_state = 0;  // 0 = untested, 1 = ok, -1 = unusable

static uint64_t gf_build_affine(uint8_t c) {
  // out_bit_i = parity(A.byte[7-i] & x); want out = c*x, so byte (7-i)
  // collects bit i of c*2^j across the basis j.
  uint64_t a = 0;
  for (int i = 0; i < 8; i++) {
    uint8_t rowbyte = 0;
    for (int j = 0; j < 8; j++) {
      if ((gf_mul_table[c][(uint8_t)(1u << j)] >> i) & 1) rowbyte |= (uint8_t)(1u << j);
    }
    a |= (uint64_t)rowbyte << (8 * (7 - i));
  }
  return a;
}

static void gfni_init() {
  if (gfni_state != 0) return;
  // the .so may have been built on a GFNI host and copied to one
  // without it: gate at RUNTIME before executing any AVX512 instruction
  if (!__builtin_cpu_supports("gfni") ||
      !__builtin_cpu_supports("avx512f") ||
      !__builtin_cpu_supports("avx512bw")) {
    gfni_state = -1;
    return;
  }
  for (int c = 0; c < 256; c++) gf_affine_matrix[c] = (uint64_t)gf_build_affine((uint8_t)c);
  // self-check the bit-layout convention against the table codec before
  // trusting it for real data
  alignas(64) uint8_t in[64], out[64];
  for (int i = 0; i < 64; i++) in[i] = (uint8_t)(i * 7 + 3);
  for (int c : {2, 29, 71, 142, 255}) {
    __m512i A = _mm512_set1_epi64((long long)gf_affine_matrix[c]);
    __m512i v = _mm512_loadu_si512((const void*)in);
    _mm512_storeu_si512((void*)out, _mm512_gf2p8affine_epi64_epi8(v, A, 0));
    for (int i = 0; i < 64; i++) {
      if (out[i] != gf_mul_table[c][in[i]]) { gfni_state = -1; return; }
    }
  }
  gfni_state = 1;
}

static void gf_mul_acc_gfni(uint8_t c, const uint8_t* in, uint8_t* out,
                            size_t n, bool first) {
  __m512i A = _mm512_set1_epi64((long long)gf_affine_matrix[c]);
  size_t i = 0;
  for (; i + 64 <= n; i += 64) {
    __m512i v = _mm512_loadu_si512((const void*)(in + i));
    __m512i r = _mm512_gf2p8affine_epi64_epi8(v, A, 0);
    if (!first)
      r = _mm512_xor_si512(r, _mm512_loadu_si512((const void*)(out + i)));
    _mm512_storeu_si512((void*)(out + i), r);
  }
  if (i < n) gf_mul_acc_scalar(c, in + i, out + i, n - i, first);
}
#endif

#if defined(SW_HAVE_GFNI)
// Column-interleaved GFNI kernel: each 64-byte column position loads the s
// input vectors ONCE and keeps all r accumulators in zmm registers, so the
// DRAM traffic is (s + r) streams over n — the row-at-a-time loop below
// makes r*s passes (≈100n bytes of traffic for RS(10,4)), which caps the
// whole codec at ~2 GB/s memory-bound regardless of how fast the
// per-element GF math is.  r is capped at 14 (RS total shards) to bound
// register/stack pressure; anything wider falls back to the row loop.
static void gf_apply_interleaved_gfni(const uint8_t* matrix, int r, int s,
                                      const uint8_t** inputs,
                                      uint8_t** outputs, size_t n) {
  __m512i A[14 * 14];  // affine matrix operands, indexed [i*s + j]
  for (int i = 0; i < r; i++)
    for (int j = 0; j < s; j++)
      A[i * s + j] =
          _mm512_set1_epi64((long long)gf_affine_matrix[matrix[i * s + j]]);
  size_t pos = 0;
  for (; pos + 64 <= n; pos += 64) {
    __m512i acc[14];
    {
      __m512i v = _mm512_loadu_si512((const void*)(inputs[0] + pos));
      for (int i = 0; i < r; i++)
        acc[i] = _mm512_gf2p8affine_epi64_epi8(v, A[i * s], 0);
    }
    for (int j = 1; j < s; j++) {
      __m512i v = _mm512_loadu_si512((const void*)(inputs[j] + pos));
      for (int i = 0; i < r; i++)
        acc[i] = _mm512_xor_si512(
            acc[i], _mm512_gf2p8affine_epi64_epi8(v, A[i * s + j], 0));
    }
    for (int i = 0; i < r; i++)
      _mm512_storeu_si512((void*)(outputs[i] + pos), acc[i]);
  }
  if (pos < n) {  // tail: the scalar table path, first-row semantics
    for (int i = 0; i < r; i++) {
      bool first = true;
      for (int j = 0; j < s; j++) {
        uint8_t c = matrix[i * s + j];
        if (c == 0) continue;
        gf_mul_acc_scalar(c, inputs[j] + pos, outputs[i] + pos, n - pos,
                          first);
        first = false;
      }
      if (first) memset(outputs[i] + pos, 0, n - pos);
    }
  }
}
#endif

void sw_gf_apply(const uint8_t* matrix, int r, int s, const uint8_t** inputs,
                 uint8_t** outputs, size_t n) {
  gf_init();
#if defined(SW_HAVE_GFNI)
  gfni_init();
  if (gfni_state == 1 && r > 0 && r <= 14 && s > 0 && s <= 14) {
    gf_apply_interleaved_gfni(matrix, r, s, inputs, outputs, n);
    return;
  }
#endif
  for (int i = 0; i < r; i++) {
    bool first = true;
    for (int j = 0; j < s; j++) {
      uint8_t c = matrix[i * s + j];
      if (c == 0) continue;
#if defined(SW_HAVE_GFNI)
      if (gfni_state == 1) {
        gf_mul_acc_gfni(c, inputs[j], outputs[i], n, first);
        first = false;
        continue;
      }
#endif
#if defined(__SSSE3__)
      gf_mul_acc_ssse3(c, inputs[j], outputs[i], n, first);
#else
      gf_mul_acc_scalar(c, inputs[j], outputs[i], n, first);
#endif
      first = false;
    }
    if (first) memset(outputs[i], 0, n);
  }
}

}  // extern "C"

extern "C" int sw_gf_impl() {
  // 3 = column-interleaved GFNI+AVX512, 1 = SSSE3, 0 = scalar
  // (introspection for tests and the loader's stale-build self-heal)
  gf_init();
#if defined(SW_HAVE_GFNI)
  gfni_init();
  if (gfni_state == 1) return 3;
#endif
#if defined(__SSSE3__)
  return 1;
#else
  return 0;
#endif
}
