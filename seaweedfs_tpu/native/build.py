"""On-demand g++ build of the native library.

No pip/apt dependencies: a single translation unit compiled straight to a
shared object next to this file.  Callers treat failure as 'native
unavailable' and fall back to numpy.
"""

from __future__ import annotations

import os
import subprocess

_SRC = os.path.join(os.path.dirname(__file__), "seaweed_native.cc")
_OUT = os.path.join(os.path.dirname(__file__), "libseaweed_native.so")


def build(force: bool = False) -> str:
    if not force and os.path.exists(_OUT) and (
        os.path.getmtime(_OUT) >= os.path.getmtime(_SRC)
    ):
        return _OUT
    # compile to a process-unique temp path, then atomically rename: a
    # concurrent process never dlopens a half-written .so
    tmp = f"{_OUT}.{os.getpid()}.tmp"
    cmd = [
        "g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-march=native",
        _SRC, "-o", tmp,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    except (subprocess.CalledProcessError, FileNotFoundError, subprocess.TimeoutExpired) as e:
        # retry without -march=native (portable baseline) — but say so:
        # a silent scalar build costs ~4x codec throughput on SIMD hosts
        import sys

        detail = getattr(e, "stderr", b"") or b""
        print("seaweedfs_tpu native: -march=native build failed, falling "
              f"back to portable scalar codec: {detail[-300:]!r}",
              file=sys.stderr)
        extra = []
        try:
            with open("/proc/cpuinfo") as f:
                flags = f.read()
            if "ssse3" in flags:
                extra.append("-mssse3")
            if "sse4_2" in flags:
                extra.append("-msse4.2")
        except OSError:
            pass
        cmd = (["g++", "-O2", "-shared", "-fPIC", "-std=c++17"] + extra +
               [_SRC, "-o", tmp])
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    os.replace(tmp, _OUT)
    return _OUT


if __name__ == "__main__":
    print(build(force=True))
