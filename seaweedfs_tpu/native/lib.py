"""ctypes loader for the C++ native library (CRC32C, GF(2^8) SIMD codec).

The native byte-path mirrors the reference's use of SIMD for CRC32C and GF
arithmetic (klauspost/crc32, klauspost/reedsolomon).  Built on demand by
``build.py``; every caller must tolerate ``available() == False`` and fall
back to numpy.
"""

from __future__ import annotations

import ctypes
import os
import threading

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False


def _so_path() -> str:
    return os.path.join(os.path.dirname(__file__), "libseaweed_native.so")


def _host_simd_tier() -> int:
    """Best sw_gf_impl tier this host can run: 3 interleaved GFNI+AVX512,
    1 SSSE3, 0 scalar — the heal target for stale/portable builds."""
    try:
        with open("/proc/cpuinfo") as f:
            flags = f.read()
    except OSError:
        return 0
    if "gfni" in flags and "avx512bw" in flags and "avx512f" in flags:
        return 3
    if "ssse3" in flags:
        return 1
    return 0


def _load() -> ctypes.CDLL | None:
    global _lib, _tried
    if _tried:  # lock-free fast path: GIL-atomic read of a settled state
        return _lib
    with _lock:
        if _tried:
            return _lib
        _tried = True
        path = _so_path()
        if not os.path.exists(path):
            try:
                from . import build

                build.build()
            except Exception:
                return None
        try:
            lib = ctypes.CDLL(path)
        except OSError:
            return None
        # self-heal a stale/portable build: a lib without sw_gf_impl, or
        # one reporting the scalar path on an SSE-capable x86 host, was
        # compiled before the SIMD kernels (or with a failed
        # -march=native) — rebuild once and reload.  This exact staleness
        # silently cost 4x codec throughput for three rounds.
        try:
            impl = lib.sw_gf_impl()
        except AttributeError:
            impl = -1
        if impl < _host_simd_tier():
            try:
                import shutil
                import tempfile

                from . import build

                path = build.build(force=True)
                # dlopen caches the old mapping for the original path in
                # this process; load the healed build via a unique copy
                fd, fresh = tempfile.mkstemp(suffix=".so")
                os.close(fd)
                try:
                    shutil.copy(path, fresh)
                    lib = ctypes.CDLL(fresh)
                finally:
                    try:
                        os.unlink(fresh)  # mapping stays valid
                    except OSError:
                        pass
            except Exception:
                try:
                    lib = ctypes.CDLL(path)
                except OSError:
                    return None
        lib.sw_crc32c_update.restype = ctypes.c_uint32
        lib.sw_crc32c_update.argtypes = [
            ctypes.c_uint32,
            ctypes.c_char_p,
            ctypes.c_size_t,
        ]
        lib.sw_gf_apply.restype = None
        lib.sw_gf_apply.argtypes = [
            ctypes.c_char_p,  # matrix rows (R*S bytes)
            ctypes.c_int,  # R
            ctypes.c_int,  # S
            # raw-address arrays (c_void_p): callers fill them from
            # ndarray.ctypes.data without per-pointer c_char_p casts
            ctypes.POINTER(ctypes.c_void_p),  # inputs
            ctypes.POINTER(ctypes.c_void_p),  # outputs
            ctypes.c_size_t,  # block len
        ]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def crc32c_update(crc: int, data: bytes) -> int:
    lib = _load()
    assert lib is not None
    return int(lib.sw_crc32c_update(crc, data, len(data)))


def gf_apply(matrix_rows, inputs: list[bytes], out_count: int) -> list[bytearray]:
    """Apply (R,S) GF matrix to S equal-length buffers -> R buffers.

    ``inputs`` entries must be bytes objects; they are passed by pointer
    (ctypes does not copy bytes for c_char_p), so this is zero-copy in.
    """
    lib = _load()
    assert lib is not None
    import numpy as np

    m = np.ascontiguousarray(matrix_rows, dtype=np.uint8)
    r, s = m.shape
    if r != out_count:
        raise ValueError(f"matrix has {r} rows, caller expected {out_count}")
    if len(inputs) != s:
        raise ValueError(f"matrix has {s} cols, got {len(inputs)} inputs")
    n = len(inputs[0])
    outs = [bytearray(n) for _ in range(r)]
    # zero-copy in: the void* values point into the caller's bytes
    # objects, which `inputs` keeps alive across the call
    in_ptrs = (ctypes.c_void_p * s)(
        *[ctypes.cast(ctypes.c_char_p(b), ctypes.c_void_p) for b in inputs])
    out_bufs = [(ctypes.c_char * n).from_buffer(o) for o in outs]
    out_ptrs = (ctypes.c_void_p * r)(
        *[ctypes.addressof(ob) for ob in out_bufs])
    lib.sw_gf_apply(m.tobytes(), r, s, in_ptrs, out_ptrs, n)
    return outs


def gf_apply_fast(mbytes: bytes, r: int, s: int, inputs, outs, n: int) -> None:
    """Minimal-overhead GF matmul: prevalidated caller, prebuilt matrix
    bytes, raw ndarray pointers straight into the C kernel.

    The codec service's per-job hot path: ``gf_apply_arrays`` spends
    ~15-20us/call on list building, ascontiguousarray checks and matrix
    tobytes — more than the kernel itself below ~64KB.  Here the CALLER
    guarantees: ``inputs``/``outs`` are C-contiguous uint8 rows of length
    ``n``, ``mbytes`` is the (r, s) matrix's raw bytes.  No checks.
    """
    lib = _load()
    in_ptrs = (ctypes.c_void_p * s)(*[a.ctypes.data for a in inputs])
    out_ptrs = (ctypes.c_void_p * r)(*[o.ctypes.data for o in outs])
    lib.sw_gf_apply(mbytes, r, s, in_ptrs, out_ptrs, n)


def gf_apply_arrays(matrix_rows, inputs, out=None):
    """Zero-copy variant of gf_apply over numpy uint8 arrays.

    `inputs` are 1-D contiguous uint8 arrays of equal length (validated);
    returns a list of fresh uint8 arrays (or fills `out` when given).
    Pointers are passed straight to the C kernel — no tobytes copies.
    """
    lib = _load()
    assert lib is not None
    import numpy as np

    m = np.ascontiguousarray(matrix_rows, dtype=np.uint8)
    r, s = m.shape
    if len(inputs) != s:
        raise ValueError(f"matrix has {s} cols, got {len(inputs)} inputs")
    n = len(inputs[0])
    arrs = []
    for x in inputs:
        a = np.ascontiguousarray(x, dtype=np.uint8)
        if a.ndim != 1 or len(a) != n:
            raise ValueError("inputs must be equal-length 1-D u8 arrays")
        arrs.append(a)
    if out is None:
        out = [np.empty(n, dtype=np.uint8) for _ in range(r)]
    # void* arrays filled with raw addresses: building c_char_p casts per
    # pointer costs ~100us/call, which dominates small degraded-read
    # decodes (the per-needle latency path calls this per interval)
    in_ptrs = (ctypes.c_void_p * s)(*[a.ctypes.data for a in arrs])
    out_ptrs = (ctypes.c_void_p * r)(*[o.ctypes.data for o in out])
    lib.sw_gf_apply(m.tobytes(), r, s, in_ptrs, out_ptrs, n)
    return out
