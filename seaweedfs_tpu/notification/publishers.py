"""Notification publisher backends.

Reference: weed/notification/log_queue (glog), aws_sqs, kafka,
google_pub_sub, gocdk_pub_sub — all implement SendMessage(key, message).
"""

from __future__ import annotations

import base64
import json
import os
import threading

from ..pb import filer_pb2
from ..util import glog


class ConfigurationError(RuntimeError):
    pass


class Publisher:
    """SendMessage(key, EventNotification) — the queue interface
    (notification/configuration.go:12)."""

    def publish(self, key: str, event: filer_pb2.EventNotification) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class LogPublisher(Publisher):
    """Logs every event (notification/log/log_queue.go)."""

    def publish(self, key: str, event: filer_pb2.EventNotification) -> None:
        glog.info("notify %s: old=%s new=%s", key,
                  event.old_entry.name, event.new_entry.name)


class MemoryPublisher(Publisher):
    """Collects events in memory — the test double."""

    def __init__(self):
        self.events: list[tuple[str, filer_pb2.EventNotification]] = []
        self._lock = threading.Lock()

    def publish(self, key: str, event: filer_pb2.EventNotification) -> None:
        copied = filer_pb2.EventNotification()
        copied.CopyFrom(event)
        with self._lock:
            self.events.append((key, copied))


class FilePublisher(Publisher):
    """Appends JSON lines to a local file — durable local queue analogue
    of the gocdk file backend; each line carries the serialized event."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self._f = open(path, "ab")
        self._lock = threading.Lock()

    def publish(self, key: str, event: filer_pb2.EventNotification) -> None:
        line = json.dumps({
            "key": key,
            "event": base64.b64encode(event.SerializeToString()).decode(),
        })
        with self._lock:
            self._f.write(line.encode() + b"\n")
            self._f.flush()

    def close(self) -> None:
        self._f.close()

    @staticmethod
    def read_events(path: str):
        """-> [(key, EventNotification)] parsed back from the file."""
        out = []
        with open(path) as f:
            for line in f:
                d = json.loads(line)
                ev = filer_pb2.EventNotification()
                ev.ParseFromString(base64.b64decode(d["event"]))
                out.append((d["key"], ev))
        return out


class KafkaPublisher(Publisher):
    """Kafka adapter (notification/kafka/kafka_queue.go): events map to
    (key=file path, value=serialized EventNotification) records.  Config
    parsing and event mapping are library-free; only the wire transport
    needs kafka-python, resolved lazily at first publish."""

    def __init__(self, hosts: list[str] | str, topic: str):
        if isinstance(hosts, str):
            hosts = [h.strip() for h in hosts.split(",") if h.strip()]
        if not hosts or not topic:
            raise ConfigurationError("kafka needs hosts + topic")
        # fail at STARTUP when the client library is absent — a publish-
        # time error would be swallowed by the meta-log listener loop
        try:
            import kafka  # type: ignore  # noqa: F401
        except ImportError:
            raise ConfigurationError(
                "kafka backend needs the kafka-python client library")
        self.hosts = hosts
        self.topic = topic
        self._producer = None

    def map_event(self, key: str,
                  event: filer_pb2.EventNotification) -> tuple[bytes, bytes]:
        return key.encode(), event.SerializeToString()

    def publish(self, key: str, event: filer_pb2.EventNotification) -> None:
        if self._producer is None:
            from kafka import KafkaProducer  # type: ignore

            self._producer = KafkaProducer(bootstrap_servers=self.hosts)
        k, v = self.map_event(key, event)
        self._producer.send(self.topic, key=k, value=v).add_errback(
            lambda e: glog.error("kafka publish %s failed: %s", key, e))

    def close(self) -> None:
        if self._producer is not None:
            self._producer.flush()
            self._producer.close()


class SqsPublisher(Publisher):
    """AWS SQS adapter (notification/aws_sqs/aws_sqs_pub.go) built on the
    framework's own SigV4 signer — no boto3.  Events go out as
    SendMessage calls whose body is the base64 serialized notification
    with the file path as a message attribute."""

    def __init__(self, queue_url: str, region: str,
                 access_key: str = "", secret_key: str = ""):
        if not queue_url or not region:
            raise ConfigurationError("aws_sqs needs queue_url + region")
        self.queue_url = queue_url
        self.region = region
        self.access_key = access_key or os.environ.get(
            "AWS_ACCESS_KEY_ID", "")
        self.secret_key = secret_key or os.environ.get(
            "AWS_SECRET_ACCESS_KEY", "")
        if not self.access_key or not self.secret_key:
            raise ConfigurationError(
                "aws_sqs needs credentials (config or AWS_ACCESS_KEY_ID/"
                "AWS_SECRET_ACCESS_KEY)")

    def build_request(self, key: str, event: filer_pb2.EventNotification):
        """-> (url, signed headers, form body) — split out so the signed
        request shape is testable without network egress."""
        import urllib.parse as _up

        from ..s3api.auth import sign_request

        body = _up.urlencode({
            "Action": "SendMessage",
            "MessageBody": base64.b64encode(
                event.SerializeToString()).decode(),
            "MessageAttribute.1.Name": "key",
            "MessageAttribute.1.Value.DataType": "String",
            "MessageAttribute.1.Value.StringValue": key,
            "Version": "2012-11-05",
        }).encode()
        u = _up.urlparse(self.queue_url)
        headers = sign_request("POST", u.netloc, u.path or "/", "sqs",
                               self.region, self.access_key,
                               self.secret_key, body)
        headers["Content-Type"] = "application/x-www-form-urlencoded"
        return self.queue_url, headers, body

    def publish(self, key: str, event: filer_pb2.EventNotification) -> None:
        import urllib.request

        url, headers, body = self.build_request(key, event)
        req = urllib.request.Request(url, data=body, method="POST",
                                     headers=headers)
        with urllib.request.urlopen(req, timeout=30) as r:
            r.read()


class GcpPubSubPublisher(Publisher):
    """Google Pub/Sub adapter (notification/google_pub_sub) over the
    public REST surface: messages carry the serialized notification
    base64'd with the path as an attribute.  A bearer token supplier
    (metadata server / service-account flow) is injected; payload
    construction is library-free and testable."""

    def __init__(self, project_id: str, topic: str, token_source=None):
        if not project_id or not topic:
            raise ConfigurationError(
                "google_pub_sub needs project_id + topic")
        if token_source is None:
            raise ConfigurationError(
                "google_pub_sub needs a token source (no default "
                "credential chain in this deployment)")
        self.project_id = project_id
        self.topic = topic
        self.token_source = token_source

    @property
    def endpoint(self) -> str:
        return (f"https://pubsub.googleapis.com/v1/projects/"
                f"{self.project_id}/topics/{self.topic}:publish")

    def build_payload(self, key: str,
                      event: filer_pb2.EventNotification) -> bytes:
        return json.dumps({
            "messages": [{
                "data": base64.b64encode(
                    event.SerializeToString()).decode(),
                "attributes": {"key": key},
            }]
        }).encode()

    def publish(self, key: str, event: filer_pb2.EventNotification) -> None:
        import urllib.request

        req = urllib.request.Request(
            self.endpoint, data=self.build_payload(key, event),
            method="POST",
            headers={"Authorization": f"Bearer {self.token_source()}",
                     "Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            r.read()


def make_publisher(kind: str, **opts) -> Publisher:
    if kind in ("log", ""):
        return LogPublisher()
    if kind == "memory":
        return MemoryPublisher()
    if kind == "file":
        return FilePublisher(opts["path"])
    if kind == "kafka":
        return KafkaPublisher(opts.get("hosts", ""), opts.get("topic", ""))
    if kind == "aws_sqs":
        return SqsPublisher(
            opts.get("sqs_queue_url", opts.get("queue_url", "")),
            opts.get("region", ""),
            opts.get("aws_access_key_id", ""),
            opts.get("aws_secret_access_key", ""),
        )
    if kind == "google_pub_sub":
        return GcpPubSubPublisher(
            opts.get("project_id", ""), opts.get("topic", ""),
            opts.get("token_source"),
        )
    if kind == "gocdk_pub_sub":
        raise ConfigurationError(
            "gocdk_pub_sub is a Go-CDK construct with no python "
            "equivalent; use kafka, aws_sqs, or google_pub_sub")
    raise ConfigurationError(f"unknown notification backend {kind!r}")
