"""Notification publisher backends.

Reference: weed/notification/log_queue (glog), aws_sqs, kafka,
google_pub_sub, gocdk_pub_sub — all implement SendMessage(key, message).
"""

from __future__ import annotations

import base64
import json
import os
import threading

from ..pb import filer_pb2
from ..util import glog


class ConfigurationError(RuntimeError):
    pass


class Publisher:
    """SendMessage(key, EventNotification) — the queue interface
    (notification/configuration.go:12)."""

    def publish(self, key: str, event: filer_pb2.EventNotification) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class LogPublisher(Publisher):
    """Logs every event (notification/log/log_queue.go)."""

    def publish(self, key: str, event: filer_pb2.EventNotification) -> None:
        glog.info("notify %s: old=%s new=%s", key,
                  event.old_entry.name, event.new_entry.name)


class MemoryPublisher(Publisher):
    """Collects events in memory — the test double."""

    def __init__(self):
        self.events: list[tuple[str, filer_pb2.EventNotification]] = []
        self._lock = threading.Lock()

    def publish(self, key: str, event: filer_pb2.EventNotification) -> None:
        copied = filer_pb2.EventNotification()
        copied.CopyFrom(event)
        with self._lock:
            self.events.append((key, copied))


class FilePublisher(Publisher):
    """Appends JSON lines to a local file — durable local queue analogue
    of the gocdk file backend; each line carries the serialized event."""

    def __init__(self, path: str):
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self._f = open(path, "ab")
        self._lock = threading.Lock()

    def publish(self, key: str, event: filer_pb2.EventNotification) -> None:
        line = json.dumps({
            "key": key,
            "event": base64.b64encode(event.SerializeToString()).decode(),
        })
        with self._lock:
            self._f.write(line.encode() + b"\n")
            self._f.flush()

    def close(self) -> None:
        self._f.close()

    @staticmethod
    def read_events(path: str):
        """-> [(key, EventNotification)] parsed back from the file."""
        out = []
        with open(path) as f:
            for line in f:
                d = json.loads(line)
                ev = filer_pb2.EventNotification()
                ev.ParseFromString(base64.b64decode(d["event"]))
                out.append((d["key"], ev))
        return out


_GATED = {
    "kafka": "kafka-python",
    "aws_sqs": "boto3",
    "google_pub_sub": "google-cloud-pubsub",
    "gocdk_pub_sub": "gocloud",
}


def make_publisher(kind: str, **opts) -> Publisher:
    if kind in ("log", ""):
        return LogPublisher()
    if kind == "memory":
        return MemoryPublisher()
    if kind == "file":
        return FilePublisher(opts["path"])
    if kind in _GATED:
        raise ConfigurationError(
            f"notification backend {kind!r} needs the {_GATED[kind]} client "
            "library, which is not available in this deployment; use "
            "'log' or 'file', or install the dependency"
        )
    raise ConfigurationError(f"unknown notification backend {kind!r}")
