"""Filer event notification: publish metadata mutations to message queues.

Reference: weed/notification/configuration.go (a single configured
Queue publisher receiving (key, EventNotification) for every filer
mutation) with backends under weed/notification/{log,kafka,aws_sqs,
google_pub_sub,gocdk_pub_sub}.

Here: ``make_publisher(kind, **opts)`` returns a Publisher.  In-process
backends (log, file, memory) are always available; network backends
(kafka/sqs/pubsub) need client libraries this image doesn't ship, so they
are registered but raise a clear ConfigurationError at construction.
"""

from .publishers import (
    ConfigurationError,
    FilePublisher,
    LogPublisher,
    MemoryPublisher,
    Publisher,
    make_publisher,
)

__all__ = [
    "Publisher",
    "LogPublisher",
    "FilePublisher",
    "MemoryPublisher",
    "ConfigurationError",
    "make_publisher",
]


def publisher_from_config(conf):
    """Build the one enabled [notification.*] of a notification.toml;
    None when the file is absent or nothing is enabled
    (notification/configuration.go LoadConfiguration)."""
    if not conf.loaded:
        return None
    for kind in ("log", "file", "kafka", "aws_sqs", "google_pub_sub"):
        if conf.get_bool(f"notification.{kind}.enabled"):
            opts = conf.get(f"notification.{kind}") or {}
            opts = {k: v for k, v in opts.items() if k != "enabled"}
            return make_publisher(kind, **opts)
    return None
