"""Crash-safe lifecycle job journal.

Append-only JSONL: every job state change is one fsynced line
`{"key": "<vid>:<transition>", ...job fields...}`, and the latest line
per key wins on replay.  A master that dies mid-transition therefore
restarts with the exact job set it was executing — `running` jobs are
demoted back to `pending` (every underlying RPC is idempotent or
two-phase, so re-running them is safe), `done`/`failed` records survive
as the duplicate-suppression memory that keeps a re-evaluation from
re-emitting a finished transition.

The file is compacted (atomic tmp+rename, latest-record-per-key) once
the line count outgrows the live key set, so the journal stays bounded
no matter how long the master lives.

Fault point `lifecycle.journal.write` fires before every append — an
injected error there must fail the job loudly (never run work the
journal didn't record).
"""

from __future__ import annotations

import json
import os
import threading
import time

from ..util import faultpoint, glog

FP_JOURNAL_WRITE = faultpoint.register("lifecycle.journal.write")

JOURNAL_NAME = "lifecycle.journal.jsonl"

# states a job moves through; "running" replays as "pending"
ACTIVE_STATES = ("pending", "running")
FINAL_STATES = ("done", "failed", "parked")


def job_key(volume_id: int, transition: str) -> str:
    return f"{volume_id}:{transition}"


class JobJournal:
    """Keyed job store with an append-only JSONL persistence layer.

    `path=None` keeps everything in memory (duplicate suppression still
    works for the life of the process; no crash safety)."""

    COMPACT_SLACK = 1024  # compact when lines exceed keys by this many

    def __init__(self, path: str | None):
        self.path = path
        self._lock = threading.Lock()
        self._jobs: dict[str, dict] = {}
        self._lines = 0
        # raft replication (ISSUE 17): when the master wires a proposer
        # (`proposer(op, payload) -> bool`, op "put"|"drop"), every
        # mutation is proposed through the raft log instead of written
        # here, and lands via apply_replicated()/apply_drop() — in log
        # order, on every quorum member — so a freshly elected leader
        # holds the exact committed job set.  A failed propose (deposed,
        # quorum lost) raises: a job the quorum didn't record must not run.
        self.proposer = None
        if path:
            self._replay()

    # -- persistence ------------------------------------------------------

    def _replay(self) -> None:
        try:
            with open(self.path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue  # torn tail write: later lines still count
                    if "key" in rec:
                        self._jobs[rec["key"]] = rec
                        self._lines += 1
        except FileNotFoundError:
            return
        resumed = 0
        for rec in self._jobs.values():
            if rec.get("state") == "running":
                # died mid-execution: the RPCs are idempotent, re-run it
                rec["state"] = "pending"
                rec["resumed"] = rec.get("resumed", 0) + 1
                resumed += 1
        if resumed:
            glog.warning("lifecycle journal: resuming %d in-flight job(s) "
                         "from %s", resumed, self.path)

    def _append_locked(self, rec: dict) -> None:
        faultpoint.inject(FP_JOURNAL_WRITE, ctx=rec.get("key", ""))
        if not self.path:
            return
        line = json.dumps(rec, sort_keys=True)
        with open(self.path, "a") as f:
            f.write(line + "\n")
            f.flush()
            os.fsync(f.fileno())
        self._lines += 1
        if self._lines > len(self._jobs) + self.COMPACT_SLACK:
            self._compact_locked()

    def _compact_locked(self) -> None:
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            for rec in self._jobs.values():
                f.write(json.dumps(rec, sort_keys=True) + "\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        self._lines = len(self._jobs)

    # -- job API ----------------------------------------------------------

    def get(self, key: str) -> dict | None:
        with self._lock:
            rec = self._jobs.get(key)
            return dict(rec) if rec else None

    def put(self, job: dict) -> None:
        """Record a job (new or state change).  Raises on journal-write
        failure BEFORE mutating memory — a job the journal didn't record
        must not exist."""
        rec = dict(job)
        rec["updated_ms"] = int(time.time() * 1000)
        if self.proposer is not None:
            self._propose("put", rec)
            return
        with self._lock:
            self._append_locked(rec)
            self._jobs[rec["key"]] = rec

    def update(self, key: str, **changes) -> dict | None:
        if self.proposer is not None:
            # merge on the proposing leader, replicate the FULL record:
            # followers apply an upsert, never a delta, so a mirror that
            # missed an earlier record still converges
            with self._lock:
                rec = self._jobs.get(key)
                if rec is None:
                    return None
                new = {**rec, **changes,
                       "updated_ms": int(time.time() * 1000)}
            self._propose("put", new)
            return dict(new)
        with self._lock:
            rec = self._jobs.get(key)
            if rec is None:
                return None
            new = {**rec, **changes,
                   "updated_ms": int(time.time() * 1000)}
            self._append_locked(new)
            self._jobs[key] = new
            return dict(new)

    def drop(self, key: str) -> None:
        if self.proposer is not None:
            self._propose("drop", {"key": key})
            return
        with self._lock:
            if self._jobs.pop(key, None) is not None and self.path:
                self._compact_locked()

    # -- raft replication (ISSUE 17) --------------------------------------

    def _propose(self, op: str, payload: dict) -> None:
        # same loud-failure discipline as a local append: the write
        # faultpoint fires first, and an uncommitted propose raises so
        # the caller never runs work the quorum didn't record
        faultpoint.inject(FP_JOURNAL_WRITE, ctx=payload.get("key", ""))
        if not self.proposer(op, payload):
            raise RuntimeError(
                f"journal {op} {payload.get('key', '')!r} not committed "
                "(not the leader, or quorum unavailable)")

    def apply_replicated(self, rec: dict) -> None:
        """Raft apply_fn target: upsert one committed record into the
        local mirror (every quorum member, leader included, in log
        order).  Bypasses the write faultpoint — the fault already had
        its chance at propose time on the leader."""
        with self._lock:
            if self.path:
                line = json.dumps(rec, sort_keys=True)
                with open(self.path, "a") as f:
                    f.write(line + "\n")
                    f.flush()
                    os.fsync(f.fileno())
                self._lines += 1
            self._jobs[rec["key"]] = dict(rec)
            if (self.path
                    and self._lines > len(self._jobs) + self.COMPACT_SLACK):
                self._compact_locked()

    def apply_drop(self, key: str) -> None:
        with self._lock:
            if self._jobs.pop(key, None) is not None and self.path:
                self._compact_locked()

    def resume_stale_running(self) -> int:
        """Failover resume: `running` records inherited from a deposed
        leader demote to `pending` with a bumped `resumed` marker —
        through the proposer when replicated, so every mirror agrees the
        job is runnable exactly once."""
        resumed = 0
        for rec in self.jobs(("running",)):
            new = self.update(rec["key"], state="pending",
                              resumed=rec.get("resumed", 0) + 1)
            if new is not None:
                resumed += 1
        if resumed:
            glog.warning("lifecycle journal: failover — demoted %d "
                         "running job(s) to pending", resumed)
        return resumed

    def jobs(self, states: tuple = ()) -> list[dict]:
        with self._lock:
            out = [dict(r) for r in self._jobs.values()
                   if not states or r.get("state") in states]
        out.sort(key=lambda r: r.get("created_ms", 0))
        return out

    def active(self) -> list[dict]:
        return self.jobs(ACTIVE_STATES)

    def counts(self) -> dict:
        with self._lock:
            out: dict[str, int] = {}
            for r in self._jobs.values():
                out[r.get("state", "?")] = out.get(r.get("state", "?"), 0) + 1
            return out
