"""Master-resident lifecycle controller: evaluate policies, run jobs.

The controller closes the loop ROADMAP 5a left open: every lifecycle
transition existed as a manual RPC or shell command, but nothing decided
WHEN to run them, serialized them against each other, or survived a
master restart mid-transition.  Here:

  * `evaluate()` scans heartbeat-fed topology state against the
    per-collection `PolicySet` and plans transitions —
    seal (fullness/age), ttl_expire, ec_encode (cool-down, via the PR 6
    codec service on the volume server), tier (idle .dat -> S3 backend),
    vacuum (garbage ratio), rebalance (node skew, reusing the shell's
    move planner);
  * plans become journaled jobs, duplicate-suppressed by
    (volume, transition) and replayed across master restarts — every
    underlying RPC (VolumeMarkReadonly, VolumeEcShardsGenerate,
    VolumeTierMoveDatToRemote, VacuumVolume*, VolumeCopy) is idempotent
    or two-phase, so a resumed job re-runs safely;
  * execution is bounded per node (one transition at a time per volume
    server by default), paced by a cluster-wide bytes/s token bucket
    (the same TokenBucket the PR 8 scrubber uses; the bucket's rate is
    also pushed to volume servers in heartbeat acks so scrub + lifecycle
    drain one per-node budget), and backs off while the PR 5 executor
    queue-depth gauges show serving pools saturated.

Fault points: `lifecycle.job.run` fires before each job executes,
`lifecycle.journal.write` before each journal append.
"""

from __future__ import annotations

import json
import os
import threading
import time

import grpc

from ..pb import rpc as rpclib
from ..pb import volume_server_pb2 as vs
from ..stats.metrics import (
    LIFECYCLE_BYTES,
    LIFECYCLE_JOBS,
    LIFECYCLE_QUEUE_DEPTH,
    LIFECYCLE_SECONDS,
    LIFECYCLE_TRANSITIONS,
)
from ..storage.scrub import TokenBucket, _saturation
from ..storage.ttl import TTL
from ..util import faultpoint, glog
from .journal import ACTIVE_STATES, JobJournal, job_key
from .policy import PolicySet

FP_JOB_RUN = faultpoint.register("lifecycle.job.run")

RATE_ENV = "SEAWEEDFS_TPU_LIFECYCLE_RATE_MBPS"
WORKERS_ENV = "SEAWEEDFS_TPU_LIFECYCLE_WORKERS"
BACKOFF_DEPTH_ENV = "SEAWEEDFS_TPU_LIFECYCLE_BACKOFF_QUEUE_DEPTH"

POLICY_FILE = "lifecycle.policy.json"

# "mass_repair" jobs share this journal (so dedup + crash-safe resume
# are one mechanism) but are planned and executed by the
# MassRepairOrchestrator, never by this controller's executor
TRANSITIONS = ("seal", "ttl_expire", "ec_encode", "tier", "vacuum",
               "rebalance", "mass_repair")

MAX_ATTEMPTS = 3
# how long a finished vacuum/rebalance suppresses re-planning the same
# (volume, transition); seal/ec/tier/ttl are permanently suppressed by
# the topology state itself (read_only flag, EC shard set, deleted vid)
REISSUE_AFTER_S = {"vacuum": 600.0, "rebalance": 600.0}

class LifecycleController:
    def __init__(
        self,
        master,
        policies: PolicySet | None = None,
        interval_s: float = 0.0,
        rate_mbps: float | None = None,
        journal_dir: str = "",
        max_workers: int | None = None,
        per_node: int = 1,
    ):
        self.master = master
        self.interval_s = interval_s
        self.journal_dir = journal_dir
        if rate_mbps is None:
            rate_mbps = float(os.environ.get(RATE_ENV, "0"))
        self.rate_mbps = rate_mbps
        # rate<=0 = unthrottled (a huge bucket, like scrub's disable path)
        self.bucket = TokenBucket(
            rate_mbps * (1 << 20) if rate_mbps > 0 else float(1 << 40))
        self.backoff_depth = float(
            os.environ.get(BACKOFF_DEPTH_ENV, "8"))
        self.per_node = max(per_node, 1)
        journal_path = (
            os.path.join(journal_dir, "lifecycle.journal.jsonl")
            if journal_dir else None)
        self.journal = JobJournal(journal_path)
        for rec in self.journal.jobs(("pending",)):
            if rec.get("resumed"):
                LIFECYCLE_JOBS.labels(rec["transition"], "resumed").inc()
        # policy precedence: persisted file (an operator's -policy set)
        # first, then an explicit constructor/CLI policy on top
        self.policies = self._load_policy_file() or PolicySet()
        if policies is not None:
            self.policies = policies
            self._save_policy_file()
        if max_workers is None:
            max_workers = int(os.environ.get(WORKERS_ENV, "4"))
        from ..util.executors import MeteredThreadPoolExecutor

        self._pool = MeteredThreadPoolExecutor(
            max_workers=max_workers, name="lifecycle",
            thread_name_prefix="lifecycle")
        self._node_gates: dict[str, threading.Semaphore] = {}
        self._gates_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._run_lock = threading.Lock()  # one run_once at a time
        self._counts = {"cycles": 0, "planned": 0, "executed": 0,
                        "errors": 0, "throttle_seconds": 0.0,
                        "backoff_seconds": 0.0, "emergency": 0}
        self._last_cycle = 0.0
        # disk-fault plane: per-node rate limit for the low-space
        # emergency reaction (the node keeps heartbeating low_space
        # until space actually frees)
        self._low_space_last: dict[str, float] = {}
        self._low_space_lock = threading.Lock()
        LIFECYCLE_QUEUE_DEPTH.set(len(self.journal.active()))

    # -- policy persistence -----------------------------------------------

    def _policy_path(self) -> str | None:
        return (os.path.join(self.journal_dir, POLICY_FILE)
                if self.journal_dir else None)

    def _load_policy_file(self) -> PolicySet | None:
        path = self._policy_path()
        if not path:
            return None
        try:
            with open(path) as f:
                return PolicySet.parse(json.load(f))
        except FileNotFoundError:
            return None
        except (OSError, ValueError) as e:
            glog.warning("lifecycle: bad policy file %s: %s", path, e)
            return None

    def _save_policy_file(self) -> None:
        path = self._policy_path()
        if not path:
            return
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(self.policies.dumps())
        os.replace(tmp, path)

    def set_policies(self, doc) -> PolicySet:
        self.policies = PolicySet.parse(doc)
        self._save_policy_file()
        return self.policies

    # -- lifecycle --------------------------------------------------------

    def start(self) -> None:
        if self.interval_s <= 0 or self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="lifecycle-controller", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        self._pool.shutdown(wait=False)

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            if not self.master.is_leader():
                continue
            try:
                self.run_once()
            except Exception as e:  # the loop must survive, not go mute
                glog.warning("lifecycle cycle failed: %s", e)

    # -- evaluation -------------------------------------------------------

    def _volume_states(self) -> tuple[dict, set, dict]:
        """Aggregate per-volume state across replicas from the live
        (heartbeat-fed) topology: -> (vid -> state dict, ec vid set,
        node -> volume count)."""
        topo = self.master.topo
        states: dict[int, dict] = {}
        ec_vids: set[int] = set()
        node_counts: dict[str, int] = {}
        with topo.lock:
            for n in topo.nodes.values():
                node_counts[n.id] = len(n.volumes)
                ec_vids.update(n.ec_shards)
                for vid, v in n.volumes.items():
                    st = states.setdefault(vid, {
                        "volume_id": vid, "collection": v.collection,
                        "size": 0, "holders": [], "read_only": True,
                        "modified": 0, "ttl": 0, "garbage": 0.0,
                    })
                    st["holders"].append(n.id)
                    st["size"] = max(st["size"], v.size)
                    st["collection"] = v.collection
                    # sealed means sealed EVERYWHERE; a half-sealed
                    # volume re-plans seal until every replica froze
                    st["read_only"] = st["read_only"] and v.read_only
                    st["modified"] = max(st["modified"],
                                         v.modified_at_second)
                    st["ttl"] = v.ttl
                    if v.size:
                        st["garbage"] = max(
                            st["garbage"], v.deleted_byte_count / v.size)
        return states, ec_vids, node_counts

    def evaluate(self, now: float | None = None) -> list[dict]:
        """Plan transitions from current topology state.  Pure decision
        logic — nothing is journaled or executed here."""
        if now is None:
            now = time.time()
        states, ec_vids, node_counts = self._volume_states()
        limit = self.master.topo.volume_size_limit
        plans: list[dict] = []
        for vid, st in sorted(states.items()):
            pol = self.policies.for_collection(st["collection"])
            quiet = now - st["modified"] if st["modified"] > 0 else -1.0
            plan = self._plan_volume(vid, st, pol, quiet, limit, ec_vids,
                                     now)
            if plan is not None:
                plans.append(plan)
        plans.extend(self._plan_rebalance(node_counts, states))
        return plans

    def _plan_volume(self, vid, st, pol, quiet, limit, ec_vids, now):
        mk = self._mk_plan
        # ttl_expire first: an expired volume needs no other care
        if (pol.ttl_expire
                and TTL.from_uint32(st["ttl"]).expired(st["modified"],
                                                       now=now)):
            return mk(vid, "ttl_expire", st, bytes_=0)
        if not st["read_only"]:
            full = (pol.seal_full_percent > 0 and limit
                    and st["size"] >= limit * pol.seal_full_percent / 100.0)
            aged = (pol.seal_age_seconds > 0 and quiet >= 0
                    and quiet >= pol.seal_age_seconds and st["size"] > 0)
            if full or aged:
                return mk(vid, "seal", st, bytes_=0)
            if (pol.vacuum_garbage_ratio > 0
                    and st["garbage"] >= pol.vacuum_garbage_ratio):
                # carry the POLICY ratio: execution must gate on the
                # same threshold planning used, not the master's global
                # default (a 0.1 policy against a 0.3 default would
                # plan forever and compact never)
                return mk(vid, "vacuum", st, bytes_=st["size"],
                          ratio=pol.vacuum_garbage_ratio)
            return None
        # sealed: encode when cold, then tier the .dat
        if (pol.ec_cooldown_seconds >= 0 and vid not in ec_vids
                and st["size"] > 0
                and quiet >= pol.ec_cooldown_seconds):
            return mk(vid, "ec_encode", st, bytes_=st["size"],
                      codec=pol.ec_codec,
                      # when a tier stage follows, the source volume
                      # must survive the encode so its .dat can move
                      keep_source=bool(pol.tier_backend))
        if (pol.tier_backend and st["size"] > 0
                and (pol.ec_cooldown_seconds < 0 or vid in ec_vids)
                and quiet >= pol.tier_idle_seconds):
            return mk(vid, "tier", st, bytes_=st["size"],
                      backend=pol.tier_backend,
                      keep_local=pol.keep_local_dat)
        return None

    # -- low-space emergency (disk-fault plane) ---------------------------

    LOW_SPACE_COOLDOWN_S = 30.0
    EMERGENCY_GARBAGE_RATIO = 0.01

    def note_low_space(self, node_id: str) -> list[dict]:
        """Heartbeat-ingest trigger: a node reports a low_space/full
        disk.  Plan emergency space recovery for the volumes it holds —
        vacuum anything with garbage (policy quiet windows and ratios
        bypassed, read-only-full volumes INCLUDED via force), and tier
        sealed volumes out when the collection's policy has a tier
        backend.  Rate-limited per node; executes asynchronously on the
        worker pool.  -> the accepted jobs."""
        now = time.monotonic()
        with self._low_space_lock:
            if (now - self._low_space_last.get(node_id, 0.0)
                    < self.LOW_SPACE_COOLDOWN_S):
                return []
            self._low_space_last[node_id] = now
        plans = self.plan_emergency(node_id)
        accepted = self.submit(plans)
        if accepted:
            self._counts["emergency"] += len(accepted)
            glog.warning(
                "lifecycle: node %s low on space — emergency %s",
                node_id, [j["key"] for j in accepted])
            keys = {j["key"] for j in accepted}
            threading.Thread(
                target=self.run_pending, kwargs={"wait": True,
                                                 "keys": keys},
                name="lifecycle-emergency", daemon=True).start()
        return accepted

    def plan_emergency(self, node_id: str) -> list[dict]:
        """Pure: space-recovery plans for volumes held on `node_id`."""
        states, ec_vids, _counts = self._volume_states()
        with self.master.topo.lock:
            node = self.master.topo.nodes.get(node_id)
            free_bytes = min(
                (d.get("free_bytes", 0)
                 for d in (node.disk_health if node else {}).values()),
                default=0)
        plans: list[dict] = []
        for vid, st in sorted(states.items()):
            if node_id not in st["holders"]:
                continue
            pol = self.policies.for_collection(st["collection"])
            # compaction writes the volume's LIVE bytes to a .cpd on the
            # SAME disk: planning one that cannot fit would burn the
            # reserved delete headroom on a doomed copy and park the job
            live = int(st["size"] * (1.0 - st["garbage"]))
            fits = free_bytes == 0 or free_bytes > live * 1.1 + (4 << 20)
            if st["garbage"] >= self.EMERGENCY_GARBAGE_RATIO and fits:
                plans.append(self._mk_plan(
                    vid, "vacuum", st, bytes_=st["size"],
                    ratio=self.EMERGENCY_GARBAGE_RATIO, force=True,
                    reason="low_space"))
            elif (pol.tier_backend and st["read_only"] and st["size"] > 0
                    and (pol.ec_cooldown_seconds < 0 or vid in ec_vids)):
                # sealed + tier-eligible: move the .dat off the node NOW
                # (idle-seconds bypassed — space is the emergency)
                plans.append(self._mk_plan(
                    vid, "tier", st, bytes_=st["size"],
                    backend=pol.tier_backend,
                    keep_local=False, reason="low_space"))
        return plans

    def _mk_plan(self, vid, transition, st, bytes_=0, **extra) -> dict:
        return {
            "key": job_key(vid, transition),
            "volume_id": vid, "transition": transition,
            "collection": st["collection"], "node": st["holders"][0],
            "holders": sorted(st["holders"]), "bytes": int(bytes_),
            **extra,
        }

    def _plan_rebalance(self, node_counts, states) -> list[dict]:
        pol = self.policies.for_collection("*")
        skews = [p.rebalance_skew for p in self.policies.policies.values()
                 if p.rebalance_skew > 0]
        skew = min(skews) if skews else pol.rebalance_skew
        if skew <= 0 or len(node_counts) < 2:
            return []
        if (max(node_counts.values()) - min(node_counts.values())) <= skew:
            return []
        from ..shell.volume_commands import plan_volume_balance_moves

        moves = plan_volume_balance_moves(
            self.master.topo.to_topology_info())
        plans = []
        for mv in moves:
            st = states.get(mv["volumeId"])
            if st is None:
                continue
            plans.append({
                "key": job_key(mv["volumeId"], "rebalance"),
                "volume_id": mv["volumeId"], "transition": "rebalance",
                "collection": st["collection"], "node": mv["source"],
                "holders": sorted(st["holders"]), "bytes": st["size"],
                "source": mv["source"], "target": mv["target"],
            })
        return plans

    # -- submission (journal + dedup) -------------------------------------

    def submit(self, plans: list[dict]) -> list[dict]:
        """Journal new jobs; duplicates (active job on the same
        (volume, transition), a volume with ANY active job, or a
        recently-finished reissuable transition) are suppressed."""
        now_ms = int(time.time() * 1000)
        active_vids = {j["volume_id"] for j in self.journal.active()}
        accepted = []
        for plan in plans:
            key = plan["key"]
            existing = self.journal.get(key)
            resurrect = False
            if existing is not None:
                state = existing.get("state")
                if state in ACTIVE_STATES:
                    continue
                if state == "parked":
                    continue  # operator attention needed, not a retry loop
                reissue = REISSUE_AFTER_S.get(plan["transition"])
                if state == "done" and reissue is None:
                    continue  # seal/ec/tier/ttl: done is done
                if (state in ("done", "failed") and reissue is not None
                        and now_ms - existing.get("updated_ms", 0)
                        < reissue * 1000):
                    continue
                # a failed job comes back as the SAME record (attempts
                # preserved) so MAX_ATTEMPTS eventually parks it instead
                # of retrying forever with a fresh counter
                resurrect = state == "failed"
            if plan["volume_id"] in active_vids:
                # one transition at a time per volume: a vacuum must not
                # race the seal that is flipping the same volume
                continue
            try:
                if resurrect:
                    fields = {k: v for k, v in plan.items()
                              if k not in ("key",)}
                    job = self.journal.update(key, state="pending",
                                              **fields)
                    if job is None:
                        continue
                else:
                    job = {**plan, "state": "pending", "attempts": 0,
                           "created_ms": now_ms}
                    self.journal.put(job)
            except Exception as e:  # journal write failed: no job
                glog.warning("lifecycle: journal write for %s failed: %s",
                             key, e)
                LIFECYCLE_JOBS.labels(plan["transition"], "error").inc()
                continue
            active_vids.add(plan["volume_id"])
            accepted.append(job)
            self._counts["planned"] += 1
        LIFECYCLE_QUEUE_DEPTH.set(len(self.journal.active()))
        return accepted

    # -- execution --------------------------------------------------------

    def _gate(self, node: str) -> threading.Semaphore:
        with self._gates_lock:
            gate = self._node_gates.get(node)
            if gate is None:
                gate = threading.Semaphore(self.per_node)
                self._node_gates[node] = gate
            return gate

    def run_pending(self, wait: bool = True,
                    keys: "set[str] | None" = None) -> list[dict]:
        """Execute pending journaled jobs on the worker pool.  `keys`
        restricts execution to that job set (a scoped
        `volume.lifecycle -apply -volumeId=…` must not drain unrelated
        resumed/queued jobs as a side effect); None runs everything."""
        pending = [j for j in self.journal.jobs(("pending",))
                   if (keys is None or j["key"] in keys)
                   # mass-repair jobs ride this journal for dedup +
                   # crash-safe resume, but the orchestrator drives them
                   # (one batched rpc per target node, not one worker
                   # per volume)
                   and j.get("transition") != "mass_repair"]
        futures = [(j, self._pool.submit(self._run_job, j))
                   for j in pending]
        results = []
        if wait:
            for job, fut in futures:
                try:
                    results.append(fut.result())
                except Exception as e:  # noqa: BLE001 — per-job isolation
                    glog.warning("lifecycle job %s failed: %s",
                                 job["key"], e)
        LIFECYCLE_QUEUE_DEPTH.set(len(self.journal.active()))
        return results

    def run_once(self) -> dict:
        """One controller cycle: evaluate -> journal -> execute."""
        with self._run_lock:
            self._counts["cycles"] += 1
            self._last_cycle = time.time()
            planned = self.submit(self.evaluate())
            results = self.run_pending(wait=True)
            return {"planned": [j["key"] for j in planned],
                    "results": results}

    def _throttle(self, job: dict) -> None:
        # saturation backoff first (the PR 5 queue-depth gauges), then
        # the bytes/s bucket — identical discipline to the PR 8 scrubber.
        # Tier jobs skip the master-side bucket: their bytes are charged
        # where the I/O happens, by the volume server's shared scrub
        # bucket (which runs at the same pushed rate) inside
        # VolumeTierMoveDatToRemote — charging both sides would bill
        # every tiered byte twice and halve effective throughput.
        while (_saturation() >= self.backoff_depth
               and not self._stop.is_set()):
            self._counts["backoff_seconds"] += 0.2
            if self._stop.wait(0.2):
                return
        n = int(job.get("bytes") or 0)
        if n > 0 and job.get("transition") != "tier":
            self._counts["throttle_seconds"] += self.bucket.consume(
                n, stop=self._stop)

    def _run_job(self, job: dict) -> dict:
        key = job["key"]
        transition = job["transition"]
        t0 = time.monotonic()
        gate = self._gate(job.get("node", ""))
        with gate:
            if not self.master.is_leader():
                # fenced (ISSUE 17): work queued before a depose must not
                # execute against volume servers the new leader now owns
                return {"key": key, "state": "fenced"}
            cur = self.journal.get(key)
            if cur is None or cur.get("state") != "pending":
                return {"key": key, "state": cur and cur.get("state")}
            self._throttle(job)
            if self._stop.is_set():
                return {"key": key, "state": "pending"}
            self.journal.update(key, state="running")
            try:
                faultpoint.inject(
                    FP_JOB_RUN, ctx=f"{transition}:{job['volume_id']}")
                detail = self._execute(job)
            except Exception as e:  # noqa: BLE001 — park after retries
                attempts = cur.get("attempts", 0) + 1
                state = "failed" if attempts < MAX_ATTEMPTS else "parked"
                self.journal.update(key, state=state, attempts=attempts,
                                    error=str(e)[:300])
                LIFECYCLE_JOBS.labels(
                    transition,
                    "parked" if state == "parked" else "error").inc()
                LIFECYCLE_TRANSITIONS.labels(transition, "error").inc()
                self._counts["errors"] += 1
                glog.warning("lifecycle %s failed (attempt %d): %s",
                             key, attempts, e)
                return {"key": key, "state": state, "error": str(e)[:300]}
        self.journal.update(key, state="done", detail=str(detail)[:300])
        LIFECYCLE_JOBS.labels(transition, "ok").inc()
        LIFECYCLE_TRANSITIONS.labels(transition, "ok").inc()
        LIFECYCLE_BYTES.labels(transition).inc(int(job.get("bytes") or 0))
        LIFECYCLE_SECONDS.labels(transition).observe(
            time.monotonic() - t0)
        self._counts["executed"] += 1
        glog.info("lifecycle: %s done (%s)", key, detail)
        return {"key": key, "state": "done", "detail": str(detail)[:300]}

    # -- transition executors ---------------------------------------------

    def _execute(self, job: dict) -> str:
        return getattr(self, f"_do_{job['transition']}")(job)

    def _stub(self, node: str):
        from ..shell.ec_commands import _node_grpc  # one address rule

        return rpclib.volume_server_stub(_node_grpc(node), timeout=600)

    def _epoch(self) -> int:
        """Fencing epoch stamped on every outgoing mutating rpc: the
        raft term this job runs under (0 = unfenced single master)."""
        fn = getattr(self.master, "leader_epoch", None)
        return fn() if callable(fn) else 0

    def fence(self, term: int) -> None:
        """Deposed (ISSUE 17): queued executor work no-ops (the
        is_leader check at claim time), in-flight jobs fail their next
        journal write (propose refuses off-leader), and the volume
        servers reject any still-outbound rpc by stale epoch."""
        self._counts["fenced"] = self._counts.get("fenced", 0) + 1
        glog.warning("lifecycle: fenced at term %d — executor queue "
                     "cancelled, running jobs will fail their journal "
                     "writes instead of racing the new leader", term)

    def _live_holders(self, job: dict) -> list[str]:
        with self.master.topo.lock:
            return [n.id for n in self.master.topo.nodes.values()
                    if job["volume_id"] in n.volumes]

    def _do_seal(self, job: dict) -> str:
        vid = job["volume_id"]
        holders = self._live_holders(job) or job["holders"]
        for node in holders:
            self._stub(node).VolumeMarkReadonly(
                vs.VolumeMarkReadonlyRequest(
                    volume_id=vid, leader_epoch=self._epoch()))
        return f"sealed on {sorted(holders)}"

    def _do_ttl_expire(self, job: dict) -> str:
        vid = job["volume_id"]
        holders = self._live_holders(job)
        if not holders:
            # ttl_expire is done-forever once journaled: succeeding
            # vacuously while every holder is offline would retain the
            # expired data for good.  Fail (retryable) instead.
            raise RuntimeError(
                f"volume {vid}: no live holder to delete from")
        for node in holders:
            self._stub(node).VolumeDelete(
                vs.VolumeDeleteRequest(
                    volume_id=vid, leader_epoch=self._epoch()))
            # drop the vid from the writable sets NOW (per holder —
            # unregister is keyed by node id): waiting for the
            # deleted-volume heartbeat delta would leave a window where
            # /dir/assign hands out fids on the deleted volume
            self.master.unregister_from_layouts([vid], node)
        return f"expired volume deleted on {sorted(holders)}"

    def _do_ec_encode(self, job: dict) -> str:
        from ..shell.commands import CommandEnv
        from ..shell.ec_commands import do_ec_encode
        from ..storage.ec.constants import TOTAL_SHARDS

        vid = job["volume_id"]
        env = CommandEnv(f"{self.master.ip}:{self.master.grpc_port}")
        detail = do_ec_encode(
            env, self.master.topo.to_topology_info(),
            vid, job["collection"],
            codec=job.get("codec", ""), delete_source=False,
            leader_epoch=self._epoch())
        if job.get("keep_source"):
            return detail  # a tier stage follows; the sealed .dat stays
        # zero-downtime source drop: the shell flow deletes the volume
        # as soon as shards mount, but heartbeat DELTAS carry the new
        # shard locations to the master — deleting before they land
        # sends degraded reads through a lookup that cannot see the
        # fresh shards yet (observed as a burst of client 5xx under
        # concurrent load).  The controller runs inside the master, so
        # it simply waits for its own topology to cover all 14 shards.
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline:
            if len(self.master.topo.lookup_ec_shards(vid)) >= TOTAL_SHARDS:
                break
            if self._stop.wait(0.2):
                break
        for node in self._live_holders(job):
            self._stub(node).VolumeDelete(
                vs.VolumeDeleteRequest(
                    volume_id=vid, leader_epoch=self._epoch()))
        return detail + "; source volume dropped"

    def _do_tier(self, job: dict) -> str:
        vid = job["volume_id"]
        holders = self._live_holders(job) or job["holders"]
        node = (job["node"] if job["node"] in holders
                else holders[0])
        stub = self._stub(node)
        try:
            stub.VolumeMarkReadonly(
                vs.VolumeMarkReadonlyRequest(
                    volume_id=vid, leader_epoch=self._epoch()))
        except grpc.RpcError:
            pass  # already sealed / racing — the move checks again
        processed = 0
        try:
            for resp in stub.VolumeTierMoveDatToRemote(
                vs.VolumeTierMoveDatToRemoteRequest(
                    volume_id=vid,
                    destination_backend_name=job["backend"],
                    keep_local_dat_file=job.get("keep_local", False),
                    leader_epoch=self._epoch(),
                )
            ):
                processed = resp.processed
        except grpc.RpcError as e:
            if (e.code() is grpc.StatusCode.FAILED_PRECONDITION
                    and "already remote" in (e.details() or "")):
                # resumed after a crash that lost the ack: the transition
                # completed — idempotent success, not a failure
                return f"already remote on {node}"
            raise
        return f".dat -> {job['backend']} on {node} ({processed} bytes)"

    def _do_vacuum(self, job: dict) -> str:
        ok = self.master.vacuum_volume(
            job["volume_id"], threshold=job.get("ratio"),
            force=bool(job.get("force")))
        return "compacted" if ok else "skipped (ratio below threshold)"

    def _do_rebalance(self, job: dict) -> str:
        from ..shell.commands import CommandEnv
        from ..shell.volume_commands import apply_volume_move

        env = CommandEnv(f"{self.master.ip}:{self.master.grpc_port}")
        return apply_volume_move(env, {
            "volumeId": job["volume_id"],
            "source": job["source"], "target": job["target"],
        })

    # -- status -----------------------------------------------------------

    def status(self) -> dict:
        jobs = self.journal.jobs()
        return {
            "enabled": self.interval_s > 0,
            "running": (self._thread is not None
                        and self._thread.is_alive()),
            "intervalSeconds": self.interval_s,
            "rateMBps": self.rate_mbps,
            "backoffQueueDepth": self.backoff_depth,
            "journalPath": self.journal.path or "",
            "policies": self.policies.to_dict(),
            "counts": dict(self._counts),
            "jobStates": self.journal.counts(),
            "lastCycle": self._last_cycle,
            "jobs": jobs[-64:],
        }
