"""Dead-node mass repair: the cluster-scale repair orchestrator.

A dead volume server drops hundreds of EC volumes to reduced redundancy
at once; per-volume rebuilds under one shared token bucket have no
global plan (arXiv:1309.0186 measures repair traffic dominating
cross-rack bandwidth during exactly this failure mode).  This module is
the master-side plan:

  * **detect** — the liveness sweep calls :meth:`on_node_dead` the
    moment a node misses its 3-pulse heartbeat window;
  * **rank** — every affected EC volume is ordered by exposure (fewest
    surviving shards first, bytes-at-risk as tiebreak), so volumes one
    shard from data loss rebuild strictly before healthier ones;
  * **spread** — rebuild targets are assigned with a hard per-node cap
    (ceil(N / alive) + 1, topology/placement.spread_rebuild_targets) so
    no node or rack becomes the write bottleneck;
  * **drive** — plans become journaled, crash-safe jobs in the PR 9
    lifecycle journal (transition ``mass_repair``, duplicate-suppressed
    by the (volume, transition) key — which also mutually excludes the
    scrub-driven repair pass), executed as ONE VolumeEcShardsBatchRebuild
    rpc per target node whose volumes source remote columns through
    cross-volume aggregated partial rpcs (storage/ec/partial.py);
  * **bound** — with a configured total-repair-time bound the
    orchestrator raises the pushed shared background-I/O rate to the
    floor the deadline requires (never below the operator's budget) and
    exposes the slack as seaweedfs_repair_batch_deadline_slack_seconds.

Fault point ``repair.batch.plan`` fires before each planning pass;
``repair.batch.source`` lives in the data plane (one injection per
volume job inside a batch serve).
"""

from __future__ import annotations

import os
import threading
import time

import grpc

from ..pb import rpc as rpclib
from ..pb import volume_server_pb2 as vs
from ..stats.metrics import (
    DISK_EVACUATE_COUNTER,
    REPAIR_BATCH_BYTES,
    REPAIR_BATCH_DEADLINE_SLACK,
    REPAIR_BATCH_JOBS,
    REPAIR_BATCH_QUEUE_DEPTH,
    REPAIR_BATCH_SECONDS,
    REPAIR_BATCH_VOLUMES,
)
from ..storage.ec.constants import DATA_SHARDS, TOTAL_SHARDS
from ..topology.placement import spread_rebuild_targets
from ..util import faultpoint, glog
from .journal import ACTIVE_STATES, job_key

FP_BATCH_PLAN = faultpoint.register("repair.batch.plan")

TRANSITION = "mass_repair"

ENABLED_ENV = "SEAWEEDFS_TPU_MASS_REPAIR"
DEADLINE_ENV = "SEAWEEDFS_TPU_MASS_REPAIR_DEADLINE_S"
WORKERS_ENV = "SEAWEEDFS_TPU_MASS_REPAIR_TARGETS"

MAX_ATTEMPTS = 3
# a finished job is not reissuable until the target's heartbeat had time
# to register the rebuilt shards with the master (else every periodic
# re-plan against the lagging topology would resurrect it for a no-op)
DONE_REISSUE_GRACE_S = 15.0
# volumes per VolumeEcShardsBatchRebuild rpc: a target's whole slice of
# a big dead node in ONE rpc would outlive any fixed deadline and turn
# a timeout into 3 wasted re-rebuilds of work that actually completed —
# chunking bounds each rpc and journals progress incrementally
JOBS_PER_RPC_ENV = "SEAWEEDFS_TPU_MASS_REPAIR_JOBS_PER_RPC"
RPC_TIMEOUT_ENV = "SEAWEEDFS_TPU_MASS_REPAIR_RPC_TIMEOUT_S"


def exposure_class(surviving: int) -> str:
    """Metric label for a volume's distance from the decode floor:
    "0" = one shard from data loss .. "3", "lost" = below the floor."""
    margin = surviving - DATA_SHARDS
    return "lost" if margin < 0 else str(min(margin, TOTAL_SHARDS
                                             - DATA_SHARDS - 1))


def rank_by_exposure(volumes: "list[dict]") -> "list[dict]":
    """Fewest surviving shards first; ties broken by bytes at risk
    (largest shard size first), volume id for determinism."""
    return sorted(volumes, key=lambda v: (
        v["surviving"], -int(v.get("shard_size", 0)), v["volume_id"]))


class MassRepairOrchestrator:
    """Master-resident; shares the lifecycle controller's journal so
    mass-repair jobs resume across master restarts and a volume under
    mass repair is invisible to every other transition planner."""

    def __init__(self, master, controller, deadline_s: float | None = None,
                 enabled: bool | None = None):
        self.master = master
        self.controller = controller
        self.journal = controller.journal
        if deadline_s is None:
            deadline_s = float(os.environ.get(DEADLINE_ENV, "0"))
        self.deadline_s = deadline_s
        if enabled is None:
            enabled = os.environ.get(ENABLED_ENV, "1").lower() not in (
                "0", "false", "off", "no")
        self.enabled = enabled
        self.max_target_rpcs = max(1, int(os.environ.get(WORKERS_ENV, "4")))
        self.jobs_per_rpc = max(1, int(os.environ.get(
            JOBS_PER_RPC_ENV, "8")))
        self.rpc_timeout_s = float(os.environ.get(RPC_TIMEOUT_ENV, "600"))
        self._lock = threading.Lock()
        # one wave at a time: the background runner and an operator's
        # `volume.repair -apply` must never both claim the same pending
        # job (the pending->running flip is get-then-update, not CAS)
        self._wave_mutex = threading.Lock()
        # used only when the master lacks _repair_claim_lock (bare test
        # doubles); real masters share one claim lock with the scrub pass
        self._submit_fallback_lock = threading.Lock()
        self._runner: threading.Thread | None = None
        self._stop = threading.Event()
        # leader fencing (ISSUE 17): set on depose, cleared on resume —
        # a running wave stops issuing batch rpcs the moment the raft
        # role flips, instead of racing the new leader's plan
        self._fence = threading.Event()
        # current batch accounting for the deadline bound: set when jobs
        # are accepted, cleared when the queue drains
        self._deadline_at = 0.0
        self._remaining_bytes = 0
        self._counts = {"deaths": 0, "planned": 0, "repaired": 0,
                        "failed": 0, "parked": 0, "unrepairable": 0,
                        "waves": 0, "evacuated": 0}
        self._last_plan = 0.0
        self._lost_seen: set[int] = set()
        # proactive evacuation state: node -> last finished run
        # (cooldown), plus the set of in-flight evacuation threads
        self._evacuations: dict[str, float] = {}
        self._evacuating: set[str] = set()
        for rec in self.journal.jobs(("pending",)):
            if rec.get("transition") == TRANSITION and rec.get("resumed"):
                REPAIR_BATCH_JOBS.labels("resumed").inc()

    # -- planning ---------------------------------------------------------

    def _affected_volumes(self) -> "list[dict]":
        """Every EC volume below TOTAL_SHARDS in the live topology, with
        holder map, surviving count and the heartbeat-learned shard
        size."""
        topo = self.master.topo
        shards: dict[int, set] = {}
        holders: dict[int, dict] = {}
        sizes: dict[int, int] = {}
        collections: dict[int, str] = {}
        with topo.lock:
            for n in topo.nodes.values():
                for vid, bits in n.ec_shards.items():
                    sids = set(bits.shard_ids())
                    shards.setdefault(vid, set()).update(sids)
                    holders.setdefault(vid, {})[n.id] = len(sids)
                    collections[vid] = n.ec_collections.get(vid, "")
                    size = n.ec_shard_sizes.get(vid, 0)
                    if size:
                        sizes[vid] = max(sizes.get(vid, 0), size)
        out = []
        for vid, sids in shards.items():
            if len(sids) >= TOTAL_SHARDS:
                continue
            out.append({
                "volume_id": vid,
                "collection": collections.get(vid, ""),
                "surviving": len(sids),
                "missing": TOTAL_SHARDS - len(sids),
                "holders": holders.get(vid, {}),
                "shard_size": sizes.get(vid, 0),
            })
        return out

    def plan(self, dead_node: str = "") -> "list[dict]":
        """Rank affected volumes by exposure and spread rebuild targets;
        pure against the current topology — nothing is journaled here."""
        faultpoint.inject(FP_BATCH_PLAN, ctx=dead_node)
        affected = rank_by_exposure(self._affected_volumes())
        repairable = [v for v in affected if v["surviving"] >= DATA_SHARDS]
        with self.master.topo.lock:
            candidates = {n.id: max(n.free_ec_slots(), 0)
                          for n in self.master.topo.nodes.values()}
        targets = spread_rebuild_targets(repairable, candidates)
        plans = []
        for v in affected:
            if v["surviving"] < DATA_SHARDS:
                if v["volume_id"] not in self._lost_seen:
                    self._lost_seen.add(v["volume_id"])
                    REPAIR_BATCH_VOLUMES.labels("lost").inc()
                    self._counts["unrepairable"] += 1
                    glog.warning(
                        "mass repair: volume %d below decode floor "
                        "(%d surviving shards) — data loss, nothing "
                        "to plan", v["volume_id"], v["surviving"])
                continue
            target = targets.get(v["volume_id"])
            if target is None:
                continue
            plans.append({
                "key": job_key(v["volume_id"], TRANSITION),
                "volume_id": v["volume_id"],
                "transition": TRANSITION,
                "collection": v["collection"],
                "node": target,
                "holders": sorted(v["holders"]),
                "surviving": v["surviving"],
                "bytes": v["missing"] * v["shard_size"],
                "shard_size": v["shard_size"],
                "dead_node": dead_node,
            })
        return plans

    # -- submission (journal + dedup) -------------------------------------

    def submit(self, plans: "list[dict]") -> "list[dict]":
        """Journal new mass-repair jobs.  Dedup mirrors the lifecycle
        controller's: the (volume, transition) key suppresses an active
        duplicate, parked jobs wait for an operator, a volume with ANY
        other active journal job is skipped (one transition at a time),
        and a volume the scrub repair pass is currently healing is left
        to it (the pass skips ours symmetrically)."""
        now_ms = int(time.time() * 1000)
        # journal the batch under the master's repair-claim lock: the
        # scrub pass registers ITS volume claims and snapshots our
        # active jobs under the same lock, so neither side can slip a
        # claim into the other's check-then-act window
        claim_lock = getattr(self.master, "_repair_claim_lock", None)
        if claim_lock is None:
            claim_lock = self._submit_fallback_lock
        with claim_lock:
            return self._submit_locked(plans, now_ms)

    def _submit_locked(self, plans: "list[dict]", now_ms: int) -> "list[dict]":
        active_vids = {j["volume_id"] for j in self.journal.active()}
        scrub_busy = set(getattr(self.master, "_scrub_repairing", ()))
        accepted = []
        for plan in plans:
            key = plan["key"]
            existing = self.journal.get(key)
            resurrect = False
            if existing is not None:
                state = existing.get("state")
                if state in ACTIVE_STATES or state == "parked":
                    continue
                if (state == "done"
                        and now_ms - existing.get("updated_ms", 0)
                        < DONE_REISSUE_GRACE_S * 1000):
                    # the rebuilt shards register with the master on the
                    # target's NEXT heartbeat — re-planning against that
                    # lag would resurrect every just-finished job for a
                    # no-op rebuild and inflate the counters
                    continue
                # done-or-failed + the volume is degraded AGAIN (plan()
                # only emits currently-degraded volumes): this is a new
                # incident (or a retry) — resurrect the same record.  A
                # fresh incident after a completed repair starts a fresh
                # attempt counter; a failed attempt keeps its count so
                # MAX_ATTEMPTS still parks it.
                resurrect = True
            if plan["volume_id"] in active_vids:
                continue
            if plan["volume_id"] in scrub_busy:
                continue
            try:
                if resurrect:
                    fields = {k: v for k, v in plan.items() if k != "key"}
                    if existing.get("state") == "done":
                        fields["attempts"] = 0
                    job = self.journal.update(key, state="pending",
                                              **fields)
                    if job is None:
                        continue
                else:
                    job = {**plan, "state": "pending", "attempts": 0,
                           "created_ms": now_ms}
                    self.journal.put(job)
            except Exception as e:  # journal write failed: no job
                glog.warning("mass repair: journal write for %s "
                             "failed: %s", key, e)
                REPAIR_BATCH_JOBS.labels("error").inc()
                continue
            active_vids.add(plan["volume_id"])
            accepted.append(job)
            REPAIR_BATCH_VOLUMES.labels(
                exposure_class(plan.get("surviving", TOTAL_SHARDS))).inc()
            self._counts["planned"] += 1
        if accepted:
            with self._lock:
                self._remaining_bytes += sum(
                    int(j.get("bytes") or 0) for j in accepted)
                if self.deadline_s > 0:
                    self._deadline_at = (
                        self._deadline_at
                        or time.monotonic() + self.deadline_s)
        self._refresh_gauges()
        return accepted

    # -- triggers ---------------------------------------------------------

    def on_node_dead(self, node_id: str) -> None:
        """Liveness-sweep hook: the node is already out of the topology,
        so plan() sees exactly the post-death shard map."""
        if not self.enabled or not self._warmed():
            return
        self._counts["deaths"] += 1
        try:
            accepted = self.submit(self.plan(dead_node=node_id))
        except Exception as e:  # noqa: BLE001 — the sweep must survive
            glog.warning("mass repair: planning for dead node %s "
                         "failed: %s", node_id, e)
            return
        if accepted:
            glog.warning(
                "mass repair: node %s dead, %d volume(s) planned "
                "(most exposed: %s)", node_id, len(accepted),
                [j["volume_id"] for j in accepted[:8]])
        self.kick()

    # -- proactive evacuation (failing disk, node still alive) ------------

    EVACUATION_COOLDOWN_S = 30.0

    def on_disk_failing(self, node_id: str) -> None:
        """Heartbeat-ingest trigger: a node reports a FAILING disk
        (K EIOs / statvfs errors).  Unlike on_node_dead the node is
        still alive and its bytes still readable — the cheapest repair
        there will ever be is to drain it NOW (arXiv:1309.0186: paying
        a planned migration beats paying the post-death repair storm).
        EC shards move via copy+mount-on-target then unmount+delete-on-
        source (readable throughout); volumes whose ONLY copy lives on
        the failing node are re-copied to a healthy peer.  Idempotent
        and rate-limited: re-triggers (the node keeps beating `failing`)
        pick up whatever the topology still shows on the node."""
        if not self.enabled:
            return
        with self._lock:
            last = self._evacuations.get(node_id, 0.0)
            if time.monotonic() - last < self.EVACUATION_COOLDOWN_S:
                return
            if node_id in self._evacuating:
                return
            self._evacuating.add(node_id)
        t = threading.Thread(target=self._evacuate, args=(node_id,),
                             name=f"evacuate-{node_id}", daemon=True)
        t.start()

    def plan_evacuation(self, node_id: str) -> "list[dict]":
        """Pure: what should move off `node_id` right now.  EC shards
        held there spread to healthy nodes by free EC slots; volumes
        with no healthy holder get one copy each."""
        topo = self.master.topo
        moves: list[dict] = []
        with topo.lock:
            node = topo.nodes.get(node_id)
            if node is None:
                return []
            healthy = [n for n in topo.nodes.values()
                       if n.id != node_id and n.has_writable_disk()]
            ec_free = {n.id: max(n.free_ec_slots(), 0) for n in healthy}
            vol_free = {n.id: max(n.free_slots(), 0) for n in healthy}
            from ..storage.ec.shard_bits import ShardBits

            for vid, bits in sorted(node.ec_shards.items()):
                coll = node.ec_collections.get(vid, "")
                # per-volume spread: stacking one volume's shards on a
                # single node would turn that node's later death into
                # data loss — prefer targets holding (or receiving) the
                # fewest shards of THIS volume, then most free slots
                vol_load = {
                    n.id: (ShardBits(n.ec_shards[vid]).count()
                           if vid in n.ec_shards else 0)
                    for n in healthy}
                for sid in bits.shard_ids():
                    candidates = [n for n in ec_free if ec_free[n] > 0]
                    if not candidates:
                        break
                    target = min(candidates, key=lambda n: (
                        vol_load.get(n, 0), -ec_free[n], n))
                    ec_free[target] -= 1
                    vol_load[target] = vol_load.get(target, 0) + 1
                    moves.append({"kind": "ec_shard", "volume_id": vid,
                                  "shard_id": sid, "collection": coll,
                                  "source": node_id, "target": target})
            for vid, v in sorted(node.volumes.items()):
                if any(vid in n.volumes for n in healthy):
                    continue  # a healthy replica already exists
                target = max(vol_free, key=lambda n: (vol_free[n], n),
                             default=None)
                if target is None or vol_free[target] <= 0:
                    continue
                vol_free[target] -= 1
                moves.append({"kind": "volume", "volume_id": vid,
                              "collection": v.collection,
                              "source": node_id, "target": target})
        return moves

    def _evacuate(self, node_id: str) -> None:
        moved = failed = 0
        try:
            moves = self.plan_evacuation(node_id)
            if moves:
                glog.warning(
                    "mass repair: disk FAILING on %s — evacuating %d "
                    "shard(s)/volume(s) proactively", node_id, len(moves))
            for mv in moves:
                if self._stop.is_set():
                    break
                try:
                    if mv["kind"] == "ec_shard":
                        self._evacuate_ec_shard(mv)
                    else:
                        self._evacuate_volume(mv)
                    DISK_EVACUATE_COUNTER.labels(mv["kind"], "ok").inc()
                    moved += 1
                except Exception as e:  # noqa: BLE001 — per-move isolation
                    DISK_EVACUATE_COUNTER.labels(mv["kind"], "error").inc()
                    failed += 1
                    glog.warning("evacuation move %s failed: %s", mv, e)
            self._counts["evacuated"] += moved
            if moved or failed:
                glog.warning("mass repair: evacuation of %s: %d moved, "
                             "%d failed", node_id, moved, failed)
        finally:
            with self._lock:
                self._evacuating.discard(node_id)
                self._evacuations[node_id] = time.monotonic()

    def _evacuate_ec_shard(self, mv: dict) -> None:
        """copy+mount on the target, then unmount+delete on the failing
        source — the two-phase order keeps the shard readable
        throughout (same discipline as the shell's ec.balance)."""
        vid, sid, coll = mv["volume_id"], mv["shard_id"], mv["collection"]
        tgt = self._target_stub(mv["target"])
        from ..shell.ec_commands import _node_grpc

        tgt.VolumeEcShardsCopy(vs.VolumeEcShardsCopyRequest(
            volume_id=vid, collection=coll, shard_ids=[sid],
            copy_ecx_file=True, copy_ecj_file=True, copy_vif_file=True,
            copy_from_data_node=_node_grpc(mv["source"]),
            leader_epoch=self._epoch()))
        tgt.VolumeEcShardsMount(vs.VolumeEcShardsMountRequest(
            volume_id=vid, collection=coll, shard_ids=[sid]))
        src = self._target_stub(mv["source"])
        src.VolumeEcShardsUnmount(vs.VolumeEcShardsUnmountRequest(
            volume_id=vid, shard_ids=[sid]))
        src.VolumeEcShardsDelete(vs.VolumeEcShardsDeleteRequest(
            volume_id=vid, collection=coll, shard_ids=[sid]))

    def _evacuate_volume(self, mv: dict) -> None:
        """Pull the sole copy of a volume onto a healthy node.  The
        failing node's copy is left in place as extra redundancy —
        death (or the operator) removes it; deleting the original while
        its disk still half-works would trade durability for tidiness."""
        from ..shell.ec_commands import _node_grpc

        self._target_stub(mv["target"]).VolumeCopy(vs.VolumeCopyRequest(
            volume_id=mv["volume_id"], collection=mv["collection"],
            source_data_node=_node_grpc(mv["source"]),
            leader_epoch=self._epoch()))

    def tick(self) -> None:
        """Periodic re-evaluation (liveness cadence): re-plans degraded
        volumes whose earlier jobs failed or were deferred behind other
        transitions, and keeps the runner alive while jobs are pending.
        Cheap and rate-limited — a healthy cluster scans nothing."""
        if (not self.enabled or not self.master.is_leader()
                or not self._warmed()):
            return
        now = time.monotonic()
        if now - self._last_plan < 5.0:
            return
        self._last_plan = now
        try:
            plans = self.plan()
            if plans:
                self.submit(plans)
        except Exception as e:  # noqa: BLE001
            glog.warning("mass repair tick failed: %s", e)
        if self.pending():
            self.kick()

    def _warmed(self) -> bool:
        """Planning gate: a freshly elected leader must finish its
        warm-up barrier (log tail applied + heartbeat cycle seen) before
        planning repairs, or it plans duplicates of work the deposed
        leader's committed journal already covers."""
        fn = getattr(self.master, "control_warmed", None)
        return fn() if callable(fn) else True

    def _epoch(self) -> int:
        fn = getattr(self.master, "leader_epoch", None)
        return fn() if callable(fn) else 0

    def fence(self, term: int) -> None:
        """Deposed: cancel the running wave between chunks; the volume
        servers reject anything already on the wire by stale epoch."""
        self._fence.set()
        glog.warning("mass repair: fenced at term %d — running waves "
                     "cancelled", term)

    def resume(self) -> None:
        """Master start: journaled mass-repair jobs that were pending or
        running at the crash replayed as pending — run them."""
        self._fence.clear()
        if self.pending():
            glog.warning("mass repair: resuming %d journaled job(s)",
                         len(self.pending()))
            if self.deadline_s > 0:
                with self._lock:
                    self._remaining_bytes = sum(
                        int(j.get("bytes") or 0) for j in self.pending())
                    self._deadline_at = time.monotonic() + self.deadline_s
            self.kick()

    def pending(self) -> "list[dict]":
        return [j for j in self.journal.jobs(("pending",))
                if j.get("transition") == TRANSITION]

    def kick(self) -> None:
        with self._lock:
            if self._runner is not None and self._runner.is_alive():
                return
            self._runner = threading.Thread(
                target=self._run, name="mass-repair", daemon=True)
            self._runner.start()

    def stop(self) -> None:
        self._stop.set()

    # -- execution --------------------------------------------------------

    def _run(self) -> None:
        # after a master restart the runner can win the race against the
        # volume servers' re-registration heartbeats — rebuild targets
        # would then fail their holder lookups and burn attempts, so
        # wait (bounded) for the topology to repopulate first
        deadline = time.monotonic() + 15.0
        while (not self.master.topo.nodes
               and time.monotonic() < deadline
               and not self._stop.wait(0.3)):
            pass
        try:
            while not self._stop.is_set() and self.master.is_leader():
                batch = self.pending()
                if not batch:
                    break
                if not self.run_wave(batch):
                    # zero progress (e.g. the journal itself cannot be
                    # written): back off instead of spinning the leader
                    # at 100% CPU on the same stuck batch
                    if self._stop.wait(2.0):
                        break
        finally:
            with self._lock:
                if not self.pending():
                    self._remaining_bytes = 0
                    self._deadline_at = 0.0
            self._refresh_gauges()

    def run_wave(self, jobs: "list[dict]") -> "list[dict]":
        """One pass over pending jobs: group by target node, one
        VolumeEcShardsBatchRebuild rpc per target (bounded concurrency),
        per-volume results journaled individually.  Exposure order is
        preserved inside each target's job list, so the most exposed
        volumes rebuild first on every node."""
        from concurrent.futures import ThreadPoolExecutor

        with self._wave_mutex:
            return self._run_wave_locked(jobs, ThreadPoolExecutor)

    def _run_wave_locked(self, jobs, ThreadPoolExecutor) -> "list[dict]":
        t0 = time.monotonic()
        self._counts["waves"] += 1
        by_target: dict[str, list[dict]] = {}
        order = {j["key"]: i for i, j in enumerate(jobs)}
        for job in sorted(jobs, key=lambda j: (
                j.get("surviving", TOTAL_SHARDS), order[j["key"]])):
            by_target.setdefault(job.get("node", ""), []).append(job)
        results: list[dict] = []

        def run_target(target: str, tjobs: "list[dict]") -> None:
            # exposure order preserved chunk by chunk: the most exposed
            # volumes ride (and finish) the first rpcs
            for at in range(0, len(tjobs), self.jobs_per_rpc):
                if self._fence.is_set() or not self.master.is_leader():
                    return  # deposed mid-wave: leave the rest pending
                run_target_chunk(target, tjobs[at:at + self.jobs_per_rpc])

        def run_target_chunk(target: str, tjobs: "list[dict]") -> None:
            claimed = []
            for job in tjobs:
                cur = self.journal.get(job["key"])
                if cur is None or cur.get("state") != "pending":
                    continue
                try:
                    self.journal.update(job["key"], state="running")
                except Exception:  # noqa: BLE001 — unjournaled = unrun
                    continue
                claimed.append({**job, **(self.journal.get(job["key"])
                                          or {})})
            if not claimed:
                return
            finished: set[str] = set()
            try:
                stub = self._target_stub(target)
                resp = stub.VolumeEcShardsBatchRebuild(
                    vs.VolumeEcShardsBatchRebuildRequest(
                        leader_epoch=self._epoch(),
                        jobs=[vs.BatchRebuildJob(
                            volume_id=j["volume_id"],
                            collection=j.get("collection", ""),
                            shard_size=int(j.get("shard_size") or 0))
                            for j in claimed]))
                by_vid = {r.volume_id: r for r in resp.results}
                for job in claimed:
                    r = by_vid.get(job["volume_id"])
                    if r is None:
                        results.append(self._finish(
                            job, error=f"target {target}: no result"))
                    elif r.error:
                        results.append(self._finish(job, error=r.error))
                    else:
                        results.append(self._finish(
                            job, rebuilt=list(r.rebuilt_shard_ids),
                            used_partial=r.used_partial))
                    finished.add(job["key"])
            except Exception as e:  # noqa: BLE001 — claimed jobs MUST
                # resolve: an rpc failure (or a journal-write error
                # mid-result-loop) fails the rest of the claim instead
                # of stranding it `running` forever — `running` would
                # suppress every future re-plan until a master restart
                code = e.code() if isinstance(
                    e, grpc.RpcError) and hasattr(e, "code") else e
                for job in claimed:
                    if job["key"] in finished:
                        continue
                    try:
                        results.append(self._finish(
                            job, error=f"target {target}: {code}"))
                    except Exception as e2:  # noqa: BLE001
                        glog.warning("mass repair: could not journal "
                                     "failure of %s: %s", job["key"], e2)

        self._refresh_gauges()
        if len(by_target) == 1:
            ((target, tjobs),) = by_target.items()
            run_target(target, tjobs)
        else:
            with ThreadPoolExecutor(
                    max_workers=self.max_target_rpcs,
                    thread_name_prefix="mass-repair-rpc") as pool:
                list(pool.map(lambda kv: run_target(*kv),
                              by_target.items()))
        REPAIR_BATCH_SECONDS.observe(time.monotonic() - t0)
        self._refresh_gauges()
        return results

    def _target_stub(self, node_id: str):
        from ..shell.ec_commands import _node_grpc  # one address rule

        return rpclib.volume_server_stub(
            _node_grpc(node_id), timeout=self.rpc_timeout_s)

    def _finish(self, job: dict, rebuilt: "list[int] | None" = None,
                used_partial: bool = False, error: str = "") -> dict:
        key = job["key"]
        if not error:
            self.journal.update(
                key, state="done", used_partial=used_partial,
                detail=f"rebuilt {sorted(rebuilt or [])}")
            REPAIR_BATCH_JOBS.labels("ok").inc()
            done_bytes = int(job.get("bytes") or 0)
            REPAIR_BATCH_BYTES.inc(done_bytes)
            with self._lock:
                self._remaining_bytes = max(
                    0, self._remaining_bytes - done_bytes)
            self._counts["repaired"] += 1
            glog.info("mass repair: %s done on %s (rebuilt %s)",
                      key, job.get("node"), sorted(rebuilt or []))
            return {"key": key, "state": "done"}
        attempts = int(job.get("attempts", 0)) + 1
        state = "failed" if attempts < MAX_ATTEMPTS else "parked"
        self.journal.update(key, state=state, attempts=attempts,
                            error=error[:300])
        REPAIR_BATCH_JOBS.labels(
            "parked" if state == "parked" else "error").inc()
        self._counts["parked" if state == "parked" else "failed"] += 1
        glog.warning("mass repair: %s %s (attempt %d): %s",
                     key, state, attempts, error)
        return {"key": key, "state": state, "error": error[:300]}

    # -- deadline bound ---------------------------------------------------

    def rate_floor_mbps(self) -> float:
        """MBps the configured total-repair-time bound requires for the
        bytes still queued — the master pushes max(budget, this) to the
        nodes, so the shared bucket can never throttle the batch past
        its deadline (0 when no deadline or nothing queued)."""
        with self._lock:
            if (self.deadline_s <= 0 or self._deadline_at <= 0
                    or self._remaining_bytes <= 0):
                return 0.0
            left_s = max(self._deadline_at - time.monotonic(), 1.0)
            return self._remaining_bytes / left_s / (1 << 20)

    def _refresh_gauges(self) -> None:
        REPAIR_BATCH_QUEUE_DEPTH.set(len(
            [j for j in self.journal.active()
             if j.get("transition") == TRANSITION]))
        with self._lock:
            if self.deadline_s <= 0 or self._deadline_at <= 0:
                REPAIR_BATCH_DEADLINE_SLACK.set(0.0)
                return
            left_s = self._deadline_at - time.monotonic()
            rate = self.controller.bucket.rate  # bytes/s budget
            projected = (self._remaining_bytes / rate) if rate > 0 else 0.0
            REPAIR_BATCH_DEADLINE_SLACK.set(left_s - projected)

    # -- status -----------------------------------------------------------

    def status(self) -> dict:
        jobs = [j for j in self.journal.jobs()
                if j.get("transition") == TRANSITION]
        with self._lock:
            deadline_left = (self._deadline_at - time.monotonic()
                             if self._deadline_at > 0 else 0.0)
            remaining = self._remaining_bytes
        return {
            "enabled": self.enabled,
            "deadlineSeconds": self.deadline_s,
            "deadlineLeftSeconds": round(deadline_left, 1),
            "remainingBytes": remaining,
            "rateFloorMBps": round(self.rate_floor_mbps(), 2),
            "counts": dict(self._counts),
            "pending": len([j for j in jobs
                            if j.get("state") in ACTIVE_STATES]),
            "jobs": jobs[-64:],
        }
