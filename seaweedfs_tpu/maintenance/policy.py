"""Declarative per-collection lifecycle policies.

One `LifecyclePolicy` names the thresholds for every transition the
controller can decide; a `PolicySet` maps collection names to policies
with a `"*"` default.  The JSON shape (policy file / `volume.lifecycle
-policy=`) is a dict of collection -> field overrides:

    {
      "*":      {"seal_full_percent": 95, "vacuum_garbage_ratio": 0.3},
      "photos": {"ec_cooldown_seconds": 3600,
                 "tier_backend": "s3.cold", "tier_idle_seconds": 86400}
    }

Disabled-by-default transitions: EC encode (no cooldown configured),
tier (no backend configured), rebalance (skew 0).  Seal, vacuum and TTL
expiry default on — they only ever act on volumes whose own state
(fullness, garbage, expired TTL) already demands it.

Timing rationale: encode-when-cold with an explicit cool-down is the
production shape arXiv:1709.05365 measures for online-vs-offline EC on
flash — encoding under an active write burst would readonly a volume
mid-stream and pay the device tax at the worst time.
"""

from __future__ import annotations

import json
from dataclasses import asdict, dataclass, field, fields


@dataclass
class LifecyclePolicy:
    # seal: freeze a volume once it is this full (percent of the cluster
    # volume size limit); 0 disables.  seal_age_seconds additionally
    # seals quiet volumes older than this even if not full (0 = off).
    seal_full_percent: float = 95.0
    seal_age_seconds: float = 0.0
    # EC encode sealed volumes after this long with no writes; negative
    # disables (the cool-down gate from arXiv:1709.05365)
    ec_cooldown_seconds: float = -1.0
    ec_codec: str = ""  # "" = the volume server's default codec
    # tier the sealed .dat to this backend ("s3.cold") after this long
    # idle; "" disables.  keep_local_dat keeps the local copy too.
    tier_backend: str = ""
    tier_idle_seconds: float = 0.0
    keep_local_dat: bool = False
    # vacuum volumes whose garbage ratio exceeds this; 0 disables
    vacuum_garbage_ratio: float = 0.3
    # delete whole volumes whose TTL has expired (volume-granularity TTL,
    # the reference's TTL volume semantics)
    ttl_expire: bool = True
    # plan volume moves when max-min per-node volume counts exceeds this;
    # 0 disables
    rebalance_skew: int = 0

    def to_dict(self) -> dict:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "LifecyclePolicy":
        known = {f.name for f in fields(cls)}
        bad = set(d) - known
        if bad:
            raise ValueError(
                f"unknown lifecycle policy fields {sorted(bad)}; "
                f"known: {sorted(known)}")
        return cls(**d)


class PolicySet:
    """collection name -> LifecyclePolicy, with a '*' default."""

    def __init__(self, policies: dict[str, LifecyclePolicy] | None = None):
        self.policies = dict(policies or {})
        self.policies.setdefault("*", LifecyclePolicy())

    @classmethod
    def parse(cls, doc: "dict | str | None") -> "PolicySet":
        """From the JSON dict shape (or its serialized string)."""
        if doc is None:
            return cls()
        if isinstance(doc, str):
            doc = json.loads(doc)
        if not isinstance(doc, dict):
            raise ValueError("lifecycle policy must be a JSON object")
        out = {}
        for coll, overrides in doc.items():
            if not isinstance(overrides, dict):
                raise ValueError(
                    f"policy for collection {coll!r} must be an object")
            out[coll] = LifecyclePolicy.from_dict(overrides)
        return cls(out)

    def for_collection(self, collection: str) -> LifecyclePolicy:
        return self.policies.get(collection) or self.policies["*"]

    def to_dict(self) -> dict:
        return {c: p.to_dict() for c, p in sorted(self.policies.items())}

    def dumps(self) -> str:
        return json.dumps(self.to_dict(), indent=2, sort_keys=True)
