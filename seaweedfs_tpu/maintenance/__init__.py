"""Autonomous storage lifecycle plane (ISSUE 9).

A master-resident controller that turns per-collection declarative
policies into journaled, idempotent background jobs:

    hot volume -> seal -> EC-encode -> cloud-tier
                          vacuum / rebalance / ttl-expire

Policies are evaluated against heartbeat-fed topology state, jobs are
persisted to a crash-safe journal (replayed on master restart,
duplicate-suppressed by (volume, transition) key), and execution is
paced by a cluster-wide bytes/s token bucket plus the PR 5 saturation
gauges so lifecycle traffic never starves foreground I/O — the
operational failure mode arXiv:1309.0186 documents for EC clusters.
"""

from .controller import LifecycleController, TRANSITIONS
from .journal import JobJournal
from .mass_repair import MassRepairOrchestrator
from .policy import LifecyclePolicy, PolicySet

__all__ = [
    "JobJournal",
    "LifecycleController",
    "LifecyclePolicy",
    "MassRepairOrchestrator",
    "PolicySet",
    "TRANSITIONS",
]
