"""`python -m seaweedfs_tpu <subcommand>` — the `weed` binary equivalent
(reference: weed/weed.go:39)."""

from .cli import main

main()
