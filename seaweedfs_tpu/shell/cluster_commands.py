"""Cluster observability shell commands.

`cluster.status` renders the master's /cluster/status JSON — topology,
filer registrations, heartbeat/snapshot ages — as the operator-facing
one-screen answer to "what does the master think the cluster looks like".
"""

from __future__ import annotations

import json

from ..util import connpool
from .commands import CommandEnv, register


def _master_http(env: CommandEnv) -> str:
    """The master's HTTP address, derived from the gRPC one (port-10000
    convention, the inverse of CommandEnv's construction)."""
    host, _, port = env.master_grpc.partition(":")
    return f"{host}:{int(port) - 10000}"


@register("cluster.status")
def cluster_status(env: CommandEnv, args: list[str]) -> str:
    """cluster.status [-json]  — nodes, filers, liveness, snapshot ages."""
    addr = _master_http(env)
    with connpool.request(
            "GET", f"http://{addr}/cluster/status", timeout=10) as r:
        doc = json.loads(r.read())
    if "-json" in args:
        return json.dumps(doc, indent=2, sort_keys=True)
    lines = [
        f"master {addr} leader={doc.get('Leader', '?')} "
        f"isLeader={doc.get('IsLeader')} "
        f"maxVolumeId={doc.get('MaxVolumeId')}",
    ]
    raft = doc.get("Raft")
    if raft:
        warm = "warmed" if raft.get("warmedUp") else "WARMING UP"
        lines.append(
            f"raft: term={raft.get('term')} role={raft.get('role')} "
            f"leader={raft.get('leaderId')} "
            f"commit={raft.get('commitIndex')}/"
            f"{raft.get('logEntries')} entries "
            f"epoch={raft.get('leaderEpoch')} "
            f"quorum={len(raft.get('peers', ())) + 1} {warm}")
    nodes = doc.get("DataNodes", {})
    lines.append(f"volume servers ({len(nodes)}):")
    for nid in sorted(nodes):
        n = nodes[nid]
        disk_state = n.get("diskState", "healthy")
        disks = n.get("disks") or {}
        free_mb = sum(d.get("freeBytes", 0) for d in disks.values()) >> 20
        disk_note = ""
        if disks:
            disk_note = f" disk={disk_state} free={free_mb}MB"
            if disk_state not in ("healthy", "low_space"):
                disk_note = disk_note.upper()  # full/failing must pop
        lines.append(
            f"  {nid} dc={n.get('dataCenter')} rack={n.get('rack')} "
            f"volumes={len(n.get('volumes', ()))} "
            f"ecVolumes={len(n.get('ecShards', {}))} "
            f"lastBeat={n.get('secondsSinceLastBeat', '?')}s ago"
            + disk_note)
    filers = doc.get("Filers", {})
    lines.append(f"filers ({len(filers)}):")
    for name in sorted(filers):
        f = filers[name]
        lines.append(
            f"  {name} http={f.get('httpAddress')} "
            f"lastSeen={f.get('secondsSinceLastSeen', '?')}s ago")
    members = _live_filers(doc)
    if members:
        from ..filer.fleet.ring import HashRing

        ring = HashRing(members)
        lines.append(
            f"filer ring: {len(ring)} shard(s) version={ring.version()} "
            f"vnodes={ring.vnodes}/node (details: filer.ring)")
    health = doc.get("Health") or {}
    slo = health.get("slo") or {}
    canary = health.get("canary") or {}
    if slo or canary:
        firing = slo.get("firing") or []
        pending = slo.get("pending") or []
        verdict = ("FIRING: " + ", ".join(firing) if firing
                   else "pending: " + ", ".join(pending) if pending
                   else "ok")
        lines.append(
            f"health: {verdict} ({slo.get('specs', 0)} SLOs, "
            f"engine {'on' if slo.get('evaluating') else 'on-demand'}; "
            "details: cluster.alerts)")
        if canary:
            probes = canary.get("probes") or {}
            rendered = " ".join(
                f"{name}={state}" for name, state in sorted(probes.items()))
            lines.append(
                f"canary: {'running' if canary.get('running') else 'off'} "
                f"tick={canary.get('tick', 0)} "
                f"byteMismatches={canary.get('byteMismatches', 0)}"
                + (f" {rendered}" if rendered else ""))
    snaps = doc.get("StatsSnapshots", {})
    if snaps:
        lines.append(f"stats snapshots ({len(snaps)}):")
        for inst in sorted(snaps):
            s = snaps[inst]
            lines.append(
                f"  {inst} type={s.get('type')} "
                f"samples={s.get('samples')} "
                f"age={s.get('ageSeconds', '?')}s")
    lines.append(
        f"federated scrape: http://{addr}/cluster/metrics ; "
        f"stitched traces: http://{addr}/cluster/traces?trace=<id>")
    return "\n".join(lines)


def _live_filers(status_doc: dict) -> list[str]:
    """Ring membership exactly as a gateway would derive it from the
    master's /cluster/status — same staleness cutoff as the router, so
    the shell renders the ring gateways actually route on."""
    from ..filer.fleet.router import STALE_FILER_S

    members = []
    for info in (status_doc.get("Filers") or {}).values():
        addr = info.get("httpAddress")
        age = float(info.get("secondsSinceLastSeen") or 0.0)
        if addr and age < STALE_FILER_S:
            members.append(addr)
    return sorted(set(members))


@register("filer.ring")
def filer_ring(env: CommandEnv, args: list[str]) -> str:
    """filer.ring [-json]  — fleet membership, per-shard entry counts,
    per-tenant quota/usage (scraped from each shard's /debug/tenants)."""
    from ..filer.fleet.ring import HashRing

    addr = _master_http(env)
    with connpool.request(
            "GET", f"http://{addr}/cluster/status", timeout=10) as r:
        doc = json.loads(r.read())
    members = _live_filers(doc)
    shards: dict[str, dict] = {}
    for member in members:
        try:
            with connpool.request(
                    "GET", f"http://{member}/debug/tenants",
                    timeout=5) as r:
                shards[member] = json.loads(r.read())
        except Exception as e:  # noqa: BLE001 — a dead shard still prints
            shards[member] = {"error": str(e)}
    if "-json" in args:
        ring = HashRing(members) if members else None
        return json.dumps({
            "members": members,
            "version": ring.version() if ring else "",
            "shards": shards,
        }, indent=2, sort_keys=True)
    if not members:
        return "filer ring: no live filers registered with the master"
    ring = HashRing(members)
    lines = [f"filer ring: {len(ring)} shard(s) "
             f"version={ring.version()} vnodes={ring.vnodes}/node"]
    for member in members:
        doc = shards.get(member, {})
        if "error" in doc:
            lines.append(f"  {member} UNREACHABLE ({doc['error']})")
            continue
        entries = doc.get("entries")
        adm = doc.get("admission", {})
        lines.append(
            f"  {member} entries={'?' if entries is None else entries} "
            f"inflight={adm.get('total', 0)}/{adm.get('capacity', '?')} "
            f"store={doc.get('store', '?')}")
        for tenant, t in sorted((doc.get("tenants") or {}).items()):
            conf, usage = t.get("config", {}), t.get("usage", {})
            quota_b = conf.get("quota_bytes", 0)
            quota_o = conf.get("quota_objects", 0)
            lines.append(
                f"    tenant {tenant}: {usage.get('objects', 0)} obj"
                + (f"/{quota_o}" if quota_o else "")
                + f", {usage.get('bytes', 0)} B"
                + (f"/{quota_b}" if quota_b else "")
                + (f", weight={conf['weight']}" if "weight" in conf
                   else ""))
    return "\n".join(lines)


@register("cluster.alerts")
def cluster_alerts(env: CommandEnv, args: list[str]) -> str:
    """cluster.alerts [-json]  — SLO states, active alerts (with
    exemplar trace ids), recent transitions, canary probe results from
    the master's /cluster/alerts."""
    addr = _master_http(env)
    with connpool.request(
            "GET", f"http://{addr}/cluster/alerts", timeout=10) as r:
        doc = json.loads(r.read())
    if "-json" in args:
        return json.dumps(doc, indent=2, sort_keys=True)
    lines = []
    states = doc.get("states", {})
    active = doc.get("alerts", [])
    lines.append(f"SLOs ({len(states)}):")
    for name in sorted(states):
        st = states[name]
        lines.append(
            f"  {name} [{st.get('severity')}] {st.get('state')} "
            f"for {st.get('sinceS', 0):.0f}s")
    if active:
        lines.append(f"active alerts ({len(active)}):")
        for a in active:
            lines.append(
                f"  {a['slo']} [{a['severity']}] {a['state']} "
                f"burn={a.get('burnShort', 0):.2f}/"
                f"{a.get('burnLong', 0):.2f}"
                + (f" value={a['value']}" if "value" in a else ""))
            for ex in a.get("exemplars", ()):
                lines.append(
                    f"    exemplar trace {ex['traceId']} "
                    f"({ex['seconds'] * 1e3:.1f}ms, le={ex['le']}) -> "
                    f"http://{addr}{ex['traceQuery']}")
    else:
        lines.append("active alerts: none")
    hist = doc.get("history", [])
    if hist:
        lines.append(f"recent transitions ({len(hist)}):")
        for h in hist[-8:]:
            lines.append(
                f"  {h['slo']} {h.get('from', '?')} -> {h['state']}")
    canary = doc.get("canary", {})
    lines.append(
        f"canary: {'running' if canary.get('running') else 'off'} "
        f"interval={canary.get('interval_s', 0)}s "
        f"tick={canary.get('tick', 0)} "
        f"byteMismatches={canary.get('byteMismatches', 0)}")
    for name in sorted(canary.get("probes", {})):
        p = canary["probes"][name]
        if p.get("skipped"):
            lines.append(f"  {name}: skipped ({p['skipped']})")
            continue
        for target in sorted(p.get("targets", {})):
            t = p["targets"][target]
            lines.append(
                f"  {name} {target}: {t['result']}"
                + (f" ({t['error']})" if t.get("error") else ""))
    return "\n".join(lines)


@register("cluster.hot")
def cluster_hot(env: CommandEnv, args: list[str]) -> str:
    """cluster.hot [-json] [-n N]  — federated heavy-hitter tables:
    the hottest needles, buckets, tenants and peer IPs cluster-wide,
    from the master's /cluster/hot."""
    addr = _master_http(env)
    n = 32
    if "-n" in args:
        try:
            n = int(args[args.index("-n") + 1])
        except (IndexError, ValueError):
            return "usage: cluster.hot [-json] [-n N]"
    with connpool.request(
            "GET", f"http://{addr}/cluster/hot?n={n}", timeout=10) as r:
        doc = json.loads(r.read())
    if "-json" in args:
        return json.dumps(doc, indent=2, sort_keys=True)
    lines = []
    nodes = doc.get("nodes", {})
    down = sorted(i for i, s in nodes.items() if "error" in s)
    lines.append(f"hot keys across {len(nodes)} node(s)"
                 + (f" ({len(down)} unreachable)" if down else ""))
    for dim, windows in sorted(doc.get("dims", {}).items()):
        rows = windows.get("current") or windows.get("previous") or []
        which = "current" if windows.get("current") else "previous"
        if not rows:
            lines.append(f"  {dim}: (no traffic this window)")
            continue
        lines.append(f"  {dim} ({which} window):")
        for e in rows[:10]:
            lines.append(
                f"    {e['key']}  ~{e['count']} hits"
                + (f" (+/-{e['error']})" if e.get("error") else "")
                + f" on {len(set(e.get('nodes', ())))} node(s)")
    for inst in down:
        lines.append(f"  {inst} UNREACHABLE ({nodes[inst]['error']})")
    return "\n".join(lines)


@register("cluster.debug")
def cluster_debug(env: CommandEnv, args: list[str]) -> str:
    """cluster.debug [-json] [-capture] [-bundle NAME]  — list flight-
    recorder debug bundles; -capture snapshots a new one across every
    live node; -bundle prints one bundle's JSON."""
    addr = _master_http(env)
    if "-bundle" in args:
        try:
            name = args[args.index("-bundle") + 1]
        except IndexError:
            return "usage: cluster.debug -bundle NAME"
        with connpool.request(
                "GET", f"http://{addr}/cluster/debug?bundle="
                f"{name}", timeout=30) as r:
            return json.dumps(json.loads(r.read()), indent=2,
                              sort_keys=True)
    if "-capture" in args:
        with connpool.request(
                "GET", f"http://{addr}/cluster/debug/capture",
                timeout=60) as r:
            meta = json.loads(r.read())
        if "-json" in args:
            return json.dumps(meta, indent=2, sort_keys=True)
        if "error" in meta:
            return f"capture failed: {meta['error']}"
        return (f"captured {meta['name']}: {len(meta.get('nodes', ()))} "
                f"node(s), {meta.get('sizeBytes', 0)} bytes")
    with connpool.request(
            "GET", f"http://{addr}/cluster/debug", timeout=10) as r:
        doc = json.loads(r.read())
    if "-json" in args:
        return json.dumps(doc, indent=2, sort_keys=True)
    bundles = doc.get("bundles", [])
    lines = [f"debug bundles ({len(bundles)}), "
             f"dir={doc.get('debugDir') or '(in-memory)'} "
             f"retain={doc.get('retain')}"]
    for b in bundles:
        lines.append(f"  {b['name']}  {b['sizeBytes']}B  "
                     f"{b['ageS']:.0f}s ago")
    if not bundles:
        lines.append("  (none captured yet; cluster.debug -capture, or "
                     "wait for an alert to fire)")
    return "\n".join(lines)


@register("cluster.geo")
def cluster_geo(env: CommandEnv, args: list[str]) -> str:
    """cluster.geo [-json]  — peer-cluster reachability + per-link
    replication health (lag, shipped/applied/conflict counters) from
    the master's /cluster/geo registry."""
    addr = _master_http(env)
    with connpool.request(
            "GET", f"http://{addr}/cluster/geo", timeout=10) as r:
        doc = json.loads(r.read())
    if "-json" in args:
        return json.dumps(doc, indent=2, sort_keys=True)
    lines = []
    peers = doc.get("peerClusters", {})
    lines.append(f"peer clusters ({len(peers)}):")
    for peer in sorted(peers):
        p = peers[peer]
        if p.get("reachable"):
            lines.append(
                f"  {peer} reachable leader={p.get('leader', '?')} "
                f"dataNodes={p.get('dataNodes')} filers={p.get('filers')}")
        else:
            lines.append(f"  {peer} UNREACHABLE ({p.get('error', '?')})")
    links = doc.get("links", {})
    if not links:
        lines.append("geo links: none reported (filer heartbeats carry "
                     "the seaweedfs_geo_* samples once links are up)")
    else:
        lines.append(f"geo link reporters ({len(links)}):")
        for inst in sorted(links):
            lines.append(f"  {inst}:")
            for name in sorted(links[inst]):
                lines.append(f"    {name} = {links[inst][name]}")
    return "\n".join(lines)
