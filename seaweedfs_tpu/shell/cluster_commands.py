"""Cluster observability shell commands.

`cluster.status` renders the master's /cluster/status JSON — topology,
filer registrations, heartbeat/snapshot ages — as the operator-facing
one-screen answer to "what does the master think the cluster looks like".
"""

from __future__ import annotations

import json

from ..util import connpool
from .commands import CommandEnv, register


def _master_http(env: CommandEnv) -> str:
    """The master's HTTP address, derived from the gRPC one (port-10000
    convention, the inverse of CommandEnv's construction)."""
    host, _, port = env.master_grpc.partition(":")
    return f"{host}:{int(port) - 10000}"


@register("cluster.status")
def cluster_status(env: CommandEnv, args: list[str]) -> str:
    """cluster.status [-json]  — nodes, filers, liveness, snapshot ages."""
    addr = _master_http(env)
    with connpool.request(
            "GET", f"http://{addr}/cluster/status", timeout=10) as r:
        doc = json.loads(r.read())
    if "-json" in args:
        return json.dumps(doc, indent=2, sort_keys=True)
    lines = [
        f"master {addr} leader={doc.get('Leader', '?')} "
        f"isLeader={doc.get('IsLeader')} "
        f"maxVolumeId={doc.get('MaxVolumeId')}",
    ]
    nodes = doc.get("DataNodes", {})
    lines.append(f"volume servers ({len(nodes)}):")
    for nid in sorted(nodes):
        n = nodes[nid]
        lines.append(
            f"  {nid} dc={n.get('dataCenter')} rack={n.get('rack')} "
            f"volumes={len(n.get('volumes', ()))} "
            f"ecVolumes={len(n.get('ecShards', {}))} "
            f"lastBeat={n.get('secondsSinceLastBeat', '?')}s ago")
    filers = doc.get("Filers", {})
    lines.append(f"filers ({len(filers)}):")
    for name in sorted(filers):
        f = filers[name]
        lines.append(
            f"  {name} http={f.get('httpAddress')} "
            f"lastSeen={f.get('secondsSinceLastSeen', '?')}s ago")
    snaps = doc.get("StatsSnapshots", {})
    if snaps:
        lines.append(f"stats snapshots ({len(snaps)}):")
        for inst in sorted(snaps):
            s = snaps[inst]
            lines.append(
                f"  {inst} type={s.get('type')} "
                f"samples={s.get('samples')} "
                f"age={s.get('ageSeconds', '?')}s")
    lines.append(
        f"federated scrape: http://{addr}/cluster/metrics ; "
        f"stitched traces: http://{addr}/cluster/traces?trace=<id>")
    return "\n".join(lines)
