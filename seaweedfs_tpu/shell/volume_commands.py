"""Volume admin commands: volume.list / volume.vacuum / volume.fix.replication
/ volume.balance / volume.move / volume.mount / volume.unmount / volume.delete.

Reference: weed/shell/command_volume_*.go.  Placement decisions are pure
functions over the TopologyInfo snapshot (tier-3 test pattern).
"""

from __future__ import annotations

import grpc

from ..pb import master_pb2
from ..pb import volume_server_pb2 as vs
from ..storage.replica_placement import ReplicaPlacement
from .commands import CommandEnv, register
from .ec_commands import _iter_nodes, _node_grpc, _parse_flags  # noqa: F401


@register("volume.list")
def volume_list(env: CommandEnv, args: list[str]) -> str:
    topo = env.topology()
    lines = []
    for dc, rack, dn in _iter_nodes(topo):
        for disk in dn.disk_infos.values():
            vols = [
                f"v{v.id}(size={v.size} files={v.file_count}"
                f"{' ro' if v.read_only else ''})"
                for v in disk.volume_infos
            ]
            ecs = [
                f"ec{e.id}[{bin(e.ec_index_bits)}]" for e in disk.ec_shard_infos
            ]
            lines.append(
                f"{dc}/{rack}/{dn.id}: {' '.join(vols + ecs) or '(empty)'}"
            )
    return "\n".join(lines)


@register("volume.vacuum")
def volume_vacuum(env: CommandEnv, args: list[str]) -> str:
    flags = _parse_flags(args)
    threshold = float(flags.get("garbageThreshold", "0.3"))
    env.master().VacuumVolume(
        master_pb2.VacuumVolumeRequest(garbage_threshold=threshold)
    )
    return "vacuum triggered"


@register("volume.scrub")
def volume_scrub(env: CommandEnv, args: list[str]) -> str:
    """On-demand integrity scan: verify needle CRCs / EC parity on disk.

    volume.scrub [-node ip:port] [-volumeId N] [-rate MBps]
    Without -node, every node is scrubbed (restricted to holders when
    -volumeId is given); findings are also queued for the master's
    repair pass via the next heartbeat."""
    flags = _parse_flags(args)
    vid = int(flags.get("volumeId", "0") or 0)
    rate = float(flags.get("rate", "0") or 0)
    if "node" in flags:
        nodes = [flags["node"]]
    else:
        nodes = []
        for _dc, _rack, dn in _iter_nodes(env.topology()):
            if vid:
                holds = any(
                    v.id == vid
                    for disk in dn.disk_infos.values()
                    for v in disk.volume_infos
                ) or any(
                    e.id == vid
                    for disk in dn.disk_infos.values()
                    for e in disk.ec_shard_infos
                )
                if not holds:
                    continue
            nodes.append(dn.id)
    if not nodes:
        return f"no node holds volume {vid}" if vid else "no nodes"
    lines = []
    for node in nodes:
        try:
            resp = env.volume_server(_node_grpc(node)).VolumeScrub(
                vs.VolumeScrubRequest(volume_id=vid, rate_mbps=rate)
            )
        except grpc.RpcError as e:
            lines.append(f"{node}: error: {e}")
            continue
        lines.append(
            f"{node}: scanned={resp.scanned} bytes={resp.scanned_bytes}"
            f" corruptNeedles={resp.corrupt_needles}"
            f" corruptShards={resp.corrupt_shards}"
            f" indexRepairs={resp.index_repairs}"
        )
        for line in resp.findings:
            lines.append(f"  finding: {line}")
    return "\n".join(lines)


@register("volume.mount")
def volume_mount(env: CommandEnv, args: list[str]) -> str:
    flags = _parse_flags(args)
    env.volume_server(_node_grpc(flags["node"])).VolumeMount(
        vs.VolumeMountRequest(volume_id=int(flags["volumeId"]))
    )
    return "mounted"


@register("volume.unmount")
def volume_unmount(env: CommandEnv, args: list[str]) -> str:
    flags = _parse_flags(args)
    env.volume_server(_node_grpc(flags["node"])).VolumeUnmount(
        vs.VolumeUnmountRequest(volume_id=int(flags["volumeId"]))
    )
    return "unmounted"


@register("volume.delete")
def volume_delete(env: CommandEnv, args: list[str]) -> str:
    flags = _parse_flags(args)
    env.volume_server(_node_grpc(flags["node"])).VolumeDelete(
        vs.VolumeDeleteRequest(volume_id=int(flags["volumeId"]))
    )
    return "deleted"


@register("volume.move")
def volume_move(env: CommandEnv, args: list[str]) -> str:
    """Copy a volume to a target node, then delete from the source.
    -source/-target are public node ids (ip:port as volume.list prints),
    the same convention as every other node-taking command."""
    flags = _parse_flags(args)
    vid = int(flags["volumeId"])
    source, target = flags["source"], flags["target"]
    _require_distinct_copy(env, vid, source, target)
    _node, collection = _locate_volume(env, vid)
    env.volume_server(_node_grpc(target)).VolumeCopy(
        vs.VolumeCopyRequest(
            volume_id=vid, collection=collection,
            source_data_node=_node_grpc(source),
        )
    )
    env.volume_server(_node_grpc(source)).VolumeDelete(
        vs.VolumeDeleteRequest(volume_id=vid))
    return f"moved {vid} {source} -> {target}"


def _require_distinct_copy(env: CommandEnv, vid: int, source: str,
                           target: str) -> None:
    """Refuse a copy that would truncate the .dat being streamed: the
    target must be a different node that does not already hold vid."""
    if source == target:
        raise RuntimeError(f"source and target are both {source}")
    for _dc, _rack, dn in _iter_nodes(env.topology()):
        if dn.id != target:
            continue
        for disk in dn.disk_infos.values():
            for v in disk.volume_infos:
                if v.id == vid:
                    raise RuntimeError(
                        f"{target} already holds volume {vid}")


@register("volume.copy")
def volume_copy(env: CommandEnv, args: list[str]) -> str:
    """Copy a volume to a target node, keeping the source
    (command_volume_copy.go)."""
    flags = _parse_flags(args)
    vid = int(flags["volumeId"])
    source, target = flags["source"], flags["target"]
    _require_distinct_copy(env, vid, source, target)
    _node, collection = _locate_volume(env, vid)
    env.volume_server(_node_grpc(target)).VolumeCopy(
        vs.VolumeCopyRequest(
            volume_id=vid, collection=collection,
            source_data_node=_node_grpc(source),
        )
    )
    return f"copied {vid} {source} -> {target}"


@register("volume.mark")
def volume_mark(env: CommandEnv, args: list[str]) -> str:
    """Mark a volume readonly or writable on a node
    (command_volume_mark.go)."""
    flags = _parse_flags(args)
    vid = int(flags["volumeId"])
    node = flags.get("node") or _locate_volume(env, vid)[0]
    stub = env.volume_server(_node_grpc(node))
    if flags.get("writable") == "true":
        stub.VolumeMarkWritable(vs.VolumeMarkWritableRequest(volume_id=vid))
        return f"volume {vid} marked writable on {node}"
    stub.VolumeMarkReadonly(vs.VolumeMarkReadonlyRequest(volume_id=vid))
    return f"volume {vid} marked readonly on {node}"


@register("volume.configure.replication")
def volume_configure_replication(env: CommandEnv, args: list[str]) -> str:
    """Change a volume's replica placement in its super block on every
    holder (command_volume_configure_replication.go)."""
    flags = _parse_flags(args)
    vid = int(flags["volumeId"])
    replication = flags["replication"]
    ReplicaPlacement.parse(replication)  # validate before touching servers
    changed = []
    for _dc, _rack, dn in _iter_nodes(env.topology()):
        for disk in dn.disk_infos.values():
            for v in disk.volume_infos:
                if v.id != vid:
                    continue
                resp = env.volume_server(_node_grpc(dn.id)).VolumeConfigure(
                    vs.VolumeConfigureRequest(
                        volume_id=vid, replication=replication
                    )
                )
                if resp.error:
                    raise RuntimeError(resp.error)
                changed.append(dn.id)
    if not changed:
        raise RuntimeError(f"volume {vid} not found in topology")
    return f"volume {vid} replication={replication} on {sorted(set(changed))}"


@register("volume.server.leave")
def volume_server_leave(env: CommandEnv, args: list[str]) -> str:
    """Ask one volume server to stop heartbeating and leave the cluster
    (command_volume_server_leave.go)."""
    flags = _parse_flags(args)
    node = flags["node"]
    env.volume_server(_node_grpc(node)).VolumeServerLeave(
        vs.VolumeServerLeaveRequest())
    return f"{node} asked to leave"


def _locate_volume(env: CommandEnv, vid: int) -> tuple[str, str]:
    """-> (node_url, collection) of the first holder of vid."""
    for _dc, _rack, dn in _iter_nodes(env.topology()):
        for disk in dn.disk_infos.values():
            for v in disk.volume_infos:
                if v.id == vid:
                    return dn.id, v.collection
    raise RuntimeError(f"volume {vid} not found in topology")


@register("volume.tier.upload")
def volume_tier_upload(env: CommandEnv, args: list[str]) -> str:
    """Move a volume's .dat to a remote tier backend; the index stays
    local and reads keep working through ranged requests.
    Reference: weed/shell/command_volume_tier_upload.go."""
    flags = _parse_flags(args)
    vid = int(flags["volumeId"])
    dest = flags.get("dest", "s3.default")
    keep = flags.get("keepLocalDatFile", "false") == "true"
    node = _node_grpc(flags.get("node") or _locate_volume(env, vid)[0])
    env.volume_server(node).VolumeMarkReadonly(
        vs.VolumeMarkReadonlyRequest(volume_id=vid)
    )
    processed = 0
    for resp in env.volume_server(node).VolumeTierMoveDatToRemote(
        vs.VolumeTierMoveDatToRemoteRequest(
            volume_id=vid,
            destination_backend_name=dest,
            keep_local_dat_file=keep,
        )
    ):
        processed = resp.processed
    return f"volume {vid} .dat -> {dest} ({processed} bytes)"


@register("volume.tier.download")
def volume_tier_download(env: CommandEnv, args: list[str]) -> str:
    """Bring a tiered volume's .dat back to local disk and make it
    writable again (weed/shell/command_volume_tier_download.go)."""
    flags = _parse_flags(args)
    vid = int(flags["volumeId"])
    node = _node_grpc(flags.get("node") or _locate_volume(env, vid)[0])
    processed = 0
    for resp in env.volume_server(node).VolumeTierMoveDatFromRemote(
        vs.VolumeTierMoveDatFromRemoteRequest(volume_id=vid)
    ):
        processed = resp.processed
    env.volume_server(node).VolumeMarkWritable(
        vs.VolumeMarkWritableRequest(volume_id=vid)
    )
    return f"volume {vid} .dat downloaded ({processed} bytes)"


def find_misplaced_volumes(topo: master_pb2.TopologyInfo) -> dict[int, dict]:
    """Pure analysis: vid -> {want, have, locations} for under/over-replication."""
    placements: dict[int, dict] = {}
    for dc, rack, dn in _iter_nodes(topo):
        for disk in dn.disk_infos.values():
            for v in disk.volume_infos:
                p = placements.setdefault(
                    v.id,
                    {"want": ReplicaPlacement.from_byte(v.replica_placement)
                     .copy_count(), "locations": [], "collection": v.collection},
                )
                p["locations"].append((dc, rack, dn.id))
    return {
        vid: {**p, "have": len(p["locations"])}
        for vid, p in placements.items()
        if len(p["locations"]) != p["want"]
    }


@register("volume.fix.replication")
def volume_fix_replication(env: CommandEnv, args: list[str]) -> str:
    topo = env.topology()
    issues = find_misplaced_volumes(topo)
    if not issues:
        return "volume.fix.replication: all volumes healthy"
    nodes = {dn.id: dn for _dc, _rack, dn in _iter_nodes(topo)}
    fixed = []
    for vid, info in sorted(issues.items()):
        have, want = info["have"], info["want"]
        locs = [n for _dc, _rack, n in info["locations"]]
        if have < want:
            candidates = [
                nid for nid, dn in nodes.items()
                if nid not in locs and _free_slots(dn) > 0
            ]
            if not candidates:
                fixed.append(f"{vid}: under-replicated, no target")
                continue
            target = candidates[0]
            try:
                env.volume_server(_node_grpc(target)).VolumeCopy(
                    vs.VolumeCopyRequest(
                        volume_id=vid, collection=info["collection"],
                        source_data_node=_node_grpc(locs[0]),
                    )
                )
                fixed.append(f"{vid}: copied to {target}")
            except grpc.RpcError as e:
                fixed.append(f"{vid}: copy failed: {e.code()}")
        elif have > want:
            victim = locs[-1]
            try:
                env.volume_server(_node_grpc(victim)).VolumeDelete(
                    vs.VolumeDeleteRequest(volume_id=vid)
                )
                fixed.append(f"{vid}: removed extra replica on {victim}")
            except grpc.RpcError as e:
                fixed.append(f"{vid}: delete failed: {e.code()}")
    return "\n".join(fixed)


def _free_slots(dn) -> int:
    free = 0
    for disk in dn.disk_infos.values():
        free += max(disk.max_volume_count - disk.volume_count, 0)
    return free


def plan_volume_balance_moves(topo) -> list[dict]:
    """Pure move planning (tier-3 testable, shared with the lifecycle
    controller's rebalance jobs): greedy donor->recipient moves that even
    out per-node volume counts, computed from ONE topology snapshot.
    A target already holding a replica of the volume is never picked —
    the copy would overwrite it and the source delete would silently
    drop the cluster one replica short — and among a donor's movable
    volumes, one whose REMAINING replicas sit outside the target's rack
    is preferred, so rebalance restores rack diversity instead of
    quietly collapsing a volume's replicas into one rack."""
    nodes = {dn.id: dn for _dc, _rack, dn in _iter_nodes(topo)}
    racks = {dn.id: (dc, rack) for dc, rack, dn in _iter_nodes(topo)}
    counts = {
        nid: sum(d.volume_count for d in dn.disk_infos.values())
        for nid, dn in nodes.items()
    }
    if not counts:
        return []
    holders: dict[int, set[str]] = {}
    on_node: dict[str, list[int]] = {nid: [] for nid in nodes}
    for _dc, _rack, dn in _iter_nodes(topo):
        for disk in dn.disk_infos.values():
            for v in disk.volume_infos:
                holders.setdefault(v.id, set()).add(dn.id)
                on_node[dn.id].append(v.id)

    def pick_vid(donor: str, target: str):
        fallback = None
        for v in on_node[donor]:
            if target in holders.get(v, set()):
                continue
            sibling_racks = {racks[h] for h in holders.get(v, set())
                             if h != donor and h in racks}
            if racks.get(target) not in sibling_racks:
                return v  # rack-diverse move: take it
            if fallback is None:
                fallback = v
        return fallback

    moves: list[dict] = []
    avg = sum(counts.values()) / len(counts)
    for nid in sorted(counts, key=counts.get, reverse=True):
        while counts[nid] > avg + 1:
            target = min(counts, key=counts.get)
            if counts[target] >= avg:
                break
            vid = pick_vid(nid, target)
            if vid is None:
                break
            moves.append({"volumeId": vid, "source": nid,
                          "target": target})
            on_node[nid].remove(vid)
            on_node[target].append(vid)
            holders[vid].discard(nid)
            holders[vid].add(target)
            counts[nid] -= 1
            counts[target] += 1
    return moves


def apply_volume_move(env: CommandEnv, move: dict) -> str:
    """Execute one planned move (copy to target, delete from source)."""
    return volume_move(env, [
        f"-volumeId={move['volumeId']}",
        f"-source={move['source']}",
        f"-target={move['target']}",
    ])


@register("volume.balance")
def volume_balance(env: CommandEnv, args: list[str]) -> str:
    """Even out volume counts across nodes (greedy, like the reference).

    volume.balance [-apply]  — default is a DRY RUN that prints the
    planned moves; -apply (or the legacy -force) executes them.  The
    lifecycle controller's rebalance jobs reuse the same planner."""
    flags = _parse_flags(args)
    apply_changes = "apply" in flags or "force" in flags
    moves = plan_volume_balance_moves(env.topology())
    if not moves:
        return "volume.balance: balanced"
    lines = [f"volume.balance: {len(moves)} move(s) planned"]
    for mv in moves:
        lines.append(f"  v{mv['volumeId']} {mv['source']} -> {mv['target']}"
                     + ("" if apply_changes
                        else " (dry run, -apply to move)"))
    if not apply_changes:
        return "\n".join(lines)
    for mv in moves:
        try:
            lines.append(apply_volume_move(env, mv))
        except (grpc.RpcError, RuntimeError) as e:
            lines.append(f"  v{mv['volumeId']} FAILED: {e}")
            break
    return "\n".join(lines)


@register("volume.evacuate")
def volume_evacuate(env: CommandEnv, args: list[str]) -> str:
    """Move every volume and EC shard off a node, then tell it to leave
    (command_volume_server_evacuate.go)."""
    flags = _parse_flags(args)
    node = flags["node"]  # ip:port (http)
    topo = env.topology()
    nodes = {dn.id: dn for _dc, _rack, dn in _iter_nodes(topo)}
    if node not in nodes:
        return f"volume.evacuate: node {node} not found"
    targets = [
        nid for nid in nodes
        if nid != node and _free_slots(nodes[nid]) > 0
    ]
    if not targets:
        return "volume.evacuate: no target nodes with free slots"
    # a node already holding a replica of vid must not be picked as its
    # target — VolumeCopy would overwrite it and the delete on the source
    # would silently drop the cluster one replica short
    holders: dict[int, set[str]] = {}
    for _dc, _rack, dn in _iter_nodes(topo):
        for disk in dn.disk_infos.values():
            for v in disk.volume_infos:
                holders.setdefault(v.id, set()).add(dn.id)
    moved, i = [], 0
    for disk in nodes[node].disk_infos.values():
        for v in disk.volume_infos:
            eligible = [
                t_ for t_ in targets if t_ not in holders.get(v.id, set())
            ]
            if not eligible:
                moved.append(f"v{v.id} SKIPPED: every target holds a replica")
                continue
            target = eligible[i % len(eligible)]
            i += 1
            try:
                volume_move(
                    env,
                    [f"-volumeId={v.id}", f"-source={node}",
                     f"-target={target}"],
                )
                moved.append(f"v{v.id}->{target}")
            except grpc.RpcError as e:
                moved.append(f"v{v.id} FAILED: {e.code()}")
        for ec in disk.ec_shard_infos:
            target = targets[i % len(targets)]
            i += 1
            shard_ids = _bits_to_ids(ec.ec_index_bits)
            try:
                env.volume_server(_node_grpc(target)).VolumeEcShardsCopy(
                    vs.VolumeEcShardsCopyRequest(
                        volume_id=ec.id, collection=ec.collection,
                        shard_ids=shard_ids, copy_ecx_file=True,
                        copy_ecj_file=True, copy_vif_file=True,
                        copy_from_data_node=_node_grpc(node),
                    )
                )
                env.volume_server(_node_grpc(target)).VolumeEcShardsMount(
                    vs.VolumeEcShardsMountRequest(
                        volume_id=ec.id, collection=ec.collection,
                        shard_ids=shard_ids,
                    )
                )
                env.volume_server(_node_grpc(node)).VolumeEcShardsUnmount(
                    vs.VolumeEcShardsUnmountRequest(
                        volume_id=ec.id, shard_ids=shard_ids
                    )
                )
                env.volume_server(_node_grpc(node)).VolumeEcShardsDelete(
                    vs.VolumeEcShardsDeleteRequest(
                        volume_id=ec.id, collection=ec.collection,
                        shard_ids=shard_ids,
                    )
                )
                moved.append(f"ec{ec.id}{shard_ids}->{target}")
            except grpc.RpcError as e:
                moved.append(f"ec{ec.id} FAILED: {e.code()}")
    if flags.get("leave", "true") != "false":
        try:
            env.volume_server(_node_grpc(node)).VolumeServerLeave(
                vs.VolumeServerLeaveRequest()
            )
        except grpc.RpcError:
            pass
    return f"volume.evacuate {node}: " + (", ".join(moved) or "nothing to move")


def _bits_to_ids(bits: int) -> list[int]:
    return [i for i in range(14) if bits & (1 << i)]


def find_replica_divergence(statuses: dict[int, list[tuple[str, object]]]):
    """Pure analysis: vid -> list of (node, file_count, dat_size) when
    replicas disagree (command_volume_check_disk.go's comparison)."""
    out = {}
    for vid, pairs in statuses.items():
        if len(pairs) < 2:
            continue
        counts = {(st.file_count, st.dat_file_size) for _n, st in pairs}
        if len(counts) > 1:
            out[vid] = [
                (n, st.file_count, st.dat_file_size) for n, st in pairs
            ]
    return out


def _collect_volume_statuses(env: CommandEnv, topo) -> dict:
    statuses: dict[int, list] = {}
    for _dc, _rack, dn in _iter_nodes(topo):
        for disk in dn.disk_infos.values():
            for v in disk.volume_infos:
                try:
                    st = env.volume_server(_node_grpc(dn.id)).ReadVolumeFileStatus(
                        vs.ReadVolumeFileStatusRequest(volume_id=v.id)
                    )
                    statuses.setdefault(v.id, []).append((dn.id, st))
                except grpc.RpcError:
                    continue
    return statuses


@register("volume.fsck")
def volume_fsck(env: CommandEnv, args: list[str]) -> str:
    """Report replicas whose file counts / sizes disagree
    (command_volume_fsck.go's consistency sweep, metadata level)."""
    topo = env.topology()
    diverged = find_replica_divergence(_collect_volume_statuses(env, topo))
    if not diverged:
        return "volume.fsck: all replicas consistent"
    lines = []
    for vid, infos in sorted(diverged.items()):
        detail = ", ".join(f"{n}: {fc} files/{sz}B" for n, fc, sz in infos)
        lines.append(f"volume {vid} diverged: {detail}")
    return "\n".join(lines)


@register("volume.check.disk")
def volume_check_disk(env: CommandEnv, args: list[str]) -> str:
    """Repair diverged replicas by tail-syncing the smaller from the
    larger (command_volume_check_disk.go)."""
    flags = _parse_flags(args)
    apply_changes = flags.get("force", "false") != "false"
    topo = env.topology()
    diverged = find_replica_divergence(_collect_volume_statuses(env, topo))
    if not diverged:
        return "volume.check.disk: all replicas consistent"
    lines = []
    for vid, infos in sorted(diverged.items()):
        best = max(infos, key=lambda x: (x[1], x[2]))
        for node, fc, sz in infos:
            if node == best[0]:
                continue
            if not apply_changes:
                lines.append(
                    f"volume {vid}: {node} ({fc} files) behind "
                    f"{best[0]} ({best[1]} files) — rerun with -force to sync"
                )
                continue
            try:
                env.volume_server(_node_grpc(node)).VolumeTailReceiver(
                    vs.VolumeTailReceiverRequest(
                        volume_id=vid,
                        since_ns=0,
                        idle_timeout_seconds=1,
                        source_volume_server=best[0],
                    )
                )
                lines.append(f"volume {vid}: synced {node} from {best[0]}")
            except grpc.RpcError as e:
                lines.append(f"volume {vid}: sync failed: {e.code()}")
    return "\n".join(lines)


def collect_volume_ids_for_tier_change(
    topo, volume_size_limit: int, from_disk_type: str,
    collection: str = "", full_percent: float = 95.0,
    quiet_for_seconds: float = 0, now: float | None = None,
) -> list[int]:
    """Pure selection (tier-3 testable): quiet, full volumes currently on
    the source tier (collectVolumeIdsForTierChange,
    command_volume_tier_move.go:153-180)."""
    import time as _time

    from ..storage.disk_location import normalize_disk_type

    if now is None:
        now = _time.time()
    want = normalize_disk_type(from_disk_type)
    vids = set()
    for _dc, _rack, dn in _iter_nodes(topo):
        for disk in dn.disk_infos.values():
            for v in disk.volume_infos:
                if normalize_disk_type(v.disk_type) != want:
                    continue
                if collection and v.collection != collection:
                    continue
                if v.size < volume_size_limit * full_percent / 100.0:
                    continue
                if (quiet_for_seconds > 0 and v.modified_at_second
                        and now - v.modified_at_second < quiet_for_seconds):
                    continue
                vids.add(v.id)
    return sorted(vids)


def pick_tier_move_target(
    topo, vid: int, to_disk_type: str,
) -> tuple[str, str] | None:
    """Pure placement (tier-3 testable): -> (source_node, target_node) or
    None.  Target = node with the most free slots on the target tier that
    does not already hold the volume (doVolumeTierMove,
    command_volume_tier_move.go:93-150)."""
    from ..storage.disk_location import normalize_disk_type

    want = normalize_disk_type(to_disk_type)
    holders = []
    candidates = []
    for _dc, _rack, dn in _iter_nodes(topo):
        holds = False
        free = 0
        for dt, disk in dn.disk_infos.items():
            for v in disk.volume_infos:
                if v.id == vid:
                    holds = True
            if normalize_disk_type(dt) == want:
                free = max(free, disk.max_volume_count - disk.volume_count)
        if holds:
            holders.append(dn.id)
        elif free > 0:
            candidates.append((free, dn.id))
    if not holders or not candidates:
        return None
    candidates.sort(reverse=True)
    return holders[0], candidates[0][1]


@register("volume.tier.move")
def volume_tier_move(env: CommandEnv, args: list[str]) -> str:
    """Move quiet, full volumes from one disk tier to another
    (command_volume_tier_move.go).  Only one replica moves; the rest are
    dropped — follow with volume.fix.replication / volume.balance, as the
    reference documents."""
    from .fs_commands import _parse_duration
    from ..storage.disk_location import readable_disk_type

    flags = _parse_flags(args)
    from_dt = flags.get("fromDiskType", "")
    to_dt = flags.get("toDiskType", "")
    if readable_disk_type(from_dt) == readable_disk_type(to_dt):
        raise RuntimeError(
            f"source tier {readable_disk_type(from_dt)} is the same as "
            f"target tier {readable_disk_type(to_dt)}")
    collection = flags.get("collection", "")
    full_percent = float(flags.get("fullPercent", "95"))
    quiet_for = _parse_duration(flags.get("quietFor", "0"))
    apply_changes = "force" in flags
    if "volumeId" in flags:
        vids = [int(flags["volumeId"])]
    else:
        topo = env.topology()
        vids = collect_volume_ids_for_tier_change(
            topo, env.volume_size_limit(), from_dt, collection,
            full_percent, quiet_for)
    lines = [f"tier move volumes: {vids}"]
    for vid in vids:
        topo = env.topology()
        picked = pick_tier_move_target(topo, vid, to_dt)
        if picked is None:
            lines.append(
                f"volume {vid}: no node with free "
                f"{readable_disk_type(to_dt)} capacity")
            continue
        source, target = picked
        lines.append(
            f"moving volume {vid} from {source} to {target} with disk "
            f"type {readable_disk_type(to_dt)}"
            + ("" if apply_changes else " (dry run, -force to apply)"))
        if not apply_changes:
            continue
        # reuse the in-hand snapshot for the replica scan AND the
        # collection lookup — no extra VolumeList round trips per volume
        replicas = []
        collection_of = ""
        for _dc, _rack, dn in _iter_nodes(topo):
            for d in dn.disk_infos.values():
                for v in d.volume_infos:
                    if v.id == vid:
                        collection_of = v.collection
                        if dn.id not in replicas:
                            replicas.append(dn.id)
        for node in replicas:
            env.volume_server(_node_grpc(node)).VolumeMarkReadonly(
                vs.VolumeMarkReadonlyRequest(volume_id=vid))
        from ..storage.disk_location import normalize_disk_type

        env.volume_server(_node_grpc(target)).VolumeCopy(
            vs.VolumeCopyRequest(
                volume_id=vid, collection=collection_of,
                source_data_node=_node_grpc(source),
                disk_type=normalize_disk_type(to_dt) or "hdd",
            )
        )
        for node in replicas:
            env.volume_server(_node_grpc(node)).VolumeDelete(
                vs.VolumeDeleteRequest(volume_id=vid))
        env.volume_server(_node_grpc(target)).VolumeMarkWritable(
            vs.VolumeMarkWritableRequest(volume_id=vid))
        lines.append(f"moved volume {vid} -> {target}")
    return "\n".join(lines)


@register("volume.lifecycle")
def volume_lifecycle(env: CommandEnv, args: list[str]) -> str:
    """Operate the master's lifecycle controller.

    volume.lifecycle                      — controller status + job list
    volume.lifecycle -dry-run [...]       — evaluate policies, print plan
    volume.lifecycle -apply [...]         — evaluate AND execute now
    volume.lifecycle -policy='<json>'     — install a policy set
    Filters for -dry-run/-apply: -volumeId=N -transition=NAME."""
    import json as _json

    flags = _parse_flags(args)
    if "policy" in flags:
        resp = env.master().Lifecycle(master_pb2.LifecycleRequest(
            action="policy", policy_json=flags["policy"]))
        return "lifecycle policy updated:\n" + resp.report
    if "apply" in flags or "dry-run" in flags or "run" in flags:
        resp = env.master().Lifecycle(master_pb2.LifecycleRequest(
            action="run",
            apply="apply" in flags,
            volume_id=int(flags.get("volumeId", "0") or 0),
            transition=flags.get("transition", ""),
        ))
        doc = _json.loads(resp.report)
        lines = []
        planned = doc.get("planned", [])
        lines.append(f"planned: {len(planned)} transition(s)"
                     + ("" if "apply" in flags
                        else " (dry run, -apply to execute)"))
        for p in planned:
            lines.append(
                f"  v{p['volume_id']} {p['transition']}"
                f" on {p.get('node', '?')} ({p.get('bytes', 0)} bytes)")
        for r in doc.get("results", []):
            lines.append(f"  {r.get('key')}: {r.get('state')}"
                         + (f" — {r['detail']}" if r.get("detail") else "")
                         + (f" — {r['error']}" if r.get("error") else ""))
        return "\n".join(lines)
    resp = env.master().Lifecycle(
        master_pb2.LifecycleRequest(action="status"))
    doc = _json.loads(resp.report)
    lines = [
        f"lifecycle: enabled={doc['enabled']} running={doc['running']}"
        f" interval={doc['intervalSeconds']}s rate={doc['rateMBps']}MB/s",
        f"journal: {doc['journalPath'] or '(memory only)'}"
        f" states={doc['jobStates']}",
        f"counts: {doc['counts']}",
    ]
    for j in doc.get("jobs", [])[-16:]:
        lines.append(
            f"  {j['key']}: {j['state']} attempts={j.get('attempts', 0)}"
            + (f" — {j['detail']}" if j.get("detail") else "")
            + (f" — {j['error']}" if j.get("error") else ""))
    return "\n".join(lines)


@register("volume.repair")
def volume_repair(env: CommandEnv, args: list[str]) -> str:
    """Operate the master's dead-node mass-repair orchestrator.

    volume.repair                — orchestrator status + recent jobs
    volume.repair -plan          — rank affected volumes by exposure,
                                   print targets; touches nothing
    volume.repair -apply         — plan, journal and execute the batch
    -node=ip:port tags the plan with the dead node it answers for."""
    import json as _json

    flags = _parse_flags(args)
    node = flags.get("node", "")
    if "plan" in flags or "apply" in flags:
        resp = env.master().Lifecycle(master_pb2.LifecycleRequest(
            action=("mass_repair_run" if "apply" in flags
                    else "mass_repair_plan"),
            node=node))
        doc = _json.loads(resp.report)
        planned = doc.get("planned", [])
        lines = [f"mass repair: {len(planned)} volume(s) planned"
                 + ("" if "apply" in flags
                    else " (dry run, -apply to execute)")]
        for p in planned:
            lines.append(
                f"  v{p['volume_id']} surviving={p['surviving']}"
                f" -> {p['node']} ({p.get('bytes', 0)} bytes)")
        for r in doc.get("results", []):
            lines.append(f"  {r.get('key')}: {r.get('state')}"
                         + (f" — {r['error']}" if r.get("error") else ""))
        return "\n".join(lines)
    resp = env.master().Lifecycle(
        master_pb2.LifecycleRequest(action="mass_repair_status"))
    doc = _json.loads(resp.report)
    lines = [
        f"mass repair: enabled={doc['enabled']} pending={doc['pending']}"
        f" deadline={doc['deadlineSeconds']}s"
        f" rateFloor={doc['rateFloorMBps']}MB/s",
        f"counts: {doc['counts']}",
    ]
    for j in doc.get("jobs", [])[-16:]:
        lines.append(
            f"  {j['key']}: {j['state']} attempts={j.get('attempts', 0)}"
            + (f" — {j['detail']}" if j.get("detail") else "")
            + (f" — {j['error']}" if j.get("error") else ""))
    return "\n".join(lines)


@register("lock")
def lock_cmd(env: CommandEnv, args: list[str]) -> str:
    return "locked" if env.acquire_lock() else "lock busy"


@register("unlock")
def unlock_cmd(env: CommandEnv, args: list[str]) -> str:
    env.release_lock()
    return "unlocked"
