"""Volume admin commands: volume.list / volume.vacuum / volume.fix.replication
/ volume.balance / volume.move / volume.mount / volume.unmount / volume.delete.

Reference: weed/shell/command_volume_*.go.  Placement decisions are pure
functions over the TopologyInfo snapshot (tier-3 test pattern).
"""

from __future__ import annotations

import grpc

from ..pb import master_pb2
from ..pb import volume_server_pb2 as vs
from ..storage.replica_placement import ReplicaPlacement
from .commands import CommandEnv, register
from .ec_commands import _iter_nodes, _node_grpc, _parse_flags


@register("volume.list")
def volume_list(env: CommandEnv, args: list[str]) -> str:
    topo = env.topology()
    lines = []
    for dc, rack, dn in _iter_nodes(topo):
        for disk in dn.disk_infos.values():
            vols = [
                f"v{v.id}(size={v.size} files={v.file_count}"
                f"{' ro' if v.read_only else ''})"
                for v in disk.volume_infos
            ]
            ecs = [
                f"ec{e.id}[{bin(e.ec_index_bits)}]" for e in disk.ec_shard_infos
            ]
            lines.append(
                f"{dc}/{rack}/{dn.id}: {' '.join(vols + ecs) or '(empty)'}"
            )
    return "\n".join(lines)


@register("volume.vacuum")
def volume_vacuum(env: CommandEnv, args: list[str]) -> str:
    flags = _parse_flags(args)
    threshold = float(flags.get("garbageThreshold", "0.3"))
    env.master().VacuumVolume(
        master_pb2.VacuumVolumeRequest(garbage_threshold=threshold)
    )
    return "vacuum triggered"


@register("volume.mount")
def volume_mount(env: CommandEnv, args: list[str]) -> str:
    flags = _parse_flags(args)
    env.volume_server(flags["node"]).VolumeMount(
        vs.VolumeMountRequest(volume_id=int(flags["volumeId"]))
    )
    return "mounted"


@register("volume.unmount")
def volume_unmount(env: CommandEnv, args: list[str]) -> str:
    flags = _parse_flags(args)
    env.volume_server(flags["node"]).VolumeUnmount(
        vs.VolumeUnmountRequest(volume_id=int(flags["volumeId"]))
    )
    return "unmounted"


@register("volume.delete")
def volume_delete(env: CommandEnv, args: list[str]) -> str:
    flags = _parse_flags(args)
    env.volume_server(flags["node"]).VolumeDelete(
        vs.VolumeDeleteRequest(volume_id=int(flags["volumeId"]))
    )
    return "deleted"


@register("volume.move")
def volume_move(env: CommandEnv, args: list[str]) -> str:
    """Copy a volume to a target node, then delete from the source."""
    flags = _parse_flags(args)
    vid = int(flags["volumeId"])
    source, target = flags["source"], flags["target"]
    topo = env.topology()
    collection = ""
    for _dc, _rack, dn in _iter_nodes(topo):
        for disk in dn.disk_infos.values():
            for v in disk.volume_infos:
                if v.id == vid:
                    collection = v.collection
    env.volume_server(target).VolumeCopy(
        vs.VolumeCopyRequest(
            volume_id=vid, collection=collection, source_data_node=source
        )
    )
    env.volume_server(source).VolumeDelete(vs.VolumeDeleteRequest(volume_id=vid))
    return f"moved {vid} {source} -> {target}"


def find_misplaced_volumes(topo: master_pb2.TopologyInfo) -> dict[int, dict]:
    """Pure analysis: vid -> {want, have, locations} for under/over-replication."""
    placements: dict[int, dict] = {}
    for dc, rack, dn in _iter_nodes(topo):
        for disk in dn.disk_infos.values():
            for v in disk.volume_infos:
                p = placements.setdefault(
                    v.id,
                    {"want": ReplicaPlacement.from_byte(v.replica_placement)
                     .copy_count(), "locations": [], "collection": v.collection},
                )
                p["locations"].append((dc, rack, dn.id))
    return {
        vid: {**p, "have": len(p["locations"])}
        for vid, p in placements.items()
        if len(p["locations"]) != p["want"]
    }


@register("volume.fix.replication")
def volume_fix_replication(env: CommandEnv, args: list[str]) -> str:
    topo = env.topology()
    issues = find_misplaced_volumes(topo)
    if not issues:
        return "volume.fix.replication: all volumes healthy"
    nodes = {dn.id: dn for _dc, _rack, dn in _iter_nodes(topo)}
    fixed = []
    for vid, info in sorted(issues.items()):
        have, want = info["have"], info["want"]
        locs = [n for _dc, _rack, n in info["locations"]]
        if have < want:
            candidates = [
                nid for nid, dn in nodes.items()
                if nid not in locs and _free_slots(dn) > 0
            ]
            if not candidates:
                fixed.append(f"{vid}: under-replicated, no target")
                continue
            target = candidates[0]
            try:
                env.volume_server(_node_grpc(target)).VolumeCopy(
                    vs.VolumeCopyRequest(
                        volume_id=vid, collection=info["collection"],
                        source_data_node=_node_grpc(locs[0]),
                    )
                )
                fixed.append(f"{vid}: copied to {target}")
            except grpc.RpcError as e:
                fixed.append(f"{vid}: copy failed: {e.code()}")
        elif have > want:
            victim = locs[-1]
            try:
                env.volume_server(_node_grpc(victim)).VolumeDelete(
                    vs.VolumeDeleteRequest(volume_id=vid)
                )
                fixed.append(f"{vid}: removed extra replica on {victim}")
            except grpc.RpcError as e:
                fixed.append(f"{vid}: delete failed: {e.code()}")
    return "\n".join(fixed)


def _free_slots(dn) -> int:
    free = 0
    for disk in dn.disk_infos.values():
        free += max(disk.max_volume_count - disk.volume_count, 0)
    return free


@register("volume.balance")
def volume_balance(env: CommandEnv, args: list[str]) -> str:
    """Even out volume counts across nodes (greedy, like the reference)."""
    topo = env.topology()
    nodes = {dn.id: dn for _dc, _rack, dn in _iter_nodes(topo)}
    counts = {
        nid: sum(d.volume_count for d in dn.disk_infos.values())
        for nid, dn in nodes.items()
    }
    if not counts:
        return "volume.balance: no nodes"
    moves = []
    avg = sum(counts.values()) / len(counts)
    for nid in sorted(counts, key=counts.get, reverse=True):
        while counts[nid] > avg + 1:
            target = min(counts, key=counts.get)
            if counts[target] >= avg:
                break
            vid = _pick_volume_on(topo, nid)
            if vid is None:
                break
            try:
                run = volume_move(
                    env,
                    [f"-volumeId={vid}", f"-source={_node_grpc(nid)}",
                     f"-target={_node_grpc(target)}"],
                )
                moves.append(run)
                counts[nid] -= 1
                counts[target] += 1
                topo = env.topology()
            except grpc.RpcError:
                break
    return "volume.balance: " + ("; ".join(moves) if moves else "balanced")


def _pick_volume_on(topo, node_id: str):
    for _dc, _rack, dn in _iter_nodes(topo):
        if dn.id != node_id:
            continue
        for disk in dn.disk_infos.values():
            for v in disk.volume_infos:
                return v.id
    return None


@register("lock")
def lock_cmd(env: CommandEnv, args: list[str]) -> str:
    return "locked" if env.acquire_lock() else "lock busy"


@register("unlock")
def unlock_cmd(env: CommandEnv, args: list[str]) -> str:
    env.release_lock()
    return "unlocked"
