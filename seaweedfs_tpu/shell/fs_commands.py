"""Admin-shell filer namespace + collection + s3 bucket commands.

Reference surface: weed/shell/command_fs_*.go (ls/cat/du/tree/mv/cd/pwd,
meta save/load/cat), command_collection_{list,delete}.go and
command_s3_{bucket_create,bucket_delete,bucket_list,clean_uploads}.go.
The designs differ where Python allows: commands return their output as a
string (run_command contract in commands.py), paths resolve against a
per-env working directory, and traversal is plain recursion over the
paged ListEntries rpc rather than goroutine/channel pipelines.

fs.meta.save/load use the same on-disk format as the reference
(command_fs_meta_save.go:74-90): a stream of [u32 big-endian size]
[marshalled filer_pb.FullEntry] records, so .meta snapshots are
interchangeable at the wire level.
"""

from __future__ import annotations

import struct
import time

import grpc

from ..pb import filer_pb2, master_pb2
from ..s3api.filer_client import FilerClient
from .commands import CommandEnv, register

BUCKETS_DIR = "/buckets"
UPLOADS_DIR = ".uploads"


# ---------------------------------------------------------------------------
# env helpers


def _filer(env: CommandEnv) -> FilerClient:
    addr = env.option.get("filer")
    if not addr:
        raise ValueError("no filer configured; start the shell with -filer")
    return FilerClient(addr)


def _cwd(env: CommandEnv) -> str:
    return env.option.get("fs_cwd", "/")


def _resolve(env: CommandEnv, path: str | None) -> str:
    """Make an absolute filer path from a command argument."""
    cwd = _cwd(env)
    if not path or path == ".":
        return cwd
    if not path.startswith("/"):
        path = cwd.rstrip("/") + "/" + path
    # normalise //, trailing / (but keep root)
    parts = [p for p in path.split("/") if p and p != "."]
    out: list[str] = []
    for p in parts:
        if p == "..":
            if out:
                out.pop()
        else:
            out.append(p)
    return "/" + "/".join(out)


def _split(path: str) -> tuple[str, str]:
    path = path.rstrip("/") or "/"
    if path == "/":
        return "/", ""
    i = path.rindex("/")
    return (path[:i] or "/"), path[i + 1 :]


def _is_directory(client: FilerClient, path: str) -> bool:
    if path == "/":
        return True
    d, n = _split(path)
    e = client.find_entry(d, n)
    return e is not None and e.is_directory


def _iter_dir(client: FilerClient, directory: str, prefix: str = ""):
    """Yield every entry of a directory (FilerClient.iter_entries)."""
    yield from client.iter_entries(directory, prefix=prefix)


def _select(client: FilerClient, path: str):
    """Resolve a path argument the way the fs.* commands do: a directory
    yields its entries; a file/prefix yields matching siblings.
    Returns (directory, [entries])."""
    if _is_directory(client, path):
        return path, list(_iter_dir(client, path))
    d, n = _split(path)
    return d, [e for e in _iter_dir(client, d, prefix=n)]


def _flags(
    args: list[str], bools: tuple[str, ...] = ("l", "a", "r", "v", "force")
) -> tuple[set[str], dict[str, str], list[str]]:
    """Split ["-l", "-name", "x", "path"] into boolean flags, -key value
    options, and positionals.  Flags named in `bools` never consume the
    next token; anything else takes a value (-key value or -key=value)."""
    short: set[str] = set()
    opts: dict[str, str] = {}
    pos: list[str] = []
    i = 0
    while i < len(args):
        a = args[i]
        if a.startswith("-") and len(a) > 1:
            key = a.lstrip("-")
            if "=" in key:
                k, _, v = key.partition("=")
                opts[k] = v
            elif key in bools:
                short.add(key)
            elif all(c in bools for c in key):  # combined -la style
                short.update(key)
            elif i + 1 < len(args):
                opts[key] = args[i + 1]
                i += 1
            else:
                short.add(key)
            i += 1
        else:
            pos.append(a)
            i += 1
    return short, opts, pos


def _parse_duration(s: str) -> float:
    """"24h" / "90m" / "1.5h" / "300s" -> seconds."""
    units = {"s": 1.0, "m": 60.0, "h": 3600.0, "d": 86400.0}
    total, num = 0.0, ""
    for ch in s:
        if ch.isdigit() or ch == ".":
            num += ch
        elif ch in units and num:
            total += float(num) * units[ch]
            num = ""
        else:
            raise ValueError(f"bad duration {s!r}")
    if num:
        total += float(num)
    return total


# ---------------------------------------------------------------------------
# fs.* namespace commands


@register("fs.pwd")
def fs_pwd(env: CommandEnv, args: list[str]) -> str:
    return _cwd(env)


@register("fs.cd")
def fs_cd(env: CommandEnv, args: list[str]) -> str:
    client = _filer(env)
    target = _resolve(env, args[0] if args else "/")
    if not _is_directory(client, target):
        raise ValueError(f"not a directory: {target}")
    env.option["fs_cwd"] = target
    return target


@register("fs.ls")
def fs_ls(env: CommandEnv, args: list[str]) -> str:
    short, _, pos = _flags(args)
    long_format = "l" in short
    show_hidden = "a" in short
    client = _filer(env)
    path = _resolve(env, pos[0] if pos else None)
    directory, entries = _select(client, path)
    out = []
    n = 0
    for e in entries:
        if not show_hidden and e.name.startswith("."):
            continue
        n += 1
        if long_format:
            a = e.attributes
            kind = "d" if e.is_directory else "-"
            size = sum(c.size for c in e.chunks) or len(e.content)
            mtime = time.strftime(
                "%Y-%m-%d %H:%M", time.localtime(a.mtime or 0))
            out.append(
                f"{kind}{a.file_mode & 0o7777:04o} {a.uid:>5} {a.gid:>5} "
                f"{size:>12} {mtime} "
                f"{directory.rstrip('/')}/{e.name}"
            )
        else:
            out.append(e.name)
    if long_format:
        out.append(f"total {n}")
    return "\n".join(out)


@register("fs.cat")
def fs_cat(env: CommandEnv, args: list[str]) -> str:
    if not args:
        raise ValueError("fs.cat <path>")
    client = _filer(env)
    path = _resolve(env, args[0])
    if _is_directory(client, path):
        raise ValueError(f"{path} is a directory")
    status, _, body = client.get_object(path)
    if status != 200:
        raise ValueError(f"read {path}: HTTP {status}")
    return body.decode("utf-8", errors="replace")


@register("fs.du")
def fs_du(env: CommandEnv, args: list[str]) -> str:
    client = _filer(env)
    path = _resolve(env, args[0] if args else None)
    out: list[str] = []

    def walk(directory: str, prefix: str) -> tuple[int, int]:
        blocks = byte_count = 0
        for e in _iter_dir(client, directory, prefix=prefix):
            child = directory.rstrip("/") + "/" + e.name
            if e.is_directory:
                b, s = walk(child, "")
            else:
                b = len(e.chunks)
                s = sum(c.size for c in e.chunks) or len(e.content)
                out.append(f"block:{b:4d}\tbyte:{s:10d}\t{child}")
            blocks += b
            byte_count += s
        return blocks, byte_count

    if _is_directory(client, path):
        b, s = walk(path, "")
        out.append(f"block:{b:4d}\tbyte:{s:10d}\t{path}")
    else:
        d, n = _split(path)
        walk(d, n)
    return "\n".join(out)


@register("fs.tree")
def fs_tree(env: CommandEnv, args: list[str]) -> str:
    client = _filer(env)
    path = _resolve(env, args[0] if args else None)
    out: list[str] = []

    def walk(directory: str, prefix: str, indent: str) -> tuple[int, int]:
        dirs = files = 0
        entries = [e for e in _iter_dir(client, directory, prefix=prefix)]
        for i, e in enumerate(entries):
            last = i == len(entries) - 1
            out.append(f"{indent}{'└──' if last else '├──'} {e.name}")
            if e.is_directory:
                dirs += 1
                sub = indent + ("    " if last else "│   ")
                d2, f2 = walk(
                    directory.rstrip("/") + "/" + e.name, "", sub)
                dirs += d2
                files += f2
            else:
                files += 1
        return dirs, files

    if _is_directory(client, path):
        out.append(path)
        dirs, files = walk(path, "", "")
    else:
        d, n = _split(path)
        dirs, files = walk(d, n, "")
    out.append(f"{dirs} directories, {files} files")
    return "\n".join(out)


@register("fs.mv")
def fs_mv(env: CommandEnv, args: list[str]) -> str:
    if len(args) != 2:
        raise ValueError("fs.mv <source> <destination>")
    client = _filer(env)
    src = _resolve(env, args[0])
    dst = _resolve(env, args[1])
    src_dir, src_name = _split(src)
    # moving INTO an existing directory keeps the source name
    if _is_directory(client, dst):
        dst_dir, dst_name = dst, src_name
    else:
        dst_dir, dst_name = _split(dst)
    client.stub().AtomicRenameEntry(
        filer_pb2.AtomicRenameEntryRequest(
            old_directory=src_dir, old_name=src_name,
            new_directory=dst_dir, new_name=dst_name,
        )
    )
    return f"move: {src} => {dst_dir.rstrip('/')}/{dst_name}"


@register("fs.rm")
def fs_rm(env: CommandEnv, args: list[str]) -> str:
    short, _, pos = _flags(args)
    if not pos:
        raise ValueError("fs.rm [-r] <path>")
    client = _filer(env)
    path = _resolve(env, pos[0])
    d, n = _split(path)
    client.delete_entry(d, n, is_delete_data=True,
                        is_recursive="r" in short)
    return f"removed {path}"


# -- fs.meta.* --------------------------------------------------------------


def _walk_full_entries(client: FilerClient, directory: str):
    """BFS over the subtree rooted at `directory`, yielding FullEntry pbs
    (the fs.meta.save stream unit, command_fs_meta_save.go:83)."""
    queue = [directory]
    while queue:
        d = queue.pop(0)
        for e in _iter_dir(client, d):
            yield filer_pb2.FullEntry(dir=d, entry=e)
            if e.is_directory:
                queue.append(d.rstrip("/") + "/" + e.name)


@register("fs.meta.save")
def fs_meta_save(env: CommandEnv, args: list[str]) -> str:
    short, opts, pos = _flags(args)
    client = _filer(env)
    path = _resolve(env, pos[0] if pos else None)
    fname = opts.get("o")
    if not fname:
        host, _, port = env.option.get("filer", "filer:8888").partition(":")
        fname = f"{host}-{port}-{time.strftime('%Y%m%d-%H%M%S')}.meta"
    dirs = files = 0
    with open(fname, "wb") as f:
        for fe in _walk_full_entries(client, path):
            blob = fe.SerializeToString()
            f.write(struct.pack(">I", len(blob)))
            f.write(blob)
            if fe.entry.is_directory:
                dirs += 1
            else:
                files += 1
    return (f"total {dirs} directories, {files} files\n"
            f"meta data for {path} is saved to {fname}")


@register("fs.meta.load")
def fs_meta_load(env: CommandEnv, args: list[str]) -> str:
    if not args:
        raise ValueError("fs.meta.load <file.meta>")
    client = _filer(env)
    stub = client.stub()
    dirs = files = 0
    out = []
    with open(args[-1], "rb") as f:
        while True:
            hdr = f.read(4)
            if len(hdr) < 4:
                break
            (size,) = struct.unpack(">I", hdr)
            fe = filer_pb2.FullEntry()
            fe.ParseFromString(f.read(size))
            stub.CreateEntry(filer_pb2.CreateEntryRequest(
                directory=fe.dir, entry=fe.entry))
            out.append(
                f"load {fe.dir.rstrip('/')}/{fe.entry.name}")
            if fe.entry.is_directory:
                dirs += 1
            else:
                files += 1
    out.append(f"total {dirs} directories, {files} files")
    out.append(f"{args[-1]} is loaded.")
    return "\n".join(out)


@register("fs.meta.cat")
def fs_meta_cat(env: CommandEnv, args: list[str]) -> str:
    if not args:
        raise ValueError("fs.meta.cat <path>")
    client = _filer(env)
    path = _resolve(env, args[0])
    d, n = _split(path)
    e = client.find_entry(d, n)
    if e is None:
        raise ValueError(f"no entry {path}")
    return str(e)


# ---------------------------------------------------------------------------
# collection.* commands (master-side)


@register("collection.list")
def collection_list(env: CommandEnv, args: list[str]) -> str:
    resp = env.master().CollectionList(master_pb2.CollectionListRequest(
        include_normal_volumes=True, include_ec_volumes=True))
    out = [f'collection:"{c.name}"' for c in resp.collections]
    out.append(f"Total {len(resp.collections)} collections.")
    return "\n".join(out)


@register("collection.delete")
def collection_delete(env: CommandEnv, args: list[str]) -> str:
    _, opts, pos = _flags(args)
    name = opts.get("collection", pos[0] if pos else "")
    if not name:
        raise ValueError("collection.delete <name>")
    env.master().CollectionDelete(
        master_pb2.CollectionDeleteRequest(name=name))
    return f"collection {name} is deleted."


# ---------------------------------------------------------------------------
# s3.* bucket commands (filer-side, /buckets convention)


def _buckets_path(client: FilerClient) -> str:
    try:
        resp = client.stub().GetFilerConfiguration(
            filer_pb2.GetFilerConfigurationRequest())
        return resp.dir_buckets or BUCKETS_DIR
    except grpc.RpcError:
        return BUCKETS_DIR


@register("s3.bucket.list")
def s3_bucket_list(env: CommandEnv, args: list[str]) -> str:
    client = _filer(env)
    out = []
    for e in _iter_dir(client, _buckets_path(client)):
        if e.is_directory and not e.name.startswith("."):
            out.append(e.name)
    return "\n".join(out)


@register("s3.bucket.create")
def s3_bucket_create(env: CommandEnv, args: list[str]) -> str:
    _, opts, pos = _flags(args)
    name = opts.get("name", pos[0] if pos else "")
    if not name:
        raise ValueError("s3.bucket.create -name <bucket>")
    client = _filer(env)
    bp = _buckets_path(client)
    now = int(time.time())
    entry = filer_pb2.Entry(
        name=name, is_directory=True,
        attributes=filer_pb2.FuseAttributes(
            mtime=now, crtime=now, file_mode=0o40777,
            collection=name,
            replication=opts.get("replication", ""),
        ),
    )
    client.create_entry(bp, entry)
    return f"created bucket {name}"


@register("s3.bucket.delete")
def s3_bucket_delete(env: CommandEnv, args: list[str]) -> str:
    _, opts, pos = _flags(args)
    name = opts.get("name", pos[0] if pos else "")
    if not name:
        raise ValueError("s3.bucket.delete -name <bucket>")
    client = _filer(env)
    bp = _buckets_path(client)
    client.delete_entry(bp, name, is_delete_data=True, is_recursive=True)
    # the bucket's backing collection goes with it (reference deletes the
    # collection so the volumes are reclaimed, command_s3_bucket_delete.go)
    try:
        env.master().CollectionDelete(
            master_pb2.CollectionDeleteRequest(name=name))
    except grpc.RpcError:
        pass  # bucket may never have grown volumes
    return f"deleted bucket {name}"


@register("s3.clean.uploads")
def s3_clean_uploads(env: CommandEnv, args: list[str]) -> str:
    _, opts, _ = _flags(args)
    age_s = _parse_duration(opts.get("timeAgo", "24h"))
    client = _filer(env)
    bp = _buckets_path(client)
    now = time.time()
    out = []
    for bucket in _iter_dir(client, bp):
        if not bucket.is_directory:
            continue
        updir = f"{bp}/{bucket.name}/{UPLOADS_DIR}"
        for up in _iter_dir(client, updir):
            if up.attributes.crtime + age_s < now:
                client.delete_entry(
                    updir, up.name, is_delete_data=True, is_recursive=True)
                out.append(f"purge {updir}/{up.name}")
    return "\n".join(out)


@register("s3.configure")
def s3_configure(env: CommandEnv, args: list[str]) -> str:
    """Manage the s3 identity config stored in the filer
    (command_s3_configure.go; same /etc/iam/identity.json the IAM API and
    the gateway's live reload use).  Without -apply the (modified) config
    is only shown."""
    import json

    bools = ("l", "a", "r", "v", "force", "delete", "apply")
    short, opts, _pos = _flags(args, bools=bools)
    client = _filer(env)
    try:
        status, _, body = client.get_object("/etc/iam/identity.json")
        conf = json.loads(body) if status == 200 and body else {}
    except Exception:
        conf = {}
    conf.setdefault("identities", [])

    user = opts.get("user", "")
    actions = [a for a in opts.get("actions", "").split(",") if a]
    buckets = [b for b in opts.get("buckets", "").split(",") if b]
    if buckets:
        actions = [f"{a}:{b}" for a in actions for b in buckets]
    access_key = opts.get("access_key", "")
    secret_key = opts.get("secret_key", "")
    delete = "delete" in short

    if user:
        ident = next((i for i in conf["identities"]
                      if i.get("name") == user), None)
        if delete and not actions and not access_key:
            conf["identities"] = [i for i in conf["identities"]
                                  if i.get("name") != user]
        else:
            if ident is None and delete:
                # nothing to delete — do NOT materialise a phantom user
                return json.dumps(conf, indent=2)
            if ident is None:
                ident = {"name": user, "credentials": [], "actions": []}
                conf["identities"].append(ident)
            if access_key:
                if delete:
                    ident["credentials"] = [
                        c for c in ident.get("credentials", [])
                        if c.get("accessKey") != access_key]
                else:
                    ident.setdefault("credentials", []).append(
                        {"accessKey": access_key,
                         "secretKey": secret_key})
            if actions:
                if delete:
                    ident["actions"] = [
                        a for a in ident.get("actions", [])
                        if a not in actions]
                else:
                    for a in actions:
                        if a not in ident.setdefault("actions", []):
                            ident["actions"].append(a)

    rendered = json.dumps(conf, indent=2)
    if "apply" in short:
        client.put_object("/etc/iam/identity.json", rendered.encode(),
                          mime="application/json")
        return rendered + "\napplied."
    return rendered


@register("fs.configure")
def fs_configure(env: CommandEnv, args: list[str]) -> str:
    """Per-path storage rules stored at /etc/seaweedfs/filer.conf
    (command_fs_configure.go): writes under locationPrefix get the
    rule's collection/replication/ttl.  Without -apply the modified
    config is only displayed."""
    from ..filer.filer_conf import CONF_PATH, FilerConf

    bools = ("l", "a", "r", "v", "force", "delete", "apply")
    short, opts, _pos = _flags(args, bools=bools)
    client = _filer(env)
    status, _, body = client.get_object(CONF_PATH)
    if status == 200:
        conf = FilerConf.from_bytes(body)
    elif status == 404:
        conf = FilerConf()
    else:
        # a transient read error must NOT silently become an empty
        # config that -apply then persists, wiping every rule
        raise IOError(f"read {CONF_PATH}: HTTP {status}")

    prefix = opts.get("locationPrefix", "")
    if prefix:
        if opts.get("collection") and prefix.startswith("/buckets/"):
            raise ValueError(
                "one s3 bucket goes to one collection and is not "
                "customizable")
        # reject values the storage layer cannot parse BEFORE they can
        # break every write under the prefix (the reference validates
        # too, command_fs_configure.go)
        replication = opts.get("replication", "")
        if replication:
            from ..storage.replica_placement import ReplicaPlacement

            ReplicaPlacement.parse(replication)  # raises on bad input
        ttl = opts.get("ttl", "")
        if ttl:
            from ..storage.ttl import TTL

            parsed = TTL.parse(ttl)  # raises on non-numeric counts
            if str(parsed) != ttl:
                raise ValueError(
                    f"bad ttl {ttl!r}: units are m/h/d/w/M/y "
                    f"(parsed back as {str(parsed) or 'empty'!r})")
        if "delete" in short:
            conf.delete(prefix)
        else:
            conf.upsert({
                "locationPrefix": prefix,
                "collection": opts.get("collection", ""),
                "replication": replication,
                "ttl": ttl,
            })

    rendered = conf.to_bytes().decode()
    if "apply" in short:
        client.put_object(CONF_PATH, conf.to_bytes(),
                          mime="application/json")
        return rendered + "\napplied."
    return rendered


@register("fs.meta.notify")
def fs_meta_notify(env: CommandEnv, args: list[str]) -> str:
    """Re-publish every entry under a path as a create event to a
    notification backend (command_fs_meta_notify.go) — backfills a queue
    after enabling notifications.  Backend comes from notification.toml
    ([notification] kind = "file"/"log"/... plus backend options)."""
    from ..notification.publishers import make_publisher
    from ..util.config import load_configuration

    _short, opts, pos = _flags(args)
    # validate the filer + path BEFORE constructing the publisher, so a
    # failed precondition cannot leak an opened (file) backend
    client = _filer(env)
    path = _resolve(env, pos[0] if pos else None)
    if not _is_directory(client, path):
        raise ValueError(f"not a directory: {path}")
    conf = load_configuration("notification")
    if "backend" in opts:
        kind = opts["backend"]
    elif "path" in opts:
        kind = "file"  # an explicit -path always wins over toml selection
    else:
        kind = conf.get_string("notification.kind", "")
    publisher = None
    if not kind:
        # scaffolded schema: per-backend [notification.<kind>] enabled
        # flags — the same selection the filer server makes
        from ..notification import publisher_from_config

        publisher = publisher_from_config(conf)
        kind = "log"
    if publisher is None:
        pub_opts = {}
        if isinstance(conf.get(f"notification.{kind}"), dict):
            pub_opts = {k: v for k, v in
                        conf.get(f"notification.{kind}").items()
                        if k != "enabled"}
        if "path" in opts:
            pub_opts["path"] = opts["path"]
        if kind == "file" and not pub_opts.get("path"):
            raise ValueError(
                "the file backend needs -path <events file> (or a "
                "[notification.file] path in notification.toml)")
        publisher = make_publisher(kind, **pub_opts)
    dirs = files = 0
    try:
        for fe in _walk_full_entries(client, path):
            ev = filer_pb2.EventNotification()
            ev.new_entry.CopyFrom(fe.entry)
            publisher.publish(
                f"{fe.dir.rstrip('/')}/{fe.entry.name}", ev)
            if fe.entry.is_directory:
                dirs += 1
            else:
                files += 1
    finally:
        publisher.close()
    return f"notified {dirs} directories, {files} files"
