"""EC admin commands: ec.encode / ec.rebuild / ec.balance / ec.decode.

Client-side orchestration over gRPC, mirroring the reference's protocol
(command_ec_encode.go:24-35 documents the 6 steps):
  1. mark the volume readonly on every replica
  2. VolumeEcShardsGenerate on one holder (this is where `-codec=tpu` lands)
  3. spread shards: balanced allocation by free EC slots, targets PULL via
     VolumeEcShardsCopy, then VolumeEcShardsMount
  4. unmount + delete moved shards on the source
  5. delete the original volume from all replicas
Shard bookkeeping flows back to the master via heartbeat deltas.
"""

from __future__ import annotations

import time

import grpc

from ..pb import master_pb2
from ..pb import volume_server_pb2 as vs
from ..storage.ec.constants import TOTAL_SHARDS
from ..storage.ec.shard_bits import ShardBits
from ..topology.placement import balanced_ec_distribution
from .commands import CommandEnv, register


def _parse_flags(args: list[str]) -> dict[str, str]:
    out = {}
    for a in args:
        if a.startswith("-"):
            k, _, v = a.lstrip("-").partition("=")
            out[k] = v if v else "true"
    return out


def _iter_nodes(topo: master_pb2.TopologyInfo):
    for dc in topo.data_center_infos:
        for rack in dc.rack_infos:
            for dn in rack.data_node_infos:
                yield dc.id, rack.id, dn


def _node_grpc(dn_id: str) -> str:
    host, port = dn_id.rsplit(":", 1)
    return f"{host}:{int(port) + 10000}"


def _volume_locations(topo, vid: int) -> list[str]:
    out = []
    for _dc, _rack, dn in _iter_nodes(topo):
        for disk in dn.disk_infos.values():
            for v in disk.volume_infos:
                if v.id == vid:
                    out.append(dn.id)
    return out


def _free_ec_slots(dn) -> int:
    free = 0
    for disk in dn.disk_infos.values():
        used_shards = sum(
            ShardBits(e.ec_index_bits).count() for e in disk.ec_shard_infos
        )
        free += max(
            (disk.max_volume_count - disk.volume_count) * 10 - used_shards, 0
        )
    return free


def collect_volume_ids_for_ec_encode(
    topo: master_pb2.TopologyInfo,
    volume_size_limit: int,
    full_percent: float,
    collection: str = "",
    quiet_for_seconds: float = 0,
    now: float | None = None,
) -> list[int]:
    """Pure selection logic (tier-3 testable): volumes full enough to
    freeze AND quiet for the requested window — encoding a volume under
    an active write burst would readonly it mid-stream
    (command_ec_encode.go collectVolumeIdsForEcEncode)."""
    if now is None:
        now = time.time()
    vids = set()
    for _dc, _rack, dn in _iter_nodes(topo):
        for disk in dn.disk_infos.values():
            for v in disk.volume_infos:
                if collection and v.collection != collection:
                    continue
                if v.size < volume_size_limit * full_percent / 100.0:
                    continue
                if (quiet_for_seconds > 0 and v.modified_at_second
                        and now - v.modified_at_second < quiet_for_seconds):
                    continue
                vids.add(v.id)
    return sorted(vids)


@register("ec.encode")
def ec_encode(env: CommandEnv, args: list[str]) -> str:
    from .fs_commands import _parse_duration

    flags = _parse_flags(args)
    collection = flags.get("collection", "")
    full_percent = float(flags.get("fullPercent", "95"))
    quiet_for = _parse_duration(flags.get("quietFor", "0"))
    codec = flags.get("codec", "")
    explicit_vid = int(flags["volumeId"]) if "volumeId" in flags else None

    topo = env.topology()
    limit = env.volume_size_limit()
    if explicit_vid is not None:
        vids = [explicit_vid]
    else:
        vids = collect_volume_ids_for_ec_encode(
            topo, limit, full_percent, collection,
            quiet_for_seconds=quiet_for,
        )
    # every volume encodes under its OWN collection — the flag only
    # FILTERS the selection; passing it through verbatim would generate
    # shards under one name and try to mount them under another
    vid_collection: dict[int, str] = {}
    for _dc, _rack, dn in _iter_nodes(topo):
        for disk in dn.disk_infos.values():
            for v in disk.volume_infos:
                vid_collection[v.id] = v.collection
    out = []
    for vid in vids:
        out.append(do_ec_encode(
            env, topo, vid, vid_collection.get(vid, collection), codec))
    return "\n".join(out) if out else "ec.encode: no volumes selected"


def do_ec_encode(env: CommandEnv, topo, vid: int, collection: str,
                 codec: str = "", delete_source: bool = True,
                 leader_epoch: int = 0) -> str:
    """Encode one volume to EC shards and spread them.

    `delete_source=False` (the lifecycle controller's tier pipeline)
    keeps the sealed source volume mounted read-only after the shards
    mount, so its .dat can still move to a remote tier — the reference
    flow (and the default) deletes the original from every replica."""
    locations = _volume_locations(topo, vid)
    if not locations:
        # freshly grown volumes may not be in the heartbeat snapshot yet;
        # the master's layout-backed lookup has them immediately
        resp = env.master().LookupVolume(
            master_pb2.LookupVolumeRequest(volume_or_file_ids=[str(vid)])
        )
        for entry in resp.volume_id_locations:
            locations = [loc.url for loc in entry.locations]
    if not locations:
        return f"ec.encode {vid}: no locations"
    if not collection:
        # a volume outside the heartbeat snapshot (LookupVolume fallback)
        # must still encode under its OWN collection — ask its holder
        try:
            st = env.volume_server(_node_grpc(locations[0])) \
                .ReadVolumeFileStatus(
                    vs.ReadVolumeFileStatusRequest(volume_id=vid))
            collection = st.collection
        except grpc.RpcError:
            pass
    # 1. freeze writes on every replica (`leader_epoch` fences the
    # lifecycle-driven path; 0 = an operator at the shell, unfenced)
    for loc in locations:
        env.volume_server(_node_grpc(loc)).VolumeMarkReadonly(
            vs.VolumeMarkReadonlyRequest(
                volume_id=vid, leader_epoch=leader_epoch)
        )
    source = locations[0]
    # 2. generate shards on the source (the TPU codec dispatch point)
    env.volume_server(_node_grpc(source)).VolumeEcShardsGenerate(
        vs.VolumeEcShardsGenerateRequest(
            volume_id=vid, collection=collection, codec=codec,
            leader_epoch=leader_epoch,
        )
    )
    # 3. spread shards by free EC slots
    nodes = {dn.id: dn for _dc, _rack, dn in _iter_nodes(topo)}
    free = {nid: _free_ec_slots(dn) for nid, dn in nodes.items()}
    free[source] = max(free.get(source, 0), 1)  # source can keep shards
    plan = balanced_ec_distribution(free, TOTAL_SHARDS)
    moved_from_source = []
    for target, sids in plan.items():
        if target == source:
            env.volume_server(_node_grpc(source)).VolumeEcShardsMount(
                vs.VolumeEcShardsMountRequest(
                    volume_id=vid, collection=collection, shard_ids=sids
                )
            )
            continue
        env.volume_server(_node_grpc(target)).VolumeEcShardsCopy(
            vs.VolumeEcShardsCopyRequest(
                volume_id=vid,
                collection=collection,
                shard_ids=sids,
                copy_ecx_file=True,
                copy_ecj_file=True,
                copy_vif_file=True,
                copy_from_data_node=_node_grpc(source),
                leader_epoch=leader_epoch,
            )
        )
        env.volume_server(_node_grpc(target)).VolumeEcShardsMount(
            vs.VolumeEcShardsMountRequest(
                volume_id=vid, collection=collection, shard_ids=sids
            )
        )
        moved_from_source.extend(sids)
    # 4. drop moved shard files from the source
    if moved_from_source:
        env.volume_server(_node_grpc(source)).VolumeEcShardsDelete(
            vs.VolumeEcShardsDeleteRequest(
                volume_id=vid, collection=collection,
                shard_ids=moved_from_source,
            )
        )
    # 5. delete the original volume everywhere (unless the caller keeps
    # the sealed source for a later tier move)
    if delete_source:
        for loc in locations:
            env.volume_server(_node_grpc(loc)).VolumeDelete(
                vs.VolumeDeleteRequest(
                    volume_id=vid, leader_epoch=leader_epoch)
            )
    return f"ec.encode {vid}: spread {dict((k, v) for k, v in plan.items())}"


@register("ec.rebuild")
def ec_rebuild(env: CommandEnv, args: list[str]) -> str:
    """ec.rebuild [-plan] [-gather] [-codec=NAME]

    Default: the rebuilder regenerates missing shards IN PLACE, sourcing
    remote intervals through the partial-sum protocol (or full interval
    streams when partials are unavailable) — no shard files are staged.
    `-gather` restores the legacy copy-everything-first flow.  `-plan`
    is a DRY RUN: print the chosen sources per lost shard with rack/DC
    and the expected bytes over each hop, touch nothing."""
    flags = _parse_flags(args)
    codec = flags.get("codec", "")
    plan_only = "plan" in flags
    gather = "gather" in flags
    topo = env.topology()
    node_locality: dict[str, tuple[str, str]] = {}
    # vid -> {node_id: bits}
    holdings: dict[int, dict[str, ShardBits]] = {}
    collections: dict[int, str] = {}
    for dc, rack, dn in _iter_nodes(topo):
        node_locality[dn.id] = (rack, dc)
        for disk in dn.disk_infos.values():
            for e in disk.ec_shard_infos:
                holdings.setdefault(e.id, {})[dn.id] = ShardBits(e.ec_index_bits)
                collections[e.id] = e.collection
    out = []
    for vid, by_node in sorted(holdings.items()):
        have = ShardBits(0)
        for bits in by_node.values():
            have = have.plus(bits)
        count = have.count()
        if count == TOTAL_SHARDS:
            continue
        if count < 10:
            out.append(f"ec.rebuild {vid}: unrepairable ({count} shards)")
            continue
        if plan_only:
            out.append(_plan_one(
                env, vid, by_node, have, node_locality))
        else:
            out.append(_rebuild_one(
                env, vid, collections.get(vid, ""), by_node, have, codec,
                gather=gather))
    return "\n".join(out) if out else "ec.rebuild: nothing to do"


def _rebuild_plan(vid: int, by_node: dict[str, ShardBits], have: ShardBits,
                  node_locality: dict[str, tuple[str, str]]) -> dict:
    """Pure planning for one volume's partial-sum rebuild (tier-3
    testable): rebuilder, lost shards, locality-ordered sources, and the
    per-rack aggregation groups the protocol will form."""
    from ..topology.placement import (
        best_ec_holder,
        group_partial_sources,
        order_ec_sources,
    )

    rebuilder = max(by_node, key=lambda n: by_node[n].count())
    my_rack, my_dc = node_locality.get(rebuilder, ("", ""))
    local = sorted(by_node[rebuilder].shard_ids())
    lost = [s for s in range(TOTAL_SHARDS) if not have.has(s)]
    # best holder per non-local shard: same-rack holders win
    candidates: dict[int, list[tuple[str, str, str]]] = {}
    for node, bits in by_node.items():
        if node == rebuilder:
            continue
        rack, dc = node_locality.get(node, ("", ""))
        for sid in bits.shard_ids():
            if sid not in local:
                candidates.setdefault(sid, []).append((node, rack, dc))
    holders = {sid: best_ec_holder(cands, my_rack, my_dc)
               for sid, cands in candidates.items()}
    sources = local[:10]
    chosen: dict[int, tuple[str, str, str]] = {}
    for sid in order_ec_sources(holders, my_rack, my_dc):
        if len(sources) >= 10:
            break
        sources.append(sid)
        chosen[sid] = holders[sid]
    return {
        "rebuilder": rebuilder,
        "rebuilder_rack": my_rack,
        "rebuilder_dc": my_dc,
        "lost": lost,
        "local_sources": sources[: len(sources) - len(chosen)],
        "remote_sources": chosen,
        "groups": group_partial_sources(chosen),
    }


def _plan_one(env: CommandEnv, vid: int, by_node: dict[str, ShardBits],
              have: ShardBits,
              node_locality: dict[str, tuple[str, str]]) -> str:
    from ..storage.ec.partial import probe_shard_size
    from ..topology.placement import ec_source_locality

    plan = _rebuild_plan(vid, by_node, have, node_locality)
    rebuilder = plan["rebuilder"]
    m = len(plan["lost"])
    try:
        shard_size = probe_shard_size(
            env.volume_server(_node_grpc(rebuilder)), vid)
    except grpc.RpcError:
        shard_size = 0

    def mb(n: int) -> str:
        return f"{n / 1e6:.1f} MB" if shard_size else f"{n}x shard"

    unit = shard_size if shard_size else 1
    lines = [
        f"ec.rebuild {vid} (plan): lost {plan['lost']} -> rebuilder "
        f"{rebuilder} ({plan['rebuilder_dc']}/{plan['rebuilder_rack']})"
        + (f", shard {mb(unit)}" if shard_size else ""),
        f"  local sources {plan['local_sources']}: 0 B over the wire",
    ]
    ingress = 0
    for g in plan["groups"]:
        label = ec_source_locality(
            g["rack"], g["dc"], plan["rebuilder_rack"], plan["rebuilder_dc"])
        member_s = " + ".join(
            f"{addr}{sids}" for addr, sids in sorted(g["members"].items()))
        intra = sum(len(s) for a, s in g["members"].items()
                    if a != g["aggregator"])
        lines.append(
            f"  {label:4s} {g['dc']}/{g['rack']}: {member_s} -> agg "
            f"{g['aggregator']}, {mb(m * unit)} to rebuilder"
            + (f" (+{mb(m * unit * intra)} intra-rack)" if intra else ""))
        ingress += m * unit
    full = len(plan["remote_sources"]) * unit
    if plan["remote_sources"]:
        ratio = full / ingress if ingress else 0.0
        lines.append(
            f"  partial ingress {mb(ingress)} vs full fetch {mb(full)} "
            f"({ratio:.1f}x)"
            + ("" if ratio >= 1.0 else
               " — full fetch preferred (rebuilder chooses it)"))
    return "\n".join(lines)


def _rebuild_one(env: CommandEnv, vid: int, collection: str,
                 by_node: dict[str, ShardBits], have: ShardBits,
                 codec: str = "", gather: bool = False) -> str:
    # rebuilder = node already holding the most shards
    rebuilder = max(by_node, key=lambda n: by_node[n].count())
    stub = env.volume_server(_node_grpc(rebuilder))
    local = by_node[rebuilder]
    if gather:
        # legacy flow: pull every shard the rebuilder lacks before the
        # local rebuild (moves full shard widths; kept for operators on
        # clusters with partial-apply disabled)
        for node, bits in by_node.items():
            if node == rebuilder:
                continue
            need = [s for s in bits.shard_ids() if not local.has(s)]
            if not need:
                continue
            stub.VolumeEcShardsCopy(
                vs.VolumeEcShardsCopyRequest(
                    volume_id=vid, collection=collection, shard_ids=need,
                    copy_from_data_node=_node_grpc(node),
                )
            )
            for s in need:
                local = local.add(s)
    resp = stub.VolumeEcShardsRebuild(
        vs.VolumeEcShardsRebuildRequest(
            volume_id=vid, collection=collection, codec=codec)
    )
    rebuilt = list(resp.rebuilt_shard_ids)
    if rebuilt:
        stub.VolumeEcShardsMount(
            vs.VolumeEcShardsMountRequest(
                volume_id=vid, collection=collection, shard_ids=rebuilt
            )
        )
    # drop the staging copies that are mounted elsewhere
    staged = [
        s for s in local.shard_ids()
        if s not in rebuilt and not by_node[rebuilder].has(s)
    ]
    if staged:
        stub.VolumeEcShardsDelete(
            vs.VolumeEcShardsDeleteRequest(
                volume_id=vid, collection=collection, shard_ids=staged
            )
        )
    return f"ec.rebuild {vid}: rebuilt {rebuilt} on {rebuilder}"


def plan_ec_balance_moves(topo, collection: str = "") -> list[dict]:
    """Pure shard-move planning from one topology snapshot (tier-3
    testable; -collection scopes both the counting and the moves,
    command_ec_balance.go)."""
    nodes = {dn.id: dn for _dc, _rack, dn in _iter_nodes(topo)}
    free = {nid: _free_ec_slots(dn) for nid, dn in nodes.items()}
    on_node: dict[str, list[tuple[int, int, str]]] = {n: [] for n in nodes}
    for _dc, _rack, dn in _iter_nodes(topo):
        for disk in dn.disk_infos.values():
            for e in disk.ec_shard_infos:
                if collection and e.collection != collection:
                    continue
                for sid in ShardBits(e.ec_index_bits).shard_ids():
                    on_node[dn.id].append((e.id, sid, e.collection))
    shard_count = {nid: len(s) for nid, s in on_node.items()}
    if not any(shard_count.values()):
        return []
    moves: list[dict] = []
    avg = sum(shard_count.values()) / max(len(shard_count), 1)
    for nid in list(nodes):
        while shard_count[nid] > avg + 1:
            target = max(
                free, key=lambda n: (free[n] - shard_count[n], n != nid))
            if target == nid or free[target] <= 0 or not on_node[nid]:
                break
            vid, sid, coll = on_node[nid].pop(0)
            moves.append({"volumeId": vid, "shardId": sid,
                          "collection": coll,
                          "source": nid, "target": target})
            shard_count[nid] -= 1
            shard_count[target] = shard_count.get(target, 0) + 1
            free[target] -= 1
    return moves


def apply_ec_move(env: CommandEnv, move: dict) -> str:
    """Execute one planned shard move: copy+mount on the target, then
    unmount+delete on the source (the two-phase order keeps the shard
    readable throughout)."""
    vid, sid = move["volumeId"], move["shardId"]
    coll = move.get("collection", "")
    source, target = move["source"], move["target"]
    tgt = env.volume_server(_node_grpc(target))
    tgt.VolumeEcShardsCopy(
        vs.VolumeEcShardsCopyRequest(
            volume_id=vid, collection=coll, shard_ids=[sid],
            copy_ecx_file=True, copy_ecj_file=True, copy_vif_file=True,
            copy_from_data_node=_node_grpc(source),
        )
    )
    tgt.VolumeEcShardsMount(
        vs.VolumeEcShardsMountRequest(
            volume_id=vid, collection=coll, shard_ids=[sid])
    )
    src = env.volume_server(_node_grpc(source))
    src.VolumeEcShardsUnmount(
        vs.VolumeEcShardsUnmountRequest(volume_id=vid, shard_ids=[sid])
    )
    src.VolumeEcShardsDelete(
        vs.VolumeEcShardsDeleteRequest(
            volume_id=vid, collection=coll, shard_ids=[sid])
    )
    return f"{vid}.{sid} {source} -> {target}"


@register("ec.balance")
def ec_balance(env: CommandEnv, args: list[str]) -> str:
    """Move shards from loaded nodes to nodes with more free EC slots.

    ec.balance [-apply] [-collection=NAME]  — default is a DRY RUN that
    prints the planned moves; -apply (or the legacy -force) executes
    them (command_ec_balance.go)."""
    flags = _parse_flags(args)
    apply_changes = "apply" in flags or "force" in flags
    collection = flags.get("collection", "")
    moves = plan_ec_balance_moves(env.topology(), collection)
    if not moves:
        return "ec.balance: balanced"
    lines = [f"ec.balance: {len(moves)} move(s) planned"]
    for mv in moves:
        lines.append(
            f"  {mv['volumeId']}.{mv['shardId']} {mv['source']} -> "
            f"{mv['target']}"
            + ("" if apply_changes else " (dry run, -apply to move)"))
    if not apply_changes:
        return "\n".join(lines)
    for mv in moves:
        try:
            lines.append(apply_ec_move(env, mv))
        except grpc.RpcError as e:
            lines.append(f"  {mv['volumeId']}.{mv['shardId']} FAILED: "
                         f"{e.code()}")
            break
    return "\n".join(lines)


@register("ec.decode")
def ec_decode(env: CommandEnv, args: list[str]) -> str:
    flags = _parse_flags(args)
    vid = int(flags["volumeId"]) if "volumeId" in flags else None
    collection = flags.get("collection", "")
    topo = env.topology()
    holdings: dict[int, dict[str, ShardBits]] = {}
    collections: dict[int, str] = {}
    for _dc, _rack, dn in _iter_nodes(topo):
        for disk in dn.disk_infos.values():
            for e in disk.ec_shard_infos:
                holdings.setdefault(e.id, {})[dn.id] = ShardBits(e.ec_index_bits)
                collections[e.id] = e.collection
    targets = [vid] if vid is not None else sorted(holdings)
    out = []
    for v in targets:
        by_node = holdings.get(v)
        if not by_node:
            out.append(f"ec.decode {v}: no shards")
            continue
        coll = collection or collections.get(v, "")
        # gather all shards onto the node with the most
        gather = max(by_node, key=lambda n: by_node[n].count())
        stub = env.volume_server(_node_grpc(gather))
        local = by_node[gather]
        for node, bits in by_node.items():
            if node == gather:
                continue
            need = [s for s in bits.shard_ids() if not local.has(s)]
            if need:
                stub.VolumeEcShardsCopy(
                    vs.VolumeEcShardsCopyRequest(
                        volume_id=v, collection=coll, shard_ids=need,
                        copy_ecx_file=True, copy_ecj_file=True,
                        copy_from_data_node=_node_grpc(node),
                    )
                )
                for s in need:
                    local = local.add(s)
        stub.VolumeEcShardsToVolume(
            vs.VolumeEcShardsToVolumeRequest(volume_id=v, collection=coll)
        )
        # drop EC remnants cluster-wide
        for node in by_node:
            env.volume_server(_node_grpc(node)).VolumeEcShardsDelete(
                vs.VolumeEcShardsDeleteRequest(
                    volume_id=v, collection=coll,
                    shard_ids=list(range(TOTAL_SHARDS)),
                )
            )
        out.append(f"ec.decode {v}: restored on {gather}")
    return "\n".join(out)
