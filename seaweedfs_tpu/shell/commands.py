"""Admin shell core: CommandEnv, registry, and the maintenance script.

Reference: weed/shell/commands.go (CommandEnv + exclusive admin lock) and
master_server.go:187-242 (the [master.maintenance] loop that runs
`ec.encode; ec.rebuild; ec.balance; volume.balance; volume.fix.replication`
every 17 minutes under the admin lock).
"""

from __future__ import annotations

import shlex
from dataclasses import dataclass, field

import grpc

from ..pb import master_pb2
from ..pb import rpc as rpclib


@dataclass
class CommandEnv:
    master_grpc: str  # "ip:grpc_port"
    locked_token: int = 0
    option: dict = field(default_factory=dict)

    def master(self) -> rpclib.Stub:
        return rpclib.master_stub(self.master_grpc, timeout=60)

    def volume_server(self, grpc_address: str) -> rpclib.Stub:
        return rpclib.volume_server_stub(grpc_address, timeout=600)

    def topology(self) -> master_pb2.TopologyInfo:
        return self.master().VolumeList(master_pb2.VolumeListRequest()).topology_info

    def volume_size_limit(self) -> int:
        resp = self.master().VolumeList(master_pb2.VolumeListRequest())
        return resp.volume_size_limit_mb * (1 << 20)

    # -- exclusive admin lock (wdclient/exclusive_locks analogue) ---------

    def acquire_lock(self) -> bool:
        try:
            resp = self.master().LeaseAdminToken(
                master_pb2.LeaseAdminTokenRequest(
                    previous_token=self.locked_token, lock_name="admin"
                )
            )
            self.locked_token = resp.token
            return True
        except grpc.RpcError:
            return False

    def release_lock(self) -> None:
        if self.locked_token:
            try:
                self.master().ReleaseAdminToken(
                    master_pb2.ReleaseAdminTokenRequest(
                        previous_token=self.locked_token, lock_name="admin"
                    )
                )
            except grpc.RpcError:
                pass
            self.locked_token = 0


COMMANDS: dict[str, object] = {}


def register(name: str):
    def deco(fn):
        COMMANDS[name] = fn
        return fn

    return deco


def run_command(env: CommandEnv, line: str) -> str:
    """Run one shell command line; returns its output text."""
    parts = shlex.split(line)
    if not parts:
        return ""
    name, args = parts[0], parts[1:]
    fn = COMMANDS.get(name)
    if fn is None:
        raise ValueError(
            f"unknown command {name!r}; available: {', '.join(sorted(COMMANDS))}"
        )
    return fn(env, args) or ""


DEFAULT_MAINTENANCE_SCRIPT = (
    # the scaffold default block, line-for-line (command/scaffold.go:503-518;
    # lock/unlock are implicit — run_maintenance holds the admin lock)
    "ec.encode -fullPercent=95 -quietFor=1h",
    "ec.rebuild -force",
    "ec.balance -force",
    "volume.balance -force",
    "volume.fix.replication",
)


def run_maintenance(env: CommandEnv, script=None) -> list[str]:
    """The [master.maintenance] script block (scaffold.go:503-518).

    `script` is a list of shell command lines (from master.toml's
    [master.maintenance].scripts); None runs the scaffold default.
    """
    out = []
    if not env.acquire_lock():
        return ["maintenance: admin lock busy"]
    try:
        for line in script if script is not None else DEFAULT_MAINTENANCE_SCRIPT:
            try:
                out.append(f"> {line}\n{run_command(env, line)}")
            except Exception as e:
                out.append(f"> {line}\nerror: {e}")
    finally:
        env.release_lock()
    return out


# import command modules for registration side effects
from . import cluster_commands  # noqa: E402,F401
from . import ec_commands  # noqa: E402,F401
from . import fs_commands  # noqa: E402,F401
from . import volume_commands  # noqa: E402,F401
