from .commands import CommandEnv, run_command  # noqa: F401
