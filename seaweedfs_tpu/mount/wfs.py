"""WFS: the mount filesystem core over the filer gRPC API.

Reference: weed/filesys/wfs.go:29-50 (the FS object: filer client, meta
cache, chunk cache, handle table), wfs_write.go (chunk save through filer
AssignVolume + direct volume-server upload), dirty_page_interval.go (write
buffering), file.go / dir.go (node ops), wfs_filer_client.go.

This object is deliberately kernel-agnostic: every operation is plain
(path, bytes) -> result, so the same code serves the libfuse ctypes
binding (mount.fuse), tests, and any userspace client.  All durable state
lives in the filer; WFS holds only caches and in-flight dirty pages.
"""

from __future__ import annotations

import errno
import os
import stat as stat_mod
import threading
import time

import grpc

from ..filer import filechunks
from ..operation import download, upload_data
from ..pb import filer_pb2
from ..pb import rpc as rpclib
from ..util.chunk_cache import TieredChunkCache
from .dirty_pages import ContinuousIntervals
from .meta_cache import MetaCache, _split


class FuseError(OSError):
    def __init__(self, errno_: int, msg: str = ""):
        super().__init__(errno_, msg or os.strerror(errno_))


class WFS:
    def __init__(
        self,
        filer_grpc: str,
        filer_http: str = "",
        chunk_size_mb: int = 4,
        collection: str = "",
        replication: str = "",
        ttl_sec: int = 0,
        cache_dir: str | None = None,
        cache_mem_mb: int = 32,
        uid: int | None = None,
        gid: int | None = None,
    ):
        self.filer_grpc = filer_grpc
        self.filer_http = filer_http
        self.chunk_size = chunk_size_mb << 20
        self.collection = collection
        self.replication = replication
        self.ttl_sec = ttl_sec
        self.uid = os.getuid() if uid is None else uid
        self.gid = os.getgid() if gid is None else gid
        self.meta = MetaCache()
        self.chunks = TieredChunkCache(
            mem_limit_bytes=cache_mem_mb << 20,
            mem_max_entry=self.chunk_size,
            disk_dir=cache_dir,
        )
        self._handles: dict[int, FileHandle] = {}
        self._next_fh = 1
        self._lock = threading.Lock()
        self._vid_cache: dict[str, tuple[float, list[str]]] = {}
        self._subscriber: threading.Thread | None = None
        self._stop = threading.Event()

    # -- filer plumbing ----------------------------------------------------

    def _stub(self, timeout: float = 30.0):
        return rpclib.filer_stub(self.filer_grpc, timeout=timeout)

    def lookup_entry(self, path: str):
        path = path.rstrip("/") or "/"
        if path == "/":
            e = filer_pb2.Entry(name="/", is_directory=True)
            e.attributes.file_mode = 0o755
            return e
        cached = self.meta.get(path)
        if cached is not None:
            return cached
        directory, name = _split(path)
        if self.meta.is_dir_listed(directory):
            return None  # authoritative listing says it doesn't exist
        try:
            resp = self._stub().LookupDirectoryEntry(
                filer_pb2.LookupDirectoryEntryRequest(
                    directory=directory, name=name
                )
            )
        except grpc.RpcError as e:
            if e.code() == grpc.StatusCode.NOT_FOUND:
                return None
            raise
        self.meta.put(path, resp.entry)
        return resp.entry

    def list_dir(self, path: str) -> list[filer_pb2.Entry]:
        path = path.rstrip("/") or "/"
        if self.meta.is_dir_listed(path):
            return sorted(self.meta.children(path), key=lambda e: e.name)
        entries = [
            r.entry
            for r in self._stub(timeout=60).ListEntries(
                filer_pb2.ListEntriesRequest(directory=path, limit=100000)
            )
        ]
        self.meta.mark_dir_listed(path, entries)
        return entries

    def _create(self, directory: str, entry, o_excl: bool = False) -> None:
        resp = self._stub().CreateEntry(
            filer_pb2.CreateEntryRequest(
                directory=directory, entry=entry, o_excl=o_excl
            )
        )
        if resp.error:
            raise FuseError(errno.EEXIST, resp.error)
        base = directory.rstrip("/") or ""
        self.meta.put(f"{base}/{entry.name}", entry)

    def _update(self, directory: str, entry) -> None:
        self._stub().UpdateEntry(
            filer_pb2.UpdateEntryRequest(directory=directory, entry=entry)
        )
        base = directory.rstrip("/") or ""
        self.meta.put(f"{base}/{entry.name}", entry)

    # -- namespace operations ---------------------------------------------

    def getattr(self, path: str) -> dict:
        entry = self.lookup_entry(path)
        if entry is None:
            raise FuseError(errno.ENOENT)
        return self.attrs_of(path, entry)

    def attrs_of(self, path: str, entry) -> dict:
        a = entry.attributes
        if entry.is_directory:
            mode = stat_mod.S_IFDIR | (a.file_mode & 0o7777 or 0o755)
        elif a.symlink_target:
            mode = stat_mod.S_IFLNK | 0o777
        else:
            mode = stat_mod.S_IFREG | (a.file_mode & 0o7777 or 0o644)
        size = max(a.file_size, filechunks.total_size(entry.chunks))
        if entry.content:
            size = max(size, len(entry.content))
        # open write-back handles know a newer size than the filer does
        with self._lock:
            for h in self._handles.values():
                if h.path == path:
                    size = max(size, h.size())
        return {
            "st_mode": mode,
            "st_size": size,
            "st_uid": a.uid or self.uid,
            "st_gid": a.gid or self.gid,
            "st_mtime": a.mtime or int(time.time()),
            "st_ctime": a.crtime or a.mtime or int(time.time()),
            "st_atime": a.mtime or int(time.time()),
            "st_nlink": max(1, entry.hard_link_counter),
            "st_blocks": (size + 511) // 512,
        }

    def mkdir(self, path: str, mode: int = 0o755) -> None:
        directory, name = _split(path)
        entry = filer_pb2.Entry(name=name, is_directory=True)
        entry.attributes.file_mode = mode & 0o7777
        entry.attributes.crtime = int(time.time())
        entry.attributes.mtime = int(time.time())
        entry.attributes.uid = self.uid
        entry.attributes.gid = self.gid
        self._create(directory, entry, o_excl=True)

    def mknod(self, path: str, mode: int = 0o644) -> None:
        directory, name = _split(path)
        entry = filer_pb2.Entry(name=name, is_directory=False)
        entry.attributes.file_mode = mode & 0o7777
        entry.attributes.crtime = int(time.time())
        entry.attributes.mtime = int(time.time())
        entry.attributes.uid = self.uid
        entry.attributes.gid = self.gid
        entry.attributes.collection = self.collection
        entry.attributes.replication = self.replication
        entry.attributes.ttl_sec = self.ttl_sec
        self._create(directory, entry, o_excl=False)

    def unlink(self, path: str) -> None:
        directory, name = _split(path)
        cached = self.meta.get(path)
        resp = self._stub().DeleteEntry(
            filer_pb2.DeleteEntryRequest(
                directory=directory, name=name, is_delete_data=True
            )
        )
        if resp.error:
            raise FuseError(errno.ENOENT, resp.error)
        self.meta.delete(path)
        if cached is not None and cached.hard_link_id:
            # sibling links' cached st_nlink went stale with this unlink
            self.meta.invalidate_hardlink(cached.hard_link_id)

    def rmdir(self, path: str) -> None:
        if self.list_dir(path):
            raise FuseError(errno.ENOTEMPTY)
        directory, name = _split(path)
        self._stub().DeleteEntry(
            filer_pb2.DeleteEntryRequest(
                directory=directory, name=name,
                is_recursive=True, is_delete_data=True,
            )
        )
        self.meta.delete(path)

    def rename(self, old: str, new: str) -> None:
        od, on = _split(old)
        nd, nn = _split(new)
        try:
            self._stub().AtomicRenameEntry(
                filer_pb2.AtomicRenameEntryRequest(
                    old_directory=od, old_name=on,
                    new_directory=nd, new_name=nn,
                )
            )
        except grpc.RpcError as e:
            raise FuseError(errno.EIO, str(e.details()))
        self.meta.delete(old)
        self.meta.delete(new)
        self.meta.invalidate_dir(od)
        self.meta.invalidate_dir(nd)
        with self._lock:  # open handles follow the file
            for h in self._handles.values():
                if h.path == old:
                    h.path = new

    def link(self, old_path: str, new_path: str) -> None:
        """Hard link (dir_link.go:25-100): promote the source entry to
        hardlink mode on the first link (random 16-byte id + marker byte,
        counter 1), bump the shared counter, and create the new name as a
        stub carrying the same id — the filer's KV meta owns the shared
        attributes/chunks from then on."""
        entry = self.lookup_entry(old_path)
        if entry is None:
            raise FuseError(errno.ENOENT)
        if entry.is_directory:
            raise FuseError(errno.EPERM)
        old_dir, _ = _split(old_path)
        e = filer_pb2.Entry()
        e.CopyFrom(entry)
        if not e.hard_link_id:
            e.hard_link_id = os.urandom(16) + b"\x01"  # HARD_LINK_MARKER
            e.hard_link_counter = 1
        e.hard_link_counter += 1
        self._update(old_dir, e)
        nd, nn = _split(new_path)
        new_entry = filer_pb2.Entry(
            name=nn, is_directory=False,
            hard_link_id=e.hard_link_id,
            hard_link_counter=e.hard_link_counter,
        )
        new_entry.attributes.CopyFrom(e.attributes)
        new_entry.chunks.extend(e.chunks)
        for k, v in e.extended.items():
            new_entry.extended[k] = v
        self._create(nd, new_entry)
        self.meta.invalidate_dir(old_dir)
        self.meta.invalidate_hardlink(e.hard_link_id)

    def symlink(self, target: str, link_path: str) -> None:
        directory, name = _split(link_path)
        entry = filer_pb2.Entry(name=name, is_directory=False)
        entry.attributes.symlink_target = target
        entry.attributes.file_mode = 0o777
        entry.attributes.crtime = int(time.time())
        entry.attributes.mtime = int(time.time())
        self._create(directory, entry)

    def readlink(self, path: str) -> str:
        entry = self.lookup_entry(path)
        if entry is None:
            raise FuseError(errno.ENOENT)
        if not entry.attributes.symlink_target:
            raise FuseError(errno.EINVAL)
        return entry.attributes.symlink_target

    def set_attr(self, path: str, mode: int | None = None,
                 uid: int | None = None, gid: int | None = None,
                 size: int | None = None, mtime: int | None = None) -> None:
        directory, _name = _split(path)
        entry = self.lookup_entry(path)
        if entry is None:
            raise FuseError(errno.ENOENT)
        entry = _copy_entry(entry)
        a = entry.attributes
        if mode is not None:
            a.file_mode = mode & 0o7777
        if uid is not None:
            a.uid = uid
        if gid is not None:
            a.gid = gid
        if mtime is not None:
            a.mtime = mtime
        if size is not None:
            self._truncate(entry, size)
        self._update(directory, entry)

    def _truncate(self, entry, size: int) -> None:
        """Drop/trim chunks beyond the new size (file.go truncation)."""
        if size == 0:
            del entry.chunks[:]
        else:
            keep = [c for c in entry.chunks if c.offset < size]
            del entry.chunks[:]
            entry.chunks.extend(keep)
        entry.attributes.file_size = size
        with self._lock:
            for h in self._handles.values():
                if h.path:
                    h.apply_truncate(size, entry)

    # -- xattr -------------------------------------------------------------

    def setxattr(self, path: str, name: str, value: bytes) -> None:
        directory, _ = _split(path)
        entry = self.lookup_entry(path)
        if entry is None:
            raise FuseError(errno.ENOENT)
        entry = _copy_entry(entry)
        entry.extended[name] = value
        self._update(directory, entry)

    def getxattr(self, path: str, name: str) -> bytes:
        entry = self.lookup_entry(path)
        if entry is None:
            raise FuseError(errno.ENOENT)
        if name not in entry.extended:
            raise FuseError(errno.ENODATA)
        return bytes(entry.extended[name])

    def listxattr(self, path: str) -> list[str]:
        entry = self.lookup_entry(path)
        if entry is None:
            raise FuseError(errno.ENOENT)
        return list(entry.extended)

    def removexattr(self, path: str, name: str) -> None:
        directory, _ = _split(path)
        entry = self.lookup_entry(path)
        if entry is None:
            raise FuseError(errno.ENOENT)
        if name not in entry.extended:
            raise FuseError(errno.ENODATA)
        entry = _copy_entry(entry)
        del entry.extended[name]
        self._update(directory, entry)

    # -- file handles ------------------------------------------------------

    def open(self, path: str, create: bool = False,
             mode: int = 0o644) -> "FileHandle":
        entry = self.lookup_entry(path)
        if entry is None:
            if not create:
                raise FuseError(errno.ENOENT)
            self.mknod(path, mode)
            entry = self.lookup_entry(path)
        h = FileHandle(self, path, entry)
        with self._lock:
            h.fh = self._next_fh
            self._next_fh += 1
            self._handles[h.fh] = h
        return h

    def handle(self, fh: int) -> "FileHandle | None":
        with self._lock:
            return self._handles.get(fh)

    def release(self, h: "FileHandle") -> None:
        h.flush()
        with self._lock:
            self._handles.pop(h.fh, None)

    # -- data plane --------------------------------------------------------

    def lookup_fid_urls(self, file_id: str) -> list[str]:
        vid = file_id.split(",", 1)[0]
        now = time.monotonic()
        hit = self._vid_cache.get(vid)
        if hit and now - hit[0] < 300.0:
            return [f"http://{u}/{file_id}" for u in hit[1]]
        resp = self._stub().LookupVolume(
            filer_pb2.LookupVolumeRequest(volume_ids=[vid])
        )
        urls = [
            loc.url
            for loc in resp.locations_map.get(vid, filer_pb2.Locations()).locations
        ]
        if urls:
            self._vid_cache[vid] = (now, urls)
        return [f"http://{u}/{file_id}" for u in urls]

    def fetch_whole_chunk(self, file_id: str) -> bytes:
        whole = self.chunks.get(file_id)
        if whole is None:
            last: Exception | None = None
            for url in self.lookup_fid_urls(file_id):
                try:
                    # single attempt per replica, no breaker: this loop IS
                    # the retry (same discipline as the filer's
                    # _download_failover), so a dead replica costs one
                    # timeout before rotating, not three
                    whole = download(url, retries=1, use_breaker=False)
                    break
                except Exception as e:  # noqa: BLE001 — try other replicas
                    last = e
            if whole is None:
                raise FuseError(errno.EIO, f"chunk {file_id}: {last}")
            self.chunks.set(file_id, whole)
        return whole

    def read_chunk_view(self, view: filechunks.ChunkView) -> bytes:
        """Whole-chunk read-through cache, sliced to the view window
        (reader_at.go:88-104 fetches and caches full chunks)."""
        whole = self.fetch_whole_chunk(view.file_id)
        if view.cipher_key:
            # chunks written through a -encryptVolumeData filer are
            # AES-GCM sealed; the cache holds ciphertext
            from ..util.cipher import decrypt

            whole = decrypt(whole, bytes(view.cipher_key))
        return whole[view.offset : view.offset + view.size]

    def resolve_chunks(self, chunks: list) -> list:
        from ..filer.filechunk_manifest import (
            has_chunk_manifest,
            resolve_chunk_manifest,
        )

        if not has_chunk_manifest(chunks):
            return chunks
        return resolve_chunk_manifest(self.fetch_whole_chunk, chunks)

    def _filer_cipher(self) -> bool:
        """Whether the filer runs with -encryptVolumeData — mount writes
        then seal chunks the same way (GetFilerConfiguration.cipher).

        Fails CLOSED: if the filer's answer is unknown, the write errors
        instead of silently storing plaintext on a cluster the operator
        configured to encrypt."""
        if not hasattr(self, "_cipher_flag"):
            try:
                resp = self._stub().GetFilerConfiguration(
                    filer_pb2.GetFilerConfigurationRequest())
            except Exception as e:
                raise FuseError(
                    errno.EIO,
                    f"cannot resolve filer cipher config: {e}")
            self._cipher_flag = bool(resp.cipher)
        return self._cipher_flag

    def assign_and_upload(self, path: str, data: bytes) -> filer_pb2.FileChunk:
        resp = self._stub().AssignVolume(
            filer_pb2.AssignVolumeRequest(
                count=1,
                collection=self.collection,
                replication=self.replication,
                ttl_sec=self.ttl_sec,
                path=path,
            )
        )
        if resp.error:
            raise FuseError(errno.EIO, resp.error)
        from ..util.cipher import maybe_seal

        stored, cipher_key = maybe_seal(data, self._filer_cipher())
        up = upload_data(
            f"http://{resp.url}/{resp.file_id}", stored, jwt=resp.auth
        )
        self.chunks.set(resp.file_id, stored)  # freshly written = hot
        chunk = filechunks.make_chunk(
            resp.file_id, 0, len(data), time.time_ns(), e_tag=up.etag
        )
        chunk.cipher_key = cipher_key
        return chunk

    # -- remote-change subscription ---------------------------------------

    def start_meta_subscription(self) -> None:
        """Keep the meta cache coherent with other writers via the filer's
        SubscribeMetadata stream (meta_cache/meta_cache_subscribe.go)."""

        def run():
            since = time.time_ns()
            while not self._stop.is_set():
                try:
                    stream = self._stub(timeout=None).SubscribeMetadata(
                        filer_pb2.SubscribeMetadataRequest(
                            client_name="mount", path_prefix="/",
                            since_ns=since,
                        )
                    )
                    for ev in stream:
                        if self._stop.is_set():
                            return
                        since = max(since, ev.ts_ns)
                        self.meta.apply_event(
                            ev.directory, ev.event_notification
                        )
                except grpc.RpcError:
                    self._stop.wait(1.0)

        self._subscriber = threading.Thread(target=run, daemon=True)
        self._subscriber.start()

    def close(self) -> None:
        self._stop.set()
        with self._lock:
            handles = list(self._handles.values())
        for h in handles:
            self.release(h)


class FileHandle:
    """One open file: dirty-page write-back + chunked reads.

    Writes buffer in ContinuousIntervals; when dirty bytes exceed the chunk
    size the largest interval is uploaded early (the reference flushes the
    biggest page list under memory pressure).  flush() drains everything,
    then commits the merged chunk list in one UpdateEntry.
    """

    def __init__(self, wfs: WFS, path: str, entry):
        self.wfs = wfs
        self.path = path
        self.entry = _copy_entry(entry)
        self.fh = 0
        self.dirty = ContinuousIntervals()
        self._pending_chunks: list[filer_pb2.FileChunk] = []
        self._dirty_meta = False
        self._lock = threading.RLock()

    def size(self) -> int:
        with self._lock:
            base = max(
                self.entry.attributes.file_size,
                filechunks.total_size(self.entry.chunks),
                len(self.entry.content),
            )
            for c in self._pending_chunks:
                base = max(base, c.offset + c.size)
            return max(base, self.dirty.max_stop())

    def read(self, offset: int, size: int) -> bytes:
        with self._lock:
            end = min(offset + size, self.size())
            if end <= offset:
                return b""
            size = end - offset
            out = bytearray(size)
            if self.entry.content:
                inline = bytes(self.entry.content[offset : offset + size])
                out[: len(inline)] = inline
            chunks = (
                self.wfs.resolve_chunks(list(self.entry.chunks))
                + self._pending_chunks
            )
            views = filechunks.view_from_chunks(chunks, offset, size)
            for v in views:
                blob = self.wfs.read_chunk_view(v)
                lo = v.logical_offset - offset
                out[lo : lo + len(blob)] = blob
            self.dirty.read(offset, size, out)
            return bytes(out)

    def write(self, offset: int, data: bytes) -> int:
        with self._lock:
            self.dirty.add(offset, data)
            self._dirty_meta = True
            # bound buffered memory: spill the largest interval once dirty
            # bytes exceed one chunk window
            while self.dirty.total_bytes() >= self.wfs.chunk_size:
                self._spill_largest()
            return len(data)

    def apply_truncate(self, size: int, truncated_entry=None) -> None:
        """Trim dirty pages, pending chunks, AND this handle's entry view so
        a later flush can't resurrect bytes past the new size."""
        with self._lock:
            for iv in self.dirty.intervals:
                if iv.offset >= size:
                    iv.data = bytearray()
                elif iv.stop > size:
                    iv.data = iv.data[: size - iv.offset]
            self.dirty.intervals = [
                iv for iv in self.dirty.intervals if iv.data
            ]
            self._pending_chunks = [
                c for c in self._pending_chunks if c.offset < size
            ]
            if truncated_entry is not None:
                self.entry = _copy_entry(truncated_entry)
            else:
                keep = [c for c in self.entry.chunks if c.offset < size]
                del self.entry.chunks[:]
                self.entry.chunks.extend(keep)
                self.entry.attributes.file_size = size

    def _spill_largest(self) -> None:
        iv = self.dirty.pop_largest()
        if iv is None:
            return
        self._upload_interval(iv.offset, bytes(iv.data))

    def _upload_interval(self, offset: int, data: bytes) -> None:
        cs = self.wfs.chunk_size
        for lo in range(0, len(data), cs):
            blob = data[lo : lo + cs]
            chunk = self.wfs.assign_and_upload(self.path, blob)
            chunk.offset = offset + lo
            self._pending_chunks.append(chunk)

    def flush(self) -> None:
        with self._lock:
            for iv in self.dirty.pop_all():
                self._upload_interval(iv.offset, bytes(iv.data))
            if not self._pending_chunks and not self._dirty_meta:
                return
            directory, _name = _split(self.path)
            # refresh: another client may have updated attributes meanwhile
            entry = self.entry
            entry.chunks.extend(self._pending_chunks)
            compacted, _garbage = filechunks.compact_chunks(list(entry.chunks))
            del entry.chunks[:]
            entry.chunks.extend(compacted)
            entry.attributes.file_size = max(
                entry.attributes.file_size,
                filechunks.total_size(entry.chunks),
            )
            entry.attributes.mtime = int(time.time())
            self._pending_chunks = []
            self._dirty_meta = False
            self.wfs._update(directory, entry)


def _copy_entry(entry) -> filer_pb2.Entry:
    c = filer_pb2.Entry()
    c.CopyFrom(entry)
    return c
