"""FUSE mount layer: kernel VFS over the filer (layer 9 of SURVEY.md §1).

- wfs.py         — the filesystem core (kernel-agnostic, fully tested)
- dirty_pages.py — write-back interval buffering
- meta_cache.py  — entry cache with listing completeness + subscription
- fuse.py        — ctypes binding to libfuse.so.2 (gated on availability)
"""

from .dirty_pages import ContinuousIntervals
from .meta_cache import MetaCache
from .wfs import WFS, FileHandle, FuseError

__all__ = [
    "WFS",
    "FileHandle",
    "FuseError",
    "ContinuousIntervals",
    "MetaCache",
    "mount_available",
]


def mount_available() -> bool:
    from .fuse import available

    return available()
