"""Dirty-page interval buffering for the mount write path.

Reference: weed/filesys/dirty_page_interval.go — writes land in an ordered
list of continuous byte intervals; an overlapping write punches out the
older bytes (newest wins), adjacent intervals merge, and flush drains the
intervals as upload units.  Keeping intervals (not fixed pages) means a
sequential writer produces exactly one growing interval and uploads one
chunk per max-chunk window, with no page-size write amplification.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass
class PageInterval:
    offset: int
    data: bytearray

    @property
    def stop(self) -> int:
        return self.offset + len(self.data)


class ContinuousIntervals:
    """Sorted, disjoint, merged dirty intervals of one open file."""

    def __init__(self):
        self.intervals: list[PageInterval] = []

    def total_bytes(self) -> int:
        return sum(len(iv.data) for iv in self.intervals)

    def max_stop(self) -> int:
        return max((iv.stop for iv in self.intervals), default=0)

    def add(self, offset: int, data: bytes) -> None:
        """Overlay [offset, offset+len) with new bytes; newest wins."""
        if not data:
            return
        new = PageInterval(offset, bytearray(data))
        out: list[PageInterval] = []
        for iv in self.intervals:
            if iv.stop < new.offset or iv.offset > new.stop:
                out.append(iv)  # fully disjoint, not even adjacent
                continue
            # overlapping or touching: keep non-overlapped remainders,
            # then merge everything contiguous into `new`
            if iv.offset < new.offset:
                left = iv.data[: new.offset - iv.offset]
                new.data[0:0] = left
                new.offset = iv.offset
            if iv.stop > new.stop:
                new.data.extend(iv.data[new.stop - iv.offset :])
        out.append(new)
        out.sort(key=lambda iv: iv.offset)
        self.intervals = out

    def read(self, offset: int, size: int, base: bytearray) -> None:
        """Overlay dirty bytes onto `base` (the already-fetched chunk data)
        for the window [offset, offset+size)."""
        stop = offset + size
        for iv in self.intervals:
            lo = max(iv.offset, offset)
            hi = min(iv.stop, stop)
            if lo < hi:
                base[lo - offset : hi - offset] = iv.data[
                    lo - iv.offset : hi - iv.offset
                ]

    def pop_largest(self) -> PageInterval | None:
        """Remove and return the biggest interval (the reference flushes the
        largest page list first when memory pressure hits)."""
        if not self.intervals:
            return None
        best = max(range(len(self.intervals)),
                   key=lambda i: len(self.intervals[i].data))
        return self.intervals.pop(best)

    def pop_all(self) -> list[PageInterval]:
        out, self.intervals = self.intervals, []
        return out
