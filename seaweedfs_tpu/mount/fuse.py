"""ctypes binding to libfuse.so.2 (FUSE 2.9 high-level API).

Reference capability: `weed mount` (weed/command/mount_std.go:52,208) via
the bazil fuse fork.  Here the kernel interface is the system libfuse
driven directly through ctypes — no third-party Python FUSE package — and
every operation delegates to the kernel-agnostic WFS object (wfs.py).

The struct layouts (struct stat, fuse_file_info, fuse_operations for
FUSE_USE_VERSION 26) follow the public fuse.h / glibc ABI on x86-64
Linux.  `available()` gates on libfuse + /dev/fuse so the package imports
cleanly on hosts without FUSE.
"""

from __future__ import annotations

import ctypes
import ctypes.util
import errno
import os
import subprocess
import threading

from ..util import glog
from .wfs import WFS, FuseError

c_off_t = ctypes.c_int64
c_mode_t = ctypes.c_uint32
c_dev_t = ctypes.c_uint64


class Timespec(ctypes.Structure):
    _fields_ = [("tv_sec", ctypes.c_long), ("tv_nsec", ctypes.c_long)]


class Stat(ctypes.Structure):  # glibc x86-64 struct stat
    _fields_ = [
        ("st_dev", c_dev_t),
        ("st_ino", ctypes.c_uint64),
        ("st_nlink", ctypes.c_uint64),
        ("st_mode", c_mode_t),
        ("st_uid", ctypes.c_uint32),
        ("st_gid", ctypes.c_uint32),
        ("__pad0", ctypes.c_int),
        ("st_rdev", c_dev_t),
        ("st_size", c_off_t),
        ("st_blksize", ctypes.c_int64),
        ("st_blocks", ctypes.c_int64),
        ("st_atim", Timespec),
        ("st_mtim", Timespec),
        ("st_ctim", Timespec),
        ("__reserved", ctypes.c_int64 * 3),
    ]


class StatVfs(ctypes.Structure):  # glibc x86-64 struct statvfs
    _fields_ = [
        ("f_bsize", ctypes.c_ulong),
        ("f_frsize", ctypes.c_ulong),
        ("f_blocks", ctypes.c_uint64),
        ("f_bfree", ctypes.c_uint64),
        ("f_bavail", ctypes.c_uint64),
        ("f_files", ctypes.c_uint64),
        ("f_ffree", ctypes.c_uint64),
        ("f_favail", ctypes.c_uint64),
        ("f_fsid", ctypes.c_ulong),
        ("f_flag", ctypes.c_ulong),
        ("f_namemax", ctypes.c_ulong),
        ("__spare", ctypes.c_int * 6),
    ]


class FuseFileInfo(ctypes.Structure):  # fuse_common.h 2.9
    _fields_ = [
        ("flags", ctypes.c_int),
        ("fh_old", ctypes.c_ulong),
        ("writepage", ctypes.c_int),
        ("flags_bits", ctypes.c_uint),  # direct_io:1 keep_cache:1 ...
        ("fh", ctypes.c_uint64),
        ("lock_owner", ctypes.c_uint64),
    ]


_FILL_DIR_T = ctypes.CFUNCTYPE(
    ctypes.c_int, ctypes.c_void_p, ctypes.c_char_p,
    ctypes.POINTER(Stat), c_off_t,
)

_P = ctypes.CFUNCTYPE  # shorthand
_VOIDP = ctypes.c_void_p
_CHARP = ctypes.c_char_p
_INT = ctypes.c_int
_SIZE = ctypes.c_size_t
_FFIP = ctypes.POINTER(FuseFileInfo)


class FuseOperations(ctypes.Structure):  # fuse.h, FUSE_USE_VERSION 26
    _fields_ = [
        # NOTE: data buffers are c_void_p, NOT c_char_p — ctypes converts a
        # c_char_p argument into a Python bytes copy, so memmove would fill
        # a throwaway instead of the kernel's buffer
        ("getattr", _P(_INT, _CHARP, ctypes.POINTER(Stat))),
        ("readlink", _P(_INT, _CHARP, _VOIDP, _SIZE)),
        ("getdir", _VOIDP),  # deprecated
        ("mknod", _P(_INT, _CHARP, c_mode_t, c_dev_t)),
        ("mkdir", _P(_INT, _CHARP, c_mode_t)),
        ("unlink", _P(_INT, _CHARP)),
        ("rmdir", _P(_INT, _CHARP)),
        ("symlink", _P(_INT, _CHARP, _CHARP)),
        ("rename", _P(_INT, _CHARP, _CHARP)),
        ("link", _P(_INT, _CHARP, _CHARP)),
        ("chmod", _P(_INT, _CHARP, c_mode_t)),
        ("chown", _P(_INT, _CHARP, ctypes.c_uint32, ctypes.c_uint32)),
        ("truncate", _P(_INT, _CHARP, c_off_t)),
        ("utime", _VOIDP),  # deprecated in favor of utimens
        ("open", _P(_INT, _CHARP, _FFIP)),
        ("read", _P(_INT, _CHARP, _VOIDP, _SIZE, c_off_t, _FFIP)),
        ("write", _P(_INT, _CHARP, _VOIDP, _SIZE, c_off_t, _FFIP)),
        ("statfs", _P(_INT, _CHARP, ctypes.POINTER(StatVfs))),
        ("flush", _P(_INT, _CHARP, _FFIP)),
        ("release", _P(_INT, _CHARP, _FFIP)),
        ("fsync", _P(_INT, _CHARP, _INT, _FFIP)),
        ("setxattr", _P(_INT, _CHARP, _CHARP, _VOIDP, _SIZE, _INT)),
        ("getxattr", _P(_INT, _CHARP, _CHARP, _VOIDP, _SIZE)),
        ("listxattr", _P(_INT, _CHARP, _VOIDP, _SIZE)),
        ("removexattr", _P(_INT, _CHARP, _CHARP)),
        ("opendir", _P(_INT, _CHARP, _FFIP)),
        ("readdir", _P(_INT, _CHARP, _VOIDP, _FILL_DIR_T, c_off_t, _FFIP)),
        ("releasedir", _P(_INT, _CHARP, _FFIP)),
        ("fsyncdir", _P(_INT, _CHARP, _INT, _FFIP)),
        ("init", _P(_VOIDP, _VOIDP)),
        ("destroy", _P(None, _VOIDP)),
        ("access", _P(_INT, _CHARP, _INT)),
        ("create", _P(_INT, _CHARP, c_mode_t, _FFIP)),
        ("ftruncate", _P(_INT, _CHARP, c_off_t, _FFIP)),
        ("fgetattr", _P(_INT, _CHARP, ctypes.POINTER(Stat), _FFIP)),
        ("lock", _VOIDP),
        ("utimens", _P(_INT, _CHARP, ctypes.POINTER(Timespec))),
        ("bmap", _VOIDP),
        ("flag_bits", ctypes.c_uint),  # flag_nullpath_ok etc.
        ("ioctl", _VOIDP),
        ("poll", _VOIDP),
        ("write_buf", _VOIDP),
        ("read_buf", _VOIDP),
        ("flock", _VOIDP),
        ("fallocate", _VOIDP),
    ]


def _libfuse():
    name = ctypes.util.find_library("fuse") or "libfuse.so.2"
    return ctypes.CDLL(name)


def available() -> bool:
    try:
        _libfuse()
    except OSError:
        return False
    return os.path.exists("/dev/fuse")


class FuseMount:
    """Run a WFS under a kernel FUSE mountpoint.

    start() spawns the libfuse main loop on a thread (single-threaded fuse
    loop: the GIL would serialize callbacks anyway and -s keeps teardown
    deterministic); stop() unmounts via fusermount and joins.
    """

    def __init__(self, wfs: WFS, mountpoint: str, allow_other: bool = False):
        self.wfs = wfs
        self.mountpoint = os.path.abspath(mountpoint)
        self.allow_other = allow_other
        self._thread: threading.Thread | None = None
        self._ops = self._make_ops()  # must outlive the mount (GC!)
        self._rc: int | None = None

    # -- callback plumbing -------------------------------------------------

    def _wrap(self, fn):
        def call(*args):
            try:
                r = fn(*args)
                return 0 if r is None else r
            except FuseError as e:
                return -e.errno
            except OSError as e:
                return -(e.errno or errno.EIO)
            except Exception as e:  # noqa: BLE001 — kernel must get an errno
                glog.warning("fuse: %s failed: %s", fn.__name__, e)
                return -errno.EIO
        call.__name__ = fn.__name__
        return call

    def _make_ops(self) -> FuseOperations:
        w = self.wfs
        fields = dict(FuseOperations._fields_)

        def getattr_(path, st):
            _fill_stat(st.contents, w.getattr(path.decode()))

        def fgetattr(path, st, ffi):
            h = w.handle(ffi.contents.fh) if ffi else None
            if h is not None:
                attrs = w.attrs_of(h.path, h.entry)
                attrs["st_size"] = h.size()
                _fill_stat(st.contents, attrs)
            else:
                _fill_stat(st.contents, w.getattr(path.decode()))

        def readlink(path, buf, size):
            target = w.readlink(path.decode()).encode()[: size - 1]
            ctypes.memmove(buf, target + b"\0", len(target) + 1)

        def mknod(path, mode, _dev):
            w.mknod(path.decode(), mode)

        def mkdir(path, mode):
            w.mkdir(path.decode(), mode)

        def unlink(path):
            w.unlink(path.decode())

        def rmdir(path):
            w.rmdir(path.decode())

        def symlink(target, link):
            w.symlink(target.decode(), link.decode())

        def rename(old, new):
            w.rename(old.decode(), new.decode())

        def link(old, new):
            w.link(old.decode(), new.decode())

        def chmod(path, mode):
            w.set_attr(path.decode(), mode=mode)

        def chown(path, uid, gid):
            w.set_attr(
                path.decode(),
                uid=uid if uid != 0xFFFFFFFF else None,
                gid=gid if gid != 0xFFFFFFFF else None,
            )

        def truncate(path, size):
            w.set_attr(path.decode(), size=size)

        def open_(path, ffi):
            h = w.open(path.decode(), create=False)
            ffi.contents.fh = h.fh

        def create(path, mode, ffi):
            h = w.open(path.decode(), create=True, mode=mode)
            ffi.contents.fh = h.fh

        def read(path, buf, size, off, ffi):
            h = w.handle(ffi.contents.fh)
            if h is None:
                return -errno.EBADF
            data = h.read(off, size)
            ctypes.memmove(buf, data, len(data))
            return len(data)

        def write(path, buf, size, off, ffi):
            h = w.handle(ffi.contents.fh)
            if h is None:
                return -errno.EBADF
            return h.write(off, ctypes.string_at(buf, size))

        def flush(path, ffi):
            h = w.handle(ffi.contents.fh)
            if h is not None:
                h.flush()

        def fsync(path, _datasync, ffi):
            h = w.handle(ffi.contents.fh)
            if h is not None:
                h.flush()

        def release(path, ffi):
            h = w.handle(ffi.contents.fh)
            if h is not None:
                w.release(h)

        def ftruncate(path, size, ffi):
            h = w.handle(ffi.contents.fh)
            if h is not None:
                h.apply_truncate(size)
            w.set_attr(path.decode(), size=size)

        def statfs(_path, sv):
            v = sv.contents
            ctypes.memset(ctypes.byref(v), 0, ctypes.sizeof(v))
            v.f_bsize = v.f_frsize = 4096
            v.f_blocks = v.f_bfree = v.f_bavail = 1 << 30
            v.f_files = v.f_ffree = v.f_favail = 1 << 30
            v.f_namemax = 1024

        def readdir(path, buf, filler, _off, _ffi):
            filler(buf, b".", None, 0)
            filler(buf, b"..", None, 0)
            for e in w.list_dir(path.decode()):
                filler(buf, e.name.encode(), None, 0)

        def setxattr(path, name, value, size, _flags):
            w.setxattr(path.decode(), name.decode(),
                       ctypes.string_at(value, size))

        def getxattr(path, name, value, size):
            data = w.getxattr(path.decode(), name.decode())
            if size == 0:
                return len(data)
            if size < len(data):
                return -errno.ERANGE
            ctypes.memmove(value, data, len(data))
            return len(data)

        def listxattr(path, buf, size):
            blob = b"".join(n.encode() + b"\0" for n in w.listxattr(path.decode()))
            if size == 0:
                return len(blob)
            if size < len(blob):
                return -errno.ERANGE
            ctypes.memmove(buf, blob, len(blob))
            return len(blob)

        def removexattr(path, name):
            w.removexattr(path.decode(), name.decode())

        def utimens(path, times):
            mtime = None
            if times:
                ts = ctypes.cast(times, ctypes.POINTER(Timespec * 2)).contents
                mtime = int(ts[1].tv_sec)
            w.set_attr(path.decode(), mtime=mtime or int(__import__("time").time()))

        def access(_path, _mode):
            return 0

        ops = FuseOperations()
        impls = {
            "getattr": getattr_, "fgetattr": fgetattr, "readlink": readlink,
            "mknod": mknod, "mkdir": mkdir, "unlink": unlink, "rmdir": rmdir,
            "symlink": symlink, "rename": rename, "link": link,
            "chmod": chmod, "chown": chown, "truncate": truncate,
            "open": open_, "create": create, "read": read, "write": write,
            "flush": flush, "fsync": fsync, "release": release,
            "ftruncate": ftruncate, "statfs": statfs, "readdir": readdir,
            "setxattr": setxattr, "getxattr": getxattr,
            "listxattr": listxattr, "removexattr": removexattr,
            "utimens": utimens, "access": access,
        }
        self._keep = []  # CFUNCTYPE objects must not be GC'd
        for name, impl in impls.items():
            proto = fields[name]
            cb = proto(self._wrap(impl))
            self._keep.append(cb)
            setattr(ops, name, cb)
        return ops

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        os.makedirs(self.mountpoint, exist_ok=True)
        lib = _libfuse()
        argv_list = [b"seaweedfs_tpu", self.mountpoint.encode(), b"-f", b"-s",
                     b"-o", b"default_permissions"]
        if self.allow_other:
            argv_list += [b"-o", b"allow_other"]
        argc = len(argv_list)
        argv = (ctypes.c_char_p * argc)(*argv_list)

        def run():
            self._rc = lib.fuse_main_real(
                argc, argv, ctypes.byref(self._ops),
                ctypes.sizeof(self._ops), None,
            )

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()
        # wait until the kernel reports a fuse mount at the mountpoint
        for _ in range(100):
            if self.is_mounted():
                return
            if not self._thread.is_alive():
                raise RuntimeError(
                    f"fuse_main exited rc={self._rc} before mounting"
                )
            threading.Event().wait(0.05)
        raise RuntimeError("fuse mount did not appear within 5s")

    def is_mounted(self) -> bool:
        try:
            with open("/proc/mounts") as f:
                return any(
                    line.split()[1] == self.mountpoint and "fuse" in line
                    for line in f
                )
        except OSError:
            return False

    def stop(self) -> None:
        self.wfs.close()
        subprocess.run(
            ["fusermount", "-u", "-z", self.mountpoint],
            capture_output=True,
        )
        if self._thread:
            self._thread.join(timeout=10)
        _restore_sigpipe()


def _restore_sigpipe() -> None:
    """Re-ignore SIGPIPE after a fuse session ends.

    libfuse's fuse_main teardown (fuse_remove_signal_handlers) resets
    SIGPIPE to SIG_DFL at the C level — invisible to signal.getsignal,
    which still reports Python's SIG_IGN — so the NEXT write to a
    half-closed socket anywhere in the process dies of SIGPIPE instead
    of raising BrokenPipeError.  Observed as the whole test process
    (and it would be a whole combined `weed server`) silently exiting
    141 on a keep-alive socket long after an unmount."""
    import signal

    try:
        signal.signal(signal.SIGPIPE, signal.SIG_IGN)
    except ValueError:
        # not the main thread: leave it — the interpreter forbids
        # handler changes here, and the caller's thread context is rare
        # (stop() is invoked from main in every current call site)
        pass


def _fill_stat(st: Stat, attrs: dict) -> None:
    ctypes.memset(ctypes.byref(st), 0, ctypes.sizeof(st))
    st.st_mode = attrs["st_mode"]
    st.st_size = attrs["st_size"]
    st.st_uid = attrs["st_uid"]
    st.st_gid = attrs["st_gid"]
    st.st_nlink = attrs.get("st_nlink", 1)
    st.st_blksize = 4096
    st.st_blocks = attrs.get("st_blocks", 0)
    st.st_atim.tv_sec = int(attrs["st_atime"])
    st.st_mtim.tv_sec = int(attrs["st_mtime"])
    st.st_ctim.tv_sec = int(attrs["st_ctime"])
