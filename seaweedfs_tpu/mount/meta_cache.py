"""Mount-side metadata cache of filer entries.

Reference: weed/filesys/meta_cache/ — the mount keeps a local cache of
Entry protos so getattr/lookup/readdir don't round-trip to the filer on
every kernel call; directories are cached whole ("visited") after the
first listing, and a background SubscribeMetadata stream keeps the cache
coherent with changes made by other clients.
"""

from __future__ import annotations

import threading
from collections import OrderedDict

from ..pb import filer_pb2


def _split(path: str) -> tuple[str, str]:
    path = path.rstrip("/") or "/"
    if path == "/":
        return "/", ""
    d, _, n = path.rpartition("/")
    return d or "/", n


class MetaCache:
    """LRU of full-path -> Entry, plus a 'directory fully listed' set.

    A cached directory means lookups for missing children can answer
    ENOENT locally (negative caching via listing completeness, the same
    trick the reference's bounded-tree visited marker plays).
    """

    def __init__(self, limit_entries: int = 65536):
        self.limit = limit_entries
        self._lock = threading.RLock()
        self._entries: OrderedDict[str, filer_pb2.Entry] = OrderedDict()
        self._listed_dirs: set[str] = set()

    # -- entry ops ---------------------------------------------------------

    def get(self, path: str):
        with self._lock:
            e = self._entries.get(path)
            if e is not None:
                self._entries.move_to_end(path)
            return e

    def put(self, path: str, entry: filer_pb2.Entry) -> None:
        with self._lock:
            self._entries[path] = entry
            self._entries.move_to_end(path)
            while len(self._entries) > self.limit:
                evicted, _ = self._entries.popitem(last=False)
                self._listed_dirs.discard(evicted)

    def delete(self, path: str) -> None:
        with self._lock:
            self._entries.pop(path, None)
            self._listed_dirs.discard(path)
            # children of a removed dir are stale too
            prefix = path.rstrip("/") + "/"
            for k in [k for k in self._entries if k.startswith(prefix)]:
                del self._entries[k]
            for k in [k for k in self._listed_dirs if k.startswith(prefix)]:
                self._listed_dirs.discard(k)

    def invalidate_hardlink(self, hard_link_id: bytes) -> None:
        """Drop every cached entry sharing a hardlink id: a link/unlink
        changes the shared counter server-side, so all sibling names'
        cached attributes are stale at once."""
        with self._lock:
            for k in [k for k, e in self._entries.items()
                      if e.hard_link_id == hard_link_id]:
                del self._entries[k]

    # -- directory completeness -------------------------------------------

    def is_dir_listed(self, dir_path: str) -> bool:
        with self._lock:
            return dir_path in self._listed_dirs

    def mark_dir_listed(self, dir_path: str, entries) -> None:
        with self._lock:
            base = dir_path.rstrip("/") or ""
            for e in entries:
                self.put(f"{base}/{e.name}", e)
            self._listed_dirs.add(dir_path)

    def children(self, dir_path: str) -> list[filer_pb2.Entry]:
        prefix = (dir_path.rstrip("/") or "") + "/"
        with self._lock:
            return [
                e
                for p, e in self._entries.items()
                if p.startswith(prefix) and "/" not in p[len(prefix):]
            ]

    def invalidate_dir(self, dir_path: str) -> None:
        with self._lock:
            self._listed_dirs.discard(dir_path)

    # -- coherence with remote mutations ----------------------------------

    def apply_event(self, directory: str, notification) -> None:
        """Fold one filer EventNotification into the cache (the mount's
        SubscribeMetadata consumer calls this)."""
        old, new = notification.old_entry, notification.new_entry
        new_dir = notification.new_parent_path or directory
        with self._lock:
            if old.name:
                self.delete(f"{directory.rstrip('/') or ''}/{old.name}")
                self.invalidate_dir(directory)
            if new.name:
                base = new_dir.rstrip("/") or ""
                # putting the fresh entry keeps a fully-listed dir complete;
                # an unlisted dir stays unlisted (next readdir refetches)
                self.put(f"{base}/{new.name}", new)
