"""IAM API: user / access-key / policy CRUD persisting s3 identities.

Reference: weed/iamapi/ (iamapi_server.go, iamapi_management_handlers.go).
"""

from .server import IamApiServer

__all__ = ["IamApiServer"]
