"""AWS IAM Query-protocol API over the filer-persisted s3 identity config.

Reference surface: weed/iamapi/iamapi_server.go (POST / router, config
stored inside the filer at /etc/iam/identity.json + policies.json) and
iamapi_management_handlers.go (the Action switch: ListUsers,
ListAccessKeys, Create/Get/DeleteUser, Create/DeleteAccessKey,
CreatePolicy, Put/Get/DeleteUserPolicy; s3-statement <-> identity-action
mapping).  The s3 gateway tails the same identity.json
(`S3ApiServer.refresh_iam_from_filer`), so changes made here take effect
on live signed requests within its refresh interval.

Design differences from the reference: responses are built with
ElementTree against the IAM 2010-05-08 namespace instead of aws-sdk-go
response structs, and DeleteUserPolicy clears the user's actions rather
than dropping the whole identity (the reference removes the identity,
which also deletes its credentials — surprising for an IAM caller).
"""

from __future__ import annotations

import json
import secrets
import string
import threading
import xml.etree.ElementTree as ET
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from ..util.httpd import FrameworkHTTPServer
from urllib.parse import parse_qs

from ..s3api.auth import (
    ACTION_ADMIN,
    ACTION_LIST,
    ACTION_READ,
    ACTION_TAGGING,
    ACTION_WRITE,
    AuthError,
    IdentityAccessManagement,
    S3HttpRequest,
)
from ..s3api.filer_client import FilerClient

IAM_XMLNS = "https://iam.amazonaws.com/doc/2010-05-08/"
IAM_CONFIG_DIR = "/etc/iam"
IAM_IDENTITY_FILE = "identity.json"
IAM_POLICIES_FILE = "policies.json"
POLICY_DOCUMENT_VERSION = "2012-10-17"

# s3 policy statement action <-> identity action (the reference's
# MapToStatementAction / MapToIdentitiesAction tables)
_STATEMENT_TO_ACTION = {
    "*": ACTION_ADMIN,
    "Put*": ACTION_WRITE,
    "Get*": ACTION_READ,
    "List*": ACTION_LIST,
    "Tagging*": ACTION_TAGGING,
}
_ACTION_TO_STATEMENT = {v: k for k, v in _STATEMENT_TO_ACTION.items()}


class IamError(Exception):
    def __init__(self, code: str, message: str, status: int = 400):
        super().__init__(message)
        self.code = code
        self.message = message
        self.status = status


def _no_such_entity(kind: str, name: str) -> IamError:
    return IamError(
        "NoSuchEntity", f"the {kind} with name {name} cannot be found.", 404
    )


def policy_to_actions(doc: dict) -> list[str]:
    """Allow-statements -> identity actions ("Read", "Write:bucket", ...)."""
    actions: list[str] = []
    for st in doc.get("Statement", []):
        if st.get("Effect") != "Allow":
            continue
        resources = st.get("Resource", [])
        stmt_actions = st.get("Action", [])
        if isinstance(resources, str):
            resources = [resources]
        if isinstance(stmt_actions, str):
            stmt_actions = [stmt_actions]
        for res in resources:
            parts = res.split(":")
            if len(parts) != 6 or parts[:3] != ["arn", "aws", "s3"]:
                continue
            target = parts[5]
            for act in stmt_actions:
                svc, _, name = act.partition(":")
                if svc != "s3":
                    continue
                mapped = _STATEMENT_TO_ACTION.get(name)
                if not mapped:
                    continue
                if target == "*":
                    actions.append(mapped)
                    continue
                bucket, _, rest = target.partition("/")
                if rest == "*":
                    actions.append(f"{mapped}:{bucket}")
    return actions


def actions_to_policy(actions: list[str]) -> dict:
    """Identity actions -> a policy document (GetUserPolicy shape)."""
    by_resource: dict[str, list[str]] = {}
    for a in actions:
        base, _, bucket = a.partition(":")
        res = f"arn:aws:s3:::{bucket}/*" if bucket else "*"
        stmt = _ACTION_TO_STATEMENT.get(base)
        if stmt:
            by_resource.setdefault(res, []).append(f"s3:{stmt}")
    return {
        "Version": POLICY_DOCUMENT_VERSION,
        "Statement": [
            {"Effect": "Allow", "Action": acts, "Resource": [res]}
            for res, acts in by_resource.items()
        ],
    }


class IamApiServer:
    """Serves the IAM Query API; state lives in the filer, not here."""

    def __init__(self, filer: str = "127.0.0.1:8888", port: int = 8111):
        self.port = port
        self.client = FilerClient(filer)
        self._httpd: ThreadingHTTPServer | None = None
        self._lock = threading.Lock()  # config read-modify-write

    # -- filer-persisted config ---------------------------------------------

    def _read_json(self, name: str) -> dict:
        try:
            status, _, body = self.client.get_object(
                f"{IAM_CONFIG_DIR}/{name}")
        except Exception:
            return {}
        if status != 200 or not body:
            return {}
        try:
            return json.loads(body)
        except ValueError:
            return {}

    def _write_json(self, name: str, conf: dict) -> None:
        self.client.put_object(
            f"{IAM_CONFIG_DIR}/{name}",
            json.dumps(conf, indent=2).encode(),
            mime="application/json",
        )

    def get_s3_config(self) -> dict:
        conf = self._read_json(IAM_IDENTITY_FILE)
        conf.setdefault("identities", [])
        return conf

    def put_s3_config(self, conf: dict) -> None:
        self._write_json(IAM_IDENTITY_FILE, conf)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        from ..util import glog

        handler = type("BoundIamHandler", (IamHandler,), {"iam_server": self})
        self._httpd = FrameworkHTTPServer(("0.0.0.0", self.port), handler)
        threading.Thread(target=self._httpd.serve_forever, daemon=True).start()
        glog.info("iam api started port=%d filer=%s",
                  self.port, self.client.http_address)

    def stop(self) -> None:
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()

    # -- actions (each takes the live config dict, returns result element) --

    @staticmethod
    def _find(conf: dict, user: str) -> dict | None:
        for ident in conf["identities"]:
            if ident.get("name") == user:
                return ident
        return None

    def do_action(self, action: str, params: dict[str, str],
                  conf: dict | None = None) -> tuple[ET.Element, bool]:
        """Returns (result XML element, config_changed)."""
        if conf is None:
            conf = self.get_s3_config()
        root = ET.Element(f"{action}Response", xmlns=IAM_XMLNS)
        result = ET.SubElement(root, f"{action}Result")
        changed = False
        user = params.get("UserName", "")

        if action == "ListUsers":
            users = ET.SubElement(result, "Users")
            for ident in conf["identities"]:
                m = ET.SubElement(users, "member")
                ET.SubElement(m, "UserName").text = ident.get("name", "")
            ET.SubElement(result, "IsTruncated").text = "false"

        elif action == "ListAccessKeys":
            keys = ET.SubElement(result, "AccessKeyMetadata")
            for ident in conf["identities"]:
                if user and ident.get("name") != user:
                    continue
                for cred in ident.get("credentials", []):
                    m = ET.SubElement(keys, "member")
                    ET.SubElement(m, "UserName").text = ident.get("name", "")
                    ET.SubElement(m, "AccessKeyId").text = cred["accessKey"]
                    ET.SubElement(m, "Status").text = "Active"
            ET.SubElement(result, "IsTruncated").text = "false"

        elif action == "CreateUser":
            if self._find(conf, user) is not None:
                raise IamError(
                    "EntityAlreadyExists",
                    f"user with name {user} already exists.", 409)
            conf["identities"].append(
                {"name": user, "credentials": [], "actions": []})
            u = ET.SubElement(result, "User")
            ET.SubElement(u, "UserName").text = user
            changed = True

        elif action == "GetUser":
            if self._find(conf, user) is None:
                raise _no_such_entity("user", user)
            u = ET.SubElement(result, "User")
            ET.SubElement(u, "UserName").text = user

        elif action == "DeleteUser":
            if self._find(conf, user) is None:
                raise _no_such_entity("user", user)
            conf["identities"] = [
                i for i in conf["identities"] if i.get("name") != user]
            changed = True

        elif action == "CreateAccessKey":
            access_key = "".join(
                secrets.choice(string.ascii_uppercase + string.digits)
                for _ in range(21))
            secret_key = "".join(
                secrets.choice(string.ascii_letters + string.digits + "/")
                for _ in range(42))
            ident = self._find(conf, user)
            if ident is None:
                ident = {"name": user, "credentials": [], "actions": []}
                conf["identities"].append(ident)
            ident.setdefault("credentials", []).append(
                {"accessKey": access_key, "secretKey": secret_key})
            k = ET.SubElement(result, "AccessKey")
            ET.SubElement(k, "UserName").text = user
            ET.SubElement(k, "AccessKeyId").text = access_key
            ET.SubElement(k, "Status").text = "Active"
            ET.SubElement(k, "SecretAccessKey").text = secret_key
            changed = True

        elif action == "DeleteAccessKey":
            key_id = params.get("AccessKeyId", "")
            ident = self._find(conf, user)
            if ident is not None:
                before = len(ident.get("credentials", []))
                ident["credentials"] = [
                    c for c in ident.get("credentials", [])
                    if c["accessKey"] != key_id]
                changed = len(ident["credentials"]) != before

        elif action == "CreatePolicy":
            name = params.get("PolicyName", "")
            try:
                doc = json.loads(params.get("PolicyDocument", ""))
            except ValueError as e:
                raise IamError("MalformedPolicyDocument", str(e))
            policies = self._read_json(IAM_POLICIES_FILE)
            policies.setdefault("policies", {})[name] = doc
            self._write_json(IAM_POLICIES_FILE, policies)
            p = ET.SubElement(result, "Policy")
            ET.SubElement(p, "PolicyName").text = name
            ET.SubElement(p, "Arn").text = f"arn:aws:iam:::policy/{name}"

        elif action == "PutUserPolicy":
            try:
                doc = json.loads(params.get("PolicyDocument", ""))
            except ValueError as e:
                raise IamError("MalformedPolicyDocument", str(e))
            ident = self._find(conf, user)
            if ident is None:
                raise _no_such_entity("user", user)
            for a in policy_to_actions(doc):
                if a not in ident.setdefault("actions", []):
                    ident["actions"].append(a)
            changed = True

        elif action == "GetUserPolicy":
            ident = self._find(conf, user)
            if ident is None or not ident.get("actions"):
                raise _no_such_entity("user", user)
            ET.SubElement(result, "UserName").text = user
            ET.SubElement(result, "PolicyName").text = \
                params.get("PolicyName", "")
            ET.SubElement(result, "PolicyDocument").text = json.dumps(
                actions_to_policy(ident["actions"]))

        elif action == "DeleteUserPolicy":
            ident = self._find(conf, user)
            if ident is None:
                raise _no_such_entity("user", user)
            ident["actions"] = []
            changed = True

        else:
            raise IamError("NotImplemented",
                           f"action {action} is not implemented", 501)

        if changed:
            self.put_s3_config(conf)
        meta = ET.SubElement(root, "ResponseMetadata")
        ET.SubElement(meta, "RequestId").text = secrets.token_hex(8)
        return root, changed


class IamHandler(BaseHTTPRequestHandler):
    iam_server: IamApiServer  # bound by IamApiServer.start
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # quiet
        pass

    def _send_xml(self, status: int, root: ET.Element) -> None:
        body = b'<?xml version="1.0" encoding="UTF-8"?>\n' + \
            ET.tostring(root)
        self.send_response(status)
        self.send_header("Content-Type", "application/xml")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_error(self, err: IamError) -> None:
        root = ET.Element("ErrorResponse", xmlns=IAM_XMLNS)
        e = ET.SubElement(root, "Error")
        ET.SubElement(e, "Code").text = err.code
        ET.SubElement(e, "Message").text = err.message
        meta = ET.SubElement(root, "ResponseMetadata")
        ET.SubElement(meta, "RequestId").text = secrets.token_hex(8)
        self._send_xml(err.status, root)

    def do_POST(self) -> None:  # noqa: N802 — BaseHTTPRequestHandler API
        length = int(self.headers.get("Content-Length") or 0)
        raw = self.rfile.read(length) if length else b""
        params = {
            k: v[0] for k, v in
            parse_qs(raw.decode("utf-8", "replace"),
                     keep_blank_values=True).items()
        }
        srv = self.iam_server
        action = params.get("Action", "")
        with srv._lock:
            conf = srv.get_s3_config()
            # admin-signed requests required once an admin identity CAN
            # sign (the reference wraps DoActions in iam.Auth(...,
            # ACTION_ADMIN) over a config snapshot from startup; we re-read
            # live, so enforcement waits until some identity has both
            # credentials and Admin — else CreateUser would lock out the
            # bootstrap sequence)
            iam = IdentityAccessManagement()
            iam.load_config(conf)
            enforce = any(
                i.credentials and i.can_do(ACTION_ADMIN, "")
                for i in iam.identities
            )
            if enforce:
                req = S3HttpRequest(
                    method="POST",
                    raw_path=self.path.partition("?")[0],
                    raw_query=self.path.partition("?")[2],
                    headers={k.lower(): v for k, v in self.headers.items()},
                )
                try:
                    ident = iam.authenticate(req)
                    iam.authorize(ident, ACTION_ADMIN, "")
                except AuthError as e:
                    self._send_error(IamError("AccessDenied", str(e), 403))
                    return
                # bind the body to the signature: a signed concrete
                # payload hash MUST match what was actually sent
                # (same contract as s3api/server.py's body handler)
                if req.expected_sha256:
                    import hashlib

                    if hashlib.sha256(raw).hexdigest() != req.expected_sha256:
                        self._send_error(IamError(
                            "AccessDenied",
                            "request body does not match the signed "
                            "x-amz-content-sha256", 403))
                        return
            try:
                root, _ = srv.do_action(action, params, conf)
            except IamError as e:
                self._send_error(e)
                return
            except Exception as e:  # noqa: BLE001
                self._send_error(IamError("ServiceFailure", str(e), 500))
                return
        self._send_xml(200, root)
