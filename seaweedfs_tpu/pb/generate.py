"""Regenerate *_pb2.py from the .proto files with protoc.

No grpc codegen plugin is available in this image, so services are wired via
grpc's generic-handler API (see rpc.py) against these message classes.
Run: python -m seaweedfs_tpu.pb.generate
"""

from __future__ import annotations

import os
import subprocess

HERE = os.path.dirname(os.path.abspath(__file__))
PROTOS = [
    "master.proto",
    "volume_server.proto",
    "filer.proto",
    "messaging.proto",
    "volume_info.proto",
    "etcd.proto",
]


def main() -> None:
    subprocess.run(
        ["protoc", f"-I{HERE}", f"--python_out={HERE}", *PROTOS],
        cwd=HERE,
        check=True,
    )
    print("generated:", ", ".join(p.replace(".proto", "_pb2.py") for p in PROTOS))


if __name__ == "__main__":
    main()
