"""gRPC plumbing without generated service stubs.

This image ships protoc but no grpc codegen plugin, so services are declared
once (method name -> kind + message classes) and wired through grpc's
generic-handler API on the server and channel.unary_unary/... on the client.
Mirrors the reference's shared connection cache (pb/grpc_client_server.go).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Callable

import grpc

from ..telemetry import trace as _trace
from ..util import failsafe as _failsafe
from . import filer_pb2, master_pb2, messaging_pb2, volume_server_pb2

UU, US, SU, SS = "uu", "us", "su", "ss"  # unary/stream request x response


@dataclass(frozen=True)
class Method:
    kind: str
    request: type
    response: type


@dataclass(frozen=True)
class Service:
    name: str  # fully-qualified, e.g. "master_pb.Seaweed"
    methods: dict


def _m(kind, req, resp):
    return Method(kind, req, resp)


MASTER = Service("master_pb.Seaweed", {
    "SendHeartbeat": _m(SS, master_pb2.Heartbeat, master_pb2.HeartbeatResponse),
    "KeepConnected": _m(SS, master_pb2.KeepConnectedRequest, master_pb2.VolumeLocation),
    "LookupVolume": _m(UU, master_pb2.LookupVolumeRequest, master_pb2.LookupVolumeResponse),
    "Assign": _m(UU, master_pb2.AssignRequest, master_pb2.AssignResponse),
    "Statistics": _m(UU, master_pb2.StatisticsRequest, master_pb2.StatisticsResponse),
    "CollectionList": _m(UU, master_pb2.CollectionListRequest, master_pb2.CollectionListResponse),
    "CollectionDelete": _m(UU, master_pb2.CollectionDeleteRequest, master_pb2.CollectionDeleteResponse),
    "VolumeList": _m(UU, master_pb2.VolumeListRequest, master_pb2.VolumeListResponse),
    "LookupEcVolume": _m(UU, master_pb2.LookupEcVolumeRequest, master_pb2.LookupEcVolumeResponse),
    "VacuumVolume": _m(UU, master_pb2.VacuumVolumeRequest, master_pb2.VacuumVolumeResponse),
    "GetMasterConfiguration": _m(UU, master_pb2.GetMasterConfigurationRequest, master_pb2.GetMasterConfigurationResponse),
    "ListMasterClients": _m(UU, master_pb2.ListMasterClientsRequest, master_pb2.ListMasterClientsResponse),
    "LeaseAdminToken": _m(UU, master_pb2.LeaseAdminTokenRequest, master_pb2.LeaseAdminTokenResponse),
    "ReleaseAdminToken": _m(UU, master_pb2.ReleaseAdminTokenRequest, master_pb2.ReleaseAdminTokenResponse),
    "Lifecycle": _m(UU, master_pb2.LifecycleRequest, master_pb2.LifecycleResponse),
})

_V = volume_server_pb2
VOLUME_SERVER = Service("volume_server_pb.VolumeServer", {
    "BatchDelete": _m(UU, _V.BatchDeleteRequest, _V.BatchDeleteResponse),
    "VacuumVolumeCheck": _m(UU, _V.VacuumVolumeCheckRequest, _V.VacuumVolumeCheckResponse),
    "VacuumVolumeCompact": _m(UU, _V.VacuumVolumeCompactRequest, _V.VacuumVolumeCompactResponse),
    "VacuumVolumeCommit": _m(UU, _V.VacuumVolumeCommitRequest, _V.VacuumVolumeCommitResponse),
    "VacuumVolumeCleanup": _m(UU, _V.VacuumVolumeCleanupRequest, _V.VacuumVolumeCleanupResponse),
    "DeleteCollection": _m(UU, _V.DeleteCollectionRequest, _V.DeleteCollectionResponse),
    "AllocateVolume": _m(UU, _V.AllocateVolumeRequest, _V.AllocateVolumeResponse),
    "VolumeSyncStatus": _m(UU, _V.VolumeSyncStatusRequest, _V.VolumeSyncStatusResponse),
    "VolumeIncrementalCopy": _m(US, _V.VolumeIncrementalCopyRequest, _V.VolumeIncrementalCopyResponse),
    "VolumeMount": _m(UU, _V.VolumeMountRequest, _V.VolumeMountResponse),
    "VolumeUnmount": _m(UU, _V.VolumeUnmountRequest, _V.VolumeUnmountResponse),
    "VolumeDelete": _m(UU, _V.VolumeDeleteRequest, _V.VolumeDeleteResponse),
    "VolumeMarkReadonly": _m(UU, _V.VolumeMarkReadonlyRequest, _V.VolumeMarkReadonlyResponse),
    "VolumeMarkWritable": _m(UU, _V.VolumeMarkWritableRequest, _V.VolumeMarkWritableResponse),
    "VolumeConfigure": _m(UU, _V.VolumeConfigureRequest, _V.VolumeConfigureResponse),
    "VolumeStatus": _m(UU, _V.VolumeStatusRequest, _V.VolumeStatusResponse),
    "VolumeCopy": _m(UU, _V.VolumeCopyRequest, _V.VolumeCopyResponse),
    "ReadVolumeFileStatus": _m(UU, _V.ReadVolumeFileStatusRequest, _V.ReadVolumeFileStatusResponse),
    "CopyFile": _m(US, _V.CopyFileRequest, _V.CopyFileResponse),
    "ReadNeedleBlob": _m(UU, _V.ReadNeedleBlobRequest, _V.ReadNeedleBlobResponse),
    "WriteNeedleBlob": _m(UU, _V.WriteNeedleBlobRequest, _V.WriteNeedleBlobResponse),
    "ReadAllNeedles": _m(US, _V.ReadAllNeedlesRequest, _V.ReadAllNeedlesResponse),
    "VolumeTailSender": _m(US, _V.VolumeTailSenderRequest, _V.VolumeTailSenderResponse),
    "VolumeTailReceiver": _m(UU, _V.VolumeTailReceiverRequest, _V.VolumeTailReceiverResponse),
    "VolumeEcShardsGenerate": _m(UU, _V.VolumeEcShardsGenerateRequest, _V.VolumeEcShardsGenerateResponse),
    "VolumeEcShardsRebuild": _m(UU, _V.VolumeEcShardsRebuildRequest, _V.VolumeEcShardsRebuildResponse),
    "VolumeEcShardsBatchRebuild": _m(UU, _V.VolumeEcShardsBatchRebuildRequest, _V.VolumeEcShardsBatchRebuildResponse),
    "VolumeEcShardsCopy": _m(UU, _V.VolumeEcShardsCopyRequest, _V.VolumeEcShardsCopyResponse),
    "VolumeEcShardsDelete": _m(UU, _V.VolumeEcShardsDeleteRequest, _V.VolumeEcShardsDeleteResponse),
    "VolumeEcShardsMount": _m(UU, _V.VolumeEcShardsMountRequest, _V.VolumeEcShardsMountResponse),
    "VolumeEcShardsUnmount": _m(UU, _V.VolumeEcShardsUnmountRequest, _V.VolumeEcShardsUnmountResponse),
    "VolumeEcShardRead": _m(US, _V.VolumeEcShardReadRequest, _V.VolumeEcShardReadResponse),
    "VolumeEcShardPartialApply": _m(US, _V.VolumeEcShardPartialApplyRequest, _V.VolumeEcShardPartialApplyResponse),
    "VolumeEcBlobDelete": _m(UU, _V.VolumeEcBlobDeleteRequest, _V.VolumeEcBlobDeleteResponse),
    "VolumeEcShardsToVolume": _m(UU, _V.VolumeEcShardsToVolumeRequest, _V.VolumeEcShardsToVolumeResponse),
    "VolumeTierMoveDatToRemote": _m(US, _V.VolumeTierMoveDatToRemoteRequest, _V.VolumeTierMoveDatToRemoteResponse),
    "VolumeTierMoveDatFromRemote": _m(US, _V.VolumeTierMoveDatFromRemoteRequest, _V.VolumeTierMoveDatFromRemoteResponse),
    "VolumeServerStatus": _m(UU, _V.VolumeServerStatusRequest, _V.VolumeServerStatusResponse),
    "VolumeServerLeave": _m(UU, _V.VolumeServerLeaveRequest, _V.VolumeServerLeaveResponse),
    "Query": _m(US, _V.QueryRequest, _V.QueriedStripe),
    "VolumeNeedleStatus": _m(UU, _V.VolumeNeedleStatusRequest, _V.VolumeNeedleStatusResponse),
    "VolumeScrub": _m(UU, _V.VolumeScrubRequest, _V.VolumeScrubResponse),
})

_F = filer_pb2
FILER = Service("filer_pb.SeaweedFiler", {
    "LookupDirectoryEntry": _m(UU, _F.LookupDirectoryEntryRequest, _F.LookupDirectoryEntryResponse),
    "ListEntries": _m(US, _F.ListEntriesRequest, _F.ListEntriesResponse),
    "CreateEntry": _m(UU, _F.CreateEntryRequest, _F.CreateEntryResponse),
    "UpdateEntry": _m(UU, _F.UpdateEntryRequest, _F.UpdateEntryResponse),
    "AppendToEntry": _m(UU, _F.AppendToEntryRequest, _F.AppendToEntryResponse),
    "DeleteEntry": _m(UU, _F.DeleteEntryRequest, _F.DeleteEntryResponse),
    "AtomicRenameEntry": _m(UU, _F.AtomicRenameEntryRequest, _F.AtomicRenameEntryResponse),
    "AssignVolume": _m(UU, _F.AssignVolumeRequest, _F.AssignVolumeResponse),
    "LookupVolume": _m(UU, _F.LookupVolumeRequest, _F.LookupVolumeResponse),
    "CollectionList": _m(UU, _F.CollectionListRequest, _F.CollectionListResponse),
    "DeleteCollection": _m(UU, _F.DeleteCollectionRequest, _F.DeleteCollectionResponse),
    "Statistics": _m(UU, _F.StatisticsRequest, _F.StatisticsResponse),
    "GetFilerConfiguration": _m(UU, _F.GetFilerConfigurationRequest, _F.GetFilerConfigurationResponse),
    "SubscribeMetadata": _m(US, _F.SubscribeMetadataRequest, _F.SubscribeMetadataResponse),
    "SubscribeLocalMetadata": _m(US, _F.SubscribeMetadataRequest, _F.SubscribeMetadataResponse),
    "KeepConnected": _m(SS, _F.KeepConnectedRequest, _F.KeepConnectedResponse),
    "LocateBroker": _m(UU, _F.LocateBrokerRequest, _F.LocateBrokerResponse),
    "KvGet": _m(UU, _F.KvGetRequest, _F.KvGetResponse),
    "KvPut": _m(UU, _F.KvPutRequest, _F.KvPutResponse),
})

_MSG = messaging_pb2
MESSAGING = Service("messaging_pb.SeaweedMessaging", {
    "Subscribe": _m(SS, _MSG.SubscriberMessage, _MSG.BrokerMessage),
    "Publish": _m(SS, _MSG.PublishRequest, _MSG.PublishResponse),
    "DeleteTopic": _m(UU, _MSG.DeleteTopicRequest, _MSG.DeleteTopicResponse),
    "ConfigureTopic": _m(UU, _MSG.ConfigureTopicRequest, _MSG.ConfigureTopicResponse),
    "GetTopicConfiguration": _m(UU, _MSG.GetTopicConfigurationRequest, _MSG.GetTopicConfigurationResponse),
    "FindBroker": _m(UU, _MSG.FindBrokerRequest, _MSG.FindBrokerResponse),
})

# etcd v3 KV plane (the real service name, so the same stub talks to a
# stock etcd server or the framework's in-process fake)
from . import etcd_pb2  # noqa: E402

ETCD_KV = Service("etcdserverpb.KV", {
    "Range": _m(UU, etcd_pb2.RangeRequest, etcd_pb2.RangeResponse),
    "Put": _m(UU, etcd_pb2.PutRequest, etcd_pb2.PutResponse),
    "DeleteRange": _m(UU, etcd_pb2.DeleteRangeRequest, etcd_pb2.DeleteRangeResponse),
    "Txn": _m(UU, etcd_pb2.TxnRequest, etcd_pb2.TxnResponse),
})


# ---------------------------------------------------------------------------
# mTLS (security/tls.py loads these from security.toml; set once at startup
# before any server/channel exists — mirrors the reference wiring where
# every component resolves its grpc credentials from config at boot)
# ---------------------------------------------------------------------------

_server_credentials: "grpc.ServerCredentials | None" = None
_channel_credentials: "grpc.ChannelCredentials | None" = None


def configure_security(server_credentials=None, channel_credentials=None) -> None:
    """Install process-wide gRPC credentials (None = plaintext)."""
    global _server_credentials, _channel_credentials
    _server_credentials = server_credentials
    _channel_credentials = channel_credentials
    with _channel_lock:
        for ch in _channels.values():
            ch.close()
        _channels.clear()


# ---------------------------------------------------------------------------
# Server side
# ---------------------------------------------------------------------------

# request-metric `type` label per service (the gRPC surface of each
# server, kept distinct from its HTTP surface's type label)
_GRPC_TYPE = {
    "master_pb.Seaweed": "masterGrpc",
    "volume_server_pb.VolumeServer": "volumeServerGrpc",
    "filer_pb.SeaweedFiler": "filerGrpc",
    "messaging_pb.SeaweedMessaging": "messagingGrpc",
    "etcdserverpb.KV": "etcdGrpc",
}


def _traced_unary(server_type: str, method: str, fn: Callable) -> Callable:
    """Wrap a unary-unary servicer fn with trace adoption + request
    metrics: the caller's `traceparent` rides in as gRPC metadata."""

    from ..telemetry import record_op

    def handler(request, context):
        md = {k: v for k, v in (context.invocation_metadata() or ())}
        with _trace.remote_context(md.get(_trace.TRACEPARENT)):
            with record_op(server_type, method):
                return fn(request, context)

    return handler


def _counted_stream(server_type: str, method: str, fn: Callable) -> Callable:
    """Streaming rpcs are counted but not timed (a stream's lifetime is
    not a request latency) and not spanned (the generator body outlives
    the handler call, so a scoped span would lie)."""

    def handler(request_or_iterator, context):
        from ..stats.metrics import REQUEST_COUNTER

        REQUEST_COUNTER.labels(server_type, method).inc()
        return fn(request_or_iterator, context)

    return handler


def generic_handler(service: Service, impl: object) -> grpc.GenericRpcHandler:
    """Build a GenericRpcHandler from an object with methods named like the
    service's rpcs.  Unimplemented rpcs answer UNIMPLEMENTED."""
    from ..stats.metrics import GRPC_BYTES

    handlers = {}
    server_type = _GRPC_TYPE.get(service.name, service.name)
    for name, m in service.methods.items():
        fn: Callable | None = getattr(impl, name, None)
        if fn is None:
            fn = _unimplemented(name)
        # serialized-byte accounting at the codec boundary: the exact
        # wire payload of every rpc, per method and direction.  Children
        # are created LAZILY on first traffic — eagerly materializing
        # rx/tx for every method of every service (~90 on a volume
        # server) would crowd the heartbeat's 512-sample stats snapshot
        # with zeros for rpcs never called
        rx_cell: list = []
        tx_cell: list = []

        def deser(data, _from=m.request.FromString, _cell=rx_cell,
                  _st=server_type, _n=name):
            if not _cell:
                _cell.append(GRPC_BYTES.labels(_st, _n, "rx"))
            _cell[0].inc(len(data))
            return _from(data)

        def ser(msg, _to=m.response.SerializeToString, _cell=tx_cell,
                _st=server_type, _n=name):
            blob = _to(msg)
            if not _cell:
                _cell.append(GRPC_BYTES.labels(_st, _n, "tx"))
            _cell[0].inc(len(blob))
            return blob
        if m.kind == UU:
            handlers[name] = grpc.unary_unary_rpc_method_handler(
                _traced_unary(server_type, name, fn), deser, ser)
        elif m.kind == US:
            handlers[name] = grpc.unary_stream_rpc_method_handler(
                _counted_stream(server_type, name, fn), deser, ser)
        elif m.kind == SU:
            handlers[name] = grpc.stream_unary_rpc_method_handler(
                _counted_stream(server_type, name, fn), deser, ser)
        else:
            handlers[name] = grpc.stream_stream_rpc_method_handler(
                _counted_stream(server_type, name, fn), deser, ser)
    return grpc.method_handlers_generic_handler(service.name, handlers)


def _unimplemented(name: str):
    def handler(request, context):
        context.abort(grpc.StatusCode.UNIMPLEMENTED, f"{name} not implemented")

    return handler


def serve(
    service_impls: list[tuple[Service, object]],
    port: int,
    host: str = "0.0.0.0",
    max_workers: int = 16,
) -> grpc.Server:
    """Start a grpc server hosting the given services; returns it started."""
    from concurrent import futures

    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        options=[
            ("grpc.max_send_message_length", 128 * 1024 * 1024),
            ("grpc.max_receive_message_length", 128 * 1024 * 1024),
        ],
    )
    for service, impl in service_impls:
        server.add_generic_rpc_handlers((generic_handler(service, impl),))
    if _server_credentials is not None:
        server.add_secure_port(f"{host}:{port}", _server_credentials)
    else:
        server.add_insecure_port(f"{host}:{port}")
    server.start()
    return server


# ---------------------------------------------------------------------------
# Client side: a stub facade over a cached channel
# ---------------------------------------------------------------------------

_channel_lock = threading.Lock()
_channels: dict[str, grpc.Channel] = {}


def get_channel(address: str) -> grpc.Channel:
    with _channel_lock:
        ch = _channels.get(address)
        if ch is None:
            options = [
                ("grpc.max_send_message_length", 128 * 1024 * 1024),
                ("grpc.max_receive_message_length", 128 * 1024 * 1024),
            ]
            if _channel_credentials is not None:
                ch = grpc.secure_channel(
                    address, _channel_credentials, options=options)
            else:
                ch = grpc.insecure_channel(address, options=options)
            _channels[address] = ch
        return ch


class Stub:
    """Callable rpc facade: stub.MethodName(request) / (request_iterator)."""

    def __init__(self, service: Service, address: str, timeout: float | None = None):
        self._service = service
        self._channel = get_channel(address)
        self._timeout = timeout

    def __getattr__(self, name: str):
        m = self._service.methods.get(name)
        if m is None:
            raise AttributeError(name)
        path = f"/{self._service.name}/{name}"
        kw = dict(
            request_serializer=m.request.SerializeToString,
            response_deserializer=m.response.FromString,
        )
        if m.kind == UU:
            call = self._channel.unary_unary(path, **kw)
        elif m.kind == US:
            call = self._channel.unary_stream(path, **kw)
        elif m.kind == SU:
            call = self._channel.stream_unary(path, **kw)
        else:
            call = self._channel.stream_stream(path, **kw)
        timeout = self._timeout
        unary_response = m.kind in (UU, SU)

        def _call_with_trace(args, kwargs):
            # the header is captured INSIDE any client span so the
            # server's span parents to it, not to the enclosing span
            metadata = list(kwargs.pop("metadata", ()) or ())
            hdr = _trace.traceparent_header()
            if hdr is not None:
                metadata.append((_trace.TRACEPARENT, hdr))
            return call(*args, metadata=metadata, **kwargs)

        def invoke(*args, **kwargs):
            if "timeout" not in kwargs:
                # deadline propagation: an ambient failsafe.Deadline caps
                # every nested rpc so a caller's total budget holds across
                # hops (a 10s stub timeout inside a 2s budget is a lie)
                effective = timeout
                dl = _failsafe.current_deadline()
                if dl is not None:
                    rem = dl.remaining()
                    if rem <= 0.0:
                        # firing a guaranteed-to-fail 1ms rpc would charge
                        # a DEADLINE_EXCEEDED to a healthy peer's breaker
                        raise _failsafe.DeadlineExceeded(
                            f"deadline exceeded before {path}")
                    effective = rem if effective is None else min(effective, rem)
                if effective is not None:
                    kwargs["timeout"] = effective
            if unary_response and _trace.current_context() is not None:
                # client-side span: only when already inside a trace (a
                # root span per background heartbeat would flood the
                # ring), and only for unary responses (a returned stream
                # outlives the call)
                with _trace.start_span(f"grpc{path}"):
                    return _call_with_trace(args, kwargs)
            return _call_with_trace(args, kwargs)

        return invoke


def master_stub(address: str, timeout: float | None = None) -> Stub:
    return Stub(MASTER, address, timeout)


def volume_server_stub(address: str, timeout: float | None = None) -> Stub:
    return Stub(VOLUME_SERVER, address, timeout)


def filer_stub(address: str, timeout: float | None = None) -> Stub:
    return Stub(FILER, address, timeout)


def etcd_kv_stub(address: str, timeout: float | None = None) -> Stub:
    return Stub(ETCD_KV, address, timeout)
