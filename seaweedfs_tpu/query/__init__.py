"""SQL-on-blob SELECT evaluation for the volume Query rpc.

Reference: weed/query/ (json/, sqltypes/) + volume_grpc_query.go:12.
"""

from .engine import query_csv_lines, query_json_lines

__all__ = ["query_json_lines", "query_csv_lines"]
