"""SELECT-over-JSON/CSV evaluation for needle contents.

Reference: weed/query/json/query_json.go (gjson path filtering +
projection) and the CSV input surface of volume_server.proto's
QueryRequest (the reference left its CSV branch empty —
volume_grpc_query.go:38; this build implements it).

A filter is (field, operand, value); operands: = != < <= > >=.
Comparison is numeric when both sides parse as numbers, else string —
the same dual behavior gjson's queryMatches gives the reference.
Fields address nested JSON with dotted paths ("a.b.c"); projections
select fields into the emitted records.
"""

from __future__ import annotations

import csv
import io
import json


def _lookup(doc, dotted: str):
    """Resolve a dotted path inside parsed JSON; None when absent."""
    node = doc
    for part in dotted.split("."):
        if isinstance(node, list):
            try:
                node = node[int(part)]
                continue
            except (ValueError, IndexError):
                return None
        if not isinstance(node, dict) or part not in node:
            return None
        node = node[part]
    return node


def _compare(value, op: str, target: str) -> bool:
    if value is None:
        return False
    if not op:
        return True  # existence check
    # numeric when both sides are numbers, else lexicographic
    try:
        left = float(value) if not isinstance(value, bool) else None
        right = float(target)
    except (TypeError, ValueError):
        left = right = None
    if left is None or right is None:
        left, right = str(value), target
        if isinstance(value, bool):
            left = "true" if value else "false"
    if op == "=":
        return left == right
    if op == "!=":
        return left != right
    if op == "<":
        return left < right
    if op == "<=":
        return left <= right
    if op == ">":
        return left > right
    if op == ">=":
        return left >= right
    return False


def query_json_lines(data: bytes, selections: list[str],
                     field: str = "", op: str = "", value: str = "",
                     document: bool = False) -> bytes:
    """Evaluate the filter over JSON lines (or one document); emit
    newline-delimited JSON records of the selected fields (all fields
    when no selection)."""
    text = data.decode("utf-8", errors="replace")
    lines = [text] if document else text.splitlines()
    out = io.StringIO()
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            doc = json.loads(line)
        except ValueError:
            continue
        if field and not _compare(_lookup(doc, field), op, value):
            continue
        if selections:
            record = {s: _lookup(doc, s) for s in selections}
        else:
            record = doc
        out.write(json.dumps(record, separators=(",", ":")))
        out.write("\n")
    return out.getvalue().encode()


def query_csv_lines(data: bytes, selections: list[str],
                    field: str = "", op: str = "", value: str = "",
                    header: str = "USE", delimiter: str = ",",
                    comment: str = "#") -> bytes:
    """Evaluate the filter over CSV rows.

    header=USE names columns from the first row (fields address columns
    by name); NONE/IGNORE address them positionally as _1, _2, ...
    Output rows contain the selected columns, CSV-encoded.
    """
    text = data.decode("utf-8", errors="replace")
    reader = csv.reader(io.StringIO(text), delimiter=delimiter or ",")
    rows = [r for r in reader
            if r and not (comment and r[0].startswith(comment))]
    if not rows:
        return b""
    if (header or "USE").upper() == "USE":
        columns = rows[0]
        rows = rows[1:]
    else:
        columns = [f"_{i + 1}" for i in range(len(rows[0]))]
        if (header or "").upper() == "IGNORE":
            rows = rows[1:]
    index = {c: i for i, c in enumerate(columns)}
    out = io.StringIO()
    writer = csv.writer(out, delimiter=delimiter or ",",
                        lineterminator="\n")
    # unknown selected columns emit empty cells so output stays aligned
    # with the requested selections (json emits null for the same case)
    sel_idx = [index.get(s) for s in selections]
    for row in rows:
        if field:
            i = index.get(field)
            cell = row[i] if i is not None and i < len(row) else None
            if not _compare(cell, op, value):
                continue
        if selections:
            writer.writerow([
                row[i] if i is not None and i < len(row) else ""
                for i in sel_idx])
        else:
            writer.writerow(row)
    return out.getvalue().encode()
