"""Volume/needle TTL: 2-byte (count, unit) encoding.

Reference: weed/storage/needle/volume_ttl.go — units minute/hour/day/week/
month/year stored as bytes 1..6, empty as (0, 0).
"""

from __future__ import annotations

from dataclasses import dataclass

EMPTY, MINUTE, HOUR, DAY, WEEK, MONTH, YEAR = range(7)

_UNIT_BY_CHAR = {"m": MINUTE, "h": HOUR, "d": DAY, "w": WEEK, "M": MONTH, "y": YEAR}
_CHAR_BY_UNIT = {v: k for k, v in _UNIT_BY_CHAR.items()}
_MINUTES_BY_UNIT = {
    EMPTY: 0,
    MINUTE: 1,
    HOUR: 60,
    DAY: 60 * 24,
    WEEK: 60 * 24 * 7,
    MONTH: 60 * 24 * 30,
    YEAR: 60 * 24 * 365,
}


@dataclass(frozen=True)
class TTL:
    count: int = 0
    unit: int = EMPTY

    @classmethod
    def parse(cls, s: str) -> "TTL":
        """'3m', '4h', '5d', '6w', '7M', '8y'; bare digits mean minutes."""
        if not s:
            return cls()
        unit_ch = s[-1]
        if unit_ch.isdigit():
            count_str, unit = s, MINUTE
        else:
            count_str, unit = s[:-1], _UNIT_BY_CHAR.get(unit_ch, EMPTY)
        return cls(int(count_str), unit)

    @classmethod
    def from_bytes(cls, b: bytes) -> "TTL":
        if b[0] == 0 and b[1] == 0:
            return cls()
        return cls(b[0], b[1])

    @classmethod
    def from_uint32(cls, v: int) -> "TTL":
        return cls.from_bytes(bytes([(v >> 8) & 0xFF, v & 0xFF]))

    def to_bytes(self) -> bytes:
        return bytes([self.count & 0xFF, self.unit & 0xFF])

    def to_uint32(self) -> int:
        if self.count == 0:
            return 0
        return (self.count << 8) | self.unit

    def minutes(self) -> int:
        return self.count * _MINUTES_BY_UNIT.get(self.unit, 0)

    def seconds(self) -> int:
        return self.minutes() * 60

    def expired(self, modified_at_second: float,
                now: float | None = None) -> bool:
        """Volume-granularity expiry (the lifecycle controller's
        ttl_expire transition): a TTL volume whose last write is older
        than the TTL is expired wholesale, like the reference's TTL
        volume deletion."""
        if self.count == 0 or self.unit == EMPTY:
            return False
        if modified_at_second <= 0:
            return False  # never-written / unknown: do not expire
        import time as _time

        if now is None:
            now = _time.time()
        return now - modified_at_second > self.seconds()

    def __str__(self) -> str:
        if self.count == 0 or self.unit == EMPTY:
            return ""
        return f"{self.count}{_CHAR_BY_UNIT[self.unit]}"
