"""EC volume runtime: open shards + sorted index, needle reads with
on-the-fly reconstruction, deletes via the `.ecj` journal.

Reference: ec_volume.go (search/locate), ec_shard.go (shard ReadAt),
ec_volume_delete.go (tombstone + journal), store_ec.go (degraded read).
The remote-shard fetch hook lets the volume server plug in gRPC reads; a
standalone EcVolume reconstructs from whatever local shards exist.
"""

from __future__ import annotations

import mmap
import os
import threading
from dataclasses import dataclass
from typing import Callable

import numpy as np

from ...ops import codec_service, gf256
from ...ops.codec import get_codec
from ...stats.metrics import (
    EC_PARTIAL_FALLBACK,
    EC_PREADV_BATCHES,
    EC_SINGLEFLIGHT,
)
from ...util.chunk_cache import IntervalCache
from .. import idx as idx_mod
from .. import types as t
from ..needle import CorruptNeedleError, Needle, actual_size
from ..super_block import VERSION3
from .constants import (
    DATA_SHARDS,
    LARGE_BLOCK_SIZE,
    SMALL_BLOCK_SIZE,
    TOTAL_SHARDS,
    to_ext,
)
from .locate import Interval, locate_data, shard_file_size


class NotFoundError(KeyError):
    pass


def _ec_odirect_enabled() -> bool:
    return os.environ.get(
        "SEAWEEDFS_TPU_EC_ODIRECT", "0").strip().lower() in (
        "1", "on", "true", "yes")


_DIRECT_ALIGN = 4096  # sector/page alignment O_DIRECT demands


@dataclass
class EcVolumeShard:
    volume_id: int
    shard_id: int
    path: str

    def __post_init__(self):
        self._f = open(self.path, "rb")
        self.size = os.path.getsize(self.path)
        self._dfd: "int | None" = None  # lazily opened O_DIRECT fd

    def read_at(self, offset: int, length: int) -> bytes:
        # positioned read: concurrent degraded reads share this handle, so
        # a seek+read pair would interleave (reference: ReadAt pread
        # discipline, ec_shard.go:93).  Deliberately NOT an mmap: a shard
        # file truncated by a racing re-copy turns a mapped read into
        # SIGBUS and kills the whole volume server (observed in the r05
        # suite); pread of a truncated/deleted-but-open file just short-
        # reads, which callers already handle.
        return os.pread(self._f.fileno(), length, offset)

    def read_many(self, spans: "list[tuple[int, int]]") -> "list[bytes] | None":
        """Scatter ONE contiguous shard-file range into per-span buffers
        with a single preadv(2) — the batched large-sequential read path.
        ``spans`` are (offset, length) pairs that must tile an ascending
        gap-free range.  Returns None on any error or shortfall so the
        caller falls back to the per-interval path, which already
        degrades local -> remote -> reconstruct."""
        if not spans:
            return []
        start = spans[0][0]
        total = sum(length for _, length in spans)
        if _ec_odirect_enabled():
            data = self._read_direct(start, total)
            if data is not None:
                out: list[bytes] = []
                at = 0
                for _, length in spans:
                    out.append(data[at:at + length])
                    at += length
                return out
        bufs = [bytearray(length) for _, length in spans]
        try:
            got = os.preadv(self._f.fileno(), bufs, start)
        except (OSError, ValueError):
            return None
        if got != total:
            return None
        return [bytes(b) for b in bufs]

    def _direct_fd(self) -> int:
        if self._dfd is None:
            try:
                self._dfd = os.open(self.path, os.O_RDONLY | os.O_DIRECT)
            except (OSError, AttributeError):
                self._dfd = -1  # filesystem refused O_DIRECT: remember
        return self._dfd

    def _read_direct(self, start: int, total: int) -> "bytes | None":
        """O_DIRECT read covering [start, start+total): page-cache bypass
        for large sequential EC scans so they do not evict the hot
        small-needle working set.  The kernel demands aligned fd offset,
        length and buffer address — an anonymous mmap is always
        page-aligned.  None -> caller uses the buffered path."""
        fd = self._direct_fd()
        if fd < 0:
            return None
        lo = start - (start % _DIRECT_ALIGN)
        hi = -(-(start + total) // _DIRECT_ALIGN) * _DIRECT_ALIGN
        try:
            buf = mmap.mmap(-1, hi - lo)
        except (OSError, ValueError):
            return None
        try:
            try:
                got = os.preadv(fd, [buf], lo)
            except OSError:
                return None
            # short read is fine only past EOF padding; the needle bytes
            # themselves must be fully covered
            if got < (start - lo) + total:
                return None
            return bytes(buf[start - lo:start - lo + total])
        finally:
            buf.close()

    def close(self) -> None:
        self._f.close()
        if self._dfd is not None and self._dfd >= 0:
            try:
                os.close(self._dfd)
            except OSError:
                pass
            self._dfd = -1


# fetch_fn(shard_id, offset, length) -> bytes | None  (e.g. a gRPC client)
FetchFn = Callable[[int, int, int], "bytes | None"]

_SF_LEADER = EC_SINGLEFLIGHT.labels("leader")
_SF_COALESCED = EC_SINGLEFLIGHT.labels("coalesced")

# one bounded process-wide executor for degraded-read remote fetches:
# the old per-call ThreadPoolExecutor paid thread spawn+teardown on
# EVERY reconstructed interval (observed as the top non-I/O cost of a
# degraded-read storm) and put no ceiling on total fetch threads
_FETCH_POOL = None
_FETCH_POOL_LOCK = threading.Lock()


_HOST_CODEC = None


def _host_codec():
    """Shared host SIMD codec for the partial-decode local term — the
    volume's own codec may be a device codec, and a per-needle degraded
    read must never pay device dispatch."""
    global _HOST_CODEC
    if _HOST_CODEC is None:
        _HOST_CODEC = get_codec("cpu")
    return _HOST_CODEC


def _fetch_pool():
    global _FETCH_POOL
    if _FETCH_POOL is None:
        with _FETCH_POOL_LOCK:
            if _FETCH_POOL is None:
                from ...util.executors import MeteredThreadPoolExecutor

                workers = int(os.environ.get(
                    "SEAWEEDFS_TPU_EC_FETCH_WORKERS", "16"))
                _FETCH_POOL = MeteredThreadPoolExecutor(
                    max_workers=workers, name="ec_fetch",
                    thread_name_prefix="ec-fetch")
    return _FETCH_POOL


class _SingleFlight:
    """One in-flight gather+decode; followers wait on the event.  The
    leader records the invalidation token its gather was captured under
    so followers can reject a result made stale by a racing
    mount/unmount/delete."""

    __slots__ = ("done", "result", "err", "token")

    def __init__(self):
        self.done = threading.Event()
        self.result: bytes | None = None
        self.err: Exception | None = None
        self.token: "tuple[int, int] | None" = None


class EcVolume:
    """An erasure-coded volume: local shards + .ecx index + .ecj journal."""

    def __init__(
        self,
        base_name: str,
        volume_id: int = 0,
        version: int = VERSION3,
        codec_name: str = "cpu",
        large_block_size: int = LARGE_BLOCK_SIZE,
        small_block_size: int = SMALL_BLOCK_SIZE,
        collection: str = "",
    ):
        self.base_name = base_name
        self.volume_id = volume_id
        self.collection = collection
        self.version = version
        self.codec = get_codec(codec_name)
        self.large_block_size = large_block_size
        self.small_block_size = small_block_size
        self.shards: dict[int, EcVolumeShard] = {}
        self._ecx = open(base_name + ".ecx", "r+b")
        self.ecx_size = os.path.getsize(base_name + ".ecx")
        self._ecx_keys_arr = None  # lazy key cache; False = don't cache
        self._ecj_lock = threading.Lock()
        self._ecx_derived_shard_size: int | None = None
        # bumped on every tombstone: the needle cache's compare-before-put
        # token (EC volumes never append, so deletes are the only writers)
        self.delete_seq = 0
        # bumped on every shard mount/unmount: re-copies swap shard file
        # contents wholesale, so reconstructed intervals captured under an
        # older layout must never be served
        self.mount_seq = 0
        self.remote_fetch: FetchFn | None = None
        # partial-sum repair client (storage.ec.partial): degraded reads
        # pull ONE coefficient-weighted partial per rack from the
        # surviving holders instead of every raw sibling interval; any
        # failure falls back to the remote_fetch gather below
        self.partial_client = None
        # corruption_hook(volume_id, shard_id): the read path calls it
        # when a needle CRC failure is traced to a local shard interval
        # (the scrubber's quarantine + confirm queue on a volume server)
        self.corruption_hook: "Callable[[int, int], None] | None" = None
        # single-flight state + reconstructed-interval LRU for degraded
        # reads (0 MB disables the cache; single-flight always on)
        self._sf_lock = threading.Lock()
        self._sf_calls: dict[tuple, _SingleFlight] = {}
        cache_mb = int(os.environ.get(
            "SEAWEEDFS_TPU_EC_INTERVAL_CACHE_MB", "32"))
        self._interval_cache = (
            IntervalCache(cache_mb << 20) if cache_mb > 0 else None
        )
        for sid in range(TOTAL_SHARDS):
            p = base_name + to_ext(sid)
            if os.path.exists(p):
                self.shards[sid] = EcVolumeShard(volume_id, sid, p)

    # -- shard management -------------------------------------------------

    def _invalidate_intervals(self) -> None:
        self.mount_seq += 1
        if self._interval_cache is not None:
            self._interval_cache.clear()

    def add_shard(self, shard_id: int) -> bool:
        if shard_id in self.shards:
            return False
        p = self.base_name + to_ext(shard_id)
        self.shards[shard_id] = EcVolumeShard(self.volume_id, shard_id, p)
        self._invalidate_intervals()
        return True

    def delete_shard(self, shard_id: int) -> None:
        sh = self.shards.pop(shard_id, None)
        if sh:
            sh.close()
            self._invalidate_intervals()

    @property
    def shard_size(self) -> int:
        """Size of every shard file.  Prefer a locally mounted shard; with
        none mounted (all shards remote), use the .dat size recorded in the
        .vif at encode time; last resort, bound it from the .ecx
        (reference: ec_decoder.go FindDatFileSize derives the same bound)."""
        if self.shards:
            return next(iter(self.shards.values())).size
        if self._ecx_derived_shard_size is None:
            self._ecx_derived_shard_size = (
                self._shard_size_from_vif() or self._shard_size_from_ecx()
            )
        return self._ecx_derived_shard_size

    def _shard_size_from_vif(self) -> int | None:
        from ..vif import load_volume_info

        info = load_volume_info(self.base_name + ".vif")
        if info is None or not info.dat_file_size:
            return None
        return shard_file_size(
            info.dat_file_size, self.large_block_size, self.small_block_size
        )

    def _shard_size_from_ecx(self) -> int:
        """One bulk read of the .ecx.  Tombstoned entries lose their size
        field, so they still contribute `offset + 1` — the volume must not
        shrink because its tail needle was deleted (the shard files on the
        other holders keep their full extent)."""
        # chunked pread: one call caps at ~2GiB on Linux and need not
        # return everything it was asked for
        parts, at = [], 0
        while at < self.ecx_size:
            part = os.pread(self._ecx.fileno(),
                            min(self.ecx_size - at, 1 << 30), at)
            if not part:
                break
            parts.append(part)
            at += len(part)
        blob = b"".join(parts)
        end = 0
        for _key, offset, size in idx_mod.walk_index_blob(blob):
            if t.size_is_deleted(size):
                end = max(end, offset + 1)
            else:
                end = max(end, offset + actual_size(size, self.version))
        return shard_file_size(end, self.large_block_size, self.small_block_size)

    def shard_ids(self) -> list[int]:
        return sorted(self.shards)

    def close(self) -> None:
        for sh in self.shards.values():
            sh.close()
        self._ecx.close()

    # -- index search (binary search over the sorted .ecx) ----------------

    def find_needle_from_ecx(self, needle_id: int) -> tuple[int, int]:
        """-> (actual_offset, size); raises NotFoundError."""
        entry = self._search_ecx(needle_id)
        if entry is None:
            raise NotFoundError(f"needle {needle_id:x}")
        _pos, offset, size = entry
        return offset, size

    # entries above this stay on the pread path (keys cache = 8B/needle;
    # 4M entries = 32MB — the low-memory property EC volumes exist for)
    _ECX_KEY_CACHE_MAX = 4 << 20

    def _ecx_keys(self):
        """Contiguous big-endian u64 key column of the .ecx, cached.

        Turns the ~log2(n) pread+unpack binary search into one numpy
        searchsorted + one pread — the .ecx search was ~16% of degraded
        read wall time.  Safe to cache: tombstoning rewrites the SIZE
        field in place, never the keys, and the .ecx never grows."""
        arr = self._ecx_keys_arr
        if arr is not None:
            return arr if arr is not False else None
        n = self.ecx_size // t.NEEDLE_MAP_ENTRY_SIZE
        if n == 0 or n > self._ECX_KEY_CACHE_MAX:
            self._ecx_keys_arr = False
            return None
        try:
            mm = np.memmap(self.base_name + ".ecx", dtype=np.uint8,
                           mode="r")
            esz = t.NEEDLE_MAP_ENTRY_SIZE
            mat = mm[: n * esz].reshape(n, esz)
            keys = np.ascontiguousarray(mat[:, :8]).view(">u8").reshape(-1)
            self._ecx_keys_arr = keys
            del mm
        except (OSError, ValueError):
            self._ecx_keys_arr = False
            return None
        return self._ecx_keys_arr

    def _search_ecx(self, needle_id: int) -> tuple[int, int, int] | None:
        """-> (entry_file_pos, actual_offset, size) | None."""
        fd = self._ecx.fileno()
        keys = self._ecx_keys()
        if keys is not None:
            i = int(np.searchsorted(keys, needle_id))
            if i >= len(keys) or int(keys[i]) != needle_id:
                return None
            pos = i * t.NEEDLE_MAP_ENTRY_SIZE
            # one fresh pread for offset/size: tombstones mutate in place
            _key, offset, size = t.unpack_index_entry(
                os.pread(fd, t.NEEDLE_MAP_ENTRY_SIZE, pos))
            return pos, offset, size
        lo, hi = 0, self.ecx_size // t.NEEDLE_MAP_ENTRY_SIZE
        while lo < hi:
            mid = (lo + hi) // 2
            buf = os.pread(fd, t.NEEDLE_MAP_ENTRY_SIZE,
                           mid * t.NEEDLE_MAP_ENTRY_SIZE)
            key, offset, size = t.unpack_index_entry(buf)
            if key == needle_id:
                return mid * t.NEEDLE_MAP_ENTRY_SIZE, offset, size
            if key < needle_id:
                lo = mid + 1
            else:
                hi = mid
        return None

    # -- delete path ------------------------------------------------------

    def delete_needle(self, needle_id: int) -> None:
        """Tombstone the .ecx entry in place and append to the .ecj journal."""
        entry = self._search_ecx(needle_id)
        if entry is None:
            return
        pos, _offset, _size = entry
        self._ecx.flush()  # don't let buffered state shadow the pwrite
        os.pwrite(self._ecx.fileno(), t.size_to_bytes(t.TOMBSTONE_FILE_SIZE),
                  pos + t.NEEDLE_ID_SIZE + t.OFFSET_SIZE)
        with self._ecj_lock:
            # seq bump under the journal lock: the needle cache's
            # compare-and-put (store.py) holds the same lock, so a put
            # can never be published after the invalidation that follows
            # this delete
            self.delete_seq += 1
            with open(self.base_name + ".ecj", "ab") as j:
                j.write(t.needle_id_to_bytes(needle_id))

    # -- read path --------------------------------------------------------

    def locate(self, needle_id: int) -> tuple[int, int, list[Interval]]:
        offset, size = self.find_needle_from_ecx(needle_id)
        if self.shard_size == 0:
            # dat_size=0 would silently produce wrong intervals for
            # remote/degraded reads — fail fast instead
            raise IOError(
                f"ec volume {self.volume_id}: shard size unknown "
                "(no local shard, empty .ecx) — cannot locate intervals"
            )
        dat_size = DATA_SHARDS * self.shard_size
        intervals = locate_data(
            self.large_block_size,
            self.small_block_size,
            dat_size,
            offset,
            actual_size(size, self.version),
        )
        return offset, size, intervals

    def read_needle(self, needle_id: int) -> Needle:
        offset, size, intervals = self.locate(needle_id)
        if t.size_is_deleted(size):
            raise NotFoundError(f"needle {needle_id:x} deleted")
        parts = self._read_intervals(intervals)
        try:
            n = Needle.from_bytes(b"".join(parts), self.version)
        except CorruptNeedleError:
            # a straight shard read handed back rotten bytes (CRC caught
            # it): re-serve each interval by reconstructing it from the
            # OTHER shards, mark the shard whose bytes disagree suspect,
            # and only fail if even the rebuilt needle is corrupt
            n = self._reread_corrupt(intervals, parts)
        if n.id != needle_id:
            raise NotFoundError(
                f"needle id mismatch: want {needle_id:x} got {n.id:x}"
            )
        return n

    def first_live_needle(self) -> "int | None":
        """First non-tombstoned needle id in the .ecx, or None — the
        canary's probe target (any live needle exercises the same
        locate + interval + decode machinery)."""
        esz = t.NEEDLE_MAP_ENTRY_SIZE
        chunk = (1 << 16) // esz * esz
        at = 0
        while at < self.ecx_size:
            blob = os.pread(self._ecx.fileno(),
                            min(chunk, self.ecx_size - at), at)
            if not blob:
                break
            for key, _offset, size in idx_mod.walk_index_blob(blob):
                if not t.size_is_deleted(size):
                    return key
            at += len(blob) - (len(blob) % esz)
            if len(blob) < esz:
                break
        return None

    def canary_read(self, drop_shard: "int | None" = None) -> dict:
        """Degraded-read canary: read one live needle with the FIRST
        locally held interval forced through the reconstruct path (as if
        its shard were lost), all other intervals read normally.  The
        needle CRC check in `Needle.from_bytes` is the byte-identity
        gate — a decode-path regression fails loudly here before a real
        shard loss finds it.  Bypasses the interval cache/single-flight
        (`_gather_and_decode` directly) so every probe pays a real
        gather + decode."""
        nid = self.first_live_needle()
        if nid is None:
            raise NotFoundError(
                f"ec volume {self.volume_id}: no live needle to probe")
        _offset, size, intervals = self.locate(nid)
        if t.size_is_deleted(size):
            raise NotFoundError(f"needle {nid:x} deleted")
        parts: list[bytes] = []
        dropped = None
        for iv in intervals:
            sid, off = iv.to_shard_id_and_offset(
                self.large_block_size, self.small_block_size)
            droppable = (sid in self.shards
                         and (drop_shard is None or sid == drop_shard))
            if droppable and dropped is None:
                parts.append(
                    self._gather_and_decode(sid, off, iv.size)[0])
                dropped = sid
            else:
                parts.append(self._read_interval(iv))
        n = Needle.from_bytes(b"".join(parts), self.version)
        if n.id != nid:
            raise IOError(
                f"canary read id mismatch: want {nid:x} got {n.id:x}")
        return {"needleId": f"{nid:x}", "droppedShard": dropped,
                "bytes": len(bytes(n.data)),
                "reconstructed": dropped is not None}

    def _reread_corrupt(self, intervals, parts) -> Needle:
        """Corruption failover for EC reads: reconstruct every interval
        from sibling shards instead of trusting the local bytes.  The
        interval whose reconstruction differs from what was read names
        the corrupt shard — reported through corruption_hook so the
        scrubber confirms and the master rebuilds it."""
        fixed: list[bytes] = []
        for iv, got in zip(intervals, parts):
            shard_id, off = iv.to_shard_id_and_offset(
                self.large_block_size, self.small_block_size
            )
            try:
                rec = self._reconstruct_interval(shard_id, off, iv.size)
            except (OSError, IOError):
                fixed.append(got)  # not enough siblings: keep what we read
                continue
            if rec != got:
                hook = self.corruption_hook
                if hook is not None:
                    try:
                        hook(self.volume_id, shard_id)
                    except Exception:  # noqa: BLE001 — never fail the read
                        pass
            fixed.append(rec)
        return Needle.from_bytes(b"".join(fixed), self.version)

    def _read_interval(self, iv: Interval) -> bytes:
        shard_id, off = iv.to_shard_id_and_offset(
            self.large_block_size, self.small_block_size
        )
        return self.read_shard_interval(shard_id, off, iv.size)

    def _read_intervals(self, intervals: "list[Interval]") -> list[bytes]:
        """Interval reads with large-sequential batching.

        The stripe layout puts blocks k and k+DATA_SHARDS adjacent in the
        SAME shard file, so a needle spanning many blocks decomposes into
        one gap-free run per shard.  Each locally-held run of >=2 spans
        collapses into a single preadv(2) scatter
        (seaweedfs_ec_preadv_batches_total) instead of a pread per
        interval; any batch shortfall — racing truncate, unmount, missing
        shard — falls back to the per-interval path, which already
        degrades local -> remote -> reconstruct."""
        located = [
            iv.to_shard_id_and_offset(
                self.large_block_size, self.small_block_size)
            for iv in intervals
        ]
        parts: "list[bytes | None]" = [None] * len(intervals)
        by_shard: dict[int, list[int]] = {}
        for k, (sid, _off) in enumerate(located):
            by_shard.setdefault(sid, []).append(k)
        for sid, idxs in by_shard.items():
            sh = self.shards.get(sid)
            if sh is None or len(idxs) < 2:
                continue
            idxs = sorted(idxs, key=lambda k: located[k][1])
            run = [idxs[0]]
            runs = [run]
            for k in idxs[1:]:
                prev = run[-1]
                if located[k][1] == located[prev][1] + intervals[prev].size:
                    run.append(k)
                else:
                    run = [k]
                    runs.append(run)
            for run in runs:
                if len(run) < 2:
                    continue
                spans = [(located[k][1], intervals[k].size) for k in run]
                got = sh.read_many(spans)
                if got is None:
                    continue  # per-interval fallback below
                EC_PREADV_BATCHES.inc()
                for k, blob in zip(run, got):
                    parts[k] = blob
        for k, iv in enumerate(intervals):
            if parts[k] is None:
                parts[k] = self.read_shard_interval(
                    located[k][0], located[k][1], iv.size)
        return parts

    def read_shard_interval(self, shard_id: int, offset: int, length: int) -> bytes:
        # 1. local shard; a short pread means a racing truncate/re-copy
        # and a closed fd means a racing unmount — both fall through to
        # remote/reconstruct instead of failing the needle read
        sh = self.shards.get(shard_id)
        if sh is not None:
            try:
                buf = sh.read_at(offset, length)
            except (OSError, ValueError):
                buf = b""
            if len(buf) == length:
                return buf
        # 2. remote shard via injected fetcher (same length discipline:
        # a peer mid-copy can short-serve too)
        if self.remote_fetch is not None:
            data = self.remote_fetch(shard_id, offset, length)
            if data is not None and len(data) == length:
                return data
        # 3. degraded: reconstruct from any DATA_SHARDS other shards
        return self._reconstruct_interval(shard_id, offset, length)

    def _cache_token(self) -> tuple[int, int]:
        """Invalidation token for reconstructed intervals: any shard
        mount/unmount or needle delete makes older captures unservable."""
        return (self.mount_seq, self.delete_seq)

    def _reconstruct_interval(self, shard_id: int, offset: int, length: int) -> bytes:
        """Reconstruct one lost interval, coalesced and cached.

        Single-flight: N concurrent readers of the SAME lost interval
        trigger ONE gather+decode; the rest wait on the leader's result
        (seaweedfs_ec_singleflight_total{result}).  Results land in a
        bounded interval LRU keyed by the volume's (mount_seq,
        delete_seq) token — compare-before-publish, so a racing shard
        mount/unmount or delete can never publish a stale interval.
        """
        cache = self._interval_cache
        key = (shard_id, offset, length)
        if cache is not None:
            data = cache.get(key, self._cache_token())
            if data is not None:
                return data
        with self._sf_lock:
            call = self._sf_calls.get(key)
            leader = call is None
            if leader:
                call = _SingleFlight()
                self._sf_calls[key] = call
        if not leader:
            _SF_COALESCED.inc()
            # generous bound: a wedged leader (remote fetch hang) must not
            # strand followers forever — they fall back to their own gather
            if call.done.wait(timeout=60.0):
                if call.err is not None:
                    raise call.err
                # same staleness discipline as the cache: a shard swap or
                # delete since the leader's capture voids the hand-off
                if call.token == self._cache_token():
                    return call.result
            return self._gather_and_decode(shard_id, offset, length)[0]
        _SF_LEADER.inc()
        try:
            data, token = self._gather_and_decode(shard_id, offset, length)
            call.result = data
            call.token = token
            if cache is not None:
                # publish under the journal lock: delete_seq bumps happen
                # under the same lock, so a tombstone that raced the
                # gather either changed the token (no publish) or is
                # ordered after this put and clears via the token check
                with self._ecj_lock:
                    if token == self._cache_token():
                        cache.put(key, data, token)
            return data
        except Exception as e:
            call.err = e
            raise
        finally:
            with self._sf_lock:
                self._sf_calls.pop(key, None)
            call.done.set()

    def _gather_and_decode(
        self, shard_id: int, offset: int, length: int
    ) -> tuple[bytes, tuple[int, int]]:
        """Gather >= DATA_SHARDS sibling intervals and decode the missing
        one; returns (bytes, invalidation token captured BEFORE the reads).

        Local shards are read inline (microseconds); the remote fetches go
        out CONCURRENTLY on the shared bounded executor so worst-case
        degraded latency is ~1 RTT, not 10 sequential RTTs (reference:
        store_ec.go:324-378 fans out one goroutine per source shard and
        joins them) — and a degraded-read storm no longer spawns a fresh
        thread pool per interval.
        """
        token = self._cache_token()
        shards: list[np.ndarray | None] = [None] * TOTAL_SHARDS
        have = 0
        # snapshot in one C-level call: mount/unmount rpcs mutate
        # self.shards from other threads
        local_shards = list(self.shards.items())
        local_shards.sort()
        for sid, sh in local_shards:
            if sid == shard_id or have >= DATA_SHARDS:
                continue
            try:
                buf = sh.read_at(offset, length)
            except (OSError, ValueError):  # racing unmount closed the file
                continue
            if len(buf) == length:
                shards[sid] = np.frombuffer(buf, dtype=np.uint8)
                have += 1
        missing = [
            sid
            for sid in range(TOTAL_SHARDS)
            if sid != shard_id and shards[sid] is None
        ]
        if have < DATA_SHARDS and self.partial_client is not None:
            # partial-sum degraded read: remote survivors send their
            # coefficient-weighted rows pre-XOR'd per rack (one 1 x W
            # partial per rack in) instead of 10 raw intervals
            try:
                return self._partial_decode(
                    shard_id, offset, length, shards), token
            except Exception:  # noqa: BLE001 — optimization, never a 5xx
                EC_PARTIAL_FALLBACK.labels("degraded").inc()
        if have < DATA_SHARDS and self.remote_fetch is not None and missing:
            def fetch(sid: int) -> "bytes | None":
                try:
                    return self.remote_fetch(sid, offset, length)
                except Exception:
                    return None

            futs = [(sid, _fetch_pool().submit(fetch, sid))
                    for sid in missing]
            for sid, fut in futs:
                buf = fut.result()
                if buf is not None and len(buf) == length:
                    shards[sid] = np.frombuffer(buf, dtype=np.uint8)
                    have += 1
        if have < DATA_SHARDS:
            raise IOError(
                f"shard {shard_id} interval unreadable: only {have} shards available"
            )
        svc = codec_service.service_for_degraded()
        if svc is not None:
            # degraded-read storms coalesce: concurrent reconstructions
            # against the same survivor set (same decode-plan row) batch
            # into ONE SIMD call on the service scheduler.  Same plan
            # cache + same kernel as reconstruct_one -> byte-identical.
            present = [i for i, s in enumerate(shards) if s is not None]
            sub = [np.asarray(shards[i], dtype=np.uint8)
                   for i in present[:DATA_SHARDS]]
            row = gf256.decode_plan_for(
                np.asarray(self.codec.matrix), DATA_SHARDS,
                present, (shard_id,))
            return svc.submit_apply(row, sub).result()[0].tobytes(), token
        if hasattr(self.codec, "reconstruct_one"):
            # latency path: decode only the wanted row, not all lost shards
            return np.asarray(
                self.codec.reconstruct_one(shards, shard_id),
                dtype=np.uint8).tobytes(), token
        rebuilt = self.codec.reconstruct(shards)
        return np.asarray(rebuilt[shard_id], dtype=np.uint8).tobytes(), token

    def _partial_decode(
        self, shard_id: int, offset: int, length: int, shards: list
    ) -> bytes:
        """Reconstruct one lost interval via the partial-sum protocol:
        the decode-plan row for `shard_id` splits by source locality —
        local shards' columns are applied here on the host kernel (a
        per-needle read must never pay device dispatch), remote columns
        ship to the holders and return as one pre-XOR'd partial per
        rack.  GF linearity makes the bytes identical to the gathered
        reconstruct_one path; any failure raises and the caller falls
        back to it."""
        client = self.partial_client
        local_rows = {sid: row for sid, row in enumerate(shards)
                      if row is not None}
        holders = {sid: h for sid, h in client.remote_shards().items()
                   if sid != shard_id and sid not in local_rows}
        need = DATA_SHARDS - len(local_rows)
        order = client.order(holders)
        if len(order) < need:
            raise IOError(
                f"shard {shard_id} interval: only "
                f"{len(local_rows) + len(order)} sources for partial decode")
        remote_srcs = order[:need]
        local_srcs = sorted(local_rows)
        sources = local_srcs + remote_srcs
        plan = gf256.decode_plan_for(
            np.asarray(self.codec.matrix), DATA_SHARDS, sources, (shard_id,))
        coef = {s: plan[:, len(local_srcs) + j]
                for j, s in enumerate(remote_srcs)}
        part = client.fetch(coef, 1, offset, length)
        if local_srcs:
            local_plan = np.ascontiguousarray(plan[:, :len(local_srcs)])
            rows_in = [np.asarray(local_rows[s], dtype=np.uint8)
                       for s in local_srcs]
            svc = codec_service.service_for_degraded()
            if svc is not None:
                out = np.asarray(
                    svc.submit_apply(local_plan, rows_in).result(),
                    dtype=np.uint8)
            else:
                out = np.asarray(
                    _host_codec().apply_rows(local_plan, rows_in),
                    dtype=np.uint8)
            part = np.bitwise_xor(part, out.reshape(part.shape))
        return part[0].tobytes()
