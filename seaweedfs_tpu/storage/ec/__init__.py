from .constants import (  # noqa: F401
    DATA_SHARDS,
    LARGE_BLOCK_SIZE,
    PARITY_SHARDS,
    SMALL_BLOCK_SIZE,
    TOTAL_SHARDS,
    to_ext,
)
from .locate import Interval, locate_data  # noqa: F401
