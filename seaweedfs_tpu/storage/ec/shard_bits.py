"""ShardBits: bitmask of which of the 14 shards a server holds.

Reference: ec_volume_info.go:65-117 (uint32 bitmask used in master
bookkeeping and balance planning).
"""

from __future__ import annotations


class ShardBits(int):
    def add(self, shard_id: int) -> "ShardBits":
        return ShardBits(self | (1 << shard_id))

    def remove(self, shard_id: int) -> "ShardBits":
        return ShardBits(self & ~(1 << shard_id))

    def has(self, shard_id: int) -> bool:
        return bool(self & (1 << shard_id))

    def shard_ids(self) -> list[int]:
        return [i for i in range(32) if self.has(i)]

    def count(self) -> int:
        return bin(self).count("1")

    def plus(self, other: "ShardBits | int") -> "ShardBits":
        return ShardBits(self | other)

    def minus(self, other: "ShardBits | int") -> "ShardBits":
        return ShardBits(self & ~other)
