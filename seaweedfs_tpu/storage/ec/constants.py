"""EC geometry constants (reference: ec_encoder.go:17-23)."""

DATA_SHARDS = 10
PARITY_SHARDS = 4
TOTAL_SHARDS = DATA_SHARDS + PARITY_SHARDS
LARGE_BLOCK_SIZE = 1024 * 1024 * 1024  # 1GB rows first
SMALL_BLOCK_SIZE = 1024 * 1024  # then 1MB rows to cap tail padding
BUFFER_SIZE = 256 * 1024  # reference encode batch unit per shard


def to_ext(shard_id: int) -> str:
    return f".ec{shard_id:02d}"
