"""Partial-sum EC repair protocol (VolumeEcShardPartialApply).

Rebuild and degraded reads used to stream DATA_SHARDS full shard
intervals across the network to one rebuilder; with PR 4/6 having made
the local GF compute cheap, the wire became the bottleneck (Rashmi et
al., arXiv:1309.0186, measure repair traffic dominating cross-rack
bandwidth; product-matrix regenerating codes, arXiv:1412.3022, formalize
the bandwidth floor).  This module moves the decode-plan matmul to the
data: each SOURCE multiplies its local shard intervals by its columns of
the shared decode plan (through the PR 6 codec service, so device codecs
batch and hosts hit the SIMD kernel) and streams the GF(2^8) partial
sum; partials XOR-combine at a rack-level aggregator so exactly one
(rows x width) block crosses each rack boundary, and the rebuilder's
network-in drops from sources x width to racks x rows x width.

GF linearity makes byte-identity structural: the XOR of the sources'
coefficient-weighted rows IS the decode-plan matmul over the gathered
rows, term for term — same plan cache, same kernels, same bytes.

Any failure (a source dying mid-stream, a stale location, a missing
holder) raises :class:`PartialUnavailable` and the caller degrades to
the existing full-shard fetch path — the protocol is an optimization,
never a new way to fail a repair.

Three layers live here so the real gRPC path and the in-process test /
bench network share one implementation:

* ``serve_partial``   — source-side core (the gRPC handler's body);
* ``PartialRepairClient`` — rebuilder-side planning + fan-out + XOR;
* ``local_source_network`` — an in-process fleet of sources for unit
  tests and ``bench.py --rebuild-only``'s A/B leg.
"""

from __future__ import annotations

import os
import threading

import numpy as np

from ...ops import codec_service
from ...pb import volume_server_pb2 as vs
from ...stats.metrics import (
    EC_PARTIAL_BYTES,
    EC_PARTIAL_JOBS,
    EC_REBUILD_BYTES,
)
from ...topology.placement import (
    best_ec_holder,
    ec_source_locality,
    group_partial_sources,
    order_ec_sources,
)
from ...util import faultpoint
from .constants import to_ext

# fires on every source serve of a partial-sum request, BEFORE the local
# shard reads, ctx = the serving node's address — chaos tests kill one
# source mid-protocol here and assert the rebuilder's clean fallback
FP_PARTIAL_APPLY = faultpoint.register("ec.partial.apply")

# fires once per VOLUME JOB inside a cross-volume batch serve, ctx =
# "<node address> vol=<vid>" — chaos kills one source mid-batch and
# asserts exactly that volume degrades per-volume while the rest of the
# batch completes on the aggregated path
FP_BATCH_SOURCE = faultpoint.register("repair.batch.source")

PARTIAL_CHUNK = 1024 * 1024

# concurrent volume jobs served per batch rpc (short-lived threads: the
# serve side must never borrow the rebuilder's fan-out pool, or an
# in-process source fleet could deadlock a full pool against itself)
BATCH_SERVE_WORKERS = int(os.environ.get(
    "SEAWEEDFS_TPU_EC_BATCH_SERVE_WORKERS", "8"))


class PartialUnavailable(IOError):
    """The protocol could not produce a combined partial (dead source,
    missing holder, bad stream) — degrade to the full-fetch path."""


# one bounded process-wide executor for the rebuilder's per-rack group
# fan-out (flat: group rpcs land on OTHER servers' handler threads, and
# serve-side delegate fan-out uses short-lived threads, so this pool
# never waits on itself)
_POOL = None
_POOL_LOCK = threading.Lock()


def _pool():
    global _POOL
    if _POOL is None:
        with _POOL_LOCK:
            if _POOL is None:
                from ...util.executors import MeteredThreadPoolExecutor

                workers = int(os.environ.get(
                    "SEAWEEDFS_TPU_EC_PARTIAL_WORKERS", "8"))
                _POOL = MeteredThreadPoolExecutor(
                    max_workers=workers, name="ec_partial",
                    thread_name_prefix="ec-partial")
    return _POOL


def compute_partial(coef: np.ndarray, rows: list) -> np.ndarray:
    """(M, K) GF coefficient rows x K equal-length byte rows -> (M, W).

    Routed through the shared codec service — concurrent partial serves
    from many rebuilds coalesce into one batched kernel call (device
    matmul when the probe finds an accelerator, host SIMD otherwise);
    falls back to the direct host codec when the service is disabled."""
    coef = np.ascontiguousarray(coef, dtype=np.uint8)
    svc = codec_service.get_service("cpu")
    if svc is not None:
        out = svc.submit_apply(coef, rows).result()
    else:
        from ...ops.codec import get_codec

        out = get_codec("cpu").apply_rows(coef, list(rows))
    return np.ascontiguousarray(np.asarray(out, dtype=np.uint8))


def pack_coefficients(coef_by_shard: "dict[int, np.ndarray]",
                      shard_ids: list[int]) -> bytes:
    """Row-major (row_count x len(shard_ids)) coefficient block whose
    column j weights shard_ids[j] — the wire layout of `coefficients`."""
    return np.ascontiguousarray(
        np.stack([np.asarray(coef_by_shard[s], dtype=np.uint8)
                  for s in shard_ids], axis=1)).tobytes()


# ---------------------------------------------------------------------------
# Source side
# ---------------------------------------------------------------------------


def serve_partial(request, read_interval, stub_for=None, ctx: str = "",
                  throttle=None) -> np.ndarray:
    """Compute one server's combined partial for a request: the local
    shards' coefficient-weighted sum, XOR'd with every delegate's
    partial (fetched concurrently).  Returns the (row_count, size)
    uint8 array.

    Raises on ANY missing contribution — a partial missing one term is
    silently wrong bytes, so the rpc must fail loudly and let the
    rebuilder fall back to full fetches.

    ``read_interval(shard_id, offset, length) -> bytes|None`` supplies
    local shard bytes; ``throttle(n)`` (optional) charges the node's
    shared background-I/O budget before the compute."""
    try:
        faultpoint.inject(FP_PARTIAL_APPLY, ctx=ctx)
        m = int(request.row_count)
        sids = list(request.shard_ids)
        width = int(request.size)
        coef = np.frombuffer(bytes(request.coefficients), dtype=np.uint8)
        if m <= 0 or width <= 0 or coef.size != m * len(sids):
            raise ValueError(
                f"bad partial-apply geometry: rows={m} width={width} "
                f"coef={coef.size} shards={len(sids)}")
        if throttle is not None:
            throttle(len(sids) * width)
        rows = []
        for sid in sids:
            buf = read_interval(sid, int(request.offset), width)
            if buf is None or len(buf) != width:
                raise IOError(
                    f"shard {sid} interval unreadable for partial apply")
            rows.append(np.frombuffer(buf, dtype=np.uint8))
        if sids:
            acc = compute_partial(coef.reshape(m, len(sids)), rows)
        else:
            acc = np.zeros((m, width), dtype=np.uint8)
        if len(request.delegates):
            if stub_for is None:
                raise IOError("delegates present but no delegate transport")
            # short-lived threads: delegate counts are bounded by rack
            # size and this runs once per served slice, so spawn cost is
            # noise next to the rpc RTT — and it cannot deadlock the
            # shared client pool from inside a handler
            parts: list = [None] * len(request.delegates)
            errs: list = []

            def fetch_one(i: int, d) -> None:
                try:
                    parts[i] = fetch_partial_once(
                        stub_for(d.grpc_address), request.volume_id,
                        request.collection, int(request.offset), width, m,
                        list(d.shard_ids), bytes(d.coefficients))
                except Exception as e:  # noqa: BLE001 — joined below
                    errs.append(e)

            threads = [threading.Thread(target=fetch_one, args=(i, d),
                                        daemon=True)
                       for i, d in enumerate(request.delegates)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            if errs:
                raise IOError(f"delegate partial failed: {errs[0]}")
            for p in parts:
                np.bitwise_xor(acc, p, out=acc)
        EC_PARTIAL_BYTES.labels("serve").inc(m * width)
        EC_PARTIAL_JOBS.labels("serve", "ok").inc()
        return acc
    except Exception:
        EC_PARTIAL_JOBS.labels("serve", "error").inc()
        raise


def serve_partial_batch(request, read_interval_for, stub_for=None,
                        ctx: str = "", throttle=None):
    """Serve a cross-volume batch (`request.batch`): every PartialVolumeJob
    is one volume's coefficient-column request, served through the SAME
    serve_partial core — jobs run concurrently so their codec-service
    submissions coalesce into the multi-volume batches the PR 6 scheduler
    was built for.  Yields ``(volume_id, ndarray | Exception)`` in
    completion order: a dead shard fails exactly ITS volume (the
    rebuilder degrades that one volume to per-volume sourcing) and never
    stalls the rest of the batch.

    ``read_interval_for(volume_id, collection)`` resolves one volume's
    `read_interval(shard_id, offset, length)` reader, or None when the
    volume is absent here."""
    import queue as _queue

    jobs = list(request.batch)
    done: _queue.Queue = _queue.Queue()
    gate = threading.Semaphore(max(BATCH_SERVE_WORKERS, 1))

    def serve_one(job) -> None:
        try:
            with gate:
                faultpoint.inject(
                    FP_BATCH_SOURCE, ctx=f"{ctx} vol={job.volume_id}")
                read_interval = read_interval_for(
                    job.volume_id, job.collection)
                if read_interval is None:
                    raise IOError(
                        f"ec volume {job.volume_id} not present here")
                done.put((job.volume_id, serve_partial(
                    job, read_interval, stub_for=stub_for, ctx=ctx,
                    throttle=throttle)))
        except Exception as e:  # noqa: BLE001 — per-volume isolation
            done.put((job.volume_id, e))

    threads = [threading.Thread(target=serve_one, args=(j,), daemon=True)
               for j in jobs]
    for t in threads:
        t.start()
    for _ in jobs:
        yield done.get()
    for t in threads:
        t.join()


def batch_response_frames(request, read_interval_for, stub_for=None,
                          ctx: str = "", throttle=None):
    """serve_partial_batch -> wire frames: per-volume data chunks tagged
    with volume_id, closed by an eof frame (carrying the error string on
    a failed job).  Shared by the gRPC handler and the in-process test /
    bench network so both speak the identical framing."""
    for vid, result in serve_partial_batch(
            request, read_interval_for, stub_for=stub_for, ctx=ctx,
            throttle=throttle):
        if isinstance(result, Exception):
            yield vs.VolumeEcShardPartialApplyResponse(
                volume_id=vid, eof=True, error=str(result) or "failed")
            continue
        blob = result.tobytes()
        for at in range(0, len(blob), PARTIAL_CHUNK):
            yield vs.VolumeEcShardPartialApplyResponse(
                volume_id=vid, data=blob[at:at + PARTIAL_CHUNK])
        yield vs.VolumeEcShardPartialApplyResponse(volume_id=vid, eof=True)


# ---------------------------------------------------------------------------
# Rebuilder side
# ---------------------------------------------------------------------------


def fetch_partial_once(stub, volume_id: int, collection: str, offset: int,
                       size: int, row_count: int, shard_ids: list[int],
                       coefficients: bytes, delegates=()) -> np.ndarray:
    """One VolumeEcShardPartialApply rpc -> the (row_count, size) block."""
    req = vs.VolumeEcShardPartialApplyRequest(
        volume_id=volume_id, collection=collection, offset=offset,
        size=size, row_count=row_count, shard_ids=shard_ids,
        coefficients=coefficients)
    for addr, sids, coef in delegates:
        req.delegates.add(grpc_address=addr, shard_ids=sids,
                          coefficients=coef)
    EC_PARTIAL_BYTES.labels("req").inc(req.ByteSize())
    blob = b"".join(bytes(r.data) for r in
                    stub.VolumeEcShardPartialApply(req) if r.data)
    if len(blob) != row_count * size:
        raise IOError(
            f"short partial stream: want {row_count * size} got {len(blob)}")
    return np.frombuffer(blob, dtype=np.uint8).reshape(row_count, size)


def probe_shard_size(stub, volume_id: int, collection: str = "") -> int:
    """size=0 probe: a holder answers with its shard file size (what a
    rebuilder with zero local shards needs to size the stream from)."""
    req = vs.VolumeEcShardPartialApplyRequest(
        volume_id=volume_id, collection=collection, size=0)
    EC_PARTIAL_BYTES.labels("req").inc(req.ByteSize())
    for r in stub.VolumeEcShardPartialApply(req):
        return int(r.shard_size)
    return 0


class PartialRepairClient:
    """Rebuilder-side orchestration: locate holders, prefer same-rack
    sources, issue one aggregated request per rack, XOR the per-rack
    partials, and label the ingress bytes by locality.

    ``locate() -> {shard_id: [(grpc_address, rack, dc), ...]}`` resolves
    holders (the caller excludes itself); ``stub_for(addr)`` returns the
    rpc stub for an address.  Lookups ride a TieredLocationCache so a
    rebuild storm does not hammer the master.
    """

    def __init__(self, volume_id: int, collection: str, locate, stub_for,
                 my_rack: str = "", my_dc: str = ""):
        from ...wdclient.location_cache import TieredLocationCache

        self.volume_id = volume_id
        self.collection = collection
        self._stub_for = stub_for
        self._cache = TieredLocationCache(locate)
        self.my_rack = my_rack
        self.my_dc = my_dc

    def remote_shards(self) -> "dict[int, tuple[str, str, str]]":
        """Best holder per shard id — same-rack holders win, address as
        tiebreak so the choice is stable across slices."""
        out: dict[int, tuple[str, str, str]] = {}
        for sid, holders in self._cache.get().items():
            if holders:
                out[sid] = best_ec_holder(holders, self.my_rack, self.my_dc)
        return out

    def invalidate(self) -> None:
        self._cache.invalidate()

    def order(self, holders: "dict[int, tuple[str, str, str]]") -> list[int]:
        return order_ec_sources(holders, self.my_rack, self.my_dc)

    def ingress_advantage(self, remote_sids, row_count: int) -> float:
        """full-fetch ingress / partial ingress for this source set:
        partial pulls (racks x row_count x width) vs full's
        (sources x width).  Below 1.0 the protocol would MOVE MORE
        bytes than it saves (e.g. 4 lost shards against 3 remote
        sources) — callers then keep the full-fetch path."""
        holders = self.remote_shards()
        chosen = {sid: holders[sid] for sid in remote_sids
                  if sid in holders}
        if not chosen or row_count <= 0:
            return 0.0
        racks = len(group_partial_sources(chosen))
        return len(chosen) / float(racks * row_count)

    def locality_of(self, sid: int) -> str:
        h = self.remote_shards().get(sid)
        if h is None:
            return "dc"
        return ec_source_locality(h[1], h[2], self.my_rack, self.my_dc)

    def shard_size(self) -> int:
        """Probe any reachable holder for the shard file size."""
        for _sid, (addr, _r, _d) in sorted(self.remote_shards().items()):
            try:
                n = probe_shard_size(
                    self._stub_for(addr), self.volume_id, self.collection)
            except Exception:  # noqa: BLE001 — try the next holder
                continue
            if n:
                return n
        return 0

    def fetch(self, coef_by_shard: "dict[int, np.ndarray]", row_count: int,
              offset: int, length: int) -> np.ndarray:
        """One aggregated (row_count, length) partial over the given
        remote source shards.  Raises PartialUnavailable on ANY failure
        — the caller falls back to full fetches (and this client drops
        its location cache, so the retry sees fresh holders)."""
        holders = self.remote_shards()
        chosen: dict[int, tuple[str, str, str]] = {}
        for sid in coef_by_shard:
            h = holders.get(sid)
            if h is None:
                raise PartialUnavailable(f"no holder for source shard {sid}")
            chosen[sid] = h
        groups = group_partial_sources(chosen)
        try:
            results = self._fetch_groups(
                groups, coef_by_shard, row_count, offset, length)
        except Exception as e:
            EC_PARTIAL_JOBS.labels("fetch", "error").inc()
            self._cache.invalidate()
            if isinstance(e, PartialUnavailable):
                raise
            raise PartialUnavailable(str(e)) from e
        acc = np.zeros((row_count, length), dtype=np.uint8)
        for g, part in results:
            label = ec_source_locality(
                g["rack"], g["dc"], self.my_rack, self.my_dc)
            EC_REBUILD_BYTES.labels(label).inc(part.nbytes)
            EC_PARTIAL_BYTES.labels("recv").inc(part.nbytes)
            np.bitwise_xor(acc, part, out=acc)
        EC_PARTIAL_JOBS.labels("fetch", "ok").inc()
        return acc

    @staticmethod
    def _group_request(g: dict, coef_by_shard) -> tuple:
        """-> (aggregator_addr, its shard ids, its coefficient block,
        [(delegate_addr, sids, coef_block)]) for one rack group — the
        one wire shape shared by the direct and the batched dispatch."""
        agg = g["aggregator"]
        agg_sids = g["members"][agg]
        delegates = [
            (addr, sids, pack_coefficients(coef_by_shard, sids))
            for addr, sids in sorted(g["members"].items())
            if addr != agg
        ]
        return agg, agg_sids, pack_coefficients(coef_by_shard, agg_sids), \
            delegates

    def _fetch_groups(self, groups, coef_by_shard, row_count: int,
                      offset: int, length: int) -> list:
        """Direct dispatch: one rpc per rack group on the shared pool.
        The batched subclass reroutes this through a cross-volume
        group-commit session instead."""

        def one_group(g: dict) -> "tuple[dict, np.ndarray]":
            agg, agg_sids, coef, delegates = self._group_request(
                g, coef_by_shard)
            part = fetch_partial_once(
                self._stub_for(agg), self.volume_id, self.collection,
                offset, length, row_count, agg_sids, coef,
                delegates=delegates)
            return g, part

        if len(groups) == 1:
            return [one_group(groups[0])]
        return list(_pool().map(one_group, groups))


# ---------------------------------------------------------------------------
# Cross-volume aggregation (ISSUE 11): many volumes, one rpc per source
# ---------------------------------------------------------------------------


class MassPartialSession:
    """Group-commit dispatcher for a mass repair: concurrent per-volume
    partial fetches from MANY volume rebuilds coalesce into one streaming
    VolumeEcShardPartialApply rpc per source server.

    The window is the natural one: each source address has its own
    worker — while its rpc is in flight, every fetch for that address
    queues up and rides its next wave (no timers), and a slow source
    never head-of-line blocks dispatch to the fast ones.  Per-volume
    eof/error frames resolve each job's future independently, so a dead
    shard fails exactly its volume (PartialUnavailable -> that volume
    falls back per-volume) and never stalls the batch.
    """

    _CLOSE = object()

    def __init__(self, stub_for, max_jobs_per_rpc: int = 64):
        from concurrent.futures import Future

        self._Future = Future
        self._stub_for = stub_for
        self.max_jobs_per_rpc = max(max_jobs_per_rpc, 1)
        import queue as _queue

        self._queue_mod = _queue
        self._lock = threading.Lock()
        # per source address: its job queue + dedicated worker thread
        self._addr_q: dict[str, object] = {}
        self._workers: list[threading.Thread] = []
        self._closed = False
        self.rpcs = 0
        self.batched_jobs = 0

    def submit(self, addr: str, job: dict):
        """Queue one per-volume rack-group job for `addr`; -> Future of
        the (row_count, size) partial.  Job fields mirror
        PartialVolumeJob (+ 'delegates': [(addr, sids, coef_bytes)])."""
        fut = self._Future()
        with self._lock:
            if self._closed:
                raise PartialUnavailable("mass partial session closed")
            q = self._addr_q.get(addr)
            if q is None:
                q = self._queue_mod.Queue()
                self._addr_q[addr] = q
                t = threading.Thread(
                    target=self._addr_run, args=(addr, q),
                    name=f"mass-partial-{addr}", daemon=True)
                self._workers.append(t)
                t.start()
        q.put((job, fut))
        return fut

    def close(self) -> None:
        with self._lock:
            self._closed = True
            queues = list(self._addr_q.values())
            workers = list(self._workers)
        for q in queues:
            q.put(self._CLOSE)
        for t in workers:
            t.join(timeout=10)
        for q in queues:  # a submit that raced the close marker
            while True:
                try:
                    left = q.get_nowait()
                except self._queue_mod.Empty:
                    break
                if left is not self._CLOSE:
                    left[1].set_exception(
                        PartialUnavailable("session closed"))

    def _addr_run(self, addr: str, q) -> None:
        while True:
            item = q.get()
            if item is self._CLOSE:
                # fail anything that raced in behind the close marker
                while True:
                    try:
                        left = q.get_nowait()
                    except self._queue_mod.Empty:
                        return
                    if left is not self._CLOSE:
                        left[1].set_exception(
                            PartialUnavailable("session closed"))
            batch = [item]
            seen_vids = {item[0]["volume_id"]}
            defer = []
            while len(batch) < self.max_jobs_per_rpc:
                try:
                    nxt = q.get_nowait()
                except self._queue_mod.Empty:
                    break
                if nxt is self._CLOSE:
                    q.put(nxt)  # re-deliver after this batch
                    break
                if nxt[0]["volume_id"] in seen_vids:
                    # frames are keyed by volume_id within one rpc, so
                    # a second slice of the same volume rides the next
                    defer.append(nxt)
                    continue
                seen_vids.add(nxt[0]["volume_id"])
                batch.append(nxt)
            for d in defer:
                q.put(d)
            self._send(addr, [(addr, job, fut) for job, fut in batch])

    def _send(self, addr: str, items: list) -> None:
        req = vs.VolumeEcShardPartialApplyRequest()
        want: dict[int, tuple] = {}
        for _addr, job, fut in items:
            b = req.batch.add(
                volume_id=job["volume_id"],
                collection=job.get("collection", ""),
                offset=job["offset"], size=job["size"],
                row_count=job["row_count"], shard_ids=job["shard_ids"],
                coefficients=job["coefficients"])
            for daddr, sids, coef in job.get("delegates", ()):
                b.delegates.add(grpc_address=daddr, shard_ids=sids,
                                coefficients=coef)
            want[job["volume_id"]] = (
                job["row_count"] * job["size"], fut)
        with self._lock:
            self.rpcs += 1
            self.batched_jobs += len(items)
        EC_PARTIAL_BYTES.labels("req").inc(req.ByteSize())
        bufs: dict[int, list] = {vid: [] for vid in want}
        try:
            for r in self._stub_for(addr).VolumeEcShardPartialApply(req):
                vid = int(r.volume_id)
                if vid not in want:
                    continue
                expect, fut = want[vid]
                if r.error:
                    if not fut.done():
                        fut.set_exception(PartialUnavailable(r.error))
                    continue
                if r.data:
                    bufs[vid].append(bytes(r.data))
                if r.eof and not fut.done():
                    blob = b"".join(bufs[vid])
                    if len(blob) != expect:
                        fut.set_exception(PartialUnavailable(
                            f"short batch stream for volume {vid}: "
                            f"want {expect} got {len(blob)}"))
                    else:
                        fut.set_result(np.frombuffer(
                            blob, dtype=np.uint8))
        except Exception as e:  # noqa: BLE001 — the rpc died mid-stream
            for _expect, fut in want.values():
                if not fut.done():
                    fut.set_exception(PartialUnavailable(str(e)))
            return
        for vid, (_expect, fut) in want.items():
            if not fut.done():
                fut.set_exception(PartialUnavailable(
                    f"no eof frame for volume {vid}"))


class BatchedPartialClient(PartialRepairClient):
    """PartialRepairClient whose rack-group rpcs ride a shared
    MassPartialSession — the per-volume protocol is unchanged (same
    groups, same coefficients, same XOR), only the transport batches
    many volumes per wire round trip.  `shard_size_hint` (from the
    orchestrator's plan, which learned sizes from heartbeats) saves the
    per-volume size-probe rpc the solo client needs."""

    # source selection skips the 1-byte liveness probes: this client's
    # holder map is freshly looked up (the dead-node notice invalidated
    # it), and a stale holder degrades exactly one volume per-volume —
    # probing every source of every volume would re-serialize the batch
    trust_holders = True

    def __init__(self, session: MassPartialSession, *args,
                 shard_size_hint: int = 0, **kwargs):
        super().__init__(*args, **kwargs)
        self._session = session
        self._size_hint = int(shard_size_hint)

    def shard_size(self) -> int:
        return self._size_hint or super().shard_size()

    def _fetch_groups(self, groups, coef_by_shard, row_count: int,
                      offset: int, length: int) -> list:
        futs = []
        for g in groups:
            agg, agg_sids, coef, delegates = self._group_request(
                g, coef_by_shard)
            futs.append((g, self._session.submit(agg, {
                "volume_id": self.volume_id,
                "collection": self.collection,
                "offset": offset, "size": length,
                "row_count": row_count, "shard_ids": agg_sids,
                "coefficients": coef, "delegates": delegates,
            })))
        return [(g, fut.result().reshape(row_count, length))
                for g, fut in futs]


# ---------------------------------------------------------------------------
# In-process source fleet (unit tests + bench --rebuild-only A/B leg)
# ---------------------------------------------------------------------------


def local_source_network(nodes: "dict[str, object]"):
    """Drive the REAL serve/fetch code without sockets: ``nodes`` maps a
    fake grpc address -> (base_name, shard_ids it "holds"), or — for
    multi-volume fleets driving the batch protocol — a dict
    ``{volume_id: (base_name, shard_ids)}``.  Returns ``stub_for``
    usable by PartialRepairClient / MassPartialSession — each stub
    executes serve_partial (or the cross-volume batch serve) inline,
    including delegate fan-out through the same fleet, and streams the
    result in PARTIAL_CHUNK chunks like the wire handler does."""
    from types import SimpleNamespace

    def _held(addr: str, vid: int):
        """-> (base, sids) this fake node holds for vid, or None."""
        entry = nodes[addr]
        if isinstance(entry, dict):
            return entry.get(vid)
        return entry  # single-volume fleet: every vid maps to it

    class _Stub:
        def __init__(self, addr: str):
            self._addr = addr

        def _read_interval_for(self, vid: int, _collection: str = ""):
            held = _held(self._addr, vid)
            if held is None:
                return None
            base, sids = held

            def read_interval(sid, off, length):
                if sid not in sids:
                    return None
                with open(base + to_ext(sid), "rb") as f:
                    f.seek(off)
                    return f.read(length)

            return read_interval

        def VolumeEcShardPartialApply(self, request):
            if len(request.batch):
                yield from batch_response_frames(
                    request, self._read_interval_for, stub_for=stub_for,
                    ctx=self._addr)
                return
            held = _held(self._addr, int(request.volume_id))
            base, sids = held if held is not None else ("", [])

            if int(request.size) == 0:
                first = next((s for s in sids
                              if os.path.exists(base + to_ext(s))), None)
                size = (os.path.getsize(base + to_ext(first))
                        if first is not None else 0)
                yield SimpleNamespace(data=b"", shard_size=size)
                return

            def read_interval(sid, off, length):
                if sid not in sids:
                    return None
                with open(base + to_ext(sid), "rb") as f:
                    f.seek(off)
                    return f.read(length)

            acc = serve_partial(request, read_interval, stub_for=stub_for,
                                ctx=self._addr)
            blob = acc.tobytes()
            for at in range(0, len(blob), PARTIAL_CHUNK):
                yield SimpleNamespace(
                    data=blob[at:at + PARTIAL_CHUNK], shard_size=0)

    def stub_for(addr: str) -> _Stub:
        return _Stub(addr)

    return stub_for
