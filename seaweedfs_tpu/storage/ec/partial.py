"""Partial-sum EC repair protocol (VolumeEcShardPartialApply).

Rebuild and degraded reads used to stream DATA_SHARDS full shard
intervals across the network to one rebuilder; with PR 4/6 having made
the local GF compute cheap, the wire became the bottleneck (Rashmi et
al., arXiv:1309.0186, measure repair traffic dominating cross-rack
bandwidth; product-matrix regenerating codes, arXiv:1412.3022, formalize
the bandwidth floor).  This module moves the decode-plan matmul to the
data: each SOURCE multiplies its local shard intervals by its columns of
the shared decode plan (through the PR 6 codec service, so device codecs
batch and hosts hit the SIMD kernel) and streams the GF(2^8) partial
sum; partials XOR-combine at a rack-level aggregator so exactly one
(rows x width) block crosses each rack boundary, and the rebuilder's
network-in drops from sources x width to racks x rows x width.

GF linearity makes byte-identity structural: the XOR of the sources'
coefficient-weighted rows IS the decode-plan matmul over the gathered
rows, term for term — same plan cache, same kernels, same bytes.

Any failure (a source dying mid-stream, a stale location, a missing
holder) raises :class:`PartialUnavailable` and the caller degrades to
the existing full-shard fetch path — the protocol is an optimization,
never a new way to fail a repair.

Three layers live here so the real gRPC path and the in-process test /
bench network share one implementation:

* ``serve_partial``   — source-side core (the gRPC handler's body);
* ``PartialRepairClient`` — rebuilder-side planning + fan-out + XOR;
* ``local_source_network`` — an in-process fleet of sources for unit
  tests and ``bench.py --rebuild-only``'s A/B leg.
"""

from __future__ import annotations

import os
import threading

import numpy as np

from ...ops import codec_service
from ...pb import volume_server_pb2 as vs
from ...stats.metrics import (
    EC_PARTIAL_BYTES,
    EC_PARTIAL_JOBS,
    EC_REBUILD_BYTES,
)
from ...topology.placement import (
    best_ec_holder,
    ec_source_locality,
    group_partial_sources,
    order_ec_sources,
)
from ...util import faultpoint
from .constants import to_ext

# fires on every source serve of a partial-sum request, BEFORE the local
# shard reads, ctx = the serving node's address — chaos tests kill one
# source mid-protocol here and assert the rebuilder's clean fallback
FP_PARTIAL_APPLY = faultpoint.register("ec.partial.apply")

PARTIAL_CHUNK = 1024 * 1024


class PartialUnavailable(IOError):
    """The protocol could not produce a combined partial (dead source,
    missing holder, bad stream) — degrade to the full-fetch path."""


# one bounded process-wide executor for the rebuilder's per-rack group
# fan-out (flat: group rpcs land on OTHER servers' handler threads, and
# serve-side delegate fan-out uses short-lived threads, so this pool
# never waits on itself)
_POOL = None
_POOL_LOCK = threading.Lock()


def _pool():
    global _POOL
    if _POOL is None:
        with _POOL_LOCK:
            if _POOL is None:
                from ...util.executors import MeteredThreadPoolExecutor

                workers = int(os.environ.get(
                    "SEAWEEDFS_TPU_EC_PARTIAL_WORKERS", "8"))
                _POOL = MeteredThreadPoolExecutor(
                    max_workers=workers, name="ec_partial",
                    thread_name_prefix="ec-partial")
    return _POOL


def compute_partial(coef: np.ndarray, rows: list) -> np.ndarray:
    """(M, K) GF coefficient rows x K equal-length byte rows -> (M, W).

    Routed through the shared codec service — concurrent partial serves
    from many rebuilds coalesce into one batched kernel call (device
    matmul when the probe finds an accelerator, host SIMD otherwise);
    falls back to the direct host codec when the service is disabled."""
    coef = np.ascontiguousarray(coef, dtype=np.uint8)
    svc = codec_service.get_service("cpu")
    if svc is not None:
        out = svc.submit_apply(coef, rows).result()
    else:
        from ...ops.codec import get_codec

        out = get_codec("cpu").apply_rows(coef, list(rows))
    return np.ascontiguousarray(np.asarray(out, dtype=np.uint8))


def pack_coefficients(coef_by_shard: "dict[int, np.ndarray]",
                      shard_ids: list[int]) -> bytes:
    """Row-major (row_count x len(shard_ids)) coefficient block whose
    column j weights shard_ids[j] — the wire layout of `coefficients`."""
    return np.ascontiguousarray(
        np.stack([np.asarray(coef_by_shard[s], dtype=np.uint8)
                  for s in shard_ids], axis=1)).tobytes()


# ---------------------------------------------------------------------------
# Source side
# ---------------------------------------------------------------------------


def serve_partial(request, read_interval, stub_for=None, ctx: str = "",
                  throttle=None) -> np.ndarray:
    """Compute one server's combined partial for a request: the local
    shards' coefficient-weighted sum, XOR'd with every delegate's
    partial (fetched concurrently).  Returns the (row_count, size)
    uint8 array.

    Raises on ANY missing contribution — a partial missing one term is
    silently wrong bytes, so the rpc must fail loudly and let the
    rebuilder fall back to full fetches.

    ``read_interval(shard_id, offset, length) -> bytes|None`` supplies
    local shard bytes; ``throttle(n)`` (optional) charges the node's
    shared background-I/O budget before the compute."""
    try:
        faultpoint.inject(FP_PARTIAL_APPLY, ctx=ctx)
        m = int(request.row_count)
        sids = list(request.shard_ids)
        width = int(request.size)
        coef = np.frombuffer(bytes(request.coefficients), dtype=np.uint8)
        if m <= 0 or width <= 0 or coef.size != m * len(sids):
            raise ValueError(
                f"bad partial-apply geometry: rows={m} width={width} "
                f"coef={coef.size} shards={len(sids)}")
        if throttle is not None:
            throttle(len(sids) * width)
        rows = []
        for sid in sids:
            buf = read_interval(sid, int(request.offset), width)
            if buf is None or len(buf) != width:
                raise IOError(
                    f"shard {sid} interval unreadable for partial apply")
            rows.append(np.frombuffer(buf, dtype=np.uint8))
        if sids:
            acc = compute_partial(coef.reshape(m, len(sids)), rows)
        else:
            acc = np.zeros((m, width), dtype=np.uint8)
        if len(request.delegates):
            if stub_for is None:
                raise IOError("delegates present but no delegate transport")
            # short-lived threads: delegate counts are bounded by rack
            # size and this runs once per served slice, so spawn cost is
            # noise next to the rpc RTT — and it cannot deadlock the
            # shared client pool from inside a handler
            parts: list = [None] * len(request.delegates)
            errs: list = []

            def fetch_one(i: int, d) -> None:
                try:
                    parts[i] = fetch_partial_once(
                        stub_for(d.grpc_address), request.volume_id,
                        request.collection, int(request.offset), width, m,
                        list(d.shard_ids), bytes(d.coefficients))
                except Exception as e:  # noqa: BLE001 — joined below
                    errs.append(e)

            threads = [threading.Thread(target=fetch_one, args=(i, d),
                                        daemon=True)
                       for i, d in enumerate(request.delegates)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            if errs:
                raise IOError(f"delegate partial failed: {errs[0]}")
            for p in parts:
                np.bitwise_xor(acc, p, out=acc)
        EC_PARTIAL_BYTES.labels("serve").inc(m * width)
        EC_PARTIAL_JOBS.labels("serve", "ok").inc()
        return acc
    except Exception:
        EC_PARTIAL_JOBS.labels("serve", "error").inc()
        raise


# ---------------------------------------------------------------------------
# Rebuilder side
# ---------------------------------------------------------------------------


def fetch_partial_once(stub, volume_id: int, collection: str, offset: int,
                       size: int, row_count: int, shard_ids: list[int],
                       coefficients: bytes, delegates=()) -> np.ndarray:
    """One VolumeEcShardPartialApply rpc -> the (row_count, size) block."""
    req = vs.VolumeEcShardPartialApplyRequest(
        volume_id=volume_id, collection=collection, offset=offset,
        size=size, row_count=row_count, shard_ids=shard_ids,
        coefficients=coefficients)
    for addr, sids, coef in delegates:
        req.delegates.add(grpc_address=addr, shard_ids=sids,
                          coefficients=coef)
    blob = b"".join(bytes(r.data) for r in
                    stub.VolumeEcShardPartialApply(req) if r.data)
    if len(blob) != row_count * size:
        raise IOError(
            f"short partial stream: want {row_count * size} got {len(blob)}")
    return np.frombuffer(blob, dtype=np.uint8).reshape(row_count, size)


def probe_shard_size(stub, volume_id: int, collection: str = "") -> int:
    """size=0 probe: a holder answers with its shard file size (what a
    rebuilder with zero local shards needs to size the stream from)."""
    req = vs.VolumeEcShardPartialApplyRequest(
        volume_id=volume_id, collection=collection, size=0)
    for r in stub.VolumeEcShardPartialApply(req):
        return int(r.shard_size)
    return 0


class PartialRepairClient:
    """Rebuilder-side orchestration: locate holders, prefer same-rack
    sources, issue one aggregated request per rack, XOR the per-rack
    partials, and label the ingress bytes by locality.

    ``locate() -> {shard_id: [(grpc_address, rack, dc), ...]}`` resolves
    holders (the caller excludes itself); ``stub_for(addr)`` returns the
    rpc stub for an address.  Lookups ride a TieredLocationCache so a
    rebuild storm does not hammer the master.
    """

    def __init__(self, volume_id: int, collection: str, locate, stub_for,
                 my_rack: str = "", my_dc: str = ""):
        from ...wdclient.location_cache import TieredLocationCache

        self.volume_id = volume_id
        self.collection = collection
        self._stub_for = stub_for
        self._cache = TieredLocationCache(locate)
        self.my_rack = my_rack
        self.my_dc = my_dc

    def remote_shards(self) -> "dict[int, tuple[str, str, str]]":
        """Best holder per shard id — same-rack holders win, address as
        tiebreak so the choice is stable across slices."""
        out: dict[int, tuple[str, str, str]] = {}
        for sid, holders in self._cache.get().items():
            if holders:
                out[sid] = best_ec_holder(holders, self.my_rack, self.my_dc)
        return out

    def invalidate(self) -> None:
        self._cache.invalidate()

    def order(self, holders: "dict[int, tuple[str, str, str]]") -> list[int]:
        return order_ec_sources(holders, self.my_rack, self.my_dc)

    def ingress_advantage(self, remote_sids, row_count: int) -> float:
        """full-fetch ingress / partial ingress for this source set:
        partial pulls (racks x row_count x width) vs full's
        (sources x width).  Below 1.0 the protocol would MOVE MORE
        bytes than it saves (e.g. 4 lost shards against 3 remote
        sources) — callers then keep the full-fetch path."""
        holders = self.remote_shards()
        chosen = {sid: holders[sid] for sid in remote_sids
                  if sid in holders}
        if not chosen or row_count <= 0:
            return 0.0
        racks = len(group_partial_sources(chosen))
        return len(chosen) / float(racks * row_count)

    def locality_of(self, sid: int) -> str:
        h = self.remote_shards().get(sid)
        if h is None:
            return "dc"
        return ec_source_locality(h[1], h[2], self.my_rack, self.my_dc)

    def shard_size(self) -> int:
        """Probe any reachable holder for the shard file size."""
        for _sid, (addr, _r, _d) in sorted(self.remote_shards().items()):
            try:
                n = probe_shard_size(
                    self._stub_for(addr), self.volume_id, self.collection)
            except Exception:  # noqa: BLE001 — try the next holder
                continue
            if n:
                return n
        return 0

    def fetch(self, coef_by_shard: "dict[int, np.ndarray]", row_count: int,
              offset: int, length: int) -> np.ndarray:
        """One aggregated (row_count, length) partial over the given
        remote source shards.  Raises PartialUnavailable on ANY failure
        — the caller falls back to full fetches (and this client drops
        its location cache, so the retry sees fresh holders)."""
        holders = self.remote_shards()
        chosen: dict[int, tuple[str, str, str]] = {}
        for sid in coef_by_shard:
            h = holders.get(sid)
            if h is None:
                raise PartialUnavailable(f"no holder for source shard {sid}")
            chosen[sid] = h
        groups = group_partial_sources(chosen)

        def one_group(g: dict) -> "tuple[dict, np.ndarray]":
            agg = g["aggregator"]
            agg_sids = g["members"][agg]
            delegates = [
                (addr, sids, pack_coefficients(coef_by_shard, sids))
                for addr, sids in sorted(g["members"].items())
                if addr != agg
            ]
            part = fetch_partial_once(
                self._stub_for(agg), self.volume_id, self.collection,
                offset, length, row_count, agg_sids,
                pack_coefficients(coef_by_shard, agg_sids),
                delegates=delegates)
            return g, part

        try:
            if len(groups) == 1:
                results = [one_group(groups[0])]
            else:
                results = list(_pool().map(one_group, groups))
        except Exception as e:
            EC_PARTIAL_JOBS.labels("fetch", "error").inc()
            self._cache.invalidate()
            raise PartialUnavailable(str(e)) from e
        acc = np.zeros((row_count, length), dtype=np.uint8)
        for g, part in results:
            label = ec_source_locality(
                g["rack"], g["dc"], self.my_rack, self.my_dc)
            EC_REBUILD_BYTES.labels(label).inc(part.nbytes)
            EC_PARTIAL_BYTES.labels("recv").inc(part.nbytes)
            np.bitwise_xor(acc, part, out=acc)
        EC_PARTIAL_JOBS.labels("fetch", "ok").inc()
        return acc


# ---------------------------------------------------------------------------
# In-process source fleet (unit tests + bench --rebuild-only A/B leg)
# ---------------------------------------------------------------------------


def local_source_network(nodes: "dict[str, tuple[str, list[int]]]"):
    """Drive the REAL serve/fetch code without sockets: ``nodes`` maps a
    fake grpc address -> (base_name, shard_ids it "holds").  Returns
    ``stub_for`` usable by PartialRepairClient — each stub executes
    serve_partial inline, including delegate fan-out through the same
    fleet, and streams the result in PARTIAL_CHUNK chunks like the wire
    handler does."""
    from types import SimpleNamespace

    class _Stub:
        def __init__(self, addr: str):
            self._addr = addr

        def VolumeEcShardPartialApply(self, request):
            base, sids = nodes[self._addr]

            if int(request.size) == 0:
                first = next((s for s in sids
                              if os.path.exists(base + to_ext(s))), None)
                size = (os.path.getsize(base + to_ext(first))
                        if first is not None else 0)
                yield SimpleNamespace(data=b"", shard_size=size)
                return

            def read_interval(sid, off, length):
                if sid not in sids:
                    return None
                with open(base + to_ext(sid), "rb") as f:
                    f.seek(off)
                    return f.read(length)

            acc = serve_partial(request, read_interval, stub_for=stub_for,
                                ctx=self._addr)
            blob = acc.tobytes()
            for at in range(0, len(blob), PARTIAL_CHUNK):
                yield SimpleNamespace(
                    data=blob[at:at + PARTIAL_CHUNK], shard_size=0)

    def stub_for(addr: str) -> _Stub:
        return _Stub(addr)

    return stub_for
