"""EC file pipeline: `.dat` -> `.ec00`..`.ec13` shards + `.ecx` sorted index.

Behavior matches the reference pipeline (ec_encoder.go:57-231): stripe the
volume into rows of 10 large (1GB) blocks while MORE than one full large row
remains, then rows of 10 small (1MB) blocks, zero-padding the tail; parity
is RS(10,4) over columns; shard files get byte-identical contents.

The batching geometry differs from the reference's fixed 256KB loop: we
stream column slices of a configurable width through the codec, which for
the TPU codec means big (10, W) uint8 blocks DMA'd to HBM and one fused
GF-matmul kernel per slice — the reference's 14 shard buffers map to one
device-resident matrix.  Output bytes are identical for any slice width
because parity is columnwise.
"""

from __future__ import annotations

import os

import numpy as np

from ...ops.codec import get_codec
from ..needle_map import NeedleMap
from .constants import (
    DATA_SHARDS,
    LARGE_BLOCK_SIZE,
    SMALL_BLOCK_SIZE,
    TOTAL_SHARDS,
    to_ext,
)

# Device batch: bytes per shard per codec call (64 x 256KB reference batches)
DEFAULT_SLICE = 16 * 1024 * 1024


def write_sorted_file_from_idx(base_name: str, ext: str = ".ecx") -> None:
    """Generate the sorted .ecx index from the .idx log (ec_encoder.go:27-54)."""
    nm = NeedleMap.load_from_idx(base_name + ".idx")
    nm.write_sorted_index(base_name + ext)


def write_ec_files(base_name: str, codec_name: str = "cpu",
                   slice_size: int = DEFAULT_SLICE) -> None:
    """Generate .ec00 ~ .ec13 from .dat (ec_encoder.go:57-59)."""
    generate_ec_files(
        base_name,
        large_block_size=LARGE_BLOCK_SIZE,
        small_block_size=SMALL_BLOCK_SIZE,
        codec_name=codec_name,
        slice_size=slice_size,
    )


def generate_ec_files(
    base_name: str,
    large_block_size: int = LARGE_BLOCK_SIZE,
    small_block_size: int = SMALL_BLOCK_SIZE,
    codec_name: str = "cpu",
    slice_size: int = DEFAULT_SLICE,
) -> None:
    codec = get_codec(codec_name)
    dat_path = base_name + ".dat"
    dat_size = os.path.getsize(dat_path)
    outs = [open(base_name + to_ext(i), "wb") for i in range(TOTAL_SHARDS)]
    try:
        with open(dat_path, "rb") as f:
            _encode_stream(
                f, dat_size, outs, codec, large_block_size, small_block_size,
                slice_size,
            )
    finally:
        for o in outs:
            o.close()


def _encode_stream(f, dat_size, outs, codec, large, small, slice_size) -> None:
    processed = 0
    remaining = dat_size
    # large rows: strictly-greater loop per the reference (ec_encoder.go:214)
    while remaining > large * DATA_SHARDS:
        _encode_row(f, processed, large, outs, codec, slice_size)
        remaining -= large * DATA_SHARDS
        processed += large * DATA_SHARDS
    while remaining > 0:
        _encode_row(f, processed, small, outs, codec, slice_size)
        remaining -= small * DATA_SHARDS
        processed += small * DATA_SHARDS


def _read_at(f, offset: int, length: int) -> np.ndarray:
    """Read with zero-fill past EOF (the reference zero-pads tail buffers)."""
    f.seek(offset)
    b = f.read(length)
    arr = np.zeros(length, dtype=np.uint8)
    if b:
        arr[: len(b)] = np.frombuffer(b, dtype=np.uint8)
    return arr


def _encode_row(f, row_start: int, block_size: int, outs, codec, slice_size) -> None:
    """Encode one stripe row: shard i covers [row_start + i*block, +block)."""
    for col in range(0, block_size, slice_size):
        width = min(slice_size, block_size - col)
        data = np.empty((DATA_SHARDS, width), dtype=np.uint8)
        for i in range(DATA_SHARDS):
            data[i] = _read_at(f, row_start + i * block_size + col, width)
        parity = codec.parity_of(data)
        for i in range(DATA_SHARDS):
            outs[i].write(data[i].tobytes())
        for i in range(parity.shape[0]):
            outs[DATA_SHARDS + i].write(parity[i].tobytes())


def rebuild_ec_files(base_name: str, codec_name: str = "cpu",
                     slice_size: int = DEFAULT_SLICE) -> list[int]:
    """Regenerate whichever .ecNN files are missing (ec_encoder.go:61-62).

    Requires >= DATA_SHARDS present shards; streams column slices, runs the
    decode matmul, writes only the missing shards.  Returns rebuilt ids.
    """
    codec = get_codec(codec_name)
    present = [i for i in range(TOTAL_SHARDS) if os.path.exists(base_name + to_ext(i))]
    missing = [i for i in range(TOTAL_SHARDS) if i not in present]
    if not missing:
        return []
    if len(present) < DATA_SHARDS:
        raise ValueError(
            f"cannot rebuild: only {len(present)} of {TOTAL_SHARDS} shards present"
        )
    shard_size = os.path.getsize(base_name + to_ext(present[0]))
    ins = {i: open(base_name + to_ext(i), "rb") for i in present}
    outs = {i: open(base_name + to_ext(i), "wb") for i in missing}
    try:
        for off in range(0, shard_size, slice_size):
            width = min(slice_size, shard_size - off)
            shards: list[np.ndarray | None] = [None] * TOTAL_SHARDS
            for i in present:
                shards[i] = _read_at(ins[i], off, width)
            rebuilt = codec.reconstruct(shards)
            for i in missing:
                outs[i].write(np.asarray(rebuilt[i], dtype=np.uint8).tobytes())
    finally:
        for h in ins.values():
            h.close()
        for h in outs.values():
            h.close()
    return missing
