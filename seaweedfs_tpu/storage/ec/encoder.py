"""EC file pipeline: `.dat` -> `.ec00`..`.ec13` shards + `.ecx` sorted index.

Behavior matches the reference pipeline (ec_encoder.go:57-231): stripe the
volume into rows of 10 large (1GB) blocks while MORE than one full large row
remains, then rows of 10 small (1MB) blocks, zero-padding the tail; parity
is RS(10,4) over columns; shard files get byte-identical contents.

The batching geometry differs from the reference's fixed 256KB loop: we
stream column slices of a configurable width through the codec, which for
the TPU codec means big (10, W) uint8 blocks DMA'd to HBM and one fused
GF-matmul kernel per slice — the reference's 14 shard buffers map to one
device-resident matrix.  Output bytes are identical for any slice width
because parity is columnwise.
"""

from __future__ import annotations

import os
import time

import numpy as np

from ...ops import codec_service, gf256
from ...ops.codec import get_codec
from ...stats.metrics import (
    EC_PARTIAL_FALLBACK,
    EC_PIPELINE_STAGE,
    EC_REBUILD_BYTES,
    EC_REBUILD_RESULT,
    EC_REBUILD_SECONDS,
    EC_REBUILD_SHARDS,
)
from ...util import faultpoint
from ..needle_map import NeedleMap
from .constants import (
    DATA_SHARDS,
    LARGE_BLOCK_SIZE,
    SMALL_BLOCK_SIZE,
    TOTAL_SHARDS,
    to_ext,
)

# Device batch: bytes per shard per codec call (64 x 256KB reference batches)
DEFAULT_SLICE = 16 * 1024 * 1024

# per-slice stage timings for the pipelined encode/rebuild: the pipeline
# runs at max(stage), so bottleneck attribution = the widest histogram
# (children resolved once — these observe on every slice)
_STAGE_PREFETCH = EC_PIPELINE_STAGE.labels("prefetch")
_STAGE_DECODE = EC_PIPELINE_STAGE.labels("decode")
_STAGE_WRITE = EC_PIPELINE_STAGE.labels("write")


def write_sorted_file_from_idx(base_name: str, ext: str = ".ecx") -> None:
    """Generate the sorted .ecx index from the .idx log (ec_encoder.go:27-54)."""
    nm = NeedleMap.load_from_idx(base_name + ".idx")
    nm.write_sorted_index(base_name + ext)


def write_ec_files(base_name: str, codec_name: str = "cpu",
                   slice_size: int = DEFAULT_SLICE, service=None) -> None:
    """Generate .ec00 ~ .ec13 from .dat (ec_encoder.go:57-59)."""
    generate_ec_files(
        base_name,
        large_block_size=LARGE_BLOCK_SIZE,
        small_block_size=SMALL_BLOCK_SIZE,
        codec_name=codec_name,
        slice_size=slice_size,
        service=service,
    )


def generate_ec_files(
    base_name: str,
    large_block_size: int = LARGE_BLOCK_SIZE,
    small_block_size: int = SMALL_BLOCK_SIZE,
    codec_name: str = "cpu",
    slice_size: int = DEFAULT_SLICE,
    progress=None,
    sync: bool = False,
    service=None,
) -> None:
    """`progress(volume_bytes_done)` fires after each slice's shard bytes
    hit the output files — lets callers (bench, shell) report live rates.
    `sync=True` fsyncs every shard file before returning, so a completed
    encode means the shards survive a crash (and so a timed encode shares
    accounting with an fsync'd raw-write baseline).

    `service` routes the GF parity compute through the shared codec
    service (ops.codec_service): slices become queued jobs the scheduler
    coalesces with OTHER concurrent volumes' slices into device-resident
    (or slab-SIMD) batches.  Default: the service engages automatically
    for device codecs when the fast probe confirms a reachable
    accelerator; host encodes keep the direct mmap path unless a caller
    that knows it is concurrent passes a service explicitly."""
    codec = get_codec(codec_name)
    if service is None:
        service = codec_service.service_for_codec(codec_name)
    dat_path = base_name + ".dat"
    dat_size = os.path.getsize(dat_path)
    outs = [open(base_name + to_ext(i), "wb") for i in range(TOTAL_SHARDS)]
    try:
        with open(dat_path, "rb") as f:
            if (hasattr(codec, "parity_into") or service is not None) \
                    and not hasattr(codec, "encode_device") and dat_size > 0:
                # host codecs: zero-copy path — stripe rows are views into
                # the mmap'd .dat, consumed in place by the GF kernel and
                # handed to writev as-is; the only user-space byte traffic
                # is the parity output.  On this class of single-core host
                # the pipeline is a SUM of stage costs, so removing the
                # (10, W) gather memcpy and the per-1MB write syscalls is
                # worth ~2x end-to-end.
                _encode_stream_mmap(
                    f, dat_size, outs, codec, large_block_size,
                    small_block_size, slice_size, progress, service,
                )
            else:
                # device codecs: overlap the prefetch thread's disk reads
                # with HBM transfer + kernel via the async dispatch
                _encode_stream_pipelined(
                    f, dat_size, outs, codec, large_block_size,
                    small_block_size, slice_size, progress, service,
                )
        if sync:
            for o in outs:
                o.flush()
                os.fsync(o.fileno())
            # new files also need their directory entry durable
            dfd = os.open(os.path.dirname(os.path.abspath(dat_path))
                          or ".", os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
    finally:
        for o in outs:
            o.close()


def _segments(dat_size: int, large: int, small: int, slice_size: int):
    """Yield (row_start, block_size, col, width) in shard-file write order."""
    processed = 0
    remaining = dat_size
    # large rows: strictly-greater loop per the reference (ec_encoder.go:214)
    while remaining > large * DATA_SHARDS:
        for col in range(0, large, slice_size):
            yield processed, large, col, min(slice_size, large - col)
        remaining -= large * DATA_SHARDS
        processed += large * DATA_SHARDS
    while remaining > 0:
        for col in range(0, small, slice_size):
            yield processed, small, col, min(slice_size, small - col)
        remaining -= small * DATA_SHARDS
        processed += small * DATA_SHARDS


def _slice_tasks(dat_size: int, large: int, small: int, slice_size: int):
    """Group stripe segments into codec-call batches of up to slice_size
    bytes per shard.

    Parity is columnwise, so segments from DIFFERENT stripe rows can share
    one codec call: shard i's bytes for consecutive rows are consecutive in
    its .ecNN file, so a batch is just a per-shard concatenation.  This
    matters enormously for the small-row region (any volume tail, and the
    whole volume when it is under 10GB): without batching every codec call
    is a (10, 1MB) stripe — 16x the dispatch count and, for device codecs,
    16x the host<->HBM round trips.

    Yields lists of (row_start, block_size, col, width) whose widths sum to
    <= slice_size, in shard-file write order.
    """
    batch: list[tuple[int, int, int, int]] = []
    batch_width = 0
    for seg in _segments(dat_size, large, small, slice_size):
        width = seg[3]
        if batch and batch_width + width > slice_size:
            yield batch
            batch, batch_width = [], 0
        batch.append(seg)
        batch_width += width
    if batch:
        yield batch


try:
    _IOV_MAX = os.sysconf("SC_IOV_MAX")
    if _IOV_MAX <= 0:  # sysconf returns -1 for "unlimited/unknown"
        _IOV_MAX = 1024
except (ValueError, OSError, AttributeError):
    _IOV_MAX = 1024


def _writev_all(fd: int, bufs: list) -> None:
    """os.writev with partial-write resume, chunked to IOV_MAX iovecs
    (a small slice_size/small_block ratio can exceed the kernel limit).
    Consumed iovecs advance an index instead of pop(0)-shifting the
    list — the shift made large batches O(n^2) in iovec count."""
    i = 0
    while i < len(bufs):
        n = os.writev(fd, bufs[i : i + _IOV_MAX])
        while i < len(bufs) and n >= len(bufs[i]):
            n -= len(bufs[i])
            i += 1
        if n and i < len(bufs):
            bufs[i] = memoryview(bufs[i])[n:]


def _encode_stream_mmap(
    f, dat_size, outs, codec, large, small, slice_size, progress=None,
    service=None,
) -> None:
    """Single-threaded zero-copy encode for host codecs.

    Per _slice_tasks batch: each stripe row of each segment is a 1-D view
    into the mmap'd .dat (page cache), passed directly to the SIMD GF
    kernel (codec.parity_into) and to writev for the data-shard appends —
    no (10, W) stripe materialisation, no per-MB write() syscalls.  Rows
    that cross EOF fall back to a small zero-padded copy (the reference
    zero-pads tail buffers, ec_encoder.go:162-192); rows fully past EOF
    share one zeros buffer.

    Threads deliberately absent: on a single-core host the prefetch/writer
    threads of the pipelined path only add GIL churn, and the kernel-side
    page-cache copies writev does are CPU work that cannot overlap itself.
    """
    import mmap

    # no MAP_POPULATE: prefaulting a 30GB volume upfront would stall the
    # encode (no progress callbacks) and thrash hosts with RAM < volume;
    # MADV_SEQUENTIAL readahead streams pages just ahead of the kernel
    mm = mmap.mmap(f.fileno(), 0, prot=mmap.PROT_READ)
    view = None
    try:
        if hasattr(mm, "madvise"):
            try:
                mm.madvise(mmap.MADV_SEQUENTIAL)
            except (ValueError, OSError):
                pass
        view = np.frombuffer(mm, dtype=np.uint8)
        n_parity = len(codec.parity_matrix) if hasattr(
            codec, "parity_matrix") else 4
        zeros: np.ndarray | None = None
        done = 0
        parity = np.empty((n_parity, slice_size), dtype=np.uint8)
        for batch in _slice_tasks(dat_size, large, small, slice_size):
            total = sum(seg[3] for seg in batch)
            # per shard: the ordered list of row buffers for this batch
            per_shard: list[list[np.ndarray]] = [[] for _ in range(DATA_SHARDS)]
            for row_start, block, col, width in batch:
                for i in range(DATA_SHARDS):
                    off = row_start + i * block + col
                    if off + width <= dat_size:
                        row = view[off:off + width]
                    elif off >= dat_size:
                        if zeros is None or len(zeros) < width:
                            zeros = np.zeros(
                                max(width, small), dtype=np.uint8)
                        row = zeros[:width]
                    else:
                        row = np.zeros(width, dtype=np.uint8)
                        n = dat_size - off
                        row[:n] = view[off:off + n]
                    per_shard[i].append(row)
            # parity per segment into contiguous per-batch output slabs
            at = 0
            futures = []
            if service is not None:
                # one vectored submit for the whole batch of segments:
                # the service coalesces them (and any concurrent
                # volume's) into one kernel call, and the data-shard
                # writev below overlaps the parity compute
                seg_ins, seg_outs = [], []
                for s, (_, _, _, width) in enumerate(batch):
                    seg_ins.append(
                        [per_shard[i][s] for i in range(DATA_SHARDS)])
                    seg_outs.append(
                        [parity[j, at:at + width] for j in range(n_parity)])
                    at += width
                futures = service.submit_parity_many(seg_ins, seg_outs)
            else:
                for s, (_, _, _, width) in enumerate(batch):
                    codec.parity_into(
                        [per_shard[i][s] for i in range(DATA_SHARDS)],
                        [parity[j, at:at + width] for j in range(n_parity)],
                    )
                    at += width
            for i in range(DATA_SHARDS):
                outs[i].flush()  # keep the buffered layer empty around writev
                _writev_all(outs[i].fileno(), per_shard[i])
            for fut in futures:
                fut.result()  # parity slab must be full before its writev
            for j in range(n_parity):
                outs[DATA_SHARDS + j].flush()
                _writev_all(outs[DATA_SHARDS + j].fileno(),
                            [parity[j, :total]])
            done += total * DATA_SHARDS
            if progress is not None:
                progress(min(done, dat_size))
    finally:
        del view  # release the exported buffer before closing the map
        try:
            mm.close()
        except BufferError:
            pass  # stray view still alive; the map dies with the process


def _encode_stream_pipelined(
    f, dat_size, outs, codec, large, small, slice_size, progress=None,
    service=None,
) -> None:
    """Overlap disk reads with compute for every codec; device codecs
    also overlap HBM transfer + kernel.

    Three stages run concurrently (SURVEY §7 hard part (b)):
      * a prefetch thread reads (10, W) stripe slices from the .dat into a
        bounded queue (disk/page-cache -> host RAM);
      * the main thread dispatches the GF matmul asynchronously (JAX returns
        before the device finishes) — one slice is always in flight;
      * while slice k+1 computes, slice k's data shards are written and its
        parity is read back (the only blocking point) and written.

    Slices are pre-packed as little-endian uint32 on the host (a free
    ndarray view) so the Pallas SWAR kernel gets its native word layout with
    no device-side bitcast (rs_pallas.make_apply_pallas .as_u32).
    """
    import queue
    import threading

    is_device_codec = hasattr(codec, "encode_device")
    if is_device_codec and service is None:  # host-only codecs need no jax
        import jax.numpy as jnp

    q: queue.Queue = queue.Queue(maxsize=2)
    stop = threading.Event()

    def _put(item) -> bool:
        """Bounded put that gives up when the consumer has bailed."""
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def reader() -> None:
        try:
            for batch in _slice_tasks(dat_size, large, small, slice_size):
                total = sum(seg[3] for seg in batch)
                with _STAGE_PREFETCH.time():
                    data = np.empty((DATA_SHARDS, total), dtype=np.uint8)
                    fill_stripe_rows(f, batch, data)
                if not _put(data):
                    return
        except Exception as e:  # surfaced by the consumer
            _put(e)
            return
        _put(None)

    t = threading.Thread(target=reader, name="ec-encode-prefetch", daemon=True)
    t.start()

    # lane-tile geometry for the fully-prepacked path: width must split into
    # whole (SUBLANES, LANES)-uint32 tiles so the jit sees only the
    # pallas_call.  Gated: this import pulls in jax, which host-only
    # encodes must not pay for.
    lane_tile_bytes = 0
    if is_device_codec and service is None:
        try:
            from ...ops.rs_pallas import LANES, SUBLANES
            lane_tile_bytes = SUBLANES * LANES * 4
        except ImportError:
            pass  # no pallas — 3d path never taken

    def dispatch(data: np.ndarray):
        """-> (device parity future, packed?) — async on the device;
        synchronous parity for host-only codecs."""
        if service is not None:
            # the codec service owns device transfer + double buffering;
            # slices become jobs it may coalesce with other volumes'
            return service.submit_parity(data), False
        if not is_device_codec:
            return codec.parity_of(data), False
        width = data.shape[1]
        if (
            lane_tile_bytes
            and width % lane_tile_bytes == 0
            and hasattr(codec, "encode_device_u32_3d")
        ):
            d3 = data.view(np.uint32).reshape(DATA_SHARDS, -1, LANES)
            out3 = codec.encode_device_u32_3d(jnp.asarray(d3))
            if out3 is not None:
                return out3, True
        if width % 4 == 0 and hasattr(codec, "encode_device_u32"):
            out32 = codec.encode_device_u32(jnp.asarray(data.view(np.uint32)))
            if out32 is not None:
                return out32, True
        return codec.encode_device(jnp.asarray(data)), False

    # writer thread: shard appends overlap the next slice's compute (the
    # write side is 1.4x the read side, so on write-bound disks this is
    # the difference between sum and max of the two)
    wq: queue.Queue = queue.Queue(maxsize=2)
    write_err: list[Exception] = []
    done = 0

    def writer() -> None:
        nonlocal done
        while True:
            pending = wq.get()
            if pending is None:
                return
            if write_err:
                continue  # drain the queue so producers never block
            try:  # EVERYTHING must land in write_err, or drain() deadlocks
                data, parity = pending
                with _STAGE_WRITE.time():
                    for i in range(DATA_SHARDS):
                        outs[i].write(data[i])  # buffer-protocol, no copy
                    # parity is a (P, W) array or a list of P rows (the
                    # codec-service future resolves to a row list)
                    for pi, prow in enumerate(parity):
                        outs[DATA_SHARDS + pi].write(prow)
                done += data.shape[1] * DATA_SHARDS
                if progress is not None:
                    progress(min(done, dat_size))
            except Exception as e:  # surfaced by the main thread
                write_err.append(e)

    wt = threading.Thread(target=writer, name="ec-encode-writer", daemon=True)
    wt.start()

    def drain(pending) -> None:
        data, parity_dev, packed = pending
        if hasattr(parity_dev, "result"):  # codec-service future
            with _STAGE_DECODE.time():  # wait = batch compute completion
                parity = parity_dev.result()
        elif isinstance(parity_dev, np.ndarray):  # host: timed at dispatch
            parity = np.ascontiguousarray(parity_dev)
        else:
            with _STAGE_DECODE.time():  # device readback = compute completion
                parity = np.ascontiguousarray(np.asarray(parity_dev))
        if packed:
            parity = parity.view(np.uint8).reshape(parity.shape[0], -1)
        wq.put((data, parity))
        if write_err:
            raise write_err[0]

    from collections import deque

    # service dispatch is a queue submit, so TWO slices ride in flight
    # (the service double-buffers H2D against compute against D2H);
    # direct device dispatch keeps the original one-async-slice window
    async_mode = is_device_codec or service is not None
    max_pending = 2 if service is not None else 1
    pending_q: deque = deque()
    try:
        while True:
            item = q.get()
            if isinstance(item, Exception):
                raise item
            if item is None:
                break
            if not async_mode:
                # synchronous codec: compute here, overlap only the writes
                with _STAGE_DECODE.time():
                    parity, packed = dispatch(item)
                drain((item, parity, packed))
                continue
            parity_dev, packed = dispatch(item)
            pending_q.append((item, parity_dev, packed))
            if len(pending_q) > max_pending:
                drain(pending_q.popleft())
        while pending_q:
            drain(pending_q.popleft())
        wq.put(None)
        wt.join()
        if write_err:
            raise write_err[0]
    finally:
        # unblock the prefetch + writer threads on error paths
        stop.set()
        while True:
            try:
                q.get_nowait()
            except queue.Empty:
                break
        t.join()
        if wt.is_alive():
            while True:
                try:
                    wq.get_nowait()
                except queue.Empty:
                    break
            wq.put(None)
            wt.join()


def fill_stripe_rows(f, batch, dest: np.ndarray) -> None:
    """Fill dest[(DATA_SHARDS, total_width)] with one _slice_tasks batch:
    row i gathers the batch's segments at `row_start + i*block + col`.
    The single home of the stripe-gather arithmetic — the serial and
    multi-volume batch encoders both call it, so their geometry cannot
    drift."""
    for i in range(DATA_SHARDS):
        row = memoryview(dest[i])
        at = 0
        for row_start, block, col, width in batch:
            _read_into(f, row_start + i * block + col, row[at:at + width])
            at += width


def _read_at(f, offset: int, length: int) -> np.ndarray:
    """Read with zero-fill past EOF (the reference zero-pads tail buffers)."""
    arr = np.empty(length, dtype=np.uint8)
    _read_into(f, offset, memoryview(arr))
    return arr


def _read_into(f, offset: int, dest: memoryview) -> None:
    """Fill `dest` from f[offset:], zero-filling past EOF, without
    intermediate bytes allocations (readinto straight to the stripe row)."""
    f.seek(offset)
    n = f.readinto(dest)
    if n is None:
        n = 0
    while 0 < n < len(dest):  # short read mid-file
        more = f.readinto(dest[n:])
        if not more:
            break
        n += more
    if n < len(dest):
        dest[n:] = bytes(len(dest) - n)


# fires once per rebuilt slice, before the source reads — chaos tests
# kill a rebuild mid-stream here and assert the clean-error contract
# (partial .ecNN outputs removed, retry succeeds)
FP_REBUILD_READ = faultpoint.register("ec.rebuild.read")


def _pread_into(fd: int, dest, offset: int) -> None:
    """Positioned read straight into a writable buffer (numpy row), no
    intermediate bytes object; loops short reads and raises on EOF
    (shard files have a fixed extent, so a short tail means a racing
    truncate/re-copy)."""
    got = 0
    length = len(dest)
    while got < length:
        n = os.preadv(fd, [dest[got:]], offset + got)
        if n <= 0:
            raise IOError(f"short shard read at {offset + got}")
        got += n


def _pick_rebuild_sources(
    base_name: str, local: list[int], remote_fetch, partial=None
) -> tuple[list[int], set[int], set[int]]:
    """-> (DATA_SHARDS source ids local-first, the remote subset of those,
    ALL remotely-available shard ids).

    With a partial-repair client, remote availability and ORDER come
    from its holder map — same-rack sources are drawn before cross-rack
    ones (topology.placement.order_ec_sources), so the expensive links
    carry as few partials as possible.  Without one, remote availability
    is probed with a 1-byte interval read through the same fetch hook
    the streaming loop uses.  Either way every non-local shard is
    covered so the caller can limit the rebuild to GLOBALLY missing
    shards — regenerating a local copy of a shard that is healthy on a
    peer would double the repair traffic and register duplicate holders
    with the master."""
    sources = list(local[:DATA_SHARDS])
    remote: set[int] = set()
    remote_available: set[int] = set()
    if partial is not None:
        holders = {sid: h for sid, h in partial.remote_shards().items()
                   if sid not in local}
        remote_available = set(holders)
        # the holder map can list a dead node (heartbeat not yet timed
        # out); a 1-byte probe of each CHOSEN source keeps that from
        # sinking the whole rebuild when a live alternate shard exists —
        # the map still decides what is globally missing, exactly like
        # the shell's planning.  Mass-repair batch clients skip the
        # probes (trust_holders): their maps were refreshed by the
        # master's dead-node notice moments ago, and a stale holder
        # costs one per-volume fallback, not a stalled batch.
        probe = (remote_fetch is not None
                 and not getattr(partial, "trust_holders", False))
        for sid in partial.order(holders):
            if len(sources) >= DATA_SHARDS:
                break
            if probe:
                try:
                    if not remote_fetch(sid, 0, 1):
                        continue
                except Exception:
                    continue
            sources.append(sid)
            remote.add(sid)
    elif remote_fetch is not None:
        for sid in range(TOTAL_SHARDS):
            if sid in local:
                continue
            try:
                probe = remote_fetch(sid, 0, 1)
            except Exception:
                probe = None
            if probe:
                remote_available.add(sid)
                if len(sources) < DATA_SHARDS:
                    sources.append(sid)
                    remote.add(sid)
    if len(sources) < DATA_SHARDS:
        raise ValueError(
            f"cannot rebuild: only {len(sources)} of {TOTAL_SHARDS} shards "
            f"reachable ({len(local)} local)"
        )
    return sources, remote, remote_available


def rebuild_ec_files(base_name: str, codec_name: str = "cpu",
                     slice_size: int = DEFAULT_SLICE,
                     progress=None, remote_fetch=None,
                     shard_size: int | None = None,
                     service=None, partial=None) -> list[int]:
    """Regenerate whichever .ecNN files are missing (ec_encoder.go:61-62).

    Runs the same three-stage pipeline as the encode path: a prefetch
    thread preads the DATA_SHARDS source shards IN PARALLEL into pooled
    slice buffers, the main thread applies the cached decode plan (async
    device dispatch for device codecs, one slice always in flight; host
    codecs compute inline on the SIMD kernel), and a writer thread
    appends the reconstructed shards — so the rebuild runs at
    max(read, decode, write) instead of their sum, and reads exactly
    DATA_SHARDS sources instead of every present shard.

    `remote_fetch(shard_id, offset, length) -> bytes|None` (the same
    contract as EcVolume.remote_fetch) lets a node holding fewer than
    DATA_SHARDS local shards stream missing source intervals from peers
    instead of failing; `shard_size` must be given when no local shard
    exists to size the stream from (a partial client's probe can answer
    it too).

    `partial` (a storage.ec.partial.PartialRepairClient) switches remote
    sourcing to the partial-sum protocol: remote sources multiply their
    intervals by their decode-plan columns locally and this node pulls
    ONE aggregated (missing x width) partial per rack instead of every
    raw interval — the local shards' plan columns are applied here and
    XOR'd in, so output bytes are identical by GF linearity.  Any
    partial failure (source death mid-stream, stale holder) degrades
    permanently to the full-fetch path for the rest of the rebuild
    (seaweedfs_ec_partial_fallback_total{path="rebuild"}).

    On any error the partial .ecNN outputs are REMOVED — a failed
    rebuild leaves no truncated shard for a later mount to trust.
    Returns rebuilt ids; `progress(shard_bytes_done)` fires after each
    reconstructed slice hits the output files.
    """
    import queue
    import threading
    from concurrent.futures import ThreadPoolExecutor

    from ...util.executors import MeteredThreadPoolExecutor

    codec = get_codec(codec_name)
    impl = getattr(codec, "_impl", codec_name)
    local = [i for i in range(TOTAL_SHARDS)
             if os.path.exists(base_name + to_ext(i))]
    if len(local) == TOTAL_SHARDS:
        return []
    picked = None
    if partial is not None:
        try:
            picked = _pick_rebuild_sources(
                base_name, local, remote_fetch, partial)
        except ValueError:
            # the holder map cannot supply 10 sources (stale locations):
            # let the probing path have a try before giving up
            EC_PARTIAL_FALLBACK.labels("rebuild").inc()
            partial = None
    if picked is None:
        picked = _pick_rebuild_sources(base_name, local, remote_fetch)
    sources, remote, remote_available = picked
    # rebuild only GLOBALLY missing shards: a shard healthy on a peer
    # needs a copy rpc, not a decode (see _pick_rebuild_sources)
    missing = [i for i in range(TOTAL_SHARDS)
               if i not in local and i not in remote_available]
    if not missing:
        return []
    if local:
        shard_size = os.path.getsize(base_name + to_ext(local[0]))
    elif shard_size is None:
        if partial is not None:
            shard_size = partial.shard_size() or None
        if shard_size is None:
            raise ValueError(
                "cannot rebuild: no local shard and no shard_size given")

    # the whole decode program for this loss pattern, from the shared
    # plan cache: one 10x10 inversion per survivor set, not per slice
    rows = gf256.decode_plan_for(
        codec.matrix, DATA_SHARDS, sources, tuple(missing))

    # partial mode: split the plan by source locality — columns for
    # local sources are applied HERE, columns for remote sources ship to
    # them as coefficient rows and come back pre-multiplied + pre-XOR'd
    local_srcs = [s for s in sources if s not in remote]
    n_local = len(local_srcs)
    use_partial = partial is not None and bool(remote)
    if use_partial and remote_fetch is not None:
        # the protocol pulls racks x missing x width; when that exceeds
        # the plain sources x width (many lost shards, few remote
        # sources), full fetch IS the bandwidth-optimal path.  Without
        # a full-fetch transport the partial path stays on regardless —
        # it is the only remote sourcing available.
        try:
            use_partial = partial.ingress_advantage(
                remote, len(missing)) >= 1.0
        except Exception:  # noqa: BLE001 — fetch failures fall back anyway
            pass
    local_plan = None
    coef_by_shard: dict[int, np.ndarray] = {}
    if use_partial:
        local_cols = [i for i, s in enumerate(sources) if s not in remote]
        if local_cols:
            local_plan = np.ascontiguousarray(rows[:, local_cols])
        coef_by_shard = {s: rows[:, i] for i, s in enumerate(sources)
                         if s in remote}
    # ingress locality labels for the full-fetch path (the partial
    # client labels its own aggregated pulls).  Evaluated per fetch, not
    # precomputed: the fetcher reports the holder it ACTUALLY read from,
    # which can shift cross-rack mid-rebuild when a same-rack peer dies.
    loc_of = getattr(remote_fetch, "locality_of", None)
    if loc_of is None and partial is not None:
        loc_of = partial.locality_of

    def _src_label(sid: int) -> str:
        try:
            return loc_of(sid) if loc_of is not None else "dc"
        except Exception:  # noqa: BLE001 — labels must never fail a read
            return "dc"

    label_child = {lab: EC_REBUILD_BYTES.labels(lab)
                   for lab in ("local", "rack", "dc")}
    if service is None:
        service = codec_service.service_for_codec(codec_name)
    is_device_codec = hasattr(codec, "apply_rows_device") and hasattr(
        codec, "encode_device")
    if is_device_codec and service is None:
        import jax.numpy as jnp

    # everything that creates on-disk or OS state is populated INSIDE the
    # guarded try below: the finally owns closing handles and removing
    # partial outputs, so no setup failure (buffer MemoryError, thread
    # spawn refusal) can leave a zero-length .ecNN for a mount to trust
    ins: dict[int, object] = {}
    outs: dict[int, object] = {}
    t_start = time.perf_counter()

    pool: queue.Queue = queue.Queue()
    q: queue.Queue = queue.Queue(maxsize=2)
    stop = threading.Event()

    def _put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _read_source(sid: int, off: int, dest: np.ndarray) -> int:
        """Fill one source row; returns the bytes fetched remotely."""
        width = len(dest)
        if sid in remote:
            buf = remote_fetch(sid, off, width)
            if buf is None or len(buf) != width:
                raise IOError(
                    f"remote shard {sid} unavailable during rebuild")
            dest[:] = np.frombuffer(buf, dtype=np.uint8)
            return width
        _pread_into(ins[sid].fileno(), dest, off)
        return 0

    def _get_buffer():
        """Stop-aware pool.get: a failed writer stops recycling buffers,
        so a bare blocking get could strand this thread forever and wedge
        the finally's join."""
        while not stop.is_set():
            try:
                return pool.get(timeout=0.1)
            except queue.Empty:
                continue
        return None

    part_on = [use_partial]  # sticky: one failure drops to full fetch

    def _fetch_partial(off: int, width: int) -> "np.ndarray | None":
        """-> (missing, width) aggregated remote partial, or None after
        a clean, PERMANENT fallback to the full-fetch path."""
        if not part_on[0]:
            return None
        try:
            return partial.fetch(coef_by_shard, len(missing), off, width)
        except Exception:
            if remote_fetch is None:
                raise  # no fallback transport: surface the clean error
            part_on[0] = False
            EC_PARTIAL_FALLBACK.labels("rebuild").inc()
            return None

    def reader(fetch_pool: ThreadPoolExecutor) -> None:
        try:
            for off in range(0, shard_size, slice_size):
                width = min(slice_size, shard_size - off)
                faultpoint.inject(FP_REBUILD_READ, ctx=base_name)
                buf = _get_buffer()
                if buf is None:
                    return
                with _STAGE_PREFETCH.time():
                    part = _fetch_partial(off, width)
                    if part is not None:
                        # only the LOCAL source rows are read here; the
                        # remote contribution arrived pre-combined
                        view = buf[:n_local, :width]
                        for j, sid in enumerate(local_srcs):
                            _pread_into(ins[sid].fileno(), view[j], off)
                        label_child["local"].inc(n_local * width)
                    else:
                        view = buf[:, :width]
                        fetched = list(fetch_pool.map(
                            lambda j: _read_source(sources[j], off, view[j]),
                            range(DATA_SHARDS)))
                        for j, nb in enumerate(fetched):
                            if nb:
                                label_child[_src_label(sources[j])].inc(nb)
                        label_child["local"].inc(
                            DATA_SHARDS * width - sum(fetched))
                if not _put((buf, view, off, width, part)):
                    return
        except Exception as e:  # surfaced by the consumer
            _put(e)
            return
        _put(None)

    wq: queue.Queue = queue.Queue(maxsize=2)
    write_err: list[Exception] = []

    def writer() -> None:
        while True:
            pending = wq.get()
            if pending is None:
                return
            if write_err:
                continue  # drain so producers never block
            try:
                buf, rebuilt, off, width = pending
                with _STAGE_WRITE.time():
                    for row, sid in zip(rebuilt, missing):
                        outs[sid].write(row)
                pool.put(buf)  # source slice fully consumed: recycle
                if progress is not None:
                    progress(off + width)
            except Exception as e:  # surfaced by the main thread
                write_err.append(e)

    fetch_pool: "ThreadPoolExecutor | None" = None
    rt = threading.Thread(target=lambda: reader(fetch_pool),
                          name="ec-rebuild-prefetch", daemon=True)
    wt = threading.Thread(target=writer, name="ec-rebuild-writer",
                          daemon=True)

    def drain(pending) -> None:
        buf, dev, off, width, part = pending
        with _STAGE_DECODE.time():  # readback/wait = decode completion
            if hasattr(dev, "result"):  # codec-service future -> row list
                rebuilt = dev.result()
            else:
                rebuilt = np.ascontiguousarray(
                    np.asarray(dev, dtype=np.uint8))
            if part is not None:  # GF addition completes the decode
                rebuilt = np.bitwise_xor(
                    np.asarray(rebuilt, dtype=np.uint8), part)
        wq.put((buf, rebuilt, off, width))
        if write_err:
            raise write_err[0]

    from collections import deque

    # service submits are queue hops, so two slices ride in flight (the
    # service double-buffers); direct device dispatch keeps one async
    async_mode = is_device_codec or service is not None
    max_pending = 2 if service is not None else 1
    pending_q: deque = deque()
    ok = False
    try:
        for i in sources:
            if i not in remote:
                ins[i] = open(base_name + to_ext(i), "rb")
        for i in missing:
            outs[i] = open(base_name + to_ext(i), "wb")
        # pooled slice buffers: 3 covers one in prefetch, one in compute,
        # one in the writer, with no per-slice (10, W) allocation churn
        for _ in range(3):
            pool.put(np.empty((DATA_SHARDS, slice_size), dtype=np.uint8))
        fetch_pool = MeteredThreadPoolExecutor(
            max_workers=DATA_SHARDS, name="ec_rebuild_read",
            thread_name_prefix="ec-rebuild-read")
        rt.start()
        wt.start()
        while True:
            item = q.get()
            if isinstance(item, Exception):
                raise item
            if item is None:
                break
            buf, view, off, width, part = item
            if part is not None and n_local == 0:
                # every source was remote: the aggregated partial IS the
                # rebuilt rows — zero GF compute at the rebuilder
                wq.put((buf, list(part), off, width))
                if write_err:
                    raise write_err[0]
                continue
            plan_mtx = local_plan if part is not None else rows
            if not async_mode:
                # host codec: SIMD decode inline, overlap only the I/O
                with _STAGE_DECODE.time():
                    rebuilt = codec.apply_rows(plan_mtx, list(view))
                    if part is not None:
                        rebuilt = np.bitwise_xor(
                            np.asarray(rebuilt, dtype=np.uint8), part)
                wq.put((buf, rebuilt, off, width))
                if write_err:
                    raise write_err[0]
                continue
            if service is not None:
                dev = service.submit_apply(plan_mtx, list(view))
            else:
                dev = codec.apply_rows_device(plan_mtx, jnp.asarray(view))
            pending_q.append((buf, dev, off, width, part))
            if len(pending_q) > max_pending:
                drain(pending_q.popleft())  # k reads back while k+1 computes
        while pending_q:
            drain(pending_q.popleft())
        wq.put(None)
        wt.join()
        if write_err:
            raise write_err[0]
        ok = True
    finally:
        stop.set()
        while True:
            try:
                q.get_nowait()
            except queue.Empty:
                break
        if rt.ident is not None:  # never-started threads cannot be joined
            rt.join()
        if wt.ident is not None and wt.is_alive():
            while True:
                try:
                    wq.get_nowait()
                except queue.Empty:
                    break
            wq.put(None)
            wt.join()
        if fetch_pool is not None:
            fetch_pool.shutdown(wait=False)
        for h in ins.values():
            h.close()
        for h in outs.values():
            h.close()
        EC_REBUILD_SECONDS.labels(impl).observe(time.perf_counter() - t_start)
        EC_REBUILD_RESULT.labels("ok" if ok else "error").inc()
        if ok:
            EC_REBUILD_SHARDS.inc(len(missing))
        else:
            # clean-error contract: no truncated shard file survives a
            # failed rebuild for a later mount to trust
            for sid in missing:
                try:
                    os.remove(base_name + to_ext(sid))
                except FileNotFoundError:
                    pass
    return missing
