"""Striped-layout interval math: map logical `.dat` ranges to shard ranges.

The volume is striped row-major across the 10 data shards: rows of 1GB
blocks while they fit, then rows of 1MB blocks (so the tail only rounds up
to 10x1MB, not 10x1GB).  Reference: ec_locate.go:15-87 and the row scheme in
ec_encoder.go:194-231.
"""

from __future__ import annotations

from dataclasses import dataclass

from .constants import DATA_SHARDS


@dataclass(frozen=True)
class Interval:
    block_index: int
    inner_block_offset: int
    size: int
    is_large_block: bool
    large_block_rows_count: int

    def to_shard_id_and_offset(
        self, large_block_size: int, small_block_size: int
    ) -> tuple[int, int]:
        off = self.inner_block_offset
        row_index = self.block_index // DATA_SHARDS
        if self.is_large_block:
            off += row_index * large_block_size
        else:
            off += (
                self.large_block_rows_count * large_block_size
                + row_index * small_block_size
            )
        return self.block_index % DATA_SHARDS, off


def _locate_offset(
    large: int, small: int, dat_size: int, offset: int
) -> tuple[int, bool, int]:
    large_row_size = large * DATA_SHARDS
    n_large_rows = dat_size // large_row_size
    if offset < n_large_rows * large_row_size:
        return offset // large, True, offset % large
    offset -= n_large_rows * large_row_size
    return offset // small, False, offset % small


def locate_data(
    large: int, small: int, dat_size: int, offset: int, size: int
) -> list[Interval]:
    """Split a logical (offset, size) range into per-block intervals."""
    block_index, is_large, inner = _locate_offset(large, small, dat_size, offset)
    # +DataShards*small so shard size alone determines the large-row count
    n_large_rows = (dat_size + DATA_SHARDS * small) // (large * DATA_SHARDS)

    intervals: list[Interval] = []
    while size > 0:
        remaining = (large if is_large else small) - inner
        take = min(size, remaining)
        intervals.append(Interval(block_index, inner, take, is_large, n_large_rows))
        if take == size:
            return intervals
        size -= take
        block_index += 1
        if is_large and block_index == n_large_rows * DATA_SHARDS:
            is_large = False
            block_index = 0
        inner = 0
    return intervals


def shard_file_size(dat_size: int, large: int, small: int) -> int:
    """Size of each .ecNN file for a given .dat size (zero-padded tail)."""
    if dat_size <= 0:
        return 0
    large_rows = (dat_size - 1) // (large * DATA_SHARDS) if dat_size > large * DATA_SHARDS else 0
    rest = dat_size - large_rows * large * DATA_SHARDS
    small_rows = -(-rest // (small * DATA_SHARDS))  # ceil
    return large_rows * large + small_rows * small
