"""EC decode-to-volume: shards -> `.dat`, `.ecx`+`.ecj` -> `.idx`.

Reference: ec_decoder.go.  Used by the `ec.decode` admin flow
(VolumeEcShardsToVolume) to turn an EC volume back into a normal one.

Note: for `.dat` sizes that are an exact multiple of 10GB the reference's
WriteDatFile (ec_decoder.go:173, `>=` loop) disagrees with its own encoder
(ec_encoder.go:214, `>` loop) about the row layout; we invert the encoder
faithfully (strict `>`), so such volumes round-trip correctly here.
"""

from __future__ import annotations

import os

from .. import types as t
from ..needle import actual_size
from ..super_block import SuperBlock
from .constants import DATA_SHARDS, LARGE_BLOCK_SIZE, SMALL_BLOCK_SIZE, to_ext


def iterate_ecx_file(base_name: str):
    """Yield (key, actual_offset, size) entries from the sorted .ecx."""
    with open(base_name + ".ecx", "rb") as f:
        while True:
            buf = f.read(t.NEEDLE_MAP_ENTRY_SIZE)
            if len(buf) != t.NEEDLE_MAP_ENTRY_SIZE:
                return
            yield t.unpack_index_entry(buf)


def iterate_ecj_file(base_name: str):
    """Yield deleted needle ids from the .ecj journal (8-byte entries)."""
    path = base_name + ".ecj"
    if not os.path.exists(path):
        return
    with open(path, "rb") as f:
        while True:
            buf = f.read(t.NEEDLE_ID_SIZE)
            if len(buf) != t.NEEDLE_ID_SIZE:
                return
            yield t.bytes_to_needle_id(buf)


def write_idx_file_from_ec_index(base_name: str) -> None:
    """.idx = copy of .ecx + a tombstone entry per .ecj key (ec_decoder.go:18-43)."""
    with open(base_name + ".idx", "wb") as idx_f:
        with open(base_name + ".ecx", "rb") as ecx_f:
            while True:
                chunk = ecx_f.read(1 << 20)
                if not chunk:
                    break
                idx_f.write(chunk)
        for key in iterate_ecj_file(base_name):
            idx_f.write(t.pack_index_entry(key, 0, t.TOMBSTONE_FILE_SIZE))


def read_ec_volume_version(base_name: str) -> int:
    """Volume version from the superblock at the start of .ec00."""
    with open(base_name + to_ext(0), "rb") as f:
        sb = SuperBlock.from_bytes(f.read(64))
    return sb.version


def find_dat_file_size(data_base_name: str, index_base_name: str) -> int:
    """Max (offset + record size) over live .ecx entries (ec_decoder.go:48-70)."""
    version = read_ec_volume_version(data_base_name)
    dat_size = 0
    for _key, offset, size in iterate_ecx_file(index_base_name):
        if t.size_is_deleted(size):
            continue
        stop = offset + actual_size(size, version)
        dat_size = max(dat_size, stop)
    return dat_size


# readinto block for the shard->dat stream: 8MB quarters the syscall
# count vs the old 1MB read()+write() pairs and the reused buffer drops
# the per-chunk bytes allocation entirely
_COPY_BLOCK = 8 << 20


def write_dat_file(base_name: str, dat_file_size: int) -> None:
    """Assemble .dat from .ec00–.ec09 by walking the stripe layout."""
    ins = [open(base_name + to_ext(i), "rb") for i in range(DATA_SHARDS)]
    buf = memoryview(bytearray(min(max(dat_file_size, 1), _COPY_BLOCK)))
    try:
        with open(base_name + ".dat", "wb") as out:
            remaining = dat_file_size
            # mirror the encoder's strict-greater large-row loop
            while remaining > DATA_SHARDS * LARGE_BLOCK_SIZE:
                for f in ins:
                    _copy(f, out, LARGE_BLOCK_SIZE, buf)
                remaining -= DATA_SHARDS * LARGE_BLOCK_SIZE
            while remaining > 0:
                for f in ins:
                    to_read = min(remaining, SMALL_BLOCK_SIZE)
                    if to_read <= 0:
                        break
                    _copy(f, out, to_read, buf)
                    remaining -= to_read
    finally:
        for f in ins:
            f.close()


def _copy(src, dst, n: int, buf: memoryview | None = None) -> None:
    """Stream n bytes src->dst through a reused buffer (readinto: no
    per-chunk bytes object, bigger blocks, fewer syscalls)."""
    if buf is None:
        buf = memoryview(bytearray(min(n, _COPY_BLOCK)))
    while n > 0:
        want = min(n, len(buf))
        got = src.readinto(buf[:want])
        if not got:
            raise IOError("unexpected EOF in shard file")
        dst.write(buf[:got])
        n -= got
