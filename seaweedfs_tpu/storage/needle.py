"""Needle records: the append-only blob format inside `.dat` volume files.

Byte-compatible with the reference (weed/storage/needle/needle_read_write.go):

  header (16B): cookie(4) id(8) size(4), all big-endian
  v2/v3 body (present when size > 0):
      data_size(4) data flags(1)
      [name_size(1) name]    if FLAG_HAS_NAME
      [mime_size(1) mime]    if FLAG_HAS_MIME
      [last_modified(5)]     if FLAG_HAS_LAST_MODIFIED  (low 5 bytes of be64)
      [ttl(2)]               if FLAG_HAS_TTL
      [pairs_size(2) pairs]  if FLAG_HAS_PAIRS
  tail: checksum(4, masked crc32c of data) + [append_at_ns(8) in v3]
        + padding to the next 8-byte boundary (padding length is 1..8: a
        record whose tail lands exactly on a boundary still gets 8 bytes).

Deviation (documented): the reference fills padding with stale bytes from a
reused scratch buffer (needle_read_write.go:49,116-120); we write zeros.
Record lengths and all parsed fields are identical, and the reader accepts
either.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field

from ..ops import crc32c
from . import types as t
from .super_block import VERSION1, VERSION2, VERSION3
from .ttl import TTL

FLAG_IS_COMPRESSED = 0x01
FLAG_HAS_NAME = 0x02
FLAG_HAS_MIME = 0x04
FLAG_HAS_LAST_MODIFIED = 0x08
FLAG_HAS_TTL = 0x10
FLAG_HAS_PAIRS = 0x20
FLAG_IS_CHUNK_MANIFEST = 0x80

LAST_MODIFIED_BYTES = 5
TTL_BYTES = 2


class CorruptNeedleError(ValueError):
    """CRC mismatch parsing a needle: the bytes on disk are rotten.

    A ValueError subclass so every existing `except ValueError` parse
    guard keeps working, while the read path and the scrubber can tell
    silent corruption apart from a garbled/short read and route it into
    quarantine + repair instead of a dead-end 500."""


def padding_length(needle_size: int, version: int) -> int:
    """1..8 bytes; the reference adds a full 8 when already aligned."""
    if version == VERSION3:
        used = t.NEEDLE_HEADER_SIZE + needle_size + t.NEEDLE_CHECKSUM_SIZE + t.TIMESTAMP_SIZE
    else:
        used = t.NEEDLE_HEADER_SIZE + needle_size + t.NEEDLE_CHECKSUM_SIZE
    return t.NEEDLE_PADDING_SIZE - (used % t.NEEDLE_PADDING_SIZE)


def body_length(needle_size: int, version: int) -> int:
    pad = padding_length(needle_size, version)
    if version == VERSION3:
        return needle_size + t.NEEDLE_CHECKSUM_SIZE + t.TIMESTAMP_SIZE + pad
    return needle_size + t.NEEDLE_CHECKSUM_SIZE + pad


def actual_size(needle_size: int, version: int) -> int:
    return t.NEEDLE_HEADER_SIZE + body_length(needle_size, version)


@dataclass
class Needle:
    cookie: int = 0
    id: int = 0
    size: int = 0  # the stored Size field (body payload length)
    data: bytes = b""
    flags: int = 0
    name: bytes = b""
    mime: bytes = b""
    last_modified: int = 0
    ttl: TTL | None = None
    pairs: bytes = b""
    checksum: int = 0  # unmasked crc32c of data
    append_at_ns: int = 0

    # -- flag helpers -----------------------------------------------------

    def has(self, flag: int) -> bool:
        return bool(self.flags & flag)

    def set(self, flag: int) -> None:
        self.flags |= flag

    @property
    def is_chunk_manifest(self) -> bool:
        return self.has(FLAG_IS_CHUNK_MANIFEST)

    # -- serialization ----------------------------------------------------

    def _computed_size(self) -> int:
        if not self.data:
            return 0
        size = 4 + len(self.data) + 1
        if self.has(FLAG_HAS_NAME):
            size += 1 + min(len(self.name), 255)
        if self.has(FLAG_HAS_MIME):
            size += 1 + len(self.mime)
        if self.has(FLAG_HAS_LAST_MODIFIED):
            size += LAST_MODIFIED_BYTES
        if self.has(FLAG_HAS_TTL):
            size += TTL_BYTES
        if self.has(FLAG_HAS_PAIRS):
            size += 2 + len(self.pairs)
        return size

    def to_bytes(self, version: int = VERSION3) -> bytes:
        """Serialize; also updates self.size/self.checksum."""
        self.checksum = crc32c.checksum(self.data)
        if version == VERSION1:
            self.size = len(self.data)
            out = bytearray()
            out += struct.pack(">I", self.cookie)
            out += t.needle_id_to_bytes(self.id)
            out += t.size_to_bytes(self.size)
            out += self.data
            out += struct.pack(">I", crc32c.mask(self.checksum))
            out += b"\0" * padding_length(self.size, version)
            return bytes(out)
        if version not in (VERSION2, VERSION3):
            raise ValueError(f"unsupported needle version {version}")

        self.size = self._computed_size()
        out = bytearray()
        out += struct.pack(">I", self.cookie)
        out += t.needle_id_to_bytes(self.id)
        out += t.size_to_bytes(self.size)
        if self.data:
            out += struct.pack(">I", len(self.data))
            out += self.data
            out += bytes([self.flags])
            if self.has(FLAG_HAS_NAME):
                name = self.name[:255]
                out += bytes([len(name)])
                out += name
            if self.has(FLAG_HAS_MIME):
                out += bytes([len(self.mime)])
                out += self.mime
            if self.has(FLAG_HAS_LAST_MODIFIED):
                out += struct.pack(">Q", self.last_modified)[8 - LAST_MODIFIED_BYTES :]
            if self.has(FLAG_HAS_TTL):
                out += (self.ttl or TTL()).to_bytes()
            if self.has(FLAG_HAS_PAIRS):
                out += struct.pack(">H", len(self.pairs))
                out += self.pairs
        out += struct.pack(">I", crc32c.mask(self.checksum))
        if version == VERSION3:
            out += struct.pack(">Q", self.append_at_ns)
        out += b"\0" * padding_length(self.size, version)
        return bytes(out)

    # -- parsing ----------------------------------------------------------

    @classmethod
    def parse_header(cls, b: bytes) -> "Needle":
        n = cls()
        n.cookie = struct.unpack(">I", b[0:4])[0]
        n.id = t.bytes_to_needle_id(b[4:12])
        n.size = t.bytes_to_size(b[12:16])
        return n

    def parse_body_v2(self, b: bytes) -> None:
        """Parse the size-long body region (v2/v3 field layout)."""
        idx, end = 0, len(b)
        if idx < end:
            data_size = struct.unpack(">I", b[idx : idx + 4])[0]
            idx += 4
            if idx + data_size > end:
                raise ValueError("needle data out of range")
            self.data = b[idx : idx + data_size]
            idx += data_size
            self.flags = b[idx]
            idx += 1
        if idx < end and self.has(FLAG_HAS_NAME):
            ln = b[idx]
            idx += 1
            self.name = b[idx : idx + ln]
            idx += ln
        if idx < end and self.has(FLAG_HAS_MIME):
            ln = b[idx]
            idx += 1
            self.mime = b[idx : idx + ln]
            idx += ln
        if idx < end and self.has(FLAG_HAS_LAST_MODIFIED):
            self.last_modified = int.from_bytes(b[idx : idx + LAST_MODIFIED_BYTES], "big")
            idx += LAST_MODIFIED_BYTES
        if idx < end and self.has(FLAG_HAS_TTL):
            self.ttl = TTL.from_bytes(b[idx : idx + TTL_BYTES])
            idx += TTL_BYTES
        if idx < end and self.has(FLAG_HAS_PAIRS):
            ln = struct.unpack(">H", b[idx : idx + 2])[0]
            idx += 2
            self.pairs = b[idx : idx + ln]
            idx += ln

    @classmethod
    def from_bytes(cls, blob: bytes, version: int = VERSION3, verify: bool = True) -> "Needle":
        """Parse a full record (header + body) as laid out on disk."""
        n = cls.parse_header(blob)
        size = n.size
        if size < 0:
            raise ValueError("cannot parse tombstoned record")
        if version == VERSION1:
            n.data = blob[t.NEEDLE_HEADER_SIZE : t.NEEDLE_HEADER_SIZE + size]
        else:
            n.parse_body_v2(blob[t.NEEDLE_HEADER_SIZE : t.NEEDLE_HEADER_SIZE + size])
        if size > 0:
            stored = struct.unpack(
                ">I",
                blob[t.NEEDLE_HEADER_SIZE + size : t.NEEDLE_HEADER_SIZE + size + 4],
            )[0]
            n.checksum = crc32c.checksum(n.data)
            if verify and stored != crc32c.mask(n.checksum):
                raise CorruptNeedleError("CRC error: data on disk corrupted")
        if version == VERSION3:
            ts_off = t.NEEDLE_HEADER_SIZE + size + t.NEEDLE_CHECKSUM_SIZE
            n.append_at_ns = struct.unpack(">Q", blob[ts_off : ts_off + 8])[0]
        return n

    def disk_size(self, version: int = VERSION3) -> int:
        return actual_size(self.size, version)
