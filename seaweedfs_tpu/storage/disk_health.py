"""Disk-fault survival plane: typed I/O failure classification + the
per-data-directory health state machine.

The dominant real-world disk failure is not a clean node death but a
device that fills up (ENOSPC) or starts throwing EIO while the process
stays alive (arXiv:1709.05365 measures device-level degradation
dominating online-EC SSD arrays).  This module is the one place that
knows how to tell those apart:

  * **Typed errors** — `DiskFullError` (out of space: the volume flips
    read-only-full and the client re-assigns on a 409) vs
    `DiskFailingError` (device errors: the disk becomes an evacuation
    candidate before it dies, arXiv:1309.0186's motivation).
  * **State machine** — per `DiskLocation` directory:
    ``healthy -> low_space -> full`` driven by statvfs watermark polling
    (`SEAWEEDFS_TPU_MIN_FREE_MB` / `SEAWEEDFS_TPU_MIN_FREE_PERCENT`),
    plus ``failing`` once a decayed EIO counter crosses
    `SEAWEEDFS_TPU_EIO_THRESHOLD`.  `failing` is sticky (a device that
    threw K I/O errors is not trusted again just because one write
    succeeded); `full` clears as soon as the watermark does.
  * **`disk.write` faultpoint family** — error / enospc / partial /
    short, fired at the backend layer (`backend.DiskFile.write_at`) so
    chaos tests and the crash-torture harness can produce exactly the
    torn-tail states a real ENOSPC/EIO mid-blob write leaves behind.
  * **statvfs dedupe** — `disk_stats()` is the one statvfs wrapper
    (grpc VolumeServerStatus and the heartbeat both use it).

Gauges: seaweedfs_disk_{free,total}_bytes{dir} + seaweedfs_disk_state{dir}
(0=healthy 1=low_space 2=full 3=failing), refreshed by every poll().
"""

from __future__ import annotations

import errno
import os
import threading

from ..stats.metrics import (
    DISK_FREE_GAUGE,
    DISK_STATE_GAUGE,
    DISK_TOTAL_GAUGE,
    DISK_WRITE_ERROR,
)
from ..util import faultpoint, glog

MIN_FREE_MB_ENV = "SEAWEEDFS_TPU_MIN_FREE_MB"
MIN_FREE_PERCENT_ENV = "SEAWEEDFS_TPU_MIN_FREE_PERCENT"
EIO_THRESHOLD_ENV = "SEAWEEDFS_TPU_EIO_THRESHOLD"

# low-space warns this many times earlier than full: the lifecycle plane
# gets a window to vacuum/tier before writers hit the hard watermark
LOW_SPACE_FACTOR = 4.0

STATES = ("healthy", "low_space", "full", "failing")
STATE_CODE = {s: i for i, s in enumerate(STATES)}

# the `disk.write` faultpoint family, fired by DiskFile.write_at.
# ctx is the file path, so `match=` scopes a fault to one data dir
# (one volume server among several in a test process).
FP_WRITE_ERROR = faultpoint.register("disk.write.error")
FP_WRITE_ENOSPC = faultpoint.register("disk.write.enospc")
FP_WRITE_PARTIAL = faultpoint.register("disk.write.partial")
FP_WRITE_SHORT = faultpoint.register("disk.write.short")

_ENOSPC_ERRNOS = (errno.ENOSPC, errno.EDQUOT)


class DiskFullError(OSError):
    """Out of space (ENOSPC/EDQUOT or watermark): the volume is
    read-only-full; clients should re-assign, not retry here."""


class DiskFailingError(OSError):
    """Device-level write failure (EIO class): the disk may be dying —
    repeated occurrences make the location an evacuation candidate."""


def is_enospc(exc: BaseException) -> bool:
    return (isinstance(exc, OSError)
            and getattr(exc, "errno", None) in _ENOSPC_ERRNOS)


def classify_write_error(exc: OSError, path: str = "") -> OSError:
    """-> the typed error to raise for a storage-write OSError (counted
    in seaweedfs_disk_write_errors_total)."""
    if isinstance(exc, (DiskFullError, DiskFailingError)):
        return exc
    if is_enospc(exc):
        DISK_WRITE_ERROR.labels("enospc").inc()
        return DiskFullError(
            errno.ENOSPC, f"disk full writing {path or '?'}: {exc}")
    kind = "eio" if getattr(exc, "errno", None) == errno.EIO else "other"
    DISK_WRITE_ERROR.labels(kind).inc()
    e = DiskFailingError(
        getattr(exc, "errno", None) or errno.EIO,
        f"disk write failed on {path or '?'}: {exc}")
    return e


def disk_stats(directory: str):
    """-> (total_bytes, free_bytes) of the filesystem holding
    `directory` — the ONE statvfs wrapper (heartbeat, grpc status and
    the watermark poll all go through here)."""
    st = os.statvfs(directory)
    return st.f_blocks * st.f_frsize, st.f_bavail * st.f_frsize


def inject_write_fault(path: str, f, offset: int, data: bytes) -> bytes:
    """Fire the `disk.write` faultpoint family for a write of `data` at
    `offset` of file object `f` (path is the match context).

    - ``disk.write.error``   -> OSError(EIO) before any byte lands
    - ``disk.write.enospc``  -> writes a TORN half, then OSError(ENOSPC)
      (the mid-blob short write a filling disk actually produces)
    - ``disk.write.partial`` -> writes a torn half, then OSError(EIO)
    - ``disk.write.short``   -> returns a truncated buffer to write
      silently (arm with mode=partial; models a lying device)

    Returns the (possibly truncated) data the caller should write."""
    if not faultpoint.FAULTS._armed:  # same fast path as inject()
        return data
    try:
        faultpoint.inject(FP_WRITE_ERROR, ctx=path)
    except faultpoint.FaultInjected as e:
        raise OSError(errno.EIO, f"injected EIO on {path}") from e
    for point, err in ((FP_WRITE_ENOSPC, errno.ENOSPC),
                       (FP_WRITE_PARTIAL, errno.EIO)):
        try:
            faultpoint.inject(point, ctx=path)
        except faultpoint.FaultInjected as e:
            torn = data[: len(data) // 2]
            if torn:
                f.seek(offset)
                f.write(torn)
                f.flush()
            raise OSError(err, os.strerror(err) + f" (injected, {path})"
                          ) from e
    out = faultpoint.inject(FP_WRITE_SHORT, ctx=path, data=data)
    return data if out is None else out


class DiskHealth:
    """Health state for one data directory.

    Thread-safe; poll() is called from the heartbeat cadence (and after
    any classified write error), write errors are recorded from the
    volume write path."""

    def __init__(self, directory: str, min_free_mb: float | None = None,
                 min_free_percent: float | None = None,
                 eio_threshold: float | None = None,
                 statvfs=None):
        self.directory = directory
        if min_free_mb is None:
            min_free_mb = float(os.environ.get(MIN_FREE_MB_ENV, "64"))
        if min_free_percent is None:
            min_free_percent = float(
                os.environ.get(MIN_FREE_PERCENT_ENV, "1"))
        if eio_threshold is None:
            eio_threshold = float(os.environ.get(EIO_THRESHOLD_ENV, "3"))
        self.min_free_bytes = int(min_free_mb * (1 << 20))
        self.min_free_percent = min_free_percent
        self.eio_threshold = eio_threshold
        self._statvfs = statvfs or disk_stats
        self._lock = threading.Lock()
        self._eio_score = 0.0
        self._saw_enospc = False
        self._state = "healthy"
        self.free_bytes = 0
        self.total_bytes = 0

    # -- watermarks -------------------------------------------------------

    def _floor(self, total: int) -> int:
        return max(self.min_free_bytes,
                   int(total * self.min_free_percent / 100.0))

    def poll(self) -> str:
        """Refresh statvfs + gauges; -> the current state."""
        try:
            total, free = self._statvfs(self.directory)
        except OSError as e:
            # the filesystem itself errors: that IS a failing disk
            glog.warning("disk health: statvfs %s failed: %s",
                         self.directory, e)
            with self._lock:
                self._eio_score = max(self._eio_score, self.eio_threshold)
            return self._set_state()
        with self._lock:
            self.total_bytes = total
            self.free_bytes = free
            if self._saw_enospc and free > self._floor(total):
                # space came back (vacuum/ttl/operator): trust statvfs
                self._saw_enospc = False
        state = self._set_state()
        DISK_FREE_GAUGE.labels(self.directory).set(free)
        DISK_TOTAL_GAUGE.labels(self.directory).set(total)
        return state

    def _set_state(self) -> str:
        with self._lock:
            floor = self._floor(self.total_bytes)
            if self._eio_score >= self.eio_threshold:
                state = "failing"  # sticky: cleared only by mark_repaired
            elif self._saw_enospc or (
                    self.total_bytes and self.free_bytes <= floor):
                state = "full"
            elif (self.total_bytes
                    and self.free_bytes <= floor * LOW_SPACE_FACTOR):
                state = "low_space"
            else:
                state = "healthy"
            if state != self._state:
                glog.warning(
                    "disk %s: %s -> %s (free=%dMB floor=%dMB eio=%.1f)",
                    self.directory, self._state, state,
                    self.free_bytes >> 20, floor >> 20, self._eio_score)
            self._state = state
        DISK_STATE_GAUGE.labels(self.directory).set(STATE_CODE[state])
        return state

    # -- write-error feedback --------------------------------------------

    def record_write_error(self, exc: BaseException) -> None:
        """Feed a classified write failure into the state machine."""
        with self._lock:
            if is_enospc(exc) or isinstance(exc, DiskFullError):
                self._saw_enospc = True
            else:
                # decayed counter, not consecutive: a disk alternating
                # ok/EIO still crosses the threshold
                self._eio_score += 1.0
        self._set_state()

    def record_write_ok(self) -> None:
        with self._lock:
            if self._eio_score and self._eio_score < self.eio_threshold:
                self._eio_score = max(0.0, self._eio_score - 0.05)

    def mark_repaired(self) -> None:
        """Operator reset after a disk was replaced/repaired."""
        with self._lock:
            self._eio_score = 0.0
            self._saw_enospc = False
        self.poll()

    # -- views ------------------------------------------------------------

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    @property
    def writable(self) -> bool:
        return self.state not in ("full", "failing")

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "dir": self.directory,
                "state": self._state,
                "free_bytes": self.free_bytes,
                "total_bytes": self.total_bytes,
                "eio_score": round(self._eio_score, 2),
            }
