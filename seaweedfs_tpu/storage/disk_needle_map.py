"""Disk-backed needle map for RAM-constrained volume servers.

Reference: weed/storage/needle_map.go:13-19 — the leveldb /
sorted-file NeedleMapKinds that keep the id->(offset,size) index OUT of
process memory.  This design is an own construction with the same
property: steady-state resident memory is a bounded overflow dict, not
20 bytes x needle count.

  * the base tier is a SORTED index file (`.sdx`, same record layout as
    `.idx`/`.ecx`) searched by on-disk binary search (the `.ecx` lookup
    discipline, ec_volume.go:225-250);
  * mutations land in a bounded in-RAM overflow (dict + tombstone set);
  * when the overflow exceeds `overflow_limit`, a STREAMING merge writes
    a new `.sdx.tmp` (sequential read of the old base against the sorted
    overflow) and atomically replaces the base — peak memory during the
    merge is the overflow, never the whole index.

Loading from a `.idx` log sorts once via the vectorised parser (transient
cost); thereafter the volume serves with O(overflow) resident memory.
"""

from __future__ import annotations

import os
from typing import Callable, Iterator

from . import idx as idx_mod
from . import types as t
from .needle_map import NeedleValue


class DiskNeedleMap:
    """NeedleMap-compatible API; base tier on disk."""

    def __init__(self, sdx_path: str, overflow_limit: int = 10_000):
        self.sdx_path = sdx_path
        self.overflow_limit = overflow_limit
        self._overflow: dict[int, tuple[int, int]] = {}
        self._deleted: set[int] = set()
        self._f = None
        self._base_count = 0
        self.file_count = 0
        self.deleted_count = 0
        self.deleted_bytes = 0
        self.maximum_key = 0
        self._live = 0
        self._content = 0
        if not os.path.exists(sdx_path):
            open(sdx_path, "wb").close()
        self._open_base()

    def _open_base(self) -> None:
        if self._f:
            self._f.close()
        self._f = open(self.sdx_path, "rb")
        self._base_count = os.path.getsize(self.sdx_path) \
            // t.NEEDLE_MAP_ENTRY_SIZE

    # -- on-disk binary search (ec_volume.go:225-250 discipline) ----------

    def _base_read(self, i: int) -> tuple[int, int, int]:
        # positioned read: concurrent lookups share this handle
        esz = t.NEEDLE_MAP_ENTRY_SIZE
        return t.unpack_index_entry(os.pread(self._f.fileno(), esz, i * esz))

    def _base_get(self, key: int) -> tuple[int, int] | None:
        lo, hi = 0, self._base_count
        while lo < hi:
            mid = (lo + hi) // 2
            k, off, size = self._base_read(mid)
            if k == key:
                return (off, size)
            if k < key:
                lo = mid + 1
            else:
                hi = mid
        return None

    # -- mutation ----------------------------------------------------------

    def put(self, key: int, offset: int, size: int) -> None:
        old = self.get(key)
        if old is not None:
            self.deleted_count += 1
            self.deleted_bytes += max(old.size, 0)
            self._live -= 1
            self._content -= max(old.size, 0)
        self._overflow[key] = (offset, size)
        self._deleted.discard(key)
        self.file_count += 1
        self.maximum_key = max(self.maximum_key, key)
        self._live += 1
        self._content += max(size, 0)
        self._maybe_merge()

    def delete(self, key: int) -> int:
        nv = self.get(key)
        if nv is None:
            return 0
        self._overflow.pop(key, None)
        self._deleted.add(key)
        self.deleted_count += 1
        self.deleted_bytes += max(nv.size, 0)
        self._live -= 1
        self._content -= max(nv.size, 0)
        self._maybe_merge()
        return max(nv.size, 0)

    def get(self, key: int) -> NeedleValue | None:
        if key in self._deleted:
            return None
        hit = self._overflow.get(key)
        if hit is None:
            hit = self._base_get(key)
        if hit is None:
            return None
        return NeedleValue(key, hit[0], hit[1])

    def __contains__(self, key: int) -> bool:
        return self.get(key) is not None

    def __len__(self) -> int:
        return self._live

    @property
    def content_size(self) -> int:
        return self._content

    # -- streaming merge ----------------------------------------------------

    def _maybe_merge(self) -> None:
        if len(self._overflow) + len(self._deleted) > self.overflow_limit:
            self._merge()

    def _merge(self) -> None:
        tmp = self.sdx_path + ".tmp"
        with open(tmp, "wb") as out:
            for nv in self.items_ascending():
                out.write(t.pack_index_entry(nv.key, nv.offset, nv.size))
            out.flush()
            os.fsync(out.fileno())
        self._f.close()
        self._f = None
        os.replace(tmp, self.sdx_path)
        self._overflow.clear()
        self._deleted.clear()
        self._open_base()

    # -- iteration (merge of sorted base + sorted overflow) -----------------

    def items_ascending(self) -> Iterator[NeedleValue]:
        pending = sorted(self._overflow.items())
        pi = 0
        for i in range(self._base_count):
            k, off, size = self._base_read(i)
            while pi < len(pending) and pending[pi][0] < k:
                ok, (ooff, osize) = pending[pi]
                yield NeedleValue(ok, ooff, osize)
                pi += 1
            if pi < len(pending) and pending[pi][0] == k:
                ok, (ooff, osize) = pending[pi]
                yield NeedleValue(ok, ooff, osize)
                pi += 1
                continue
            if k in self._deleted:
                continue
            yield NeedleValue(k, off, size)
        while pi < len(pending):
            ok, (ooff, osize) = pending[pi]
            yield NeedleValue(ok, ooff, osize)
            pi += 1

    def ascending_visit(self, fn: Callable[[NeedleValue], None]) -> None:
        for nv in self.items_ascending():
            fn(nv)

    def sorted_keys(self) -> list[int]:
        return [nv.key for nv in self.items_ascending()]

    def next_key_after(self, key: int) -> int | None:
        for nv in self.items_ascending():
            if nv.key > key:
                return nv.key
        return None

    def write_sorted_index(self, path: str | os.PathLike) -> None:
        with open(path, "wb") as out:
            for nv in self.items_ascending():
                out.write(t.pack_index_entry(nv.key, nv.offset, nv.size))

    def close(self) -> None:
        if self._f:
            self._f.close()
            self._f = None

    # -- construction --------------------------------------------------------

    @classmethod
    def load_from_idx(cls, idx_path: str | os.PathLike,
                      sdx_path: str | None = None,
                      overflow_limit: int = 10_000) -> "DiskNeedleMap":
        """Replay the append-ordered .idx into a fresh sorted base.

        The sort itself is the vectorised in-memory pass (transient);
        serving memory afterwards is O(overflow_limit)."""
        idx_path = str(idx_path)
        if sdx_path is None:
            sdx_path = idx_path[: -len(".idx")] + ".sdx" \
                if idx_path.endswith(".idx") else idx_path + ".sdx"
        from .needle_map import NeedleMap

        mem = NeedleMap.load_from_idx(idx_path)
        mem.write_sorted_index(sdx_path)
        m = cls(sdx_path, overflow_limit=overflow_limit)
        m.file_count = mem.file_count
        m.deleted_count = mem.deleted_count
        m.deleted_bytes = mem.deleted_bytes
        m.maximum_key = mem.maximum_key
        m._live = len(mem)
        m._content = mem.content_size
        return m
