"""Volume superblock: the 8-byte `.dat` header.

Layout (reference: weed/storage/super_block/super_block.go:16-23):
  byte 0   version (1..3)
  byte 1   replica placement byte
  byte 2-3 TTL
  byte 4-5 compaction revision (big-endian)
  byte 6-7 extra size (protobuf blob follows when nonzero)
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field

from .replica_placement import ReplicaPlacement
from .ttl import TTL

SUPER_BLOCK_SIZE = 8

VERSION1 = 1
VERSION2 = 2
VERSION3 = 3
CURRENT_VERSION = VERSION3


@dataclass
class SuperBlock:
    version: int = CURRENT_VERSION
    replica_placement: ReplicaPlacement = field(default_factory=ReplicaPlacement)
    ttl: TTL = field(default_factory=TTL)
    compaction_revision: int = 0
    extra: bytes = b""

    def block_size(self) -> int:
        if self.version in (VERSION2, VERSION3):
            return SUPER_BLOCK_SIZE + len(self.extra)
        return SUPER_BLOCK_SIZE

    def to_bytes(self) -> bytes:
        hdr = bytearray(SUPER_BLOCK_SIZE)
        hdr[0] = self.version
        hdr[1] = self.replica_placement.to_byte()
        hdr[2:4] = self.ttl.to_bytes()
        struct.pack_into(">H", hdr, 4, self.compaction_revision)
        if self.extra:
            if len(self.extra) > 256 * 256 - 2:
                raise ValueError("super block extra too large")
            struct.pack_into(">H", hdr, 6, len(self.extra))
            return bytes(hdr) + self.extra
        return bytes(hdr)

    @classmethod
    def from_bytes(cls, b: bytes) -> "SuperBlock":
        if len(b) < SUPER_BLOCK_SIZE:
            raise ValueError("super block truncated")
        version = b[0]
        rp = ReplicaPlacement.from_byte(b[1])
        ttl = TTL.from_bytes(b[2:4])
        rev = struct.unpack_from(">H", b, 4)[0]
        extra_size = struct.unpack_from(">H", b, 6)[0]
        extra = bytes(b[SUPER_BLOCK_SIZE : SUPER_BLOCK_SIZE + extra_size]) if extra_size else b""
        return cls(version, rp, ttl, rev, extra)
