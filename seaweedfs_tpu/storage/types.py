"""Scalar storage types and on-disk constants.

Byte-compatible with the reference formats (all integers big-endian):
  * needle id: uint64 (reference: weed/storage/types/needle_id_type.go)
  * offset: 4 bytes storing actual_offset/8 -> 32GB max volume
    (weed/storage/types/offset_4bytes.go:12-15); `set_offset_size(5)`
    switches the process to the 5-byte variant (offset_5bytes.go:
    4 big-endian lower bytes + 1 high byte appended, 17-byte index
    entries, 8TB volumes) — the runtime analogue of the reference's
    `5BytesOffset` build tag, so consumers must read these constants via
    module attribute access (`t.OFFSET_SIZE`), never `from ... import`.
  * size: int32 with tombstone -1 (weed/storage/types/needle_types.go:16-39)
  * .idx entry: 8+OFFSET_SIZE+4 bytes (NeedleMapEntrySize)
"""

from __future__ import annotations

import struct

NEEDLE_ID_SIZE = 8
OFFSET_SIZE = 4
SIZE_SIZE = 4
COOKIE_SIZE = 4
TIMESTAMP_SIZE = 8
NEEDLE_HEADER_SIZE = COOKIE_SIZE + NEEDLE_ID_SIZE + SIZE_SIZE  # 16
NEEDLE_MAP_ENTRY_SIZE = NEEDLE_ID_SIZE + OFFSET_SIZE + SIZE_SIZE  # 16
NEEDLE_PADDING_SIZE = 8
NEEDLE_CHECKSUM_SIZE = 4
TOMBSTONE_FILE_SIZE = -1
MAX_POSSIBLE_VOLUME_SIZE = 4 * 1024 * 1024 * 1024 * 8  # 32GB

_U64 = struct.Struct(">Q")
_U32 = struct.Struct(">I")
_U16 = struct.Struct(">H")


def set_offset_size(n: int) -> None:
    """Switch the process between 4-byte (32GB volumes) and 5-byte (8TB
    volumes) offsets.  Must run before any volume/index is opened; the
    two widths are NOT file-compatible (same constraint as rebuilding
    the reference with the 5BytesOffset tag)."""
    global OFFSET_SIZE, NEEDLE_MAP_ENTRY_SIZE, MAX_POSSIBLE_VOLUME_SIZE
    if n not in (4, 5):
        raise ValueError("offset size must be 4 or 5")
    OFFSET_SIZE = n
    NEEDLE_MAP_ENTRY_SIZE = NEEDLE_ID_SIZE + OFFSET_SIZE + SIZE_SIZE
    MAX_POSSIBLE_VOLUME_SIZE = (4 << 30) * 8 * (256 if n == 5 else 1)


def size_is_deleted(size: int) -> bool:
    return size < 0 or size == TOMBSTONE_FILE_SIZE


def size_is_valid(size: int) -> bool:
    return size > 0 and size != TOMBSTONE_FILE_SIZE


def offset_to_bytes(actual_offset: int) -> bytes:
    """Store actual byte offset / 8 in OFFSET_SIZE big-endian-ish bytes
    (5-byte layout: 4 BE lower bytes then the high byte, matching
    offset_5bytes.go OffsetToBytes)."""
    if actual_offset % NEEDLE_PADDING_SIZE:
        raise ValueError(f"offset {actual_offset} not 8-byte aligned")
    stored = actual_offset // NEEDLE_PADDING_SIZE
    if OFFSET_SIZE == 4:
        return _U32.pack(stored)
    return _U32.pack(stored & 0xFFFFFFFF) + bytes([(stored >> 32) & 0xFF])


def bytes_to_offset(b: bytes) -> int:
    """Return the *actual* byte offset (stored value * 8)."""
    stored = _U32.unpack(b[:4])[0]
    if OFFSET_SIZE == 5:
        stored |= b[4] << 32
    return stored * NEEDLE_PADDING_SIZE


def size_to_bytes(size: int) -> bytes:
    return _U32.pack(size & 0xFFFFFFFF)


def bytes_to_size(b: bytes) -> int:
    v = _U32.unpack(b[:4])[0]
    return v - (1 << 32) if v & 0x80000000 else v


def needle_id_to_bytes(nid: int) -> bytes:
    return _U64.pack(nid)


def bytes_to_needle_id(b: bytes) -> int:
    return _U64.unpack(b[:8])[0]


def pack_index_entry(key: int, actual_offset: int, size: int) -> bytes:
    return needle_id_to_bytes(key) + offset_to_bytes(actual_offset) + size_to_bytes(size)


def unpack_index_entry(b: bytes) -> tuple[int, int, int]:
    """-> (needle_id, actual_offset, size)"""
    return (
        bytes_to_needle_id(b[0:8]),
        bytes_to_offset(b[8 : 8 + OFFSET_SIZE]),
        bytes_to_size(b[8 + OFFSET_SIZE : 8 + OFFSET_SIZE + 4]),
    )
