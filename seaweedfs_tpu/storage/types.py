"""Scalar storage types and on-disk constants.

Byte-compatible with the reference formats (all integers big-endian):
  * needle id: uint64 (reference: weed/storage/types/needle_id_type.go)
  * offset: 4 bytes storing actual_offset/8 -> 32GB max volume
    (weed/storage/types/offset_4bytes.go:12-15)
  * size: int32 with tombstone -1 (weed/storage/types/needle_types.go:16-39)
  * .idx entry: 8+4+4 = 16 bytes (NeedleMapEntrySize)
"""

from __future__ import annotations

import struct

NEEDLE_ID_SIZE = 8
OFFSET_SIZE = 4
SIZE_SIZE = 4
COOKIE_SIZE = 4
TIMESTAMP_SIZE = 8
NEEDLE_HEADER_SIZE = COOKIE_SIZE + NEEDLE_ID_SIZE + SIZE_SIZE  # 16
NEEDLE_MAP_ENTRY_SIZE = NEEDLE_ID_SIZE + OFFSET_SIZE + SIZE_SIZE  # 16
NEEDLE_PADDING_SIZE = 8
NEEDLE_CHECKSUM_SIZE = 4
TOMBSTONE_FILE_SIZE = -1
MAX_POSSIBLE_VOLUME_SIZE = 4 * 1024 * 1024 * 1024 * 8  # 32GB

_U64 = struct.Struct(">Q")
_U32 = struct.Struct(">I")
_U16 = struct.Struct(">H")


def size_is_deleted(size: int) -> bool:
    return size < 0 or size == TOMBSTONE_FILE_SIZE


def size_is_valid(size: int) -> bool:
    return size > 0 and size != TOMBSTONE_FILE_SIZE


def offset_to_bytes(actual_offset: int) -> bytes:
    """Store actual byte offset / 8 in 4 big-endian bytes."""
    if actual_offset % NEEDLE_PADDING_SIZE:
        raise ValueError(f"offset {actual_offset} not 8-byte aligned")
    return _U32.pack(actual_offset // NEEDLE_PADDING_SIZE)


def bytes_to_offset(b: bytes) -> int:
    """Return the *actual* byte offset (stored value * 8)."""
    return _U32.unpack(b[:4])[0] * NEEDLE_PADDING_SIZE


def size_to_bytes(size: int) -> bytes:
    return _U32.pack(size & 0xFFFFFFFF)


def bytes_to_size(b: bytes) -> int:
    v = _U32.unpack(b[:4])[0]
    return v - (1 << 32) if v & 0x80000000 else v


def needle_id_to_bytes(nid: int) -> bytes:
    return _U64.pack(nid)


def bytes_to_needle_id(b: bytes) -> int:
    return _U64.unpack(b[:8])[0]


def pack_index_entry(key: int, actual_offset: int, size: int) -> bytes:
    return needle_id_to_bytes(key) + offset_to_bytes(actual_offset) + size_to_bytes(size)


def unpack_index_entry(b: bytes) -> tuple[int, int, int]:
    """-> (needle_id, actual_offset, size)"""
    return (
        bytes_to_needle_id(b[0:8]),
        bytes_to_offset(b[8:12]),
        bytes_to_size(b[12:16]),
    )
