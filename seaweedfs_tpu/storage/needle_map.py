"""In-memory needle maps: needle id -> (offset, size).

The reference's memory kind is a two-level compact map — sorted batched
arrays plus an overflow area, ~20 bytes/entry, rebuilt in 100k-entry
sections (weed/storage/needle_map/compact_map.go:28-50, with a 10M-entry
perf test).  The same shape here, vectorised: the base tier is three
parallel sorted numpy arrays (uint64 key, int64 offset, int32 size — 20
bytes/entry), recent mutations land in a small dict/set overflow, and the
tiers merge when the overflow reaches ``merge_threshold``.  Lookups check
the overflow then binary-search the base (np.searchsorted); iteration and
the `.ecx` writer force a merge and stream the arrays.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Callable, Iterator

import numpy as np

from . import idx as idx_mod
from . import types as t


@dataclass(frozen=True)
class NeedleValue:
    key: int
    offset: int  # actual byte offset
    size: int

    def to_index_bytes(self) -> bytes:
        return t.pack_index_entry(self.key, self.offset, self.size)


class NeedleMap:
    """Live-needle map with deleted-byte accounting, loadable from .idx."""

    def __init__(self, merge_threshold: int = 100_000) -> None:
        self._keys = np.empty(0, dtype=np.uint64)
        self._offsets = np.empty(0, dtype=np.int64)
        self._sizes = np.empty(0, dtype=np.int32)
        self._overflow: dict[int, tuple[int, int]] = {}
        self._overflow_deleted: set[int] = set()
        self._merge_threshold = merge_threshold
        self._live = 0
        self._content = 0
        self.file_count = 0
        self.deleted_count = 0
        self.deleted_bytes = 0
        self.maximum_key = 0

    # -- base-tier helpers -------------------------------------------------

    def _base_find(self, key: int) -> int:
        """Index of key in the sorted base arrays, or -1."""
        if len(self._keys) == 0:
            return -1
        i = int(np.searchsorted(self._keys, np.uint64(key)))
        if i < len(self._keys) and int(self._keys[i]) == key:
            return i
        return -1

    def _maybe_merge(self) -> None:
        if len(self._overflow) + len(self._overflow_deleted) >= self._merge_threshold:
            self._merge()

    def _merge(self) -> None:
        if not self._overflow and not self._overflow_deleted:
            return
        drop = self._overflow_deleted | set(self._overflow)
        keys, offsets, sizes = self._keys, self._offsets, self._sizes
        if len(keys) and drop:
            drop_arr = np.fromiter(drop, dtype=np.uint64, count=len(drop))
            pos = np.searchsorted(keys, drop_arr)
            pos = pos[pos < len(keys)]
            hit = pos[np.isin(keys[pos], drop_arr)]
            if len(hit):
                mask = np.ones(len(keys), dtype=bool)
                mask[hit] = False
                keys, offsets, sizes = keys[mask], offsets[mask], sizes[mask]
        if self._overflow:
            n = len(self._overflow)
            ins_k = np.fromiter(self._overflow.keys(), dtype=np.uint64, count=n)
            order = np.argsort(ins_k, kind="stable")
            ins_k = ins_k[order]
            vals = list(self._overflow.values())
            ins_o = np.asarray([vals[i][0] for i in order], dtype=np.int64)
            ins_s = np.asarray([vals[i][1] for i in order], dtype=np.int32)
            pos = np.searchsorted(keys, ins_k)
            keys = np.insert(keys, pos, ins_k)
            offsets = np.insert(offsets, pos, ins_o)
            sizes = np.insert(sizes, pos, ins_s)
        self._keys, self._offsets, self._sizes = keys, offsets, sizes
        self._overflow.clear()
        self._overflow_deleted.clear()

    # -- mutation ---------------------------------------------------------

    def put(self, key: int, offset: int, size: int) -> None:
        old = self.get(key)
        if old is not None:
            if old.size > 0:
                self.deleted_count += 1
                self.deleted_bytes += old.size
                self._content -= old.size
        else:
            self._live += 1
        self._overflow[key] = (offset, size)
        self._overflow_deleted.discard(key)
        self.file_count += 1
        if size > 0:
            self._content += size
        if key > self.maximum_key:
            self.maximum_key = key
        self._maybe_merge()

    def delete(self, key: int) -> int:
        old = self.get(key)
        if old is None:
            return 0
        self.deleted_count += 1
        freed = max(old.size, 0)
        self.deleted_bytes += freed
        self._content -= freed
        self._live -= 1
        self._overflow.pop(key, None)
        if self._base_find(key) >= 0:
            self._overflow_deleted.add(key)
            self._maybe_merge()
        return freed

    # -- lookup -----------------------------------------------------------

    def get(self, key: int) -> NeedleValue | None:
        v = self._overflow.get(key)
        if v is not None:
            return NeedleValue(key, v[0], v[1])
        if key in self._overflow_deleted:
            return None
        i = self._base_find(key)
        if i < 0:
            return None
        return NeedleValue(key, int(self._offsets[i]), int(self._sizes[i]))

    def __contains__(self, key: int) -> bool:
        return self.get(key) is not None

    def __len__(self) -> int:
        return self._live

    @property
    def content_size(self) -> int:
        return self._content

    # -- iteration --------------------------------------------------------

    def ascending_visit(self, fn: Callable[[NeedleValue], None]) -> None:
        for v in self.items_ascending():
            fn(v)

    def sorted_keys(self) -> list[int]:
        self._merge()
        return self._keys.tolist()

    def items_ascending(self) -> Iterator[NeedleValue]:
        self._merge()
        for i in range(len(self._keys)):
            yield NeedleValue(
                int(self._keys[i]), int(self._offsets[i]), int(self._sizes[i])
            )

    def next_key_after(self, key: int) -> int | None:
        self._merge()
        i = int(np.searchsorted(self._keys, np.uint64(key), side="right"))
        return int(self._keys[i]) if i < len(self._keys) else None

    # -- persistence ------------------------------------------------------

    @classmethod
    def load_from_idx(cls, path: str | os.PathLike) -> "NeedleMap":
        """Replay a .idx file: tombstones/zero offsets delete, else insert.

        Mirrors readNeedleMap in the reference ec_encoder.go:289-306.
        Pure-append files (no deletes, no overwrites — the common case) take
        a fully vectorised path; otherwise entries replay sequentially.
        """
        nm = cls()
        keys, offsets, sizes = idx_mod.parse_index_arrays(path)
        n = len(keys)
        if n == 0:
            return nm
        clean = (
            bool((offsets != 0).all())
            and bool((sizes > 0).all())
            and len(np.unique(keys)) == n
        )
        if clean:
            order = np.argsort(keys, kind="stable")
            nm._keys = keys[order].copy()
            nm._offsets = offsets[order].copy()
            nm._sizes = sizes[order].copy()
            nm._live = n
            nm.file_count = n
            nm._content = int(sizes.sum())
            nm.maximum_key = int(keys.max())
            return nm
        for i in range(n):
            key, offset, size = int(keys[i]), int(offsets[i]), int(sizes[i])
            if offset != 0 and not t.size_is_deleted(size):
                nm.put(key, offset, size)
            else:
                nm.delete(key)
        return nm

    def write_sorted_index(self, path: str | os.PathLike) -> None:
        """Write entries in ascending key order (the .ecx format) — a
        vectorised big-endian pack of the merged base arrays."""
        self._merge()
        n = len(self._keys)
        esz = t.NEEDLE_MAP_ENTRY_SIZE
        off_end = 8 + t.OFFSET_SIZE
        out = np.empty((n, esz), dtype=np.uint8)
        out[:, 0:8] = self._keys.astype(">u8")[:, None].view(np.uint8).reshape(n, 8)
        stored = self._offsets // t.NEEDLE_PADDING_SIZE
        out[:, 8:12] = (stored & 0xFFFFFFFF).astype(">u4")[:, None] \
            .view(np.uint8).reshape(n, 4)
        if t.OFFSET_SIZE == 5:
            out[:, 12] = (stored >> 32).astype(np.uint8)
        out[:, off_end : off_end + 4] = (
            self._sizes.astype(np.uint32).astype(">u4")[:, None]
            .view(np.uint8).reshape(n, 4)
        )
        with open(path, "wb") as f:
            f.write(out.tobytes())
