"""In-memory needle maps: needle id -> (offset, size).

The reference offers several kinds (compact two-level map, leveldb, sorted
file — weed/storage/needle_map.go:13-19).  Here the in-memory kind is a dict
plus sorted-key cache — idiomatic Python with the same observable behavior
(live needles only; deletes drop entries; ascending visit for .ecx
generation); the compact-section memory layout is a Go-ism we don't copy.
"""

from __future__ import annotations

import bisect
import os
from dataclasses import dataclass
from typing import Callable, Iterator

from . import idx as idx_mod
from . import types as t


@dataclass(frozen=True)
class NeedleValue:
    key: int
    offset: int  # actual byte offset
    size: int

    def to_index_bytes(self) -> bytes:
        return t.pack_index_entry(self.key, self.offset, self.size)


class NeedleMap:
    """Live-needle map with deleted-byte accounting, loadable from .idx."""

    def __init__(self) -> None:
        self._m: dict[int, NeedleValue] = {}
        self._sorted_keys: list[int] | None = None
        self.file_count = 0
        self.deleted_count = 0
        self.deleted_bytes = 0
        self.maximum_key = 0

    # -- mutation ---------------------------------------------------------

    def put(self, key: int, offset: int, size: int) -> None:
        old = self._m.get(key)
        if old is not None and old.size > 0:
            self.deleted_count += 1
            self.deleted_bytes += old.size
        self._m[key] = NeedleValue(key, offset, size)
        self.file_count += 1
        self.maximum_key = max(self.maximum_key, key)
        self._sorted_keys = None

    def delete(self, key: int) -> int:
        old = self._m.pop(key, None)
        if old is None:
            return 0
        self.deleted_count += 1
        self.deleted_bytes += max(old.size, 0)
        self._sorted_keys = None
        return max(old.size, 0)

    # -- lookup -----------------------------------------------------------

    def get(self, key: int) -> NeedleValue | None:
        return self._m.get(key)

    def __contains__(self, key: int) -> bool:
        return key in self._m

    def __len__(self) -> int:
        return len(self._m)

    @property
    def content_size(self) -> int:
        return sum(v.size for v in self._m.values() if v.size > 0)

    # -- iteration --------------------------------------------------------

    def ascending_visit(self, fn: Callable[[NeedleValue], None]) -> None:
        for key in self.sorted_keys():
            fn(self._m[key])

    def sorted_keys(self) -> list[int]:
        if self._sorted_keys is None:
            self._sorted_keys = sorted(self._m)
        return self._sorted_keys

    def items_ascending(self) -> Iterator[NeedleValue]:
        for k in self.sorted_keys():
            yield self._m[k]

    def next_key_after(self, key: int) -> int | None:
        ks = self.sorted_keys()
        i = bisect.bisect_right(ks, key)
        return ks[i] if i < len(ks) else None

    # -- persistence ------------------------------------------------------

    @classmethod
    def load_from_idx(cls, path: str | os.PathLike) -> "NeedleMap":
        """Replay a .idx file: tombstones/zero offsets delete, else insert.

        Mirrors readNeedleMap in the reference ec_encoder.go:289-306.
        """
        nm = cls()

        def visit(key: int, offset: int, size: int) -> None:
            if offset != 0 and not t.size_is_deleted(size):
                nm.put(key, offset, size)
            else:
                nm.delete(key)

        idx_mod.walk_index_file(path, visit)
        return nm

    def write_sorted_index(self, path: str | os.PathLike) -> None:
        """Write entries in ascending key order (the .ecx format)."""
        with open(path, "wb") as f:
            for v in self.items_ascending():
                f.write(v.to_index_bytes())
