"""`.vif` sidecar: protobuf VolumeInfo (version, tier files, replication).

Reference: weed/pb/volume_info.go — written as protobuf-JSON text in the
reference; we write binary protobuf with a JSON fallback reader for
interoperability with hand-edited files.
"""

from __future__ import annotations

import json
import os

from google.protobuf import json_format

from ..pb import volume_info_pb2


def save_volume_info(path: str, version: int, replication: str = "",
                     dat_file_size: int = 0,
                     remote_files: list[dict] | None = None) -> None:
    """``dat_file_size`` records the logical .dat size; EC volumes with no
    local shard use it to recover interval geometry (a tombstoned .ecx
    entry loses its size, so the index alone can under-bound the volume).

    ``remote_files`` records tier placement (volume_info.proto RemoteFile
    dicts: backend_type/backend_id/key/file_size/modified_time/extension);
    a volume whose .dat moved to a remote tier is reopened through it."""
    info = volume_info_pb2.VolumeInfo(
        version=version, replication=replication, dat_file_size=dat_file_size
    )
    for rf in remote_files or ():
        info.files.add(**rf)
    with open(path, "w") as f:
        f.write(json_format.MessageToJson(info))


def load_volume_info(path: str) -> volume_info_pb2.VolumeInfo | None:
    if not os.path.exists(path):
        return None
    with open(path, "rb") as f:
        raw = f.read()
    if not raw:
        return None
    try:
        return json_format.Parse(raw.decode("utf-8"), volume_info_pb2.VolumeInfo())
    except (json.JSONDecodeError, json_format.ParseError, UnicodeDecodeError):
        return volume_info_pb2.VolumeInfo.FromString(raw)
