"""`.vif` sidecar: protobuf VolumeInfo (version, tier files, replication).

Reference: weed/pb/volume_info.go — written as protobuf-JSON text in the
reference; we write binary protobuf with a JSON fallback reader for
interoperability with hand-edited files.
"""

from __future__ import annotations

import json
import os

from google.protobuf import json_format

from ..pb import volume_info_pb2


def save_volume_info(path: str, version: int, replication: str = "") -> None:
    info = volume_info_pb2.VolumeInfo(version=version, replication=replication)
    with open(path, "w") as f:
        f.write(json_format.MessageToJson(info))


def load_volume_info(path: str) -> volume_info_pb2.VolumeInfo | None:
    if not os.path.exists(path):
        return None
    with open(path, "rb") as f:
        raw = f.read()
    if not raw:
        return None
    try:
        return json_format.Parse(raw.decode("utf-8"), volume_info_pb2.VolumeInfo())
    except (json.JSONDecodeError, json_format.ParseError, UnicodeDecodeError):
        return volume_info_pb2.VolumeInfo.FromString(raw)
