"""File ids: "<volume_id>,<key_hex><cookie_hex8>" (reference: needle/file_id.go).

The key is minimal-length hex (no leading zeros); the cookie is always the
last 8 hex chars.  "3,01637037d6" -> vid 3, key 0x01, cookie 0x637037d6.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class FileId:
    volume_id: int
    key: int
    cookie: int

    def __str__(self) -> str:
        return f"{self.volume_id},{self.key:x}{self.cookie:08x}"

    @classmethod
    def parse(cls, fid: str) -> "FileId":
        fid = fid.strip()
        if "," not in fid:
            raise ValueError(f"bad file id {fid!r}")
        vid_str, key_hash = fid.split(",", 1)
        # tolerate a trailing "_<count>" chunk suffix and file extension
        if "." in key_hash:
            key_hash = key_hash.split(".", 1)[0]
        if "_" in key_hash:
            key_hash = key_hash.split("_", 1)[0]
        if len(key_hash) <= 8:
            raise ValueError(f"file id {fid!r} too short for key+cookie")
        return cls(
            volume_id=int(vid_str),
            key=int(key_hash[:-8], 16),
            cookie=int(key_hash[-8:], 16),
        )


def parse_volume_or_file_id(s: str) -> int:
    """Accept '3' or '3,01637037d6' and return the volume id."""
    return int(s.split(",", 1)[0])
