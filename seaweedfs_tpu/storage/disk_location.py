"""A data directory holding volumes and EC shards.

Reference: weed/storage/disk_location.go + disk_location_ec.go — scans for
`<collection>_<vid>.dat` / bare `<vid>.dat` volumes and `.ecNN`/`.ecx` shard
groups at startup.
"""

from __future__ import annotations

import os
import re
import threading

from .disk_health import DiskHealth
from .ec.volume import EcVolume
from .super_block import SuperBlock
from .volume import Volume

_EC_RE = re.compile(r"\.ec[0-9][0-9]$")


def parse_volume_file_name(name: str) -> tuple[str, int]:
    """'c_12' -> ('c', 12); '12' -> ('', 12)."""
    if "_" in name:
        collection, vid = name.rsplit("_", 1)
        return collection, int(vid)
    return "", int(name)


def normalize_disk_type(s: str) -> str:
    """'' and 'hdd' are the same (default) type, as in the reference's
    types.ToDiskType (weed/storage/types/volume_disk_type.go)."""
    s = (s or "").strip().lower()
    return "" if s == "hdd" else s


def readable_disk_type(s: str) -> str:
    return normalize_disk_type(s) or "hdd"


class DiskLocation:
    def __init__(self, directory: str, max_volume_count: int = 7,
                 codec_name: str = "cpu", disk_type: str = ""):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.max_volume_count = max_volume_count
        self.codec_name = codec_name
        self.disk_type = normalize_disk_type(disk_type)
        self.volumes: dict[int, Volume] = {}
        self.ec_volumes: dict[int, EcVolume] = {}
        # disk-fault survival plane: one health state machine per data
        # directory; every volume's write errors feed it
        self.health = DiskHealth(self.directory)
        self._lock = threading.RLock()
        self.load_existing_volumes()

    # -- discovery --------------------------------------------------------

    def load_existing_volumes(self) -> None:
        with self._lock:
            for fname in sorted(os.listdir(self.directory)):
                if fname.endswith(".dat"):
                    base = fname[: -len(".dat")]
                    try:
                        collection, vid = parse_volume_file_name(base)
                    except ValueError:
                        continue
                    if vid not in self.volumes:
                        try:
                            v = Volume(self.directory, collection, vid)
                            v.disk_type = self.disk_type
                            v.health = self.health
                            self.volumes[vid] = v
                        except Exception:
                            continue
            self.load_all_ec_shards()

    def load_all_ec_shards(self) -> None:
        """Group .ecNN files by volume; instantiate when the .ecx exists."""
        seen: set[int] = set()
        for fname in sorted(os.listdir(self.directory)):
            if not _EC_RE.search(fname):
                continue
            base = fname[:-5]
            try:
                collection, vid = parse_volume_file_name(base)
            except ValueError:
                continue
            if vid in seen or vid in self.ec_volumes:
                continue
            base_path = os.path.join(self.directory, base)
            if os.path.exists(base_path + ".ecx"):
                self.ec_volumes[vid] = EcVolume(
                    base_path, vid, codec_name=self.codec_name
                )
                self.ec_volumes[vid].collection = collection
                seen.add(vid)

    # -- volume lifecycle -------------------------------------------------

    def add_volume(self, vid: int, collection: str,
                   super_block: SuperBlock | None = None) -> Volume:
        with self._lock:
            if vid in self.volumes:
                return self.volumes[vid]
            v = Volume(self.directory, collection, vid, super_block=super_block)
            v.disk_type = self.disk_type
            v.health = self.health
            self.volumes[vid] = v
            return v

    def delete_volume(self, vid: int) -> bool:
        with self._lock:
            v = self.volumes.pop(vid, None)
            if v is None:
                return False
            base = v.file_name()
            v.close()
            for ext in (".dat", ".idx", ".vif", ".note"):
                try:
                    os.remove(base + ext)
                except FileNotFoundError:
                    pass
            return True

    def unmount_volume(self, vid: int) -> bool:
        with self._lock:
            v = self.volumes.pop(vid, None)
            if v is None:
                return False
            v.close()
            return True

    def base_name(self, vid: int, collection: str = "") -> str:
        name = f"{collection}_{vid}" if collection else str(vid)
        return os.path.join(self.directory, name)

    def volume_count(self) -> int:
        return len(self.volumes)
