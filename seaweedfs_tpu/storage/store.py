"""Store: the per-volume-server aggregate over disk locations.

Routes needle operations by volume id, manages EC volumes/shards, and builds
master heartbeats with full + incremental (delta) volume and EC registrations.
Reference: weed/storage/store.go + store_ec.go.
"""

from __future__ import annotations

import os
import threading

from ..ops.codec import effective_codec
from ..pb import master_pb2
from ..util import glog
from .disk_location import DiskLocation
from .ec import constants as ecc
from .ec.encoder import (
    rebuild_ec_files,
    write_ec_files,
    write_sorted_file_from_idx,
)
from .ec.decoder import (
    find_dat_file_size,
    write_dat_file,
    write_idx_file_from_ec_index,
)
from .ec.shard_bits import ShardBits
from .ec.volume import EcVolume
from .needle import CorruptNeedleError, Needle
from ..util.chunk_cache import NeedleCache
from .disk_health import DiskFailingError, DiskFullError
from .replica_placement import ReplicaPlacement
from .super_block import CURRENT_VERSION, SuperBlock
from .ttl import TTL
from .vacuum import commit_compact, compact
from .vif import save_volume_info


class Store:
    def __init__(
        self,
        directories: list[str],
        ip: str = "localhost",
        port: int = 8080,
        public_url: str = "",
        data_center: str = "",
        rack: str = "",
        codec_name: str = "cpu",
        max_volume_counts: dict[str, int] | None = None,
        disk_types: list[str] | None = None,
        needle_cache_mb: int | None = None,  # None = env / 32MB default
    ):
        self.ip = ip
        self.port = port
        self.public_url = public_url or f"{ip}:{port}"
        self.data_center = data_center
        self.rack = rack
        self.codec_name = codec_name
        disk_types = disk_types or []
        self.locations = [
            DiskLocation(
                d, codec_name=codec_name,
                disk_type=disk_types[i] if i < len(disk_types) else "",
            )
            for i, d in enumerate(directories)
        ]
        if max_volume_counts is None:
            max_volume_counts = {}
            for loc in self.locations:
                max_volume_counts[loc.disk_type] = (
                    max_volume_counts.get(loc.disk_type, 0)
                    + loc.max_volume_count)
        self.max_volume_counts = max_volume_counts
        self._lock = threading.RLock()
        # delta channels to the master (drained into heartbeats)
        self.new_volumes: list[master_pb2.VolumeShortInformationMessage] = []
        self.deleted_volumes: list[master_pb2.VolumeShortInformationMessage] = []
        self.new_ec_shards: list[master_pb2.VolumeEcShardInformationMessage] = []
        self.deleted_ec_shards: list[master_pb2.VolumeEcShardInformationMessage] = []
        self.volume_size_limit = 30 * 1024 * 1024 * 1024
        # vid -> FetchFn factory, injected by the volume server so EcVolumes
        # can read remote shards (store_ec.go's readRemoteEcShardInterval)
        self.ec_fetcher_factory = None
        # vid -> PartialRepairClient factory (storage/ec/partial.py):
        # rebuilds and degraded reads pull coefficient-weighted partial
        # sums from the sources instead of raw shard intervals
        self.partial_client_factory = None
        # self-healing integrity plane (storage/scrub.py): the volume
        # server installs its Scrubber here; the read path feeds CRC
        # failures into its quarantine + confirm queue
        self.scrubber = None
        # disk-fault plane: fired after a classified write fault (or a
        # watermark state change) so the volume server can push a full
        # heartbeat NOW instead of on the next pulse — the master must
        # stop assigning to a full disk within one beat
        self.on_disk_event = None
        # hot-needle cache: repeated small-file GETs skip needle-map
        # lookup, disk read and CRC parse.  Per-store (never process
        # global: two in-process test clusters may reuse volume ids);
        # 0 disables
        if needle_cache_mb is None:
            needle_cache_mb = int(
                os.environ.get("SEAWEEDFS_TPU_NEEDLE_CACHE_MB", "32"))
        self.needle_cache = (
            NeedleCache(needle_cache_mb << 20) if needle_cache_mb > 0
            else None
        )

    # -- lookup -----------------------------------------------------------

    def find_volume(self, vid: int):
        for loc in self.locations:
            v = loc.volumes.get(vid)
            if v is not None:
                return v
        return None

    def find_ec_volume(self, vid: int) -> EcVolume | None:
        for loc in self.locations:
            ev = loc.ec_volumes.get(vid)
            if ev is not None:
                return ev
        return None

    def _location_of(self, vid: int) -> DiskLocation | None:
        for loc in self.locations:
            if vid in loc.volumes or vid in loc.ec_volumes:
                return loc
        return None

    def has_free_location(self, disk_type: str = "") -> DiskLocation | None:
        """Freest location, optionally restricted to a disk type
        ('' accepts the default/hdd tier only when requested as such by
        an explicit allocation; None semantics: any type when no volume
        of the requested type exists is NOT applied — the reference
        refuses allocation on a missing tier)."""
        from .disk_location import normalize_disk_type

        want = normalize_disk_type(disk_type)
        best, free = None, 0
        for loc in self.locations:
            if loc.disk_type != want:
                continue
            f = loc.max_volume_count - loc.volume_count()
            if f > free:
                best, free = loc, f
        return best

    # -- volume lifecycle -------------------------------------------------

    def add_volume(self, vid: int, collection: str, replication: str = "000",
                   ttl: str = "", preallocate: int = 0,
                   disk_type: str = "") -> None:
        with self._lock:
            if self.find_volume(vid) is not None:
                raise ValueError(f"volume {vid} already exists")
            loc = self.has_free_location(disk_type)
            if loc is None:
                raise IOError("no free disk location")
            sb = SuperBlock(
                version=CURRENT_VERSION,
                replica_placement=ReplicaPlacement.parse(replication),
                ttl=TTL.parse(ttl),
            )
            v = loc.add_volume(vid, collection, super_block=sb)
            save_volume_info(v.file_name() + ".vif", v.version)
            self.new_volumes.append(self._short_info(v))

    def delete_volume(self, vid: int) -> bool:
        with self._lock:
            for loc in self.locations:
                v = loc.volumes.get(vid)
                if v is not None:
                    info = self._short_info(v)
                    if loc.delete_volume(vid):
                        if self.needle_cache is not None:
                            self.needle_cache.drop_volume(vid)
                        if self.scrubber is not None:
                            self.scrubber.quarantine.drop_volume(vid)
                        self.deleted_volumes.append(info)
                        return True
            return False

    def unmount_volume(self, vid: int) -> bool:
        with self._lock:
            for loc in self.locations:
                v = loc.volumes.get(vid)
                if v is not None:
                    info = self._short_info(v)
                    if loc.unmount_volume(vid):
                        if self.needle_cache is not None:
                            self.needle_cache.drop_volume(vid)
                        if self.scrubber is not None:
                            self.scrubber.forget_volume(vid)
                        self.deleted_volumes.append(info)
                        return True
            return False

    def mount_volume(self, vid: int) -> bool:
        with self._lock:
            for loc in self.locations:
                for fname in os.listdir(loc.directory):
                    if not fname.endswith(".dat"):
                        continue
                    base = fname[:-4]
                    from .disk_location import parse_volume_file_name

                    try:
                        collection, fvid = parse_volume_file_name(base)
                    except ValueError:
                        continue
                    if fvid == vid:
                        v = loc.add_volume(vid, collection)
                        self.new_volumes.append(self._short_info(v))
                        if self.scrubber is not None:
                            # a (re)mount replaced the volume's bytes —
                            # a repair's VolumeCopy lands here; stale
                            # findings/quarantine must not re-deliver
                            self.scrubber.forget_volume(vid)
                        return True
            return False

    def mark_readonly(self, vid: int) -> bool:
        v = self.find_volume(vid)
        if v is None:
            return False
        v.read_only = True
        return True

    def mark_writable(self, vid: int) -> bool:
        v = self.find_volume(vid)
        if v is None:
            return False
        v.read_only = False
        v.read_only_reason = ""
        return True

    # -- disk-fault survival plane ----------------------------------------

    def apply_disk_health(self) -> list:
        """Poll every location's watermark state machine and reconcile
        volume writability with it: a full/failing disk flips its
        volumes read-only-full (reads keep serving); a recovered disk
        flips back exactly the volumes the fault plane froze — an
        operator's or the lifecycle plane's read-only stays.
        -> [DiskHealth snapshot per location], heartbeat-ready."""
        snaps = []
        for loc in self.locations:
            h = loc.health
            state = h.poll()
            writable = state not in ("full", "failing")
            with loc._lock:
                for v in loc.volumes.values():
                    if not writable:
                        if not v.read_only and not v.is_remote:
                            v.read_only = True
                            v.read_only_reason = "full"
                    elif v.read_only and v.read_only_reason == "full":
                        v.read_only = False
                        v.read_only_reason = ""
            snaps.append(h.snapshot())
        return snaps

    def note_write_fault(self, vid: int) -> None:
        """A volume mutation just failed with a typed disk error: the
        volume already flipped read-only-full; re-poll the watermarks
        (the whole location may be full) and wake the heartbeat so the
        master re-routes within one beat, not one pulse."""
        self.apply_disk_health()
        cb = self.on_disk_event
        if cb is not None:
            try:
                cb()
            except Exception:  # noqa: BLE001 — never fail the write path
                pass

    # -- needle ops -------------------------------------------------------

    def invalidate_needle(self, vid: int, needle_id: int) -> None:
        """Drop one needle from the hot cache.  Called by every mutation
        that goes through the store, and by handlers that write/delete on
        a Volume directly (tail receivers, EC blob deletes)."""
        if self.needle_cache is not None:
            self.needle_cache.invalidate(vid, needle_id)

    def write_needle(self, vid: int, n: Needle) -> int:
        v = self.find_volume(vid)
        if v is None:
            raise KeyError(f"volume {vid} not found")
        try:
            _offset, size = v.append_needle(n)
        except (DiskFullError, DiskFailingError):
            self.note_write_fault(vid)
            raise
        self.invalidate_needle(vid, n.id)
        return size

    def read_needle(self, vid: int, needle_id: int,
                    expected_cookie: int | None = None) -> Needle:
        cache = self.needle_cache
        if cache is not None:
            n = cache.get(vid, needle_id)
            if n is not None:
                if expected_cookie is not None and n.cookie != expected_cookie:
                    raise PermissionError("cookie mismatch")
                return n
        v = self.find_volume(vid)
        if v is not None:
            seq = v.write_seq  # snapshot BEFORE the read
            try:
                n = v.read_needle(needle_id, expected_cookie)
            except CorruptNeedleError:
                # silent corruption on the hot path: quarantine the
                # needle (the scrubber confirms + the master repairs)
                # and let the retryable error reach the caller, whose
                # replica failover rotates to a healthy copy
                if self.scrubber is not None:
                    self.scrubber.suspect_needle(vid, needle_id)
                raise
            if cache is not None:
                # compare-and-put under the volume lock: a racing
                # append/delete bumps write_seq before its own
                # invalidate, so a stale needle can never be published
                # after the invalidation that should have killed it
                with v._lock:
                    if v.write_seq == seq:
                        cache.put(vid, needle_id, n)
            return n
        ev = self.find_ec_volume(vid)
        if ev is not None:
            seq = ev.delete_seq
            n = ev.read_needle(needle_id)
            if cache is not None:
                # same compare-and-put discipline as the volume path,
                # serialized by the journal lock the deleter bumps
                # delete_seq under — without it a preempted reader could
                # publish a tombstoned needle after its invalidation
                with ev._ecj_lock:
                    if ev.delete_seq == seq:
                        cache.put(vid, needle_id, n)
            if expected_cookie is not None and n.cookie != expected_cookie:
                raise PermissionError("cookie mismatch")
            return n
        raise KeyError(f"volume {vid} not found")

    def needle_extent(self, vid: int, needle_id: int):
        """-> (NeedleExtent | None, fallback_reason | None) for the
        zero-copy GET path.  A needle-cache hit declines the extent —
        bytes already in memory beat a disk→socket sendfile; EC and
        remote-tier volumes decline too (their bytes aren't a contiguous
        local .dat range).  Raises KeyError like read_needle when
        neither a volume nor the needle exists."""
        cache = self.needle_cache
        if cache is not None and cache.get(vid, needle_id) is not None:
            return None, "cache"
        v = self.find_volume(vid)
        if v is None:
            if self.find_ec_volume(vid) is not None:
                return None, "ec"
            raise KeyError(f"volume {vid} not found")
        if v.is_remote:
            return None, "remote"
        ext = v.needle_extent(needle_id)
        if ext is None:
            return None, "error"
        return ext, None

    def delete_needle(self, vid: int, needle_id: int) -> int:
        v = self.find_volume(vid)
        if v is None:
            raise KeyError(f"volume {vid} not found")
        try:
            freed = v.delete_needle(needle_id)
        except (DiskFullError, DiskFailingError):
            self.note_write_fault(vid)
            raise
        self.invalidate_needle(vid, needle_id)
        return freed

    def delete_ec_needle(self, vid: int, needle_id: int) -> int:
        """Tombstone a needle in a local EC volume (.ecx in place + .ecj).
        Returns the needle's stored size (0 when already gone).
        Reference: store_ec_delete.go DeleteEcShardNeedle local half."""
        ev = self.find_ec_volume(vid)
        if ev is None:
            raise KeyError(f"ec volume {vid} not found")
        try:
            _offset, size = ev.find_needle_from_ecx(needle_id)
        except KeyError:
            return 0
        ev.delete_needle(needle_id)
        self.invalidate_needle(vid, needle_id)
        return max(size, 0)

    # -- vacuum -----------------------------------------------------------

    def check_compact_volume(self, vid: int) -> float:
        v = self.find_volume(vid)
        return v.garbage_level() if v else 0.0

    def compact_volume(self, vid: int) -> int:
        v = self.find_volume(vid)
        if v is None:
            raise KeyError(f"volume {vid} not found")
        if (v.is_remote or v._tier_in_progress
                or getattr(v, "_ec_encode_in_progress", False)):
            # compacting would swap the .dat under a remote placement,
            # an in-flight tier upload, or an EC generate — all of
            # which read the files by path
            raise ValueError(
                f"volume {vid} is remote-tiered, tiering or EC-encoding;"
                " not compactable")
        on_corrupt = None
        if self.scrubber is not None:
            # a needle the copy skipped as rotten leaves the compacted
            # index too — only a whole-volume re-copy from a healthy
            # replica brings it back, so the finding must reach the
            # master even though it can't be re-verified in place
            def on_corrupt(needle_id: int) -> None:
                self.scrubber.report_corruption(
                    vid, "replica", needle_id=needle_id,
                    detail="corrupt needle dropped during vacuum")
        _base, snapshot = compact(v, on_corrupt=on_corrupt)
        self._compact_snapshots = getattr(self, "_compact_snapshots", {})
        self._compact_snapshots[vid] = snapshot
        return snapshot

    def commit_compact_volume(self, vid: int) -> None:
        v = self.find_volume(vid)
        if v is None:
            raise KeyError(f"volume {vid} not found")
        snapshot = getattr(self, "_compact_snapshots", {}).pop(vid, None)
        if snapshot is None:
            raise ValueError(f"no compaction in progress for {vid}")
        commit_compact(v, snapshot)
        # every offset (and the handle) changed wholesale
        if self.needle_cache is not None:
            self.needle_cache.drop_volume(vid)

    def cleanup_compact_volume(self, vid: int) -> None:
        v = self.find_volume(vid)
        if v is None:
            return
        base = v.file_name()
        for ext in (".cpd", ".cpx"):
            try:
                os.remove(base + ext)
            except FileNotFoundError:
                pass
        getattr(self, "_compact_snapshots", {}).pop(vid, None)

    # -- EC ops -----------------------------------------------------------

    def generate_ec_shards(self, vid: int, collection: str,
                           codec_name: str | None = None) -> None:
        """The VolumeEcShardsGenerate work: .dat -> .ecNN + .ecx + .vif."""
        v = self.find_volume(vid)
        if v is None:
            raise KeyError(f"volume {vid} not found")
        base = v.file_name()
        v.sync()
        # the encoder reads .dat/.idx BY PATH: a vacuum commit swapping
        # them mid-generation (possible since the emergency path may
        # force-vacuum read-only volumes) would mix pre- and post-
        # compact offsets into the shards — mutual exclusion both ways
        v._ec_encode_in_progress = True
        try:
            requested = codec_name or self.codec_name
            effective, reason = effective_codec(requested)
            if reason:
                glog.warning(
                    "ec.encode vol=%d: codec %s unreachable (%s), using %s",
                    vid, requested, reason, effective)
            write_ec_files(base, codec_name=requested)
            write_sorted_file_from_idx(base)
            save_volume_info(base + ".vif", v.version,
                             dat_file_size=os.path.getsize(base + ".dat"))
        finally:
            v._ec_encode_in_progress = False

    def rebuild_ec_shards(self, vid: int, collection: str,
                          codec_name: str | None = None,
                          partial=None,
                          shard_size: int | None = None) -> list[int]:
        """Rebuild locally-missing shard files.  A node holding fewer
        than DATA_SHARDS local shards streams the missing SOURCE
        intervals from peers through the same gRPC shard-read fetcher
        the degraded-read path uses, instead of failing (the shell's
        gather-copies-first flow still works and simply never needs the
        hook).  `partial`/`shard_size` override the per-volume defaults —
        a mass rebuild hands every volume a BatchedPartialClient on one
        shared session plus the size hint from the master's plan."""
        base = self._ec_base(vid, collection)
        remote_fetch = None
        ev = self.find_ec_volume(vid)
        if ev is not None:
            remote_fetch = ev.remote_fetch
            if partial is None:
                partial = ev.partial_client
            if shard_size is None:
                try:
                    shard_size = ev.shard_size or None
                except (OSError, IOError):
                    shard_size = None
        else:
            if self.ec_fetcher_factory is not None:
                remote_fetch = self.ec_fetcher_factory(vid)
            if partial is None and self.partial_client_factory is not None:
                partial = self.partial_client_factory(vid)
        if partial is not None:
            # a rebuild decides which shards are GLOBALLY missing from
            # the holder map — it must never trust a TTL-cached view
            # that predates the loss (or the repair becomes a no-op)
            partial.invalidate()
        requested = codec_name or self.codec_name
        effective, reason = effective_codec(requested)
        if reason:
            glog.warning(
                "ec.rebuild vol=%d: codec %s unreachable (%s), using %s",
                vid, requested, reason, effective)
        return rebuild_ec_files(
            base, codec_name=requested,
            remote_fetch=remote_fetch, shard_size=shard_size,
            partial=partial)

    def _ec_base(self, vid: int, collection: str = "") -> str:
        for loc in self.locations:
            ev = loc.ec_volumes.get(vid)
            if ev is not None:
                return ev.base_name
            base = loc.base_name(vid, collection)
            if os.path.exists(base + ".ecx") or os.path.exists(base + ".ec00"):
                return base
            base = loc.base_name(vid, "")
            if os.path.exists(base + ".ecx") or os.path.exists(base + ".ec00"):
                return base
        raise KeyError(f"ec volume {vid} not found")

    def ec_base_for_rebuild(self, vid: int, collection: str = "") -> str:
        """Base path for a mass-rebuild target: the existing EC base when
        this node already holds any piece of the volume, else a fresh
        base on the freest location (a spread rebuild target may hold
        NOTHING of the volume yet — the caller pulls .ecx/.ecj/.vif from
        a surviving holder before decoding into it)."""
        try:
            return self._ec_base(vid, collection)
        except KeyError:
            loc = self.has_free_location() or self.locations[0]
            return loc.base_name(vid, collection)

    def mount_ec_shards(self, vid: int, collection: str,
                        shard_ids: list[int]) -> None:
        with self._lock:
            ev = self.find_ec_volume(vid)
            if ev is None:
                base = self._ec_base(vid, collection)
                ev = EcVolume(base, vid, codec_name=self.codec_name)
                ev.collection = collection
                if self.ec_fetcher_factory is not None:
                    ev.remote_fetch = self.ec_fetcher_factory(vid)
                if self.partial_client_factory is not None:
                    ev.partial_client = self.partial_client_factory(vid)
                if self.scrubber is not None:
                    ev.corruption_hook = self.scrubber.suspect_shard
                # keep only the requested shards mounted
                for sid in list(ev.shards):
                    if sid not in shard_ids:
                        ev.delete_shard(sid)
                self._location_for_base(base).ec_volumes[vid] = ev
            else:
                for sid in shard_ids:
                    ev.add_shard(sid)
            if self.scrubber is not None:
                # a (re)mounted shard's bytes are fresh (repair rebuilds
                # land here): stale findings must not re-deliver
                self.scrubber.forget_shards(vid, shard_ids)
            try:
                shard_size = ev.shard_size
            except (OSError, IOError):
                shard_size = 0
            self.new_ec_shards.append(
                master_pb2.VolumeEcShardInformationMessage(
                    id=vid,
                    collection=collection,
                    ec_index_bits=int(_bits(shard_ids)),
                    shard_size=shard_size,
                )
            )

    def _location_for_base(self, base: str) -> DiskLocation:
        d = os.path.dirname(base)
        for loc in self.locations:
            if loc.directory == d:
                return loc
        return self.locations[0]

    def unmount_ec_shards(self, vid: int, shard_ids: list[int]) -> None:
        with self._lock:
            ev = self.find_ec_volume(vid)
            if ev is None:
                return
            for sid in shard_ids:
                ev.delete_shard(sid)
            self.deleted_ec_shards.append(
                master_pb2.VolumeEcShardInformationMessage(
                    id=vid,
                    collection=getattr(ev, "collection", ""),
                    ec_index_bits=int(_bits(shard_ids)),
                )
            )
            if not ev.shards:
                for loc in self.locations:
                    if loc.ec_volumes.get(vid) is ev:
                        del loc.ec_volumes[vid]
                ev.close()
                if self.needle_cache is not None:
                    self.needle_cache.drop_volume(vid)

    def delete_ec_shards(self, vid: int, collection: str,
                         shard_ids: list[int]) -> None:
        with self._lock:
            self.unmount_ec_shards(vid, shard_ids)
            try:
                base = self._ec_base(vid, collection)
            except KeyError:
                return
            for sid in shard_ids:
                try:
                    os.remove(base + ecc.to_ext(sid))
                except FileNotFoundError:
                    pass
            # if no shards remain on disk, remove the index files too
            if not any(
                os.path.exists(base + ecc.to_ext(i))
                for i in range(ecc.TOTAL_SHARDS)
            ):
                for ext in (".ecx", ".ecj"):
                    try:
                        os.remove(base + ext)
                    except FileNotFoundError:
                        pass

    def ec_shards_to_volume(self, vid: int, collection: str) -> None:
        """Convert a complete local EC volume back to a normal volume."""
        base = self._ec_base(vid, collection)
        dat_size = find_dat_file_size(base, base)
        write_dat_file(base, dat_size)
        write_idx_file_from_ec_index(base)
        ev = self.find_ec_volume(vid)
        if ev is not None:
            self.unmount_ec_shards(vid, list(ev.shards))
        self.mount_volume(vid)

    # -- heartbeat --------------------------------------------------------

    def _short_info(self, v) -> master_pb2.VolumeShortInformationMessage:
        return master_pb2.VolumeShortInformationMessage(
            id=v.volume_id,
            collection=v.collection,
            replica_placement=v.super_block.replica_placement.to_byte(),
            version=v.version,
            ttl=v.super_block.ttl.to_uint32(),
            disk_type=getattr(v, "disk_type", ""),
        )

    def collect_heartbeat(self) -> master_pb2.Heartbeat:
        # reconcile writability with the watermarks FIRST, so this
        # beat's read_only bits already reflect a just-filled disk
        disk_snaps = self.apply_disk_health()
        hb = master_pb2.Heartbeat(
            ip=self.ip,
            port=self.port,
            public_url=self.public_url,
            data_center=self.data_center,
            rack=self.rack,
        )
        max_key = 0
        for loc in self.locations:
            for v in loc.volumes.values():
                max_key = max(max_key, v.needle_map.maximum_key)
                hb.volumes.add(
                    id=v.volume_id,
                    size=v.content_size,
                    collection=v.collection,
                    file_count=v.file_count(),
                    delete_count=v.needle_map.deleted_count,
                    deleted_byte_count=v.needle_map.deleted_bytes,
                    read_only=v.read_only,
                    replica_placement=v.super_block.replica_placement.to_byte(),
                    version=v.version,
                    ttl=v.super_block.ttl.to_uint32(),
                    compact_revision=v.super_block.compaction_revision,
                    modified_at_second=v.last_modified_second,
                    disk_type=loc.disk_type,
                )
            for vid, ev in loc.ec_volumes.items():
                try:
                    shard_size = ev.shard_size
                except (OSError, IOError):
                    shard_size = 0
                hb.ec_shards.add(
                    id=vid,
                    collection=getattr(ev, "collection", ""),
                    ec_index_bits=int(_bits(ev.shard_ids())),
                    # bytes-at-risk hint: the master's mass-repair
                    # orchestrator ranks exposure ties by size and sizes
                    # rebuild streams without per-volume probe rpcs
                    shard_size=shard_size,
                )
        hb.max_file_key = max_key
        # per-disk health rides every full beat: free/total bytes + the
        # state machine verdict — the master gates assignment, triggers
        # emergency vacuum (low_space) and proactive evacuation (failing)
        for snap in disk_snaps:
            hb.disk_health.add(
                dir=snap["dir"],
                state=snap["state"],
                free_bytes=snap["free_bytes"],
                total_bytes=snap["total_bytes"],
            )
        for k, c in self.max_volume_counts.items():
            hb.max_volume_counts[k] = c
        if not hb.volumes:
            hb.has_no_volumes = True
        if not hb.ec_shards:
            hb.has_no_ec_shards = True
        return hb

    def drain_deltas(self):
        """Pop pending incremental registrations for the heartbeat stream."""
        with self._lock:
            out = (
                self.new_volumes,
                self.deleted_volumes,
                self.new_ec_shards,
                self.deleted_ec_shards,
            )
            self.new_volumes = []
            self.deleted_volumes = []
            self.new_ec_shards = []
            self.deleted_ec_shards = []
            return out

    def status(self) -> dict:
        return {
            "volumes": sorted(
                vid for loc in self.locations for vid in loc.volumes
            ),
            "ec_volumes": {
                vid: ev.shard_ids()
                for loc in self.locations
                for vid, ev in loc.ec_volumes.items()
            },
        }

    def close(self) -> None:
        for loc in self.locations:
            for v in loc.volumes.values():
                v.close()
            for ev in loc.ec_volumes.values():
                ev.close()


def _bits(shard_ids) -> ShardBits:
    b = ShardBits(0)
    for sid in shard_ids:
        b = b.add(sid)
    return b
