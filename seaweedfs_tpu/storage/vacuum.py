"""Volume compaction (vacuum): reclaim space from deleted needles.

Reference behavior (weed/storage/volume_vacuum.go): copy live needles into
shadow files `.cpd`/`.cpx`, then commit by renaming over the originals and
reloading.  The reference's compaction runs concurrently with writes and
replays the raced tail via makeupDiff; here compaction copies under the
volume lock up to the snapshot offset, then commit re-checks for appends
past the snapshot and replays them from the old `.dat` before renaming —
the same recovery obligation, expressed as a replay loop instead of idx
diffing.
"""

from __future__ import annotations

import os
import struct

from ..stats.metrics import SCRUB_ERRORS
from ..util import glog
from . import types as t
from .needle import CorruptNeedleError, Needle, actual_size
from .volume import Volume


def compact(volume: Volume, on_corrupt=None) -> tuple[str, int]:
    """Write .cpd/.cpx shadow files with live needles; returns (base, snapshot).

    Holds the volume lock only long enough to snapshot the end offset; the
    copy itself reads from the immutable prefix of the append-only .dat.
    `on_corrupt(needle_id)` fires for every needle skipped as rotten so
    the caller can queue a repair (Store.compact_volume wires the
    scrubber) — after commit the needle is gone from the local index and
    only a replica re-copy restores it.
    """
    base = volume.file_name()
    with volume._lock:
        volume.sync()
        snapshot_end = volume.content_size
        live = {v.key: v for v in volume.needle_map.items_ascending()}
        version = volume.version
        sb = volume.super_block

    cpd = base + ".cpd"
    cpx = base + ".cpx"
    sb_bytes = bytearray(sb.to_bytes())
    sb_bytes[4:6] = int(sb.compaction_revision + 1).to_bytes(2, "big")
    with open(base + ".dat", "rb") as src, open(cpd, "wb") as dat_out, open(
        cpx, "wb"
    ) as idx_out:
        dat_out.write(bytes(sb_bytes))
        offset = len(sb_bytes)
        for key in sorted(live, key=lambda k: live[k].offset):
            nv = live[key]
            if nv.size <= 0 or nv.offset >= snapshot_end:
                continue
            src.seek(nv.offset)
            blob = src.read(actual_size(nv.size, version))
            # verify while copying: a silently-rotten needle must not be
            # laundered into the compacted volume as fresh-looking bytes
            # (seaweedfs_scrub_errors_total{kind="vacuum"}); the skipped
            # needle heals from a replica via the scrub/repair plane
            try:
                n = Needle.from_bytes(blob, version)
                if n.id != key:
                    raise CorruptNeedleError(
                        f"record at {nv.offset} carries id {n.id:x}")
            except (CorruptNeedleError, ValueError, IndexError,
                    struct.error) as e:
                SCRUB_ERRORS.labels("vacuum").inc()
                glog.warning(
                    "vacuum: skipping corrupt needle %x in volume %d: %s",
                    key, volume.volume_id, e)
                if on_corrupt is not None:
                    on_corrupt(key)
                continue
            dat_out.write(blob)
            idx_out.write(t.pack_index_entry(key, offset, nv.size))
            offset += len(blob)
    return base, snapshot_end


def commit_compact(volume: Volume, snapshot_end: int) -> None:
    """Swap in the shadow files, replaying any appends that raced the copy."""
    base = volume.file_name()
    cpd = base + ".cpd"
    cpx = base + ".cpx"
    with volume._lock:
        volume.sync()
        current_end = volume.content_size
        if current_end > snapshot_end:
            _replay_tail(volume, base, cpd, cpx, snapshot_end, current_end)
        directory, collection, vid = (
            volume.directory,
            volume.collection,
            volume.volume_id,
        )
        # location-scoped attributes survive the in-place re-init: the
        # disk's health machine and tier must keep feeding the same
        # state after a compaction swaps the files underneath
        health, disk_type = volume.health, volume.disk_type
        volume.close()
        os.replace(cpd, base + ".dat")
        os.replace(cpx, base + ".idx")
        # reopen in place: swap internals from a freshly loaded volume
        volume.__init__(directory, collection, vid)
        volume.health = health
        volume.disk_type = disk_type


def _replay_tail(volume: Volume, base: str, cpd: str, cpx: str,
                 snapshot_end: int, current_end: int) -> None:
    """Append records written after the snapshot to the shadow files.

    Mirrors makeupDiff (volume_vacuum.go:179): walk the raced tail of the
    old .dat and apply each record (write or tombstone) to .cpd/.cpx.
    """
    version = volume.version
    with open(base + ".dat", "rb") as src, open(cpd, "r+b") as dat_out, open(
        cpx, "ab"
    ) as idx_out:
        dat_out.seek(0, os.SEEK_END)
        pos = snapshot_end
        while pos < current_end:
            src.seek(pos)
            hdr = src.read(t.NEEDLE_HEADER_SIZE)
            if len(hdr) < t.NEEDLE_HEADER_SIZE:
                break
            n = Needle.parse_header(hdr)
            size = max(n.size, 0)
            rec_len = actual_size(size, version)
            src.seek(pos)
            blob = src.read(rec_len)
            live = volume.needle_map.get(n.id)
            if n.size > 0 and live is not None and live.offset == pos:
                out_off = dat_out.tell()
                dat_out.write(blob)
                idx_out.write(t.pack_index_entry(n.id, out_off, n.size))
            elif n.size == 0 or (live is None):
                # tombstone or superseded record
                idx_out.write(
                    t.pack_index_entry(n.id, 0, t.TOMBSTONE_FILE_SIZE)
                )
            pos += rec_len


def vacuum_volume(volume: Volume) -> None:
    """Full check-compact-commit cycle for one volume."""
    _base, snapshot = compact(volume)
    commit_compact(volume, snapshot)
