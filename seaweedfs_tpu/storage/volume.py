"""Volume: one `.dat` needle log + `.idx` index + in-memory needle map.

Reference behavior (weed/storage/volume.go, volume_write.go, volume_read.go,
volume_checking.go): append-only writes under a lock, tombstone deletes (an
empty needle marks deletion in the log, the index records size -1), CRC
verification on read, and load-time integrity checking that truncates torn
tail appends.

The `.dat` bytes flow through a BackendStorageFile (backend.py — the seam
from weed/storage/backend/backend.go:15): local volumes use DiskFile;
a volume whose `.vif` records a remote tier placement opens a read-only
RemoteBackendFile instead (volume_tier.go LoadRemoteFile), with the index
and needle map still local.
"""

from __future__ import annotations

import itertools
import os
import struct
import threading
import time

from . import types as t
from ..ops import crc32c
from ..util import faultpoint, glog
from .backend import DiskFile, get_backend
from .disk_health import DiskFullError, classify_write_error
from .group_commit import GroupCommitter, Pending
from .idx import IndexWriter, append_index_tombstone, walk_index_file
from .needle import Needle, actual_size, body_length
from .needle_map import NeedleMap

# chaos point inside the (unlocked) disk-read section of the needle read
# path: lets tests prove two GETs on one volume overlap
FP_DISK_READ = faultpoint.register("volume.disk.read")

# global mutation-sequence source: values never repeat, even across a
# vacuum's in-place re-__init__, so a cached sequence observed before
# a swap can never collide with one issued after it
_MUTATION_SEQ = itertools.count(1)

# process-wide index kind (needle_map.go:13-19 NeedleMapKind): "memory"
# (compact in-RAM map) or "disk" (sorted-file map with bounded RAM);
# selected by the volume server's -index flag before volumes load
DEFAULT_NEEDLE_MAP_KIND = "memory"


def set_needle_map_kind(kind: str) -> None:
    global DEFAULT_NEEDLE_MAP_KIND
    if kind not in ("memory", "disk"):
        raise ValueError("index kind must be memory or disk")
    DEFAULT_NEEDLE_MAP_KIND = kind


def durability_mode() -> str:
    """Per-mutation durability (group_commit.py): "none" (page cache
    only, today's default), "sync" (one fsync pair per mutation), or
    "batch" (group-commit barrier — one fsync acks many mutations)."""
    mode = os.environ.get("SEAWEEDFS_TPU_DURABILITY", "none").strip().lower()
    return mode if mode in ("none", "sync", "batch") else "none"
from .super_block import CURRENT_VERSION, SUPER_BLOCK_SIZE, SuperBlock
from .vif import load_volume_info, save_volume_info


class NeedleExtent:
    """A needle's payload located on disk for zero-copy serving: a
    dup'd .dat fd the caller OWNS (close() exactly once) plus the byte
    range os.sendfile should ship, and the metadata-only Needle (no
    data) for headers/cookie checks."""

    __slots__ = ("fd", "data_offset", "data_len", "needle", "_closed")

    def __init__(self, fd: int, data_offset: int, data_len: int,
                 needle: Needle):
        self.fd = fd
        self.data_offset = data_offset
        self.data_len = data_len
        self.needle = needle
        self._closed = False

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            try:
                os.close(self.fd)
            except OSError:
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class Volume:
    def __init__(self, directory: str, collection: str, volume_id: int,
                 super_block: SuperBlock | None = None):
        self.directory = directory
        self.collection = collection
        self.volume_id = volume_id
        self.disk_type = ""  # normalized; "" == hdd (set by DiskLocation)
        self.read_only = False
        # why the volume is read-only: "" (operator/seal), or "full"
        # (disk-fault plane: flips back writable when space returns)
        self.read_only_reason = ""
        # the DiskLocation's DiskHealth (set by DiskLocation); write
        # errors feed its state machine
        self.health = None
        self._tier_in_progress = False
        self._ec_encode_in_progress = False
        self._lock = threading.RLock()
        # bumped on every append/delete (and fresh on vacuum re-init):
        # the needle cache's compare-before-put token (store.py)
        self.write_seq = next(_MUTATION_SEQ)
        base = self.file_name()
        self.volume_info = load_volume_info(base + ".vif")
        remote = self._remote_dat_file()
        if remote is not None:
            # .dat lives on a remote tier: serve reads through it, stay
            # read-only until tier.download brings the bytes back
            self._dat = remote
            self.read_only = True
            self.super_block = SuperBlock.from_bytes(
                self._dat.read_at(0, 64)
            )
        else:
            is_new = not os.path.exists(base + ".dat")
            self.super_block = super_block or SuperBlock()
            self._dat = DiskFile(base + ".dat")
            if is_new:
                self._dat.write_at(0, self.super_block.to_bytes())
            else:
                self.super_block = SuperBlock.from_bytes(
                    self._dat.read_at(0, 64)
                )
        self.version = self.super_block.version
        # quiet-window bookkeeping for ec.encode -quietFor: seed from the
        # .dat mtime at load so a restart doesn't reset the quiet clock
        try:
            self.last_modified_second = int(
                os.path.getmtime(base + ".dat"))
        except OSError:
            self.last_modified_second = int(time.time())
        kind = DEFAULT_NEEDLE_MAP_KIND
        if kind == "disk":
            from .disk_needle_map import DiskNeedleMap

            self.needle_map = (
                DiskNeedleMap.load_from_idx(base + ".idx")
                if os.path.exists(base + ".idx")
                else DiskNeedleMap(base + ".sdx")
            )
        else:
            self.needle_map = (
                NeedleMap.load_from_idx(base + ".idx")
                if os.path.exists(base + ".idx")
                else NeedleMap()
            )
        self.check_and_fix_integrity()
        self._idx = IndexWriter(base + ".idx")
        self.durability = durability_mode()
        self._group = (GroupCommitter(self)
                       if self.durability == "batch" and not self.is_remote
                       else None)
        # (needle_id, offset) pairs whose payload CRC has been verified
        # for zero-copy serving: sendfile ships bytes the CPU never
        # sees, so the first extent serve of a needle pays one userspace
        # read + crc32c and later serves skip it.  Keyed by offset so an
        # overwrite (new offset) re-verifies; bounded, cleared on
        # overflow (worst case = re-verify, never serve rotten bytes).
        self._extent_verified: set[tuple[int, int]] = set()

    def _remote_dat_file(self):
        """RemoteBackendFile when the .vif maps the .dat to a configured
        tier; None for plain local volumes (or unconfigured backends)."""
        if self.volume_info is None:
            return None
        for rf in self.volume_info.files:
            if rf.extension and rf.extension != ".dat":
                continue
            backend = get_backend(f"{rf.backend_type}.{rf.backend_id}")
            if backend is None:
                raise IOError(
                    f"volume {self.volume_id}: .dat is on unconfigured "
                    f"backend {rf.backend_type}.{rf.backend_id}"
                )
            return backend.remote_file(rf.key, rf.file_size)
        return None

    @property
    def is_remote(self) -> bool:
        return self._dat.is_remote

    # -- naming -----------------------------------------------------------

    def file_name(self) -> str:
        name = f"{self.volume_id}"
        if self.collection:
            name = f"{self.collection}_{name}"
        return os.path.join(self.directory, name)

    # -- write path -------------------------------------------------------

    def _check_writable(self, for_delete: bool = False) -> None:
        if not self.read_only:
            return
        if self.read_only_reason == "full":
            if for_delete:
                # deletes FREE space and a tombstone is ~40 bytes: they
                # run against the reserved watermark headroom (the disk
                # flipped full while min-free bytes remained), otherwise
                # a full disk could never be drained back to healthy
                return
            raise DiskFullError(
                28, f"volume {self.volume_id} is full (read-only-full)")
        raise PermissionError(f"volume {self.volume_id} is read-only")

    def _fail_write(self, e: OSError, start: int,
                    idx_pos: int | None = None) -> OSError:
        """Roll a failed mutation back to a consistent pre-write state:
        truncate the .dat to `start` (dropping any torn blob bytes the
        failed write landed) and the .idx to `idx_pos`; feed the error
        into the disk health machine; flip read-only-full on ENOSPC.
        Returns the typed error to raise (DiskFullError/DiskFailingError).
        No in-memory index entry exists for the unacked bytes — callers
        only publish to the needle map after every durable write
        succeeded."""
        typed = classify_write_error(e, self._dat.name)
        try:
            self._dat.truncate(start)
        except OSError as e2:  # rollback itself failed: disk is dying
            glog.warning("volume %d: rollback truncate to %d failed: %s "
                         "(load-time healer will truncate on remount)",
                         self.volume_id, start, e2)
        if idx_pos is not None:
            try:
                self._idx.truncate(idx_pos)
            except OSError:
                pass  # a torn trailing idx entry is dropped by the loader
        if self.health is not None:
            self.health.record_write_error(typed)
        if isinstance(typed, DiskFullError):
            # read-only-full: reads keep serving, writers get the typed
            # 409 and re-assign; mark_writable/space recovery clears it
            self.read_only = True
            self.read_only_reason = "full"
        return typed

    def _publish_append(self, needle_id: int, offset: int,
                        size: int) -> None:
        """Make an append visible: needle-map entry + write_seq bump +
        health credit.  Callers hold the volume lock.  In batch mode the
        flush barrier calls this AFTER its fsync — no reader can observe
        a needle whose bytes aren't durable yet."""
        old = self.needle_map.get(needle_id)
        if old is None or old.offset < offset:
            self.needle_map.put(needle_id, offset, size)
        if self.health is not None:
            self.health.record_write_ok()
        self.write_seq = next(_MUTATION_SEQ)

    def _publish_delete(self, needle_id: int) -> None:
        self.needle_map.delete(needle_id)
        if self.health is not None:
            self.health.record_write_ok()
        self.write_seq = next(_MUTATION_SEQ)

    def _sync_now(self, start: int, idx_pos: int | None) -> None:
        """Strict per-mutation durability ("sync" mode): one fsync pair
        before the publish/ack, rolled back like any failed write."""
        try:
            self._dat.sync()
            self._idx.flush()
        except OSError as e:
            raise self._fail_write(e, start, idx_pos) from e

    def append_needle(self, n: Needle) -> tuple[int, int]:
        """Append; returns (actual_offset, stored_size).

        Crash/fault discipline: the needle map and .idx are only updated
        after the .dat blob landed in full; any OSError rolls the .dat
        back to its pre-append size and surfaces as a typed
        DiskFullError/DiskFailingError — a mid-blob ENOSPC can never
        leave a published index entry pointing at a torn tail.

        Durability modes (group_commit.py): "none" acks from the page
        cache; "sync" fsyncs per append; "batch" parks on the volume's
        flush barrier OUTSIDE the lock — concurrent writers keep
        appending while this one waits, and one fsync acks them all."""
        group = self._group
        with self._lock:
            self._check_writable()
            start = self._dat.file_size()
            offset = start
            pad = -offset % t.NEEDLE_PADDING_SIZE  # heal torn tail
            if offset + pad >= t.MAX_POSSIBLE_VOLUME_SIZE:
                raise IOError("volume size limit exceeded")
            try:
                if pad:
                    self._dat.write_at(offset, b"\0" * pad)
                    offset += pad
                if not n.append_at_ns:
                    n.append_at_ns = time.time_ns()
                self.last_modified_second = int(time.time())
                blob = n.to_bytes(self.version)
                wrote = self._dat.write_at(offset, blob)
                if wrote != len(blob):
                    raise OSError(
                        5, f"short write: {wrote}/{len(blob)} bytes")
            except OSError as e:
                raise self._fail_write(e, start) from e
            idx_pos = None
            old = self.needle_map.get(n.id)
            if old is None or old.offset < offset:
                idx_pos = self._idx.tell()
                try:
                    self._idx.put(n.id, offset, n.size)
                except OSError as e:
                    # the blob is durable but unindexed: roll BOTH back —
                    # an acked write must be remount-provable via the .idx
                    raise self._fail_write(e, start, idx_pos) from e
            if group is None:
                if self.durability == "sync":
                    self._sync_now(start, idx_pos)
                self._publish_append(n.id, offset, n.size)
                return offset, n.size
            pending = Pending(
                lambda: self._publish_append(n.id, offset, n.size),
                start, idx_pos)
        group.park(pending)  # outside the lock: the barrier batches
        return offset, n.size

    def delete_needle(self, needle_id: int,
                      at_ns: int | None = None) -> int:
        """Append a tombstone marker needle; returns freed byte count.

        `at_ns` preserves the ORIGIN's tombstone timestamp when the
        delete is replayed from another server (tail receivers, backup
        mirrors) — a locally-stamped tombstone would poison tail
        watermarks under clock skew."""
        group = self._group
        with self._lock:
            self._check_writable(for_delete=True)
            existing = self.needle_map.get(needle_id)
            if existing is None:
                return 0
            marker = Needle(id=needle_id, cookie=0, data=b"")
            start = self._dat.file_size()
            offset = start
            # tombstones grow the log too: the offset cap append_needle
            # enforces guards index addressability (offsets store /8 in
            # 32 bits), so a full-size volume must not creep past it
            # via deletes either
            if offset >= t.MAX_POSSIBLE_VOLUME_SIZE:
                raise IOError("volume size limit exceeded")
            marker.append_at_ns = at_ns or time.time_ns()
            blob = marker.to_bytes(self.version)
            try:
                wrote = self._dat.write_at(offset, blob)
                if wrote != len(blob):
                    raise OSError(
                        5, f"short write: {wrote}/{len(blob)} bytes")
            except OSError as e:
                raise self._fail_write(e, start) from e
            idx_pos = self._idx.tell()
            try:
                self._idx.delete(needle_id, offset)
            except OSError as e:
                raise self._fail_write(e, start, idx_pos) from e
            self.last_modified_second = int(time.time())
            freed = max(existing.size, 0)
            if group is None:
                if self.durability == "sync":
                    self._sync_now(start, idx_pos)
                self._publish_delete(needle_id)
                return freed
            pending = Pending(
                lambda: self._publish_delete(needle_id), start, idx_pos)
        group.park(pending)  # tombstones ride the same barrier: the
        # batch rollback may truncate anything above its start, so every
        # mutation on a batch-mode volume must be IN the batch
        return freed

    # -- read path --------------------------------------------------------

    def read_needle(self, needle_id: int, expected_cookie: int | None = None) -> Needle:
        """Lock-split read: the lock covers only the needle-map lookup and
        the .dat handle snapshot; the disk read itself runs outside it via
        a positioned pread, so concurrent GETs on one volume overlap
        instead of serializing behind each other's I/O.

        Safety: the .dat is append-only, so an offset published in the
        needle map always names fully-written bytes in the snapshotted
        handle; the only racer that can hurt is a handle SWAP (vacuum
        commit / tier move), which closes the old fd — that read fails
        with OSError/ValueError (or short-reads) and retries under the
        lock against the fresh handle and a fresh map entry."""
        with self._lock:
            nv = self.needle_map.get(needle_id)
            if nv is None or t.size_is_deleted(nv.size):
                raise KeyError(f"needle {needle_id:x} not found")
            dat = self._dat
            version = self.version
        faultpoint.inject(FP_DISK_READ, ctx=str(self.volume_id))
        n = None
        try:
            blob = dat.pread(nv.offset, actual_size(nv.size, version))
            parsed = Needle.from_bytes(blob, version)
            if parsed.size == nv.size:
                n = parsed
        except (OSError, ValueError, struct.error):
            pass
        if n is None:
            # racing handle swap: a closed fd errors/short-reads, and a
            # REUSED fd number can even hand back `want` bytes of the
            # wrong file — any inconsistency (error, short read, parse
            # failure, size mismatch) re-resolves everything under the
            # lock, where the locked path's own errors are authoritative
            with self._lock:
                nv = self.needle_map.get(needle_id)
                if nv is None or t.size_is_deleted(nv.size):
                    raise KeyError(f"needle {needle_id:x} not found")
                version = self.version
                blob = self._dat.read_at(
                    nv.offset, actual_size(nv.size, version)
                )
            n = Needle.from_bytes(blob, version)
            if n.size != nv.size:
                raise IOError("size mismatch reading needle")
        if expected_cookie is not None and n.cookie != expected_cookie:
            raise PermissionError("cookie mismatch")
        return n

    def needle_extent(self, needle_id: int) -> "NeedleExtent | None":
        """Zero-copy serving descriptor: the needle's METADATA (header,
        flags, name/mime, stored checksum) parsed from two small preads,
        plus a dup'd fd + (offset, length) naming the payload bytes in
        the .dat — os.sendfile streams them disk→socket without ever
        entering userspace.  The dup (taken under the lock) pins the
        open file description, so a racing vacuum handle swap can
        neither close it mid-send nor recycle the fd number onto another
        file; the dup'd fd reads the OLD append-only .dat, whose bytes
        for this needle are immutable.

        Returns None when the volume can't serve an extent (remote tier,
        v1 layout, empty payload, parse anomaly) — callers fall back to
        the ordinary read path.  Raises KeyError like read_needle when
        the needle doesn't exist."""
        with self._lock:
            nv = self.needle_map.get(needle_id)
            if nv is None or t.size_is_deleted(nv.size):
                raise KeyError(f"needle {needle_id:x} not found")
            dat = self._dat
            version = self.version
            if dat.is_remote or version not in (2, 3) or nv.size <= 0:
                return None
            try:
                fd = os.dup(dat.fileno())
            except (OSError, ValueError, AttributeError):
                return None
        try:
            head = os.pread(fd, t.NEEDLE_HEADER_SIZE + 4, nv.offset)
            if len(head) != t.NEEDLE_HEADER_SIZE + 4:
                raise ValueError("short header read")
            n = Needle.parse_header(head)
            if n.id != needle_id or n.size != nv.size:
                raise ValueError("stale extent header")
            data_size = struct.unpack(
                ">I", head[t.NEEDLE_HEADER_SIZE:])[0]
            meta_len = nv.size - 4 - data_size
            if meta_len < 1:  # at least the flags byte
                raise ValueError("needle data out of range")
            tail_len = meta_len + t.NEEDLE_CHECKSUM_SIZE
            if version == 3:
                tail_len += t.TIMESTAMP_SIZE
            tail = os.pread(
                fd, tail_len,
                nv.offset + t.NEEDLE_HEADER_SIZE + 4 + data_size)
            if len(tail) != tail_len:
                raise ValueError("short meta read")
            # a zero-length fake data field turns the tail into a valid
            # v2 body, so the standard field walk parses flags/name/mime
            n.parse_body_v2(struct.pack(">I", 0) + tail[:meta_len])
            stored = struct.unpack(
                ">I", tail[meta_len:meta_len + 4])[0]
            n.checksum = crc32c.unmask(stored)
            if version == 3:
                n.append_at_ns = struct.unpack(
                    ">Q", tail[meta_len + 4:meta_len + 12])[0]
            # first serve of this (needle, offset) pays one userspace
            # read to verify the payload CRC — sendfile would otherwise
            # ship rotten bytes as a 200 that the ordinary read path
            # turns into CorruptNeedleError + quarantine.  The read also
            # warms the page cache for the sendfile that follows.
            vkey = (needle_id, nv.offset)
            if vkey not in self._extent_verified:
                data = os.pread(
                    fd, data_size, nv.offset + t.NEEDLE_HEADER_SIZE + 4)
                if (len(data) != data_size
                        or crc32c.checksum(data) != n.checksum):
                    raise ValueError("extent payload CRC mismatch")
                if len(self._extent_verified) >= 65536:
                    self._extent_verified.clear()
                self._extent_verified.add(vkey)
            return NeedleExtent(
                fd, nv.offset + t.NEEDLE_HEADER_SIZE + 4, data_size, n)
        except (OSError, ValueError, struct.error):
            os.close(fd)
            return None

    # -- remote tier ------------------------------------------------------

    def tier_to_remote(self, backend_name: str, keep_local: bool = False,
                       progress=None) -> int:
        """Upload the .dat to a remote tier, record it in the .vif, and
        reopen through the remote file (volume.tier.upload;
        volume_grpc_tier.go).  Returns bytes uploaded.

        The upload itself runs OUTSIDE the volume lock: the volume is
        read-only and the .dat append-only, so the bytes are immutable
        while they move — reads keep being served throughout, which
        matters when a throttled lifecycle tier job paces the upload
        over many seconds (the progress callback is the token-bucket
        hook)."""
        backend = get_backend(backend_name)
        if backend is None:
            raise IOError(f"backend {backend_name} not configured")
        with self._lock:
            if self.is_remote:
                raise IOError(f"volume {self.volume_id} is already remote")
            if self._tier_in_progress:
                raise IOError(
                    f"volume {self.volume_id}: tier move already running")
            self._tier_in_progress = True
            self.read_only = True  # no appends while the bytes move
            self._dat.sync()
            base = self.file_name()
            key = f"{os.path.basename(base)}.dat"
            size = self._dat.file_size()
        try:
            backend.upload_file(base + ".dat", key, progress=progress)
            with self._lock:
                save_volume_info(
                    base + ".vif", self.version,
                    replication=str(
                        self.super_block.replica_placement or ""),
                    dat_file_size=size,
                    remote_files=[{
                        "backend_type": backend.backend_type,
                        "backend_id": backend.backend_id,
                        "key": key,
                        "file_size": size,
                        "modified_time": int(time.time()),
                        "extension": ".dat",
                    }],
                )
                self.volume_info = load_volume_info(base + ".vif")
                self._dat.close()
                self._dat = backend.remote_file(key, size)
                if not keep_local:
                    os.remove(base + ".dat")
                return size
        finally:
            with self._lock:
                self._tier_in_progress = False

    def tier_to_local(self, progress=None) -> int:
        """Download the .dat back from its remote tier and reopen locally
        (volume.tier.download).  Returns bytes downloaded."""
        with self._lock:
            if not self.is_remote:
                return 0
            remote = self._dat
            base = self.file_name()
            got = remote.backend.download_file(
                remote.key, base + ".dat", progress=progress
            )
            remote.backend.delete_file(remote.key)
            save_volume_info(
                base + ".vif", self.version,
                replication=str(self.super_block.replica_placement or ""),
                dat_file_size=got,
            )
            self.volume_info = load_volume_info(base + ".vif")
            self._dat = DiskFile(base + ".dat")
            self.read_only = False
            return got

    # -- stats / lifecycle ------------------------------------------------

    def flush(self) -> None:
        """Fence buffered appends so other handles see consistent
        .dat/.idx files (bulk copy streams them by path)."""
        with self._lock:
            self._dat.sync()
            self._idx.flush()

    @property
    def content_size(self) -> int:
        # under the lock: tier transitions swap self._dat and a heartbeat
        # thread polling sizes must not see the half-closed handle
        with self._lock:
            return self._dat.file_size()

    def garbage_level(self) -> float:
        size = self.content_size
        return self.needle_map.deleted_bytes / size if size else 0.0

    def file_count(self) -> int:
        return len(self.needle_map)

    def sync(self) -> None:
        with self._lock:
            self._dat.sync()
            self._idx.flush()

    def close(self) -> None:
        with self._lock:
            self._dat.close()
            self._idx.close()
            if hasattr(self.needle_map, "close"):
                self.needle_map.close()

    # -- integrity --------------------------------------------------------

    def check_and_fix_integrity(self) -> None:
        """Verify the last index entry matches the .dat; truncate torn tails.

        Reference: CheckAndFixVolumeDataIntegrity (volume_checking.go:17) —
        the last entry's record must lie fully inside the file and carry the
        expected needle id; otherwise the torn tail is truncated away.
        Remote-tier volumes skip the fix (their bytes are immutable).
        """
        file_size = self._dat.file_size()
        last = None
        for v in self.needle_map.items_ascending():
            if last is None or v.offset > last.offset:
                last = v
        if last is None:
            return
        end = last.offset + actual_size(max(last.size, 0), self.version)
        if end > file_size:
            if self.is_remote:
                raise IOError(
                    f"volume {self.volume_id}: remote .dat shorter than index"
                )
            if self._repad_torn_tail(last, file_size, end):
                return
            # torn append: drop the entry and truncate to the previous
            # record.  The drop must ALSO reach the on-disk .idx (as a
            # tombstone): the stale entry would otherwise resurface on
            # the next load and claim whatever new record gets appended
            # at the reclaimed offset — truncating an acked write
            self.needle_map.delete(last.key)
            append_index_tombstone(self.file_name() + ".idx", last.key)
            self._dat.truncate(last.offset)
            return
        hdr = self._dat.read_at(last.offset, t.NEEDLE_HEADER_SIZE)
        if len(hdr) == t.NEEDLE_HEADER_SIZE:
            n = Needle.parse_header(hdr)
            if n.id != last.key:
                self.needle_map.delete(last.key)
                append_index_tombstone(
                    self.file_name() + ".idx", last.key)

    def _repad_torn_tail(self, last, file_size: int, end: int) -> bool:
        """Tear-at-padding-boundary heal: when ONLY trailing padding
        bytes of the last record are missing (every real byte — header,
        body, checksum, v3 timestamp — is present and CRC-clean), the
        acked needle is intact; dropping it would turn a cosmetic tear
        into acked-write loss.  Re-pad the file to the aligned end
        instead.  -> True when healed."""
        from .needle import padding_length

        have = file_size - last.offset
        size = max(last.size, 0)
        unpadded = (actual_size(size, self.version)
                    - padding_length(size, self.version))
        if have < unpadded:
            return False  # real bytes missing: a genuine torn append
        try:
            blob = self._dat.read_at(last.offset, have)
            n = Needle.from_bytes(blob, self.version)
        except (ValueError, struct.error, OSError):
            return False
        if n.id != last.key or n.size != last.size:
            return False
        self._dat.write_at(file_size, b"\0" * (end - file_size))
        glog.info("volume %d: re-padded torn tail (%d pad bytes) for "
                  "needle %x", self.volume_id, end - file_size, last.key)
        return True
