"""Volume: one `.dat` needle log + `.idx` index + in-memory needle map.

Reference behavior (weed/storage/volume.go, volume_write.go, volume_read.go,
volume_checking.go): append-only writes under a lock, tombstone deletes (an
empty needle marks deletion in the log, the index records size -1), CRC
verification on read, and load-time integrity checking that truncates torn
tail appends.
"""

from __future__ import annotations

import os
import threading
import time

from . import types as t
from .idx import IndexWriter, walk_index_file
from .needle import Needle, actual_size, body_length
from .needle_map import NeedleMap
from .super_block import CURRENT_VERSION, SUPER_BLOCK_SIZE, SuperBlock


class Volume:
    def __init__(self, directory: str, collection: str, volume_id: int,
                 super_block: SuperBlock | None = None):
        self.directory = directory
        self.collection = collection
        self.volume_id = volume_id
        self.read_only = False
        self._lock = threading.RLock()
        base = self.file_name()
        is_new = not os.path.exists(base + ".dat")
        self.super_block = super_block or SuperBlock()
        self._dat = open(base + ".dat", "a+b")
        if is_new:
            self._dat.write(self.super_block.to_bytes())
            self._dat.flush()
        else:
            self._dat.seek(0)
            self.super_block = SuperBlock.from_bytes(self._dat.read(64))
        self.version = self.super_block.version
        self.needle_map = (
            NeedleMap.load_from_idx(base + ".idx")
            if os.path.exists(base + ".idx")
            else NeedleMap()
        )
        self.check_and_fix_integrity()
        self._idx = IndexWriter(base + ".idx")

    # -- naming -----------------------------------------------------------

    def file_name(self) -> str:
        name = f"{self.volume_id}"
        if self.collection:
            name = f"{self.collection}_{name}"
        return os.path.join(self.directory, name)

    # -- write path -------------------------------------------------------

    def append_needle(self, n: Needle) -> tuple[int, int]:
        """Append; returns (actual_offset, stored_size)."""
        with self._lock:
            if self.read_only:
                raise PermissionError(f"volume {self.volume_id} is read-only")
            self._dat.seek(0, os.SEEK_END)
            offset = self._dat.tell()
            if offset % t.NEEDLE_PADDING_SIZE:  # heal torn tail
                pad = t.NEEDLE_PADDING_SIZE - offset % t.NEEDLE_PADDING_SIZE
                self._dat.write(b"\0" * pad)
                offset += pad
            if offset >= t.MAX_POSSIBLE_VOLUME_SIZE:
                raise IOError("volume size limit exceeded")
            if not n.append_at_ns:
                n.append_at_ns = time.time_ns()
            blob = n.to_bytes(self.version)
            self._dat.write(blob)
            self._dat.flush()
            old = self.needle_map.get(n.id)
            if old is None or old.offset < offset:
                self.needle_map.put(n.id, offset, n.size)
                self._idx.put(n.id, offset, n.size)
            return offset, n.size

    def delete_needle(self, needle_id: int) -> int:
        """Append a tombstone marker needle; returns freed byte count."""
        with self._lock:
            existing = self.needle_map.get(needle_id)
            if existing is None:
                return 0
            marker = Needle(id=needle_id, cookie=0, data=b"")
            self._dat.seek(0, os.SEEK_END)
            offset = self._dat.tell()
            marker.append_at_ns = time.time_ns()
            self._dat.write(marker.to_bytes(self.version))
            self._dat.flush()
            self.needle_map.delete(needle_id)
            self._idx.delete(needle_id, offset)
            return max(existing.size, 0)

    # -- read path --------------------------------------------------------

    def read_needle(self, needle_id: int, expected_cookie: int | None = None) -> Needle:
        with self._lock:
            nv = self.needle_map.get(needle_id)
            if nv is None or t.size_is_deleted(nv.size):
                raise KeyError(f"needle {needle_id:x} not found")
            self._dat.seek(nv.offset)
            blob = self._dat.read(actual_size(nv.size, self.version))
        n = Needle.from_bytes(blob, self.version)
        if n.size != nv.size:
            raise IOError("size mismatch reading needle")
        if expected_cookie is not None and n.cookie != expected_cookie:
            raise PermissionError("cookie mismatch")
        return n

    # -- stats / lifecycle ------------------------------------------------

    def flush(self) -> None:
        """Fence buffered appends so other handles see consistent
        .dat/.idx files (bulk copy streams them by path)."""
        with self._lock:
            self._dat.flush()
            self._idx.flush()

    @property
    def content_size(self) -> int:
        self._dat.seek(0, os.SEEK_END)
        return self._dat.tell()

    def garbage_level(self) -> float:
        size = self.content_size
        return self.needle_map.deleted_bytes / size if size else 0.0

    def file_count(self) -> int:
        return len(self.needle_map)

    def sync(self) -> None:
        with self._lock:
            self._dat.flush()
            os.fsync(self._dat.fileno())
            self._idx.flush()

    def close(self) -> None:
        with self._lock:
            self._dat.flush()
            self._dat.close()
            self._idx.close()

    # -- integrity --------------------------------------------------------

    def check_and_fix_integrity(self) -> None:
        """Verify the last index entry matches the .dat; truncate torn tails.

        Reference: CheckAndFixVolumeDataIntegrity (volume_checking.go:17) —
        the last entry's record must lie fully inside the file and carry the
        expected needle id; otherwise the torn tail is truncated away.
        """
        self._dat.seek(0, os.SEEK_END)
        file_size = self._dat.tell()
        last = None
        for v in self.needle_map.items_ascending():
            if last is None or v.offset > last.offset:
                last = v
        if last is None:
            return
        end = last.offset + actual_size(max(last.size, 0), self.version)
        if end > file_size:
            # torn append: drop the entry and truncate to the previous record
            self.needle_map.delete(last.key)
            self._dat.truncate(last.offset)
            return
        self._dat.seek(last.offset)
        hdr = self._dat.read(t.NEEDLE_HEADER_SIZE)
        if len(hdr) == t.NEEDLE_HEADER_SIZE:
            n = Needle.parse_header(hdr)
            if n.id != last.key:
                self.needle_map.delete(last.key)
