"""S3 remote tier: BackendStorage over any S3-compatible endpoint.

Reference: weed/storage/backend/s3_backend/ (aws-sdk based).  Here the
client is a minimal SigV4-signing HTTP client built on the SAME signing
primitives the gateway verifies with (s3api/auth.py) — so the tier can
target any S3 service, including this framework's own gateway (the
cluster test does exactly that: a volume's .dat tiers into a bucket
served by the same cluster).
"""

from __future__ import annotations

import datetime
import hashlib
import urllib.error
import urllib.parse
import urllib.request

from ..s3api import auth as s3auth
from ..util import glog
from .backend import BackendStorage, register_backend


class S3Backend(BackendStorage):
    def __init__(self, backend_id: str, endpoint: str, bucket: str,
                 access_key: str = "", secret_key: str = "",
                 region: str = "us-east-1"):
        super().__init__("s3", backend_id)
        self.endpoint = endpoint.rstrip("/")  # e.g. http://127.0.0.1:8333
        self.bucket = bucket
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region

    # -- signed request plumbing ------------------------------------------

    def _request(self, method: str, key: str, data: bytes | None = None,
                 headers: dict | None = None, query: str = "",
                 timeout: float = 60.0):
        path = f"/{self.bucket}/{urllib.parse.quote(key)}"
        url = f"{self.endpoint}{path}" + (f"?{query}" if query else "")
        headers = dict(headers or {})
        host = urllib.parse.urlparse(self.endpoint).netloc
        payload_hash = hashlib.sha256(data or b"").hexdigest()
        if self.access_key:
            now = datetime.datetime.now(datetime.timezone.utc)
            amz_date = now.strftime("%Y%m%dT%H%M%SZ")
            date = now.strftime("%Y%m%d")
            headers["x-amz-date"] = amz_date
            headers["x-amz-content-sha256"] = payload_hash
            signed = sorted(
                {"host", "x-amz-date", "x-amz-content-sha256"}
                | {k.lower() for k in headers if k.lower().startswith("x-amz")}
            )
            canon_headers = {k.lower(): v for k, v in headers.items()}
            canon_headers["host"] = host
            canon = s3auth.canonical_request(
                method, path, query, canon_headers, signed, payload_hash
            )
            sig = s3auth.sign_v4(
                self.secret_key, date, self.region, "s3", amz_date, canon
            )
            headers["Authorization"] = (
                f"AWS4-HMAC-SHA256 Credential={self.access_key}/{date}/"
                f"{self.region}/s3/aws4_request, "
                f"SignedHeaders={';'.join(signed)}, Signature={sig}"
            )
        req = urllib.request.Request(url, data=data, method=method,
                                     headers=headers)
        return urllib.request.urlopen(req, timeout=timeout)

    # -- BackendStorage interface -----------------------------------------

    def upload_file(self, local_path: str, key: str, progress=None,
                    part_size: int = 8 << 20) -> int:
        """Whole-object PUT streamed from disk in memory-bounded parts via
        the gateway's multipart API when the file is large."""
        import os

        total = os.path.getsize(local_path)
        with open(local_path, "rb") as f:
            if total <= part_size:
                with self._request("PUT", key, f.read()):
                    pass
                if progress:
                    progress(total)
                return total
            upload_id = self._initiate_multipart(key)
            etags = []
            sent = 0
            part = 1
            try:
                while True:
                    blob = f.read(part_size)
                    if not blob:
                        break
                    with self._request(
                        "PUT", key, blob,
                        query=f"partNumber={part}&uploadId={upload_id}",
                    ) as r:
                        etags.append(r.headers.get("ETag", "").strip('"'))
                    sent += len(blob)
                    part += 1
                    if progress:
                        progress(sent)
                self._complete_multipart(key, upload_id, etags)
            except Exception:
                try:
                    with self._request("DELETE", key,
                                       query=f"uploadId={upload_id}"):
                        pass
                except urllib.error.URLError:
                    glog.warning("s3 tier: abort multipart %s failed", key)
                raise
        return total

    def _initiate_multipart(self, key: str) -> str:
        import xml.etree.ElementTree as ET

        with self._request("POST", key, query="uploads") as r:
            root = ET.fromstring(r.read())
        for el in root.iter():
            if el.tag.endswith("UploadId"):
                return el.text or ""
        raise IOError("no UploadId in InitiateMultipartUpload response")

    def _complete_multipart(self, key: str, upload_id: str,
                            etags: list[str]) -> None:
        body = "<CompleteMultipartUpload>" + "".join(
            f"<Part><PartNumber>{i + 1}</PartNumber><ETag>{e}</ETag></Part>"
            for i, e in enumerate(etags)
        ) + "</CompleteMultipartUpload>"
        with self._request("POST", key, body.encode(),
                           query=f"uploadId={upload_id}"):
            pass

    def download_file(self, key: str, local_path: str, progress=None,
                      chunk: int = 8 << 20) -> int:
        got = 0
        with self._request("GET", key) as r, open(local_path, "wb") as f:
            while True:
                blob = r.read(chunk)
                if not blob:
                    break
                f.write(blob)
                got += len(blob)
                if progress:
                    progress(got)
        return got

    def delete_file(self, key: str) -> None:
        try:
            with self._request("DELETE", key):
                pass
        except urllib.error.HTTPError as e:
            if e.code != 404:
                raise

    def read_range(self, key: str, offset: int, size: int) -> bytes:
        with self._request(
            "GET", key,
            headers={"Range": f"bytes={offset}-{offset + size - 1}"},
        ) as r:
            return r.read()


def make_s3_backend(backend_id: str, conf: dict) -> S3Backend:
    """Build + register from a config dict (the [storage.backend.s3.<id>]
    TOML table: endpoint, bucket, access_key, secret_key, region)."""
    b = S3Backend(
        backend_id,
        endpoint=conf.get("endpoint", ""),
        bucket=conf.get("bucket", ""),
        access_key=conf.get("access_key", conf.get("aws_access_key_id", "")),
        secret_key=conf.get("secret_key", conf.get("aws_secret_access_key", "")),
        region=conf.get("region", "us-east-1"),
    )
    register_backend(b)
    return b
