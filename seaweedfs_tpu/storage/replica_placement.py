"""Replica placement: the 'XYZ' digit policy byte.

Reference: weed/storage/super_block/replica_placement.go — digit 0 is copies
in other data centers, digit 1 other racks, digit 2 same rack.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class ReplicaPlacement:
    same_rack: int = 0
    diff_rack: int = 0
    diff_dc: int = 0

    @classmethod
    def parse(cls, s: str) -> "ReplicaPlacement":
        vals = [0, 0, 0]
        for i, ch in enumerate(s[:3]):
            d = ord(ch) - ord("0")
            if not 0 <= d <= 2:
                raise ValueError(f"unknown replication type {s!r}")
            vals[i] = d
        return cls(diff_dc=vals[0], diff_rack=vals[1], same_rack=vals[2])

    @classmethod
    def from_byte(cls, b: int) -> "ReplicaPlacement":
        return cls.parse(f"{b:03d}")

    def to_byte(self) -> int:
        return self.diff_dc * 100 + self.diff_rack * 10 + self.same_rack

    def copy_count(self) -> int:
        return self.diff_dc + self.diff_rack + self.same_rack + 1

    def __str__(self) -> str:
        return f"{self.diff_dc}{self.diff_rack}{self.same_rack}"
