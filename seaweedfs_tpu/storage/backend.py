"""Tiered storage backend: the seam between a Volume and its bytes.

Reference: weed/storage/backend/backend.go:15-48 — `BackendStorageFile`
(ReadAt/WriteAt/Truncate/Close/Name/Sync) is what a Volume reads and
writes through; `BackendStorage` is a named remote tier (the reference
ships an S3 tier) that can hold a volume's `.dat` while the index stays
local.  A volume moved to a remote tier is read-only: reads go through
ranged requests (with a block cache), writes require `tier.download`
back to disk first.

Backends register under "<type>.<id>" names (backend.go:32-46, config
from `[storage.backend]` in master.toml); see backend_s3.py for the S3
implementation that can target any S3 endpoint — including this
framework's own gateway.
"""

from __future__ import annotations

import os
import threading
from abc import ABC, abstractmethod
from collections import OrderedDict


class BackendStorageFile(ABC):
    """Byte-addressed file the Volume reads/writes through."""

    name: str = ""

    @abstractmethod
    def read_at(self, offset: int, size: int) -> bytes: ...

    def pread(self, offset: int, size: int) -> bytes:
        """Positioned read safe for concurrent callers.  The default
        delegates to read_at; DiskFile overrides with a true lock-free
        os.pread so reads on one volume don't serialize."""
        return self.read_at(offset, size)

    @abstractmethod
    def write_at(self, offset: int, data: bytes) -> int: ...

    @abstractmethod
    def file_size(self) -> int: ...

    @abstractmethod
    def truncate(self, size: int) -> None: ...

    def sync(self) -> None:
        pass

    def close(self) -> None:
        pass

    @property
    def is_remote(self) -> bool:
        return False


class DiskFile(BackendStorageFile):
    """Plain local file (backend/disk_file.go)."""

    def __init__(self, path: str):
        self.name = path
        new = not os.path.exists(path)
        self._f = open(path, "w+b" if new else "r+b")
        self._lock = threading.Lock()

    def read_at(self, offset: int, size: int) -> bytes:
        with self._lock:
            self._f.seek(offset)
            return self._f.read(size)

    def pread(self, offset: int, size: int) -> bytes:
        """Lock-free positioned read: os.pread shares no file-position
        state, so concurrent GETs on one volume proceed in parallel.
        Racing handle swaps (vacuum commit, tier moves) surface as
        OSError/ValueError on the closed fd — Volume.read_needle falls
        back to the locked path, where it re-reads the fresh handle."""
        f = self._f
        if f.closed:
            raise ValueError(f"{self.name}: file closed")
        return os.pread(f.fileno(), size, offset)

    def fileno(self) -> int:
        """Raw fd for zero-copy serving (os.sendfile).  Callers that
        outlive the volume lock must os.dup() it so a racing handle swap
        (vacuum commit) can neither close it mid-send nor let the kernel
        recycle the number onto another file."""
        f = self._f
        if f.closed:
            raise ValueError(f"{self.name}: file closed")
        return f.fileno()

    def write_at(self, offset: int, data: bytes) -> int:
        """-> bytes actually written.  The `disk.write` faultpoint family
        fires here (storage/disk_health.py): error/enospc/partial raise a
        classified OSError (enospc/partial after landing a TORN half),
        short silently truncates — so every caller's rollback and the
        load-time torn-tail healer can be exercised without a real dying
        disk."""
        from .disk_health import inject_write_fault

        with self._lock:
            data = inject_write_fault(self.name, self._f, offset, data)
            self._f.seek(offset)
            self._f.write(data)
            self._f.flush()
            return len(data)

    def append(self, data: bytes) -> int:
        """-> offset the data landed at."""
        with self._lock:
            self._f.seek(0, os.SEEK_END)
            offset = self._f.tell()
            self._f.write(data)
            self._f.flush()
            return offset

    def file_size(self) -> int:
        with self._lock:
            self._f.seek(0, os.SEEK_END)
            return self._f.tell()

    def truncate(self, size: int) -> None:
        with self._lock:
            self._f.truncate(size)

    def sync(self) -> None:
        with self._lock:
            self._f.flush()
            os.fsync(self._f.fileno())

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.flush()
                self._f.close()


class BackendStorage(ABC):
    """A named remote tier (backend.go:48): upload/download/delete whole
    volume files plus ranged reads for serving."""

    def __init__(self, backend_type: str, backend_id: str):
        self.backend_type = backend_type
        self.backend_id = backend_id

    @property
    def name(self) -> str:
        return f"{self.backend_type}.{self.backend_id}"

    @abstractmethod
    def upload_file(self, local_path: str, key: str,
                    progress=None) -> int: ...

    @abstractmethod
    def download_file(self, key: str, local_path: str,
                      progress=None) -> int: ...

    @abstractmethod
    def delete_file(self, key: str) -> None: ...

    @abstractmethod
    def read_range(self, key: str, offset: int, size: int) -> bytes: ...

    def remote_file(self, key: str, size: int) -> "RemoteBackendFile":
        return RemoteBackendFile(self, key, size)


class RemoteBackendFile(BackendStorageFile):
    """Read-only view of a remote-tier object with an LRU block cache so
    needle reads don't pay one ranged request per header+body."""

    BLOCK = 1 << 20

    def __init__(self, backend: BackendStorage, key: str, size: int,
                 cache_blocks: int = 32):
        self.backend = backend
        self.key = key
        self.name = f"{backend.name}/{key}"
        self._size = size
        self._cache: OrderedDict[int, bytes] = OrderedDict()
        self._cache_blocks = cache_blocks
        self._lock = threading.Lock()

    @property
    def is_remote(self) -> bool:
        return True

    def _block(self, idx: int) -> bytes:
        with self._lock:
            blk = self._cache.get(idx)
            if blk is not None:
                self._cache.move_to_end(idx)
                return blk
        lo = idx * self.BLOCK
        n = min(self.BLOCK, self._size - lo)
        blk = self.backend.read_range(self.key, lo, n)
        with self._lock:
            self._cache[idx] = blk
            while len(self._cache) > self._cache_blocks:
                self._cache.popitem(last=False)
        return blk

    def read_at(self, offset: int, size: int) -> bytes:
        if offset >= self._size:
            return b""
        size = min(size, self._size - offset)
        out = bytearray()
        while size > 0:
            idx, within = divmod(offset, self.BLOCK)
            blk = self._block(idx)
            piece = blk[within : within + size]
            if not piece:
                break
            out += piece
            offset += len(piece)
            size -= len(piece)
        return bytes(out)

    def write_at(self, offset: int, data: bytes) -> int:
        raise PermissionError(f"{self.name}: remote-tier volumes are read-only")

    def file_size(self) -> int:
        return self._size

    def truncate(self, size: int) -> None:
        raise PermissionError(f"{self.name}: remote-tier volumes are read-only")


# -- registry ----------------------------------------------------------------

_BACKENDS: dict[str, BackendStorage] = {}
_REG_LOCK = threading.Lock()


def register_backend(backend: BackendStorage) -> None:
    with _REG_LOCK:
        _BACKENDS[backend.name] = backend


def get_backend(name: str) -> BackendStorage | None:
    with _REG_LOCK:
        return _BACKENDS.get(name)


def configured_backends() -> list[str]:
    with _REG_LOCK:
        return sorted(_BACKENDS)
