from . import types  # noqa: F401
from .needle import CorruptNeedleError, Needle  # noqa: F401
from .needle_map import NeedleMap, NeedleValue  # noqa: F401
from .replica_placement import ReplicaPlacement  # noqa: F401
from .super_block import (  # noqa: F401
    CURRENT_VERSION,
    VERSION1,
    VERSION2,
    VERSION3,
    SuperBlock,
)
from .ttl import TTL  # noqa: F401
