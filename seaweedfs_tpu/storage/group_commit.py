"""Group-commit flush barrier: ONE fsync acks a whole batch of appends.

Durability modes for a Volume (``SEAWEEDFS_TPU_DURABILITY``):

  none   (default) today's behavior — mutations reach the kernel page
         cache per write, fsync only on explicit Volume.sync(); a crash
         can lose recently-acked writes (the torture harness acks after
         an explicit sync for exactly this reason).
  sync   strict strawman: every mutation pays its own fsync pair
         (.dat + .idx) before the ack.  Durable but serial — the A/B
         baseline the batch mode is measured against.
  batch  group commit: concurrent mutations land their bytes in the
         .dat/.idx under the volume lock, then park on this barrier.
         The first parker becomes the flush LEADER: it waits up to
         ``SEAWEEDFS_TPU_FSYNC_MAX_DELAY_MS`` (default ~2ms) for up to
         ``SEAWEEDFS_TPU_FSYNC_MAX_BATCH`` (default 64) mutations to
         accumulate, fsyncs the .dat and .idx ONCE, publishes every
         batched entry to the needle map in append order, and wakes the
         waiters.  No ack and no needle-map publish happen before the
         barrier's fsync — the PR 14 contract (a crash loses only
         unacked writes; acked writes are remount-provable via the
         .idx) holds with N writers sharing one fsync.

Failure discipline: if the barrier's fsync fails, the WHOLE batch (plus
anything queued behind it — their bytes sit above the rollback point)
rolls back through Volume._fail_write: the .dat and .idx truncate to
the lowest pre-mutation positions, the error is classified
(DiskFullError/DiskFailingError), ENOSPC flips the volume
read-only-full, and every parked writer gets the typed error.  Nothing
was published, so no reader ever saw the rolled-back needles.
"""

from __future__ import annotations

import os
import threading
import time

from ..stats.metrics import (
    FSYNC_BATCH_COMMITS,
    FSYNC_BATCH_SIZE,
    FSYNC_BATCH_WRITES,
)
from ..util import glog

# a parked writer waits this long for its barrier before giving up: far
# above any sane fsync, so it only fires if the leader thread died
_PARK_TIMEOUT_S = 60.0


def batch_knobs() -> tuple[int, float]:
    """(max_batch, max_delay_seconds) from the env, clamped sane."""
    try:
        max_batch = int(os.environ.get("SEAWEEDFS_TPU_FSYNC_MAX_BATCH", "64"))
    except ValueError:
        max_batch = 64
    try:
        delay_ms = float(
            os.environ.get("SEAWEEDFS_TPU_FSYNC_MAX_DELAY_MS", "2"))
    except ValueError:
        delay_ms = 2.0
    return max(1, max_batch), max(0.0, delay_ms) / 1e3


class Pending:
    """One parked mutation: its publish thunk + rollback positions."""

    __slots__ = ("publish", "dat_start", "idx_start", "done", "error")

    def __init__(self, publish, dat_start: int, idx_start: int | None):
        self.publish = publish
        self.dat_start = dat_start
        self.idx_start = idx_start
        self.done = threading.Event()
        self.error: BaseException | None = None


class GroupCommitter:
    """Leader-elected flush barrier for one Volume."""

    def __init__(self, volume):
        self._volume = volume
        self.max_batch, self.max_delay = batch_knobs()
        self._cv = threading.Condition()
        self._queue: list[Pending] = []
        self._flushing = False

    def park(self, p: Pending) -> None:
        """Block until `p` is fsync-durable and published (or its batch
        rolled back, in which case the typed error re-raises here).
        Call with NO volume lock held — the whole point is that other
        writers append while this one waits."""
        with self._cv:
            self._queue.append(p)
            self._cv.notify()
            if not self._flushing:
                self._flushing = True
                leader = True
            else:
                leader = False
        if leader:
            self._lead()
        if not p.done.wait(_PARK_TIMEOUT_S):
            raise IOError(
                f"volume {self._volume.volume_id}: flush barrier timed out")
        if p.error is not None:
            raise p.error

    # -- leader -----------------------------------------------------------

    def _lead(self) -> None:
        v = self._volume
        while True:
            with self._cv:
                deadline = time.monotonic() + self.max_delay
                while len(self._queue) < self.max_batch:
                    left = deadline - time.monotonic()
                    if left <= 0:
                        break
                    self._cv.wait(left)
                batch = self._queue
                self._queue = []
            if batch:
                try:
                    v._dat.sync()
                    v._idx.flush()
                except (OSError, ValueError) as e:
                    # ValueError = fsync raced a handle swap/close; feed
                    # the health machine an EIO-shaped error either way
                    if not isinstance(e, OSError):
                        e = OSError(5, str(e))
                    self._fail(batch, e)
                else:
                    self._commit(batch)
            with self._cv:
                if not self._queue:
                    self._flushing = False
                    return
                # entries arrived during the fsync: run another round

    def _commit(self, batch: list[Pending]) -> None:
        with self._volume._lock:
            for p in batch:  # append order: later offsets win in the map
                try:
                    p.publish()
                except Exception as e:  # noqa: BLE001 — isolate waiters
                    p.error = e
        FSYNC_BATCH_COMMITS.inc()
        FSYNC_BATCH_WRITES.inc(len(batch))
        FSYNC_BATCH_SIZE.observe(len(batch))
        for p in batch:
            p.done.set()

    def _fail(self, batch: list[Pending], e: OSError) -> None:
        # anything queued behind the failed batch has bytes ABOVE the
        # rollback point — it must fail (and roll back) with it
        with self._cv:
            batch = batch + self._queue
            self._queue = []
        dat_start = min(p.dat_start for p in batch)
        idx_starts = [p.idx_start for p in batch if p.idx_start is not None]
        idx_start = min(idx_starts) if idx_starts else None
        v = self._volume
        glog.warning(
            "volume %d: group-commit fsync failed (%s); rolling back "
            "%d parked mutation(s) to dat=%d", v.volume_id, e,
            len(batch), dat_start)
        with v._lock:
            typed = v._fail_write(e, dat_start, idx_start)
        for p in batch:
            p.error = typed
            p.done.set()
