"""Self-healing integrity plane, volume-server side: the scrub daemon.

Proactive silent-corruption detection for sealed data.  A background
thread walks

  * every volume's needles — each record is re-read from the .dat and its
    CRC verified against the index entry (the load-time torn-tail check
    in volume.py only inspects the LAST record; scrub covers the body),
  * every EC volume's shards — RS(10,4) parity is recomputed over sampled
    intervals through the shared codec service (the TPU does the
    verification matmul when one is reachable) and compared byte-for-byte
    against the stored parity shards, with a consistency probe that
    localizes WHICH shard is rotten,
  * each volume's on-disk .idx — and when the index itself fails
    verification, the scrubber's last resort is the offline idx rebuild
    (`tools/offline.fix_index`, the `weed fix` equivalent) + reload.

Everything runs under a token-bucket bytes/s throttle
(SEAWEEDFS_TPU_SCRUB_RATE_MBPS) that additionally backs off while the
PR 5 executor queue-depth gauges show the serving pools saturated —
arXiv:1709.05365's lesson that background EC I/O must be rate-governed
or it starves foreground reads.  Per-volume cursors persist to a JSON
file in each disk location so a restart resumes instead of rescanning.

Findings are quarantined (bounded per-volume suspect sets the read path
also feeds) and ride the next heartbeat to the master, whose maintenance
repair pass re-copies corrupt replicas / rebuilds corrupt shards.
"""

from __future__ import annotations

import json
import os
import struct
import threading
import time

import numpy as np

from ..ops import codec_service, gf256
from ..ops.codec import get_codec
from ..stats.metrics import (
    EXECUTOR_QUEUE_DEPTH,
    SCRUB_BYTES,
    SCRUB_ERRORS,
    SCRUB_NEEDLES,
    SCRUB_REPAIRS,
)
from ..util import faultpoint, glog
from . import types as t
from .ec.constants import DATA_SHARDS, TOTAL_SHARDS
from .idx import walk_index_file
from .needle import CorruptNeedleError, Needle, actual_size

# chaos points: `scrub.read` fires before every scrubber disk read,
# `scrub.verify` passes the just-read bytes through (so `partial` mode
# models a torn read reaching the verifier)
FP_SCRUB_READ = faultpoint.register("scrub.read")
FP_SCRUB_VERIFY = faultpoint.register("scrub.verify")

RATE_ENV = "SEAWEEDFS_TPU_SCRUB_RATE_MBPS"
INTERVAL_ENV = "SEAWEEDFS_TPU_SCRUB_INTERVAL_S"
EC_INTERVAL_ENV = "SEAWEEDFS_TPU_SCRUB_EC_INTERVAL_KB"
BACKOFF_DEPTH_ENV = "SEAWEEDFS_TPU_SCRUB_BACKOFF_QUEUE_DEPTH"

CURSOR_FILE = "scrub.cursor.json"


class TokenBucket:
    """Bytes/s throttle: consume() blocks until the bucket covers `n`.

    Capacity is one second of rate, so a cold start can burst at most
    1s worth — the measured rate over any window >= a few seconds stays
    within ~2x of the configured rate (the acceptance bound).  A single
    read LARGER than the capacity is granted once the bucket is full and
    charged as debt (tokens go negative), so later reads pay it back —
    the bucket never deadlocks on an oversized needle.
    """

    def __init__(self, rate_bytes_s: float):
        self._lock = threading.Lock()
        self._rate = max(float(rate_bytes_s), 1.0)
        self._tokens = self._rate  # full bucket: first read never stalls
        self._last = time.monotonic()

    def set_rate(self, rate_bytes_s: float) -> None:
        with self._lock:
            self._rate = max(float(rate_bytes_s), 1.0)
            self._tokens = min(self._tokens, self._rate)

    @property
    def rate(self) -> float:
        return self._rate

    def consume(self, n: int, stop: "threading.Event | None" = None) -> float:
        """Block until `n` bytes of budget exist; returns seconds waited."""
        waited = 0.0
        while True:
            with self._lock:
                now = time.monotonic()
                self._tokens = min(
                    self._rate, self._tokens + (now - self._last) * self._rate
                )
                self._last = now
                if self._tokens >= n or (
                    n > self._rate and self._tokens >= self._rate
                ):
                    # oversized n: grant at full bucket, go into debt
                    self._tokens -= n
                    return waited
                need = (min(n, self._rate) - self._tokens) / self._rate
            step = min(max(need, 0.01), 0.2)
            if stop is not None and stop.wait(step):
                return waited
            if stop is None:
                time.sleep(step)
            waited += step


class Quarantine:
    """Bounded per-volume suspect sets fed by the read path and the
    scrubber.  A suspect entry means "a CRC failed here at least once";
    the scrubber confirms (-> finding -> repair) or clears (transient)."""

    MAX_PER_VOLUME = 1024

    def __init__(self):
        self._lock = threading.Lock()
        self._needles: dict[int, set[int]] = {}
        self._shards: dict[int, set[int]] = {}

    def _mark(self, table: dict, vid: int, member: int) -> bool:
        with self._lock:
            s = table.setdefault(vid, set())
            if member in s:
                return False
            if len(s) >= self.MAX_PER_VOLUME:
                return False  # bounded: beyond this the volume itself is toast
            s.add(member)
            return True

    def mark_needle(self, vid: int, needle_id: int) -> bool:
        return self._mark(self._needles, vid, needle_id)

    def mark_shard(self, vid: int, shard_id: int) -> bool:
        return self._mark(self._shards, vid, shard_id)

    def clear_needle(self, vid: int, needle_id: int) -> None:
        with self._lock:
            self._needles.get(vid, set()).discard(needle_id)

    def clear_shard(self, vid: int, shard_id: int) -> None:
        with self._lock:
            self._shards.get(vid, set()).discard(shard_id)

    def drop_volume(self, vid: int) -> None:
        with self._lock:
            self._needles.pop(vid, None)
            self._shards.pop(vid, None)

    def is_needle_suspect(self, vid: int, needle_id: int) -> bool:
        with self._lock:
            return needle_id in self._needles.get(vid, ())

    def status(self) -> dict:
        with self._lock:
            return {
                "needles": {str(v): sorted(s) for v, s in
                            self._needles.items() if s},
                "shards": {str(v): sorted(s) for v, s in
                           self._shards.items() if s},
            }


def _saturation() -> float:
    """Max queue depth across every metered SERVING pool — the PR 5
    saturation signal background work backs off on.  The lifecycle
    controller's own worker pool is excluded: its queued background
    jobs are not foreground pressure, and counting them would let a
    deep lifecycle backlog stall the very workers draining it."""
    with EXECUTOR_QUEUE_DEPTH._lock:
        items = list(EXECUTOR_QUEUE_DEPTH._children.items())
    return max((c.value for k, c in items if k[0] != "lifecycle"),
               default=0.0)


class Scrubber:
    """Per-store scrub daemon + on-demand scan entry points."""

    def __init__(self, store, rate_mbps: float | None = None,
                 interval_s: float | None = None):
        self.store = store
        if rate_mbps is None:
            rate_mbps = float(os.environ.get(RATE_ENV, "4"))
        if interval_s is None:
            interval_s = float(os.environ.get(INTERVAL_ENV, "300"))
        self.rate_mbps = rate_mbps
        self.interval_s = interval_s
        self.ec_interval = max(
            int(float(os.environ.get(EC_INTERVAL_ENV, "256"))) << 10, 4096)
        self.backoff_depth = float(os.environ.get(BACKOFF_DEPTH_ENV, "8"))
        # rate<=0 disables the DAEMON only; on-demand scans then run
        # unthrottled (a 1-byte/s floor would wedge them instead)
        self._default_rate = (rate_mbps * (1 << 20) if rate_mbps > 0
                              else float(1 << 40))
        # the node's own configured rate, kept so a withdrawn cluster
        # budget (master push of 0) can restore it
        self._local_rate = self._default_rate
        # flips True while the master pushes a cluster background budget
        # (HeartbeatResponse.lifecycle_rate_mbps); gates whether tier
        # uploads charge the shared bucket
        self._shared_budget = False
        self.bucket = TokenBucket(self._default_rate)
        self.quarantine = Quarantine()
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread: threading.Thread | None = None
        self._lock = threading.Lock()
        # outstanding confirmed findings, keyed (vid, kind, shard,
        # needle): re-delivered on EVERY full heartbeat until the target
        # verifies healthy (or the volume/shard is remounted by a
        # repair) — a beat that dies mid-send loses nothing
        self._outstanding: dict[tuple, dict] = {}
        self._recent: list[dict] = []     # kept for status / the scrub rpc
        self._confirm_q: list[dict] = []  # read-path suspicions to verify
        self._cursors: dict[str, dict] = {}  # directory -> {"volume": {...}}
        self._counts = {
            "passes": 0, "scanned_needles": 0, "scanned_bytes": 0,
            "corrupt_needles": 0, "corrupt_shards": 0, "index_repairs": 0,
            "backoff_seconds": 0.0, "confirms": 0,
        }
        self._last_pass_started = 0.0
        self._last_pass_seconds = 0.0
        self._load_cursors()

    # -- lifecycle --------------------------------------------------------

    @property
    def enabled(self) -> bool:
        return self.rate_mbps > 0

    def set_shared_rate(self, rate_mbps: float) -> None:
        """Adopt (or drop) the master-pushed cluster background-I/O
        budget (HeartbeatResponse.lifecycle_rate_mbps): scrub reads AND
        lifecycle tier uploads drain this ONE bucket, so their combined
        rate on a node stays within the budget.  Overrides the local
        SEAWEEDFS_TPU_SCRUB_RATE_MBPS default while pushed; a push of 0
        (master unthrottled / flag removed) restores the local default
        instead of latching the stale budget forever."""
        if rate_mbps <= 0:
            if self._shared_budget:
                glog.info("scrub: cluster background budget withdrawn; "
                          "restoring local default %.1f MB/s",
                          self._local_rate / (1 << 20))
                self._shared_budget = False
                self._default_rate = self._local_rate
                self.bucket.set_rate(self._local_rate)
            return
        rate = rate_mbps * (1 << 20)
        if rate == self._default_rate and self._shared_budget:
            return
        glog.info("scrub: adopting cluster background budget %.1f MB/s "
                  "(was %.1f)", rate_mbps, self._default_rate / (1 << 20))
        self._default_rate = rate
        self._shared_budget = True
        self.bucket.set_rate(rate)

    def throttle_background(self, n: int) -> None:
        """Charge `n` bytes of non-scrub background I/O (tier uploads)
        to the shared bucket — only once the master has pushed an
        explicit cluster budget; without one, manual tier uploads stay
        unthrottled as before (the scrub default rate is sized for
        scrub reads, not for moving whole volumes)."""
        if n > 0 and self._shared_budget:
            self.bucket.consume(n, stop=self._stop)

    def start(self) -> None:
        if not self.enabled or self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="scrub-daemon", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        self._save_cursors()

    def _loop(self) -> None:
        next_pass = time.monotonic() + self.interval_s
        while not self._stop.is_set():
            self._wake.wait(timeout=1.0)
            self._wake.clear()
            if self._stop.is_set():
                return
            try:
                self._confirm_pending()
                if time.monotonic() >= next_pass:
                    self.scrub_once()
                    next_pass = time.monotonic() + self.interval_s
            except Exception as e:  # the daemon must survive, not go mute
                glog.warning("scrub pass failed: %s", e)
                next_pass = time.monotonic() + self.interval_s

    # -- cursors ----------------------------------------------------------

    def _cursor_path(self, directory: str) -> str:
        return os.path.join(directory, CURSOR_FILE)

    def _load_cursors(self) -> None:
        for loc in self.store.locations:
            try:
                with open(self._cursor_path(loc.directory)) as f:
                    self._cursors[loc.directory] = json.load(f)
            except (OSError, ValueError):
                self._cursors[loc.directory] = {"volume": {}, "ec": {}}

    def _save_cursors(self) -> None:
        for loc in self.store.locations:
            cur = self._cursors.get(loc.directory)
            if cur is None:
                continue
            path = self._cursor_path(loc.directory)
            try:
                tmp = path + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(cur, f)
                os.replace(tmp, path)
            except OSError as e:
                glog.warning("scrub cursor save failed for %s: %s",
                             loc.directory, e)

    def _cursor(self, directory: str, kind: str, vid: int) -> int:
        return int(self._cursors.get(directory, {}).get(kind, {})
                   .get(str(vid), 0))

    def _set_cursor(self, directory: str, kind: str, vid: int,
                    value: int) -> None:
        self._cursors.setdefault(
            directory, {"volume": {}, "ec": {}}
        ).setdefault(kind, {})[str(vid)] = int(value)

    # -- findings ---------------------------------------------------------

    MAX_OUTSTANDING = 4096

    def _report(self, vid: int, kind: str, shard_id: int = 0,
                needle_id: int = 0, detail: str = "") -> None:
        key = (vid, kind, shard_id, needle_id)
        with self._lock:
            if key in self._outstanding:
                return
            # bounded: one repair clears a whole volume's entries; a
            # flood beyond this is one rotten disk, not 4096 findings
            if len(self._outstanding) >= self.MAX_OUTSTANDING:
                return
            finding = {
                "volume_id": vid, "kind": kind, "shard_id": shard_id,
                "needle_id": needle_id, "detail": detail,
                "detected_at_ms": int(time.time() * 1000),
            }
            self._outstanding[key] = finding
            self._recent.append(finding)
            del self._recent[:-256]
        glog.warning("scrub finding: vol=%d kind=%s shard=%d needle=%x %s",
                     vid, kind, shard_id, needle_id, detail)

    def report_corruption(self, vid: int, kind: str = "replica",
                          shard_id: int = 0, needle_id: int = 0,
                          detail: str = "") -> None:
        """Public entry for other detectors (vacuum) whose finding can no
        longer be re-verified in place (e.g. the rotten needle was
        dropped from the compacted index): goes straight to the master
        for a whole-volume repair."""
        self._report(vid, kind, shard_id=shard_id, needle_id=needle_id,
                     detail=detail)

    def _clear_reported(self, vid: int, kind: str, shard_id: int = 0,
                        needle_id: int = 0) -> None:
        """A previously-reported target verified healthy (post-repair):
        stop re-delivering it and lift the quarantine."""
        with self._lock:
            self._outstanding.pop((vid, kind, shard_id, needle_id), None)
        if kind == "replica":
            self.quarantine.clear_needle(vid, needle_id)
        elif kind == "ec_shard":
            self.quarantine.clear_shard(vid, shard_id)

    def _absolve_needle(self, vid: int, key: int) -> None:
        """A needle verified healthy on a regular pass: if it was ever
        reported/quarantined (pre-repair), clear that state so a LATER
        re-corruption of the same needle is reported again."""
        with self._lock:
            if (vid, "replica", 0, key) not in self._outstanding:
                if not self.quarantine.is_needle_suspect(vid, key):
                    return
            self._outstanding.pop((vid, "replica", 0, key), None)
        self.quarantine.clear_needle(vid, key)

    def forget_volume(self, vid: int) -> None:
        """A repair (or any remount) replaced the volume's bytes: clear
        its quarantine and stop re-delivering its findings — if rot
        survives, the next pass re-detects and re-reports."""
        with self._lock:
            for k in [k for k in self._outstanding if k[0] == vid]:
                del self._outstanding[k]
        self.quarantine.drop_volume(vid)

    def forget_shards(self, vid: int, shard_ids) -> None:
        """EC shards were (re)mounted — same contract as forget_volume."""
        sids = set(shard_ids)
        with self._lock:
            for k in [k for k in self._outstanding
                      if k[0] == vid and k[1] == "ec_shard" and k[2] in sids]:
                del self._outstanding[k]
        for sid in sids:
            self.quarantine.clear_shard(vid, sid)

    def outstanding_findings(self, limit: int = 256) -> list[dict]:
        """Confirmed findings for the next heartbeat.  NOT drained:
        every full beat re-delivers until the target heals (at-least-
        once; the master keys findings idempotently), so a stream that
        dies mid-send loses nothing."""
        with self._lock:
            return [dict(f) for f in
                    list(self._outstanding.values())[:limit]]

    def recent_findings(self, vid: int | None = None) -> list[dict]:
        with self._lock:
            return [f for f in self._recent
                    if vid is None or f["volume_id"] == vid]

    # -- read-path feed ---------------------------------------------------

    def suspect_needle(self, vid: int, needle_id: int) -> None:
        """Read path saw a CRC failure: quarantine + queue for confirm."""
        if self.quarantine.mark_needle(vid, needle_id):
            SCRUB_ERRORS.labels("read_path").inc()
        with self._lock:
            self._confirm_q.append({"vid": vid, "needle_id": needle_id})
            del self._confirm_q[:-1024]
        self._wake.set()

    def suspect_shard(self, vid: int, shard_id: int) -> None:
        if self.quarantine.mark_shard(vid, shard_id):
            SCRUB_ERRORS.labels("read_path").inc()
        with self._lock:
            self._confirm_q.append({"vid": vid, "shard_id": shard_id})
            del self._confirm_q[:-1024]
        self._wake.set()

    def _confirm_pending(self) -> None:
        with self._lock:
            pending, self._confirm_q = self._confirm_q, []
        # dedupe: a degraded-read storm enqueues the same target many
        # times; one verification answers them all
        seen: set[tuple] = set()
        deduped = []
        for item in pending:
            key = (item["vid"], item.get("needle_id"), item.get("shard_id"))
            if key in seen:
                continue
            seen.add(key)
            deduped.append(item)
        for item in deduped:
            vid = item["vid"]
            self._counts["confirms"] += 1
            if "needle_id" in item:
                v = self.store.find_volume(vid)
                if v is None:
                    continue
                nv = v.needle_map.get(item["needle_id"])
                if nv is None or t.size_is_deleted(nv.size):
                    self.quarantine.clear_needle(vid, item["needle_id"])
                    continue
                self._verify_volume_needle(v, nv)
            else:
                ev = self.store.find_ec_volume(vid)
                if ev is not None and item.get("shard_id") in ev.shards:
                    # a targeted parity sweep of the suspect shard's file
                    self._scrub_ec_volume(ev, loc_dir=None,
                                          only_shard=item["shard_id"])

    # -- scan entry points ------------------------------------------------

    def scrub_once(self, rate_mbps: float | None = None) -> dict:
        """One full pass over every volume and EC volume, resuming from
        the persisted cursors.  Returns a summary dict."""
        if rate_mbps:
            self.bucket.set_rate(rate_mbps * (1 << 20))
        started = time.monotonic()
        self._last_pass_started = time.time()
        summary = {"volumes": 0, "ec_volumes": 0, "corrupt_needles": 0,
                   "corrupt_shards": 0, "scanned_bytes": 0,
                   "index_repairs": 0}
        for loc in self.store.locations:
            for vid in sorted(loc.volumes):
                v = loc.volumes.get(vid)
                if v is None or v.is_remote:
                    continue
                r = self._scrub_volume(v, loc.directory)
                summary["volumes"] += 1
                summary["corrupt_needles"] += r["corrupt_needles"]
                summary["scanned_bytes"] += r["bytes"]
                summary["index_repairs"] += r["index_repairs"]
                if self._stop.is_set():
                    break
            for vid in sorted(loc.ec_volumes):
                ev = loc.ec_volumes.get(vid)
                if ev is None:
                    continue
                r = self._scrub_ec_volume(ev, loc.directory)
                summary["ec_volumes"] += 1
                summary["corrupt_shards"] += r["corrupt_shards"]
                summary["scanned_bytes"] += r["bytes"]
                if self._stop.is_set():
                    break
        self._counts["passes"] += 1
        self._last_pass_seconds = time.monotonic() - started
        summary["seconds"] = self._last_pass_seconds
        if rate_mbps:
            self.bucket.set_rate(self._default_rate)
        self._save_cursors()
        return summary

    def scrub_volume(self, vid: int, rate_mbps: float | None = None) -> dict:
        """On-demand scan of one volume (the `volume.scrub` rpc)."""
        if rate_mbps:
            self.bucket.set_rate(rate_mbps * (1 << 20))
        try:
            v = self.store.find_volume(vid)
            if v is not None:
                loc = self.store._location_of(vid)
                # on-demand = full scan: reset the cursor first
                d = loc.directory if loc else self.store.locations[0].directory
                self._set_cursor(d, "volume", vid, 0)
                return self._scrub_volume(v, d)
            ev = self.store.find_ec_volume(vid)
            if ev is not None:
                loc = self.store._location_of(vid)
                d = loc.directory if loc else self.store.locations[0].directory
                self._set_cursor(d, "ec", vid, 0)
                return self._scrub_ec_volume(ev, d)
            raise KeyError(f"volume {vid} not found")
        finally:
            if rate_mbps:
                self.bucket.set_rate(self._default_rate)

    # -- throttle ---------------------------------------------------------

    def _throttle(self, n: int) -> None:
        # back off while the serving pools are saturated: scrub I/O must
        # never starve foreground reads (the PR 5 queue-depth gauges are
        # the signal)
        while (_saturation() >= self.backoff_depth
               and not self._stop.is_set()):
            self._counts["backoff_seconds"] += 0.2
            if self._stop.wait(0.2):
                return
        self._counts["backoff_seconds"] += self.bucket.consume(
            n, stop=self._stop)

    # -- volume scan ------------------------------------------------------

    def _scrub_volume(self, v, loc_dir: str | None) -> dict:
        vid = v.volume_id
        result = {"corrupt_needles": 0, "bytes": 0, "scanned": 0,
                  "index_repairs": 0}
        with v._lock:
            entries = sorted(
                v.needle_map.items_ascending(), key=lambda nv: nv.offset)
            dat = v._dat
            version = v.version
            file_size = dat.file_size()
        cursor = self._cursor(loc_dir, "volume", vid) if loc_dir else 0
        index_suspect = 0
        for nv in entries:
            if self._stop.is_set():
                break
            if nv.offset < cursor or t.size_is_deleted(nv.size):
                continue
            rec_len = actual_size(nv.size, version)
            if nv.offset + rec_len > file_size:
                # an entry past EOF survived the load-time tail fix:
                # the index itself is suspect
                index_suspect += 1
                continue
            self._throttle(rec_len)
            ok = self._verify_volume_needle(v, nv)
            result["scanned"] += 1
            result["bytes"] += rec_len
            if ok is False:
                result["corrupt_needles"] += 1
            elif ok is None:
                index_suspect += 1
            if loc_dir:
                self._set_cursor(loc_dir, "volume", vid, nv.offset + rec_len)
        else:
            # full pass completed: wrap the cursor and check the on-disk
            # index against the in-memory map (tombstone rewrites and the
            # append log must agree; disagreement = index rot)
            if loc_dir:
                self._set_cursor(loc_dir, "volume", vid, 0)
            if not self._verify_index(v):
                index_suspect += 1
        if index_suspect:
            SCRUB_ERRORS.labels("index").inc(index_suspect)
            if self._repair_index(v):
                result["index_repairs"] += 1
                self._counts["index_repairs"] += 1
            else:
                self._report(vid, "index",
                             detail=f"{index_suspect} bad index entries")
        else:
            self._clear_reported(vid, "index")
        self._counts["scanned_needles"] += result["scanned"]
        self._counts["scanned_bytes"] += result["bytes"]
        self._counts["corrupt_needles"] += result["corrupt_needles"]
        SCRUB_BYTES.labels("volume").inc(result["bytes"])
        return result

    def _verify_volume_needle(self, v, nv):
        """-> True healthy / False corrupt (reported) / None index-suspect.

        Same lock discipline as Volume.read_needle: lock-free pread off a
        snapshotted handle, any inconsistency re-checked under the lock
        (where a racing vacuum/tier swap resolves) before it counts as
        corruption."""
        vid = v.volume_id
        key = nv.key if hasattr(nv, "key") else nv.id
        with v._lock:
            cur = v.needle_map.get(key)
            if cur is None or cur.offset != nv.offset or cur.size != nv.size:
                return True  # raced a delete/vacuum: nothing to verify
            dat = v._dat
            version = v.version
        try:
            faultpoint.inject(FP_SCRUB_READ, ctx=f"{vid}")
            blob = dat.pread(nv.offset, actual_size(nv.size, version))
            blob = faultpoint.inject(FP_SCRUB_VERIFY, ctx=f"{vid}", data=blob)
            n = Needle.from_bytes(blob, version)
            if n.id != key:
                return self._recheck_volume_needle(v, nv, key)
            if n.size != nv.size:
                return self._recheck_volume_needle(v, nv, key)
        except CorruptNeedleError:
            return self._recheck_volume_needle(v, nv, key)
        except (OSError, ValueError, struct.error, IndexError):
            # handle swap / short read / garbled header: recheck under lock
            return self._recheck_volume_needle(v, nv, key)
        SCRUB_NEEDLES.labels("volume", "ok").inc()
        # healthy (regular pass or confirm): lift any stale report /
        # quarantine left from before a repair
        self._absolve_needle(vid, key)
        return True

    def _recheck_volume_needle(self, v, nv, key):
        """Authoritative verification under the volume lock."""
        vid = v.volume_id
        with v._lock:
            cur = v.needle_map.get(key)
            if cur is None or cur.offset != nv.offset or cur.size != nv.size:
                return True  # superseded while we looked: not corruption
            try:
                blob = v._dat.read_at(
                    nv.offset, actual_size(nv.size, v.version))
                n = Needle.from_bytes(blob, v.version)
            except CorruptNeedleError:
                SCRUB_NEEDLES.labels("volume", "corrupt").inc()
                SCRUB_ERRORS.labels("needle").inc()
                self.quarantine.mark_needle(vid, key)
                self._report(vid, "replica", needle_id=key,
                             detail="needle CRC mismatch")
                return False
            except (OSError, ValueError, struct.error, IndexError) as e:
                SCRUB_NEEDLES.labels("volume", "corrupt").inc()
                SCRUB_ERRORS.labels("needle").inc()
                self.quarantine.mark_needle(vid, key)
                self._report(vid, "replica", needle_id=key,
                             detail=f"unreadable record: {e}")
                return False
        if n.id != key:
            # valid record, wrong id: the INDEX points at the wrong
            # offset — index rot, not data rot
            return None
        if n.size != nv.size:
            return None
        SCRUB_NEEDLES.labels("volume", "ok").inc()
        self._absolve_needle(vid, key)
        return True

    # -- index verification / last-resort rebuild -------------------------

    def _verify_index(self, v) -> bool:
        """Replay the on-disk .idx and compare its final live map to the
        in-memory needle map — they are written in lockstep, so any
        divergence means the .idx on disk is rotten."""
        idx_path = v.file_name() + ".idx"
        with v._lock:
            try:
                v._idx.flush()
            except (OSError, ValueError):
                return False
            if not os.path.exists(idx_path):
                return True  # nothing persisted yet
            try:
                live: dict[int, tuple[int, int]] = {}
                for key, offset, size in walk_index_file(idx_path):
                    if t.size_is_deleted(size) or offset == 0:
                        live.pop(key, None)
                    else:
                        live[key] = (offset, size)
            except (OSError, ValueError, struct.error):
                return False
            mem = {nv.key: (nv.offset, nv.size)
                   for nv in v.needle_map.items_ascending()
                   if not t.size_is_deleted(nv.size)}
        return live == mem

    def _repair_index(self, v) -> bool:
        """Last resort: rebuild the .idx by scanning the .dat (`weed fix`)
        and reload the volume in place, exactly like a vacuum commit."""
        from ..tools.offline import fix_index

        vid = v.volume_id
        try:
            with v._lock:
                directory, collection = v.directory, v.collection
                v.close()
                n = fix_index(directory, vid, collection)
                v.__init__(directory, collection, vid)
            if self.store.needle_cache is not None:
                self.store.needle_cache.drop_volume(vid)
            SCRUB_REPAIRS.labels("index", "ok").inc()
            glog.warning("scrub: rebuilt index for volume %d (%d entries)",
                         vid, n)
            self._clear_reported(vid, "index")
            return True
        except Exception as e:  # noqa: BLE001 — report, keep scrubbing
            SCRUB_REPAIRS.labels("index", "error").inc()
            glog.error("scrub: index rebuild for volume %d failed: %s",
                       vid, e)
            return False

    # -- EC scan ----------------------------------------------------------

    def _parity_rows(self, codec, data: np.ndarray) -> list[np.ndarray]:
        """Recompute RS parity for one (10, W) interval stack, through the
        shared codec service when the store's codec has one (device
        verification matmul), else the host SIMD kernel."""
        svc = codec_service.service_for_codec(self.store.codec_name)
        if svc is not None:
            return list(svc.submit_parity(data).result())
        return list(codec.parity_of(data))

    def _scrub_ec_volume(self, ev, loc_dir: str | None,
                         only_shard: int | None = None) -> dict:
        vid = ev.volume_id
        result = {"corrupt_shards": 0, "bytes": 0, "scanned": 0}
        codec = get_codec("cpu")  # the verification math; device via service
        try:
            shard_size = ev.shard_size
        except (OSError, IOError):
            shard_size = 0
        if not shard_size or not ev.shards:
            return result
        cursor = self._cursor(loc_dir, "ec", vid) if loc_dir else 0
        if cursor >= shard_size:
            cursor = 0
        off = cursor
        while off < shard_size and not self._stop.is_set():
            width = min(self.ec_interval, shard_size - off)
            rows = self._gather_ec_interval(ev, off, width)
            if rows is None:
                SCRUB_NEEDLES.labels("ec", "skipped").inc()
                off += width
                continue
            n_read = sum(1 for r in rows.values() if r is not None)
            self._throttle(n_read * width)
            result["bytes"] += n_read * width
            result["scanned"] += 1
            bad = self._verify_ec_interval(ev, codec, rows, off, width)
            for sid in bad:
                result["corrupt_shards"] += 1
                self._counts["corrupt_shards"] += 1
                SCRUB_NEEDLES.labels("ec", "corrupt").inc()
                SCRUB_ERRORS.labels("shard").inc()
                self.quarantine.mark_shard(vid, sid)
                self._report(vid, "ec_shard", shard_id=sid,
                             detail=f"parity mismatch at {off}+{width}")
            if not bad:
                SCRUB_NEEDLES.labels("ec", "ok").inc()
            off += width
            if loc_dir:
                self._set_cursor(loc_dir, "ec", vid,
                                 0 if off >= shard_size else off)
        if (cursor == 0 and off >= shard_size
                and result["corrupt_shards"] == 0
                and not self._stop.is_set()):
            # a COMPLETE clean pass: lift stale shard reports/quarantine
            # left from before a repair so later re-corruption re-reports
            # (for a targeted confirm, only the suspect shard is cleared)
            targets = ([only_shard] if only_shard is not None
                       else list(ev.shards))
            for sid in targets:
                self._clear_reported(vid, "ec_shard", shard_id=sid)
        self._counts["scanned_bytes"] += result["bytes"]
        SCRUB_BYTES.labels("ec").inc(result["bytes"])
        return result

    def _gather_ec_interval(self, ev, off: int, width: int):
        """-> {shard_id: bytes|None} for all 14 shards (local reads +
        remote fetches), or None when fewer than the 10 data shards are
        reachable (cannot verify parity)."""
        rows: dict[int, bytes | None] = {}
        for sid in range(TOTAL_SHARDS):
            buf = None
            sh = ev.shards.get(sid)
            faultpoint.inject(FP_SCRUB_READ, ctx=f"ec{ev.volume_id}")
            if sh is not None:
                try:
                    buf = sh.read_at(off, width)
                except (OSError, ValueError):
                    buf = None
                if buf is not None and len(buf) != width:
                    buf = None
            if buf is None and ev.remote_fetch is not None:
                try:
                    buf = ev.remote_fetch(sid, off, width)
                except Exception:  # noqa: BLE001 — peer death is routine
                    buf = None
                if buf is not None and len(buf) != width:
                    buf = None
            if buf is not None:
                buf = faultpoint.inject(
                    FP_SCRUB_VERIFY, ctx=f"ec{ev.volume_id}", data=buf)
                if len(buf) != width:
                    buf = None
            rows[sid] = buf
        if sum(1 for sid in range(DATA_SHARDS) if rows[sid] is not None) \
                < DATA_SHARDS:
            return None
        return rows

    def _verify_ec_interval(self, ev, codec, rows: dict, off: int,
                            width: int) -> list[int]:
        """Recompute parity; on mismatch, localize the rotten shard(s) by
        substitution: for each candidate, reconstruct it from the OTHER
        shards and test whether the substituted set is self-consistent.
        Returns the locally-present corrupt shard ids."""
        data = np.stack([
            np.frombuffer(rows[sid], dtype=np.uint8)
            for sid in range(DATA_SHARDS)
        ])
        parity = self._parity_rows(codec, data)
        mismatch = False
        for j, prow in enumerate(parity):
            stored = rows.get(DATA_SHARDS + j)
            if stored is None:
                continue
            if not np.array_equal(
                    np.frombuffer(stored, dtype=np.uint8),
                    np.asarray(prow, dtype=np.uint8)):
                mismatch = True
        if not mismatch:
            return []
        present = sorted(sid for sid, b in rows.items() if b is not None)
        local = set(ev.shards)
        corrupt: list[int] = []
        for cand in present:
            if cand not in local:
                continue  # a peer's shard: its own scrubber will find it
            others = [s for s in present if s != cand]
            if len(others) < DATA_SHARDS:
                continue
            plan = gf256.decode_plan_for(
                np.asarray(codec.matrix), DATA_SHARDS, others, (cand,))
            srcs = [np.frombuffer(rows[s], dtype=np.uint8)
                    for s in others[:DATA_SHARDS]]
            rebuilt = np.asarray(
                codec.apply_rows(plan, srcs)[0], dtype=np.uint8)
            if np.array_equal(
                    rebuilt, np.frombuffer(rows[cand], dtype=np.uint8)):
                continue  # substitution changes nothing: cand consistent
            # test consistency of the set with cand replaced
            subst = dict(rows)
            subst[cand] = rebuilt.tobytes()
            d2 = np.stack([
                np.frombuffer(subst[sid], dtype=np.uint8)
                for sid in range(DATA_SHARDS)])
            p2 = self._parity_rows(codec, d2)
            consistent = True
            for j, prow in enumerate(p2):
                stored = subst.get(DATA_SHARDS + j)
                if stored is None:
                    continue
                if not np.array_equal(
                        np.frombuffer(stored, dtype=np.uint8),
                        np.asarray(prow, dtype=np.uint8)):
                    consistent = False
                    break
            if consistent:
                corrupt.append(cand)
        if not corrupt:
            # could not localize (multiple corruptions / too few shards):
            # report the first locally-present mismatching parity shard so
            # SOMETHING rides the heartbeat rather than silence
            for j in range(len(parity)):
                sid = DATA_SHARDS + j
                if rows.get(sid) is not None and sid in local:
                    corrupt.append(sid)
                    break
        return corrupt

    # -- status -----------------------------------------------------------

    def status(self) -> dict:
        with self._lock:
            pending = len(self._confirm_q)
            outstanding = len(self._outstanding)
        return {
            "enabled": self.enabled,
            "running": self._thread is not None and self._thread.is_alive(),
            "rateMBps": self.rate_mbps,
            "intervalSeconds": self.interval_s,
            "ecIntervalBytes": self.ec_interval,
            "backoffQueueDepth": self.backoff_depth,
            "counts": dict(self._counts),
            "lastPassStarted": self._last_pass_started,
            "lastPassSeconds": round(self._last_pass_seconds, 3),
            "pendingConfirms": pending,
            "outstandingFindings": outstanding,
            "quarantine": self.quarantine.status(),
            "cursors": {d: c for d, c in self._cursors.items()},
        }
