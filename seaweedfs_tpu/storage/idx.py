"""`.idx` index files: a flat log of 16-byte (key, offset, size) entries.

Reference: weed/storage/idx/walk.go.  Offsets are stored /8; size -1 (or the
0xFFFFFFFF tombstone) marks deletion; a (0, 0) offset entry also deletes.
"""

from __future__ import annotations

import io
import os
from typing import Callable, Iterator

from . import types as t


def walk_index_blob(blob: bytes) -> Iterator[tuple[int, int, int]]:
    """Yield (key, actual_offset, size) for every 16-byte entry."""
    n = len(blob) - (len(blob) % t.NEEDLE_MAP_ENTRY_SIZE)
    for i in range(0, n, t.NEEDLE_MAP_ENTRY_SIZE):
        yield t.unpack_index_entry(blob[i : i + t.NEEDLE_MAP_ENTRY_SIZE])


def walk_index_file(
    path: str | os.PathLike,
    fn: Callable[[int, int, int], None] | None = None,
) -> list[tuple[int, int, int]]:
    """Walk a .idx file; returns entries (and calls fn per entry if given)."""
    out = []
    with open(path, "rb") as f:
        while True:
            chunk = f.read(t.NEEDLE_MAP_ENTRY_SIZE * 1024)
            if not chunk:
                break
            for e in walk_index_blob(chunk):
                if fn is not None:
                    fn(*e)
                out.append(e)
    return out


def parse_index_arrays(path: str | os.PathLike):
    """Vectorised parse of a whole .idx file -> (keys, offsets, sizes) numpy
    arrays (uint64, int64 actual bytes, int32).  Entry order preserved."""
    import numpy as np

    with open(path, "rb") as f:
        blob = f.read()
    esz = t.NEEDLE_MAP_ENTRY_SIZE
    off_end = 8 + t.OFFSET_SIZE
    n = len(blob) // esz
    raw = np.frombuffer(blob, dtype=np.uint8, count=n * esz).reshape(n, esz)
    # explicit big-endian dtypes keep this host-endianness-independent
    keys = raw[:, 0:8].copy().view(">u8").reshape(n).astype(np.uint64)
    stored = raw[:, 8:12].copy().view(">u4").reshape(n).astype(np.int64)
    if t.OFFSET_SIZE == 5:  # high byte appended after the BE lower word
        stored = stored | (raw[:, 12].astype(np.int64) << 32)
    offsets = stored * t.NEEDLE_PADDING_SIZE
    sizes = raw[:, off_end : off_end + 4].copy().view(">i4") \
        .reshape(n).astype(np.int32)
    return keys, offsets, sizes


def heal_index_tail(path: str | os.PathLike) -> int:
    """Truncate a torn trailing PARTIAL entry (a crash mid-put leaves
    size % 16 != 0).  Readers already ignore the partial tail, but an
    append landing after it would misalign every later entry — so the
    writer path must drop it first.  -> the healed file size."""
    try:
        size = os.path.getsize(path)
    except OSError:
        return 0
    healed = size - size % t.NEEDLE_MAP_ENTRY_SIZE
    if healed != size:
        with open(path, "r+b") as f:
            f.truncate(healed)
    return healed


def append_index_tombstone(path: str | os.PathLike, key: int) -> None:
    """Record that `key`'s last index entry is dead (load-time healer:
    its .dat record was truncated away).  Without this, the stale entry
    would resurface on the NEXT load and claim whatever new record was
    appended at the reclaimed offset — truncating an acked write."""
    if not os.path.exists(path):
        return
    heal_index_tail(path)
    with open(path, "ab") as f:
        f.write(t.pack_index_entry(key, 0, t.TOMBSTONE_FILE_SIZE))
        f.flush()
        os.fsync(f.fileno())


class IndexWriter:
    """Append-only .idx writer.

    Every entry is flushed to the KERNEL immediately (no fsync): the
    .dat append reaches the page cache per write, and the load-time
    torn-tail healer treats unindexed .dat bytes as garbage — a
    userspace-buffered .idx lagging by many entries would turn a plain
    SIGTERM into real data loss (the reference's Go writes are
    unbuffered syscalls, so its index never lags more than one entry).
    """

    def __init__(self, path: str | os.PathLike):
        self.path = os.fspath(path)
        heal_index_tail(self.path)  # never append after a torn entry
        self._f: io.BufferedWriter = open(path, "ab")

    def _write(self, entry: bytes) -> None:
        # the disk.write faultpoint family covers index appends too —
        # a torn .idx entry is exactly what a crash mid-put leaves, and
        # the loader must shrug it off (walk drops the partial tail)
        from .disk_health import inject_write_fault

        entry = inject_write_fault(self.path, self._f, self._f.tell(),
                                   entry)
        self._f.write(entry)
        self._f.flush()

    def put(self, key: int, actual_offset: int, size: int) -> None:
        self._write(t.pack_index_entry(key, actual_offset, size))

    def delete(self, key: int, actual_offset: int) -> None:
        """Tombstone entry: offset of the delete marker, size -1."""
        self._write(t.pack_index_entry(key, actual_offset,
                                       t.TOMBSTONE_FILE_SIZE))

    def tell(self) -> int:
        """Current append position (rollback point for a failed
        volume mutation)."""
        self._f.flush()
        return self._f.tell()

    def truncate(self, size: int) -> None:
        """Roll a failed append back to a previous tell()."""
        self._f.flush()
        self._f.truncate(size)
        self._f.seek(size)

    def flush(self) -> None:
        self._f.flush()
        os.fsync(self._f.fileno())

    def close(self) -> None:
        self._f.close()
