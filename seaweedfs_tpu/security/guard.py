"""IP whitelist guard for HTTP surfaces.

Reference: weed/security/guard.go:43 — requests from addresses outside the
whitelist are rejected; an empty whitelist admits everyone.
"""

from __future__ import annotations

import ipaddress


class Guard:
    def __init__(self, whitelist: list[str] | None = None):
        self.networks: list = []
        for item in whitelist or []:
            item = item.strip()
            if not item:
                continue
            try:
                if "/" in item:
                    self.networks.append(ipaddress.ip_network(item, strict=False))
                else:
                    self.networks.append(ipaddress.ip_network(f"{item}/32"))
            except ValueError:
                continue

    def allows(self, remote_ip: str) -> bool:
        if not self.networks:
            return True
        try:
            addr = ipaddress.ip_address(remote_ip)
        except ValueError:
            return False
        return any(addr in net for net in self.networks)
