"""mTLS for the gRPC substrate.

Reference: weed/security/tls.go — every component can present a client
certificate and verify peers against a shared CA; servers require and
verify client certs ([grpc] section of security.toml: ca, plus
<component>.cert/.key for master/volume/filer/client/...).

The grpc-python binding expresses the same policy with
ssl_server_credentials(require_client_auth=True) and
ssl_channel_credentials; pb/rpc.py consults `configure(...)`'d state when
opening listening ports and channels, so turning mTLS on is a config
change, not a code change.  `generate_dev_certs` creates a CA + per-
component certs for tests and dev clusters (the reference points users at
openssl for this).
"""

from __future__ import annotations

import datetime
import ipaddress
import os

import grpc


def _read_cert_triplet(config, component: str):
    """Resolve + read (key, cert, ca) bytes for a component, or None.

    Keys follow the reference convention: cert/key at
    `grpc.<component>.cert/.key` (or bare `<component>.cert/.key`), the
    shared CA at `grpc.ca`.
    """
    if config is None or not config.loaded:
        return None
    cert_file = config.get_string(f"grpc.{component}.cert") or \
        config.get_string(f"{component}.cert")
    key_file = config.get_string(f"grpc.{component}.key") or \
        config.get_string(f"{component}.key")
    ca_file = config.get_string("grpc.ca")
    if not (cert_file and key_file and ca_file):
        return None
    with open(key_file, "rb") as f:
        key = f.read()
    with open(cert_file, "rb") as f:
        cert = f.read()
    with open(ca_file, "rb") as f:
        ca = f.read()
    return key, cert, ca


def load_server_credentials(config, component: str):
    """-> grpc.ServerCredentials or None when the config has no certs.

    Mirrors LoadServerTLS (tls.go:26): client certs required + verified.
    """
    triplet = _read_cert_triplet(config, component)
    if triplet is None:
        return None
    key, cert, ca = triplet
    return grpc.ssl_server_credentials(
        [(key, cert)], root_certificates=ca, require_client_auth=True
    )


def load_client_credentials(config, component: str = "client"):
    """-> grpc.ChannelCredentials or None (LoadClientTLS, tls.go:69)."""
    triplet = _read_cert_triplet(config, component)
    if triplet is None:
        return None
    key, cert, ca = triplet
    return grpc.ssl_channel_credentials(
        root_certificates=ca, private_key=key, certificate_chain=cert
    )


def generate_dev_certs(directory: str,
                       components=("master", "volume", "filer", "client"),
                       days: int = 365) -> dict[str, tuple[str, str]]:
    """Create a CA plus one cert/key pair per component under `directory`.

    Certificates carry SANs for localhost/127.0.0.1 so in-process cluster
    tests can dial by IP.  Returns {"ca": (ca_path, ""), component:
    (cert_path, key_path), ...}.
    """
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    os.makedirs(directory, exist_ok=True)
    now = datetime.datetime.now(datetime.timezone.utc)
    out: dict[str, tuple[str, str]] = {}

    def _key():
        return rsa.generate_private_key(public_exponent=65537, key_size=2048)

    def _write(path: str, data: bytes) -> None:
        with open(path, "wb") as f:
            f.write(data)

    ca_key = _key()
    ca_name = x509.Name(
        [x509.NameAttribute(NameOID.COMMON_NAME, "seaweedfs-tpu dev ca")])
    ca_cert = (
        x509.CertificateBuilder()
        .subject_name(ca_name).issuer_name(ca_name)
        .public_key(ca_key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=days))
        .add_extension(x509.BasicConstraints(ca=True, path_length=None),
                       critical=True)
        .sign(ca_key, hashes.SHA256())
    )
    ca_path = os.path.join(directory, "ca.crt")
    _write(ca_path, ca_cert.public_bytes(serialization.Encoding.PEM))
    out["ca"] = (ca_path, "")

    for comp in components:
        key = _key()
        subject = x509.Name(
            [x509.NameAttribute(NameOID.COMMON_NAME, f"{comp}.seaweedfs")])
        cert = (
            x509.CertificateBuilder()
            .subject_name(subject).issuer_name(ca_name)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(minutes=5))
            .not_valid_after(now + datetime.timedelta(days=days))
            .add_extension(
                x509.SubjectAlternativeName([
                    x509.DNSName("localhost"),
                    x509.DNSName(f"{comp}.seaweedfs"),
                    x509.IPAddress(ipaddress.ip_address("127.0.0.1")),
                ]),
                critical=False,
            )
            .sign(ca_key, hashes.SHA256())
        )
        cert_path = os.path.join(directory, f"{comp}.crt")
        key_path = os.path.join(directory, f"{comp}.key")
        _write(cert_path, cert.public_bytes(serialization.Encoding.PEM))
        _write(key_path, key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.TraditionalOpenSSL,
            serialization.NoEncryption(),
        ))
        out[comp] = (cert_path, key_path)
    return out
