"""JWT write tokens: HS256, claim-bound to a file id.

Reference: weed/security/jwt.go:21-58 — the master signs a short-lived
token on Assign carrying the fid; the volume server verifies it on
POST/DELETE when a signing key is configured.  Unsigned clusters skip both
sides (the default).
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import time

DEFAULT_EXPIRES_SECONDS = 10


def _b64(data: bytes) -> bytes:
    return base64.urlsafe_b64encode(data).rstrip(b"=")


def _unb64(data: bytes) -> bytes:
    return base64.urlsafe_b64decode(data + b"=" * (-len(data) % 4))


def encode_jwt(key: bytes, claims: dict) -> str:
    header = _b64(json.dumps({"alg": "HS256", "typ": "JWT"}).encode())
    payload = _b64(json.dumps(claims, separators=(",", ":")).encode())
    signing_input = header + b"." + payload
    sig = _b64(hmac.new(key, signing_input, hashlib.sha256).digest())
    return (signing_input + b"." + sig).decode()


def decode_jwt(key: bytes, token: str) -> dict | None:
    """-> claims, or None when the signature/structure/expiry is invalid."""
    try:
        header, payload, sig = token.encode().split(b".")
    except ValueError:
        return None
    want = _b64(hmac.new(key, header + b"." + payload, hashlib.sha256).digest())
    if not hmac.compare_digest(want, sig):
        return None
    try:
        claims = json.loads(_unb64(payload))
    except (ValueError, UnicodeDecodeError):
        return None
    exp = claims.get("exp")
    if exp is not None and time.time() > exp:
        return None
    return claims


def gen_write_jwt(key: bytes, fid: str,
                  expires_seconds: int = DEFAULT_EXPIRES_SECONDS) -> str:
    """Signed token authorizing one write/delete of `fid` (jwt.go GenJwt)."""
    if not key:
        return ""
    return encode_jwt(key, {"exp": int(time.time()) + expires_seconds,
                            "sub": fid})


def verify_write_jwt(key: bytes, token: str, fid: str) -> bool:
    """Volume-server side check (jwt.go ValidateJwt + fid claim match)."""
    claims = decode_jwt(key, token)
    if claims is None:
        return False
    # tokens bound to a fid authorize exactly that fid; an empty sub is a
    # master-issued wildcard (reference allows unbound tokens)
    sub = claims.get("sub", "")
    return sub == "" or sub == fid


def token_from_header(auth_header: str | None) -> str:
    """Extract the bearer token from an Authorization header."""
    if not auth_header:
        return ""
    parts = auth_header.split()
    if len(parts) == 2 and parts[0].upper() == "BEARER":
        return parts[1]
    return ""
