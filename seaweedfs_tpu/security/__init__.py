"""Security: JWT write tokens, IP whitelist guard, TLS config.

Reference surface: weed/security (jwt.go, guard.go, tls.go).
"""

from .jwt import decode_jwt, encode_jwt, gen_write_jwt, verify_write_jwt
from .guard import Guard

__all__ = [
    "encode_jwt", "decode_jwt", "gen_write_jwt", "verify_write_jwt", "Guard",
]
