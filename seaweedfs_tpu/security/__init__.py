"""Security: JWT write tokens, IP whitelist guard, gRPC mTLS.

Reference surface: weed/security (jwt.go, guard.go, tls.go).
"""

from .jwt import decode_jwt, encode_jwt, gen_write_jwt, verify_write_jwt
from .guard import Guard
from .tls import (
    generate_dev_certs,
    load_client_credentials,
    load_server_credentials,
)

__all__ = [
    "encode_jwt", "decode_jwt", "gen_write_jwt", "verify_write_jwt", "Guard",
    "load_server_credentials", "load_client_credentials",
    "generate_dev_certs",
]
