"""CLI entry: `python -m seaweedfs_tpu <subcommand>`.

Reference surface: weed/command/command.go (27 subcommands).  Implemented:
master, volume, server (master+volume), filer, shell, bench, version,
ec.encode (offline), fix (rebuild .idx from .dat), export, compact.
"""

from __future__ import annotations

import argparse
import sys
import time


def cmd_master(args) -> None:
    from .master.server import MasterServer
    from .util.config import load_configuration

    # TOML tier: master.toml supplies the maintenance script + sequencer
    # defaults; explicit CLI flags win (util/config.go two-tier model)
    mconf = load_configuration("master")
    interval = args.maintenanceInterval
    if interval is None:  # flag not given -> TOML, else 0 (disabled)
        interval = mconf.get_float("master.maintenance.periodic_seconds")
    # scripts=[] in the TOML means "run nothing", which run_maintenance
    # distinguishes from None (= its default suite)
    raw_scripts = mconf.get("master.maintenance.scripts")
    script = raw_scripts if isinstance(raw_scripts, list) else None
    sequencer = mconf.get_string("master.sequencer.type", "memory")
    node_id = mconf.get_int("master.sequencer.sequencer_snowflake_id")

    lifecycle_policy = None
    if args.lifecyclePolicy:
        import json

        with open(args.lifecyclePolicy) as f:
            lifecycle_policy = json.load(f)
    slo_specs = None
    if args.sloSpecs:
        from .telemetry.slo import specs_from_json

        slo_specs = specs_from_json(args.sloSpecs)
    m = MasterServer(
        ip=args.ip,
        port=args.port,
        volume_size_limit_mb=args.volumeSizeLimitMB,
        default_replication=args.defaultReplication,
        maintenance_interval=interval,
        maintenance_script=script,
        lifecycle_interval=args.lifecycleInterval,
        lifecycle_dir=args.lifecycleDir,
        lifecycle_rate_mbps=args.lifecycleRateMBps,
        lifecycle_policy=lifecycle_policy,
        repair_deadline_s=args.repairDeadlineS,
        sequencer=sequencer,
        sequencer_node_id=node_id,
        sequencer_etcd_urls=mconf.get_string(
            "master.sequencer.sequencer_etcd_urls", "127.0.0.1:2379"),
        metrics_port=args.metricsPort,
        jwt_signing_key=args.jwtKey or _security_jwt_key(),
        peers=args.peers.split(",") if args.peers else None,
        raft_state_dir=args.raftDir,
        peer_clusters=(args.peerClusters.split(",")
                       if args.peerClusters else None),
        slo_interval=args.sloInterval,
        slo_specs=slo_specs,
        canary_interval=args.canaryInterval,
        canary_s3=args.canaryS3,
        alert_webhook=args.alertWebhook,
        debug_dir=args.debugDir,
    )
    m.start()
    print(f"master listening http={args.port} grpc={m.grpc_port}")
    _wait()


def cmd_volume(args) -> None:
    from .util.config import load_configuration
    from .volume.server import VolumeServer

    if getattr(args, "offset5", False):
        from .storage import types as _t

        _t.set_offset_size(5)
    if getattr(args, "index", "memory") != "memory":
        from .storage.volume import set_needle_map_kind

        set_needle_map_kind(args.index)
    codec = getattr(args, "ec_codec", "")
    if not codec:  # flag not given -> master.toml [codec].type, else cpu
        codec = load_configuration("master").get_string("codec.type", "cpu")
    v = VolumeServer(
        directories=args.dir.split(","),
        master_addresses=[
            _grpc_addr(m) for m in args.mserver.split(",")
        ],
        ip=args.ip,
        port=args.port,
        data_center=args.dataCenter,
        rack=args.rack,
        codec_name=codec,
        max_volume_count=args.max,
        metrics_port=args.metricsPort,
        jwt_signing_key=args.jwtKey or _security_jwt_key(),
        whitelist=(args.whiteList.split(",") if args.whiteList
                   else _security_white_list()),
        tier_backends=_load_tier_backends(args.tierBackends),
        tcp_port=args.tcpPort,
    )
    v.start()
    print(f"volume server http={args.port} grpc={v.grpc_port} dirs={args.dir}")
    _wait()


def cmd_server(args) -> None:
    """`weed server`: master + volume, optionally filer and s3 gateway in
    one process (command/server.go)."""
    from .master.server import MasterServer
    from .util.config import load_configuration
    from .volume.server import VolumeServer

    codec = getattr(args, "ec_codec", "")
    if not codec:
        codec = load_configuration("master").get_string("codec.type", "cpu")
    m = MasterServer(ip=args.ip, port=args.masterPort)
    m.start()
    v = VolumeServer(
        directories=args.dir.split(","),
        master_addresses=[f"{args.ip}:{m.grpc_port}"],
        ip=args.ip,
        port=args.port,
        codec_name=codec,
    )
    v.start()
    extras = []
    if args.filer or args.s3:
        from .filer.server import FilerServer

        from .notification import publisher_from_config

        store, store_path, store_options = _filer_store_selection(
            args.filerStore)
        filer = FilerServer(
            masters=[f"{args.ip}:{m.grpc_port}"],
            ip=args.ip, port=args.filerPort, store=store,
            store_path=store_path, store_options=store_options,
            notification=publisher_from_config(
                load_configuration("notification")),
        )
        filer.start()
        extras.append(f"filer={args.filerPort}")
        if args.s3:
            from .s3api.server import S3ApiServer

            s3 = S3ApiServer(
                filer=f"{args.ip}:{args.filerPort}", port=args.s3Port,
                iam_config_filer_path="/etc/iam/identity.json",
            )
            s3.start()
            extras.append(f"s3={args.s3Port}")
    print(f"server: master={args.masterPort} volume={args.port}"
          + ("" if not extras else " " + " ".join(extras)))
    _wait()


def _filer_store_selection(flag_store: str) -> tuple[str, str, dict]:
    """filer.toml picks the store backend; the -store flag (a path)
    keeps its historical meaning of "sqlite at this path" and wins when
    given.  -> (store, store_path, store_options)."""
    from .util.config import load_configuration

    store, store_path, store_options = "sqlite", flag_store, {}
    fconf = load_configuration("filer")
    if fconf.loaded and flag_store == "./filer.db":  # flag left at default
        for kind, path_key in (("sqlite", "dbFile"), ("leveldb", "dir"),
                               ("leveldb2", "dir"), ("leveldb3", "dir"),
                               ("redis", ""), ("etcd", ""),
                               ("elastic7", ""), ("mongodb", ""),
                               ("cassandra", ""),
                               ("mysql", ""), ("postgres", ""),
                               ("memory", "")):
            if fconf.get_bool(f"{kind}.enabled"):
                store = kind
                if path_key:
                    store_path = fconf.get_string(
                        f"{kind}.{path_key}", store_path)
                break
        if store == "redis":
            store_options = {
                "host": fconf.get_string("redis.host", "127.0.0.1"),
                "port": fconf.get_int("redis.port", 6379),
                "db": fconf.get_int("redis.db", 0),
            }
        elif store == "etcd":
            store_options = {
                "servers": fconf.get_string("etcd.servers",
                                            "127.0.0.1:2379"),
            }
        elif store == "elastic7":
            store_options = {
                "servers": fconf.get_string("elastic7.servers",
                                            "http://127.0.0.1:9200"),
                "username": fconf.get_string("elastic7.username", ""),
                "password": fconf.get_string("elastic7.password", ""),
            }
        elif store == "mongodb":
            store_options = {
                "host": fconf.get_string("mongodb.host", "127.0.0.1"),
                "port": fconf.get_int("mongodb.port", 27017),
                "database": fconf.get_string("mongodb.database",
                                             "seaweedfs"),
            }
        elif store == "cassandra":
            store_options = {
                "host": fconf.get_string("cassandra.host", "127.0.0.1"),
                "port": fconf.get_int("cassandra.port", 9042),
                "keyspace": fconf.get_string("cassandra.keyspace",
                                             "seaweedfs"),
            }
        elif store in ("mysql", "postgres"):
            port_default = {"mysql": 3306, "postgres": 5432}[store]
            user_default = {"mysql": "root", "postgres": "postgres"}[store]
            store_options = {
                "hostname": fconf.get_string(f"{store}.hostname",
                                             "localhost"),
                "port": fconf.get_int(f"{store}.port", port_default),
                "username": fconf.get_string(f"{store}.username",
                                             user_default),
                "password": fconf.get_string(f"{store}.password", ""),
                "database": fconf.get_string(f"{store}.database",
                                             "seaweedfs"),
            }
    return store, store_path, store_options


def cmd_filer(args) -> None:
    from .filer.server import FilerServer
    from .notification import publisher_from_config
    from .util.config import load_configuration

    store, store_path, store_options = _filer_store_selection(args.store)
    notification = publisher_from_config(load_configuration("notification"))

    f = FilerServer(
        masters=[_grpc_addr(m) for m in args.master.split(",")],
        ip=args.ip,
        port=args.port,
        store=store,
        store_path=store_path,
        max_mb=args.maxMB,
        metrics_port=args.metricsPort,
        peers=args.peers.split(",") if args.peers else None,
        cipher=args.cipher,
        store_options=store_options,
        notification=notification,
        cluster_id=args.clusterId,
        geo_peers=args.geoPeers.split(",") if args.geoPeers else None,
        geo_rate_mbps=args.geoRateMBps,
        meta_log_dir=args.metaLogDir,
    )
    f.start()
    print(f"filer http={args.port} grpc={f.grpc_port}")
    _wait()


def cmd_mount(args) -> None:
    from .mount.fuse import FuseMount, available
    from .mount.wfs import WFS

    if not available():
        raise SystemExit(
            "mount: libfuse.so.2 or /dev/fuse unavailable on this host"
        )
    filer_http = args.filer
    wfs = WFS(
        filer_grpc=_grpc_addr(filer_http),
        filer_http=filer_http,
        chunk_size_mb=args.chunkSizeLimitMB,
        collection=args.collection,
        replication=args.replication,
        cache_dir=args.cacheDir or None,
        cache_mem_mb=args.cacheCapacityMB,
    )
    wfs.start_meta_subscription()
    m = FuseMount(wfs, args.dir, allow_other=args.allowOthers)
    m.start()
    print(f"mounted {filer_http} on {args.dir}")
    try:
        _wait()
    finally:
        m.stop()


def cmd_msg_broker(args) -> None:
    from .messaging.broker import MessageBrokerServer

    b = MessageBrokerServer(
        filer=args.filer,
        ip=args.ip,
        port=args.port,
        peers=args.peers.split(",") if args.peers else None,
    )
    b.start()
    print(f"message broker grpc={args.port} filer={args.filer}")
    _wait()


def cmd_filer_replicate(args) -> None:
    from .replication import FilerSource, Replicator
    from .replication.sink import FilerSink, LocalSink, S3Sink

    if args.sink:
        args.sink_type = args.sink_type or "local"
        if args.sink_type == "filer":
            sink = FilerSink(args.sink)
        elif args.sink_type == "s3":
            endpoint, _, bucket = args.sink.partition("/")
            sink = S3Sink(endpoint, bucket or "backup")
        else:
            sink = LocalSink(args.sink)
        label = f"{args.sink_type}:{args.sink}"
    else:  # no -sink flag: replication.toml picks it (scaffold.go model)
        from .replication.sink import sink_from_config
        from .util.config import load_configuration

        if args.sink_type:
            raise SystemExit(
                "-sink.type without -sink would be silently ignored; "
                "either give both flags or configure replication.toml")
        conf = load_configuration("replication", required=True)
        sink, label = sink_from_config(conf)
        # (explicit flags always win; toml fills only omitted ones)
        # [source.filer] wins over flag DEFAULTS in toml mode, so the
        # scaffolded source section is honored, not silently ignored
        if conf.get_bool("source.filer.enabled"):
            addr = conf.get_string("source.filer.grpcAddress", "")
            if addr and args.filer is None:
                from .replication.source import GRPC_PORT_OFFSET

                host, _, port_s = addr.partition(":")
                try:
                    port = int(port_s)
                    if port <= GRPC_PORT_OFFSET:
                        raise ValueError
                except ValueError:
                    raise SystemExit(
                        f"[source.filer] grpcAddress {addr!r} must be "
                        "host:port with the gRPC port (HTTP port + "
                        f"{GRPC_PORT_OFFSET})") from None
                args.filer = f"{host}:{port - GRPC_PORT_OFFSET}"
            if args.filerPath is None:
                args.filerPath = conf.get_string("source.filer.directory",
                                                 "/")
    src_filer = args.filer or "127.0.0.1:8888"
    src_path = args.filerPath or "/"
    rep = Replicator(FilerSource(src_filer), sink, src_path)
    print(f"replicating {src_filer}{src_path} -> {label}")
    rep.run()


def cmd_filer_backup(args) -> None:
    from .replication import FilerSource, LocalSink, Replicator

    rep = Replicator(FilerSource(args.filer), LocalSink(args.dir),
                     args.filerPath)
    print(f"backing up {args.filer}{args.filerPath} -> {args.dir}")
    rep.run()


def cmd_filer_meta_tail(args) -> None:
    from .replication.source import subscribe_metadata

    for resp in subscribe_metadata(args.filer, args.pathPrefix,
                                   client_name="meta.tail"):
        n = resp.event_notification
        kind = ("delete" if not n.new_entry.name
                else "create" if not n.old_entry.name else "update")
        name = n.new_entry.name or n.old_entry.name
        print(f"{resp.ts_ns} {kind} {resp.directory}/{name}")


def cmd_filer_meta_backup(args) -> None:
    """Continuously back up filer metadata into a local store
    (command/filer_meta_backup.go)."""
    from .replication.meta_backup import MetaBackup

    store, store_path, store_options = _filer_store_selection(args.store)
    mb = MetaBackup.with_store(args.filer, store, store_path,
                               filer_dir=args.filerDir, **store_options)
    mb.run(restart=args.restart)


def cmd_filer_sync(args) -> None:
    """Bidirectional sync between two filers.  Both directions share one
    sync signature: every replayed mutation carries it, and each side's
    subscription skips events so signed — writes cannot ping-pong
    (command/filer_sync.go)."""
    import random
    import threading

    from .replication import FilerSource, Replicator
    from .replication.sink import FilerSink

    a, b = args.a, args.b
    sig = random.randint(1, 2**31 - 1)
    ra = Replicator(FilerSource(a), FilerSink(b, signature=sig),
                    args.filerPath, signature=sig)
    rb = Replicator(FilerSource(b), FilerSink(a, signature=sig),
                    args.filerPath, signature=sig)
    ta = threading.Thread(target=ra.run, daemon=True)
    tb = threading.Thread(target=rb.run, daemon=True)
    ta.start()
    tb.start()
    print(f"filer.sync {a} <-> {b} prefix={args.filerPath}")
    _wait()


def cmd_s3(args) -> None:
    from .s3api.server import S3ApiServer

    s = S3ApiServer(
        filer=args.filer,
        port=args.port,
        config_path=args.config,
        domain=args.domainName,
        iam_config_filer_path=args.iam_config or "",
        masters=args.master or "",
        geo_masters=args.geoMaster or "",
    )
    s.start()
    print(f"s3 gateway http={args.port} "
          + (f"masters={args.master} (fleet discovery)" if args.master
             else f"filer={args.filer}"))
    _wait()


def cmd_iam(args) -> None:
    from .iamapi.server import IamApiServer

    s = IamApiServer(filer=args.filer, port=args.port)
    s.start()
    print(f"iam api http={args.port} filer={args.filer}")
    _wait()


def cmd_backup(args) -> None:
    from .tools.backup import backup_volume

    res = backup_volume(args.server, args.volumeId, args.dir,
                        collection=args.collection)
    print(f"volume {args.volumeId}: appended {res['appended']} needles"
          + (" (full resync)" if res["full_resync"] else ""))


def cmd_upload(args) -> None:
    import json as _json

    from .tools.backup import upload_files

    results = upload_files(args.master, args.files,
                           collection=args.collection,
                           replication=args.replication, ttl=args.ttl)
    print(_json.dumps(results, indent=2))


def cmd_download(args) -> None:
    from .tools.backup import download_files

    for path in download_files(args.server, args.fids, args.dir):
        print(path)


def cmd_filer_cat(args) -> None:
    import sys as _sys

    from .tools.backup import filer_cat

    _sys.stdout.buffer.write(filer_cat(args.filer, args.path))


def cmd_filer_copy(args) -> None:
    from .tools.backup import filer_copy

    for p in filer_copy(args.filer, args.sources, args.dest):
        print(p)


def cmd_gateway(args) -> None:
    from .gateway import GatewayServer

    g = GatewayServer(
        masters=args.master.split(","),
        filers=args.filer.split(",") if args.filer else None,
        port=args.port,
    )
    g.start()
    print(f"gateway http={args.port} masters={args.master}")
    _wait()


def cmd_webdav(args) -> None:
    from .webdav.server import WebDavServer

    s = WebDavServer(filer=args.filer, port=args.port)
    s.start()
    print(f"webdav http={args.port} filer={args.filer}")
    _wait()


def cmd_ftp(args) -> None:
    """FTP gateway over the filer (the reference's ftpd is an 81-LoC
    stub; this one serves)."""
    import json as _json

    from .ftpd.server import FtpServer

    users = {}
    if args.users:
        users = _json.loads(open(args.users).read())
    s = FtpServer(filer=args.filer, ip=args.ip, port=args.port, users=users)
    s.start()
    print(f"ftp on {args.ip}:{s.port} filer={args.filer}")
    _wait()


def cmd_shell(args) -> None:
    from .shell.commands import CommandEnv, run_command
    from .util.config import load_configuration

    master, filer = args.master, getattr(args, "filer", "")
    sconf = load_configuration("shell")
    if sconf.loaded:  # shell.toml fills only OMITTED flags (default=None)
        if master is None:
            master = sconf.get_string("cluster.default.master", "")
        if not filer:
            filer = sconf.get_string("cluster.default.filer", "")
    master = master or "127.0.0.1:9333"
    env = CommandEnv(_grpc_addr(master))
    if filer:
        env.option["filer"] = filer
    if args.command:
        print(run_command(env, args.command))
        return
    while True:
        try:
            line = input("> ")
        except EOFError:
            break
        if line.strip() in ("exit", "quit"):
            break
        try:
            print(run_command(env, line))
        except Exception as e:
            print(f"error: {e}")


def cmd_bench(args) -> None:
    from .tools.benchmark import run_benchmark

    run_benchmark(
        master=args.master,
        num_files=args.n,
        file_size=args.size,
        concurrency=args.c,
        do_read=not args.write_only,
    )


def cmd_fix(args) -> None:
    from .tools.offline import fix_index

    fix_index(args.dir, args.volumeId, args.collection)
    print(f"rebuilt index for volume {args.volumeId}")


def cmd_compact(args) -> None:
    from .storage.vacuum import vacuum_volume
    from .storage.volume import Volume

    v = Volume(args.dir, args.collection, args.volumeId)
    vacuum_volume(v)
    v.close()
    print(f"compacted volume {args.volumeId}")


def cmd_export(args) -> None:
    from .tools.offline import export_volume

    n = export_volume(args.dir, args.volumeId, args.collection, args.output)
    print(f"exported {n} needles to {args.output}")


def _load_tier_backends(path: str) -> dict | None:
    if not path:
        return None
    import json

    with open(path) as f:
        return json.load(f)


def _grpc_addr(master: str) -> str:
    """Convert a server's HTTP address to its gRPC address (+10000)."""
    host, port = master.rsplit(":", 1)
    return f"{host}:{int(port) + 10000}"


def _wait() -> None:
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass


def cmd_scaffold(args) -> None:
    import os

    from .util.scaffold import scaffold

    text = scaffold(args.config)
    if args.output == "-":
        print(text, end="")
    else:
        path = os.path.join(args.output, f"{args.config}.toml")
        with open(path, "w") as f:
            f.write(text)
        print(f"wrote {path}")


# cmd -> the certificate identity its gRPC LISTENER presents; commands
# absent here are pure clients. "server" hosts master+volume in one
# process behind one listener credential set.
_TLS_COMPONENT = {
    "master": "master", "volume": "volume", "filer": "filer",
    "server": "master", "msgBroker": "broker",
}


def _security_jwt_key() -> str:
    """security.toml [jwt.signing].key — the flagless way to arm write
    JWTs cluster-wide (scaffold.go's security template)."""
    from .util.config import load_configuration

    return load_configuration("security").get_string("jwt.signing.key")


def _security_white_list() -> list[str] | None:
    from .util.config import load_configuration

    wl = load_configuration("security").get_list("guard.white_list")
    return [str(ip) for ip in wl] or None


def _configure_security(cmd: str) -> None:
    """Load security.toml and install mTLS credentials for this process
    (reference: every command resolves LoadServerTLS/LoadClientTLS at
    boot from the shared security.toml)."""
    from .pb import rpc as rpclib
    from .security.tls import load_client_credentials, load_server_credentials
    from .util.config import load_configuration

    conf = load_configuration("security")
    if not conf.loaded:
        return
    component = _TLS_COMPONENT.get(cmd, "client")
    server_creds = (
        load_server_credentials(conf, component)
        if cmd in _TLS_COMPONENT else None
    )
    channel_creds = load_client_credentials(conf, component)
    if server_creds or channel_creds:
        rpclib.configure_security(server_creds, channel_creds)


def _setup_profiling(args) -> None:
    if getattr(args, "cpuprofile", "") or getattr(args, "memprofile", ""):
        from .util.grace import setup_profiling

        setup_profiling(args.cpuprofile, args.memprofile)


def main(argv=None) -> None:
    p = argparse.ArgumentParser(prog="seaweedfs_tpu")
    p.add_argument("-cpuprofile", default="",
                   help="write a cProfile dump here at exit")
    p.add_argument("-memprofile", default="",
                   help="write a tracemalloc top-allocations report here "
                        "at exit")
    sub = p.add_subparsers(dest="cmd", required=True)

    m = sub.add_parser("master")
    m.add_argument("-ip", default="127.0.0.1")
    m.add_argument("-port", type=int, default=9333)
    m.add_argument("-volumeSizeLimitMB", type=int, default=30 * 1024)
    m.add_argument("-defaultReplication", default="000")
    m.add_argument("-maintenanceInterval", type=float, default=None,
               help="seconds between maintenance runs; 0 disables "
                    "(default: master.toml periodic_seconds)")
    m.add_argument("-lifecycleInterval", type=float, default=0.0,
                   help="lifecycle controller cycle seconds; 0 = manual "
                        "only (volume.lifecycle -apply)")
    m.add_argument("-lifecycleDir", default="",
                   help="crash-safe lifecycle journal directory; empty "
                        "keeps jobs in memory only")
    m.add_argument("-lifecycleRateMBps", type=float, default=None,
                   help="cluster background-I/O budget shared by "
                        "lifecycle jobs and scrub (None = env "
                        "SEAWEEDFS_TPU_LIFECYCLE_RATE_MBPS, 0 = "
                        "unthrottled)")
    m.add_argument("-lifecyclePolicy", default="",
                   help="JSON policy file: {collection: {field: value}}")
    m.add_argument("-repairDeadlineS", type=float, default=None,
                   help="total-repair-time bound for dead-node mass "
                        "repair; when a -lifecycleRateMBps budget is "
                        "set, the pushed background rate is raised to "
                        "what the bound requires (without a budget "
                        "repair traffic is unthrottled, so the bound "
                        "needs no boost).  None = env "
                        "SEAWEEDFS_TPU_MASS_REPAIR_DEADLINE_S, 0 = "
                        "no bound")
    m.add_argument("-metricsPort", type=int, default=0)
    m.add_argument("-jwtKey", default="")
    m.add_argument("-peers", default="",
                   help="comma-separated master quorum ip:port list (raft)")
    m.add_argument("-raftDir", default=".",
                   help="directory for persisted raft state")
    m.add_argument("-peerClusters", default="",
                   help="comma-separated REMOTE-cluster master http "
                        "addresses for the /cluster/geo registry")
    m.add_argument("-sloInterval", type=float, default=15.0,
                   help="SLO engine evaluation tick seconds (burn-rate "
                        "rules over family-filtered federation scrapes); "
                        "0 = evaluate only when /cluster/alerts is read")
    m.add_argument("-sloSpecs", default="",
                   help="JSON file with a list of SLO spec objects "
                        "(replaces the default suite; see METRICS.md "
                        "'SLOs & alerts')")
    m.add_argument("-canaryInterval", type=float, default=0.0,
                   help="synthetic canary probe tick seconds (black-box "
                        "write/read/delete, EC degraded read, routed "
                        "metadata, geo sentinel); 0 disables")
    m.add_argument("-canaryS3", default="",
                   help="S3 gateway http address the metadata_rt canary "
                        "routes through (empty = probe a registered "
                        "filer directly)")
    m.add_argument("-alertWebhook", default="",
                   help="POST every alert state transition to this URL "
                        "as JSON (the log sink always runs)")
    m.add_argument("-debugDir", default="",
                   help="flight-recorder bundle directory: alerts "
                        "transitioning to firing (and cluster.debug "
                        "-capture) snapshot cluster debug bundles here "
                        "with bounded retention (empty = in-memory ring)")
    m.set_defaults(fn=cmd_master)

    v = sub.add_parser("volume")
    v.add_argument("-dir", default="./data")
    v.add_argument("-mserver", default="127.0.0.1:9333")
    v.add_argument("-ip", default="127.0.0.1")
    v.add_argument("-port", type=int, default=8080)
    v.add_argument("-dataCenter", default="")
    v.add_argument("-rack", default="")
    v.add_argument("-max", type=int, default=7)
    v.add_argument("-port.tcp", dest="tcpPort", type=int, default=0,
                   help="experimental raw-TCP needle data path (0=off)")
    v.add_argument("-index", default="memory",
                   choices=("memory", "disk"),
                   help="needle map kind: in-RAM compact map, or "
                        "disk-backed sorted file for RAM-constrained "
                        "servers")
    v.add_argument("-offset.5bytes", dest="offset5", action="store_true",
                   help="5-byte needle offsets: 8TB volumes instead of "
                        "32GB (index files are NOT compatible with the "
                        "default 4-byte layout)")
    v.add_argument("-ec.codec", dest="ec_codec", default="",
                   choices=["auto", "cpu", "tpu", "tpu_xor", "tpu_mxu"])
    v.add_argument("-metricsPort", type=int, default=0)
    v.add_argument("-jwtKey", default="")
    v.add_argument("-whiteList", default="")
    v.add_argument("-tierBackends", default="",
                   help="JSON file: {\"s3.default\": {\"endpoint\": ...}}")
    v.set_defaults(fn=cmd_volume)

    s = sub.add_parser("server")
    s.add_argument("-dir", default="./data")
    s.add_argument("-ip", default="127.0.0.1")
    s.add_argument("-masterPort", type=int, default=9333)
    s.add_argument("-port", type=int, default=8080)
    s.add_argument("-ec.codec", dest="ec_codec", default="")
    s.add_argument("-filer", action="store_true",
                   help="also start a filer")
    s.add_argument("-filer.port", dest="filerPort", type=int, default=8888)
    s.add_argument("-filer.store", dest="filerStore", default="./filer.db")
    s.add_argument("-s3", action="store_true",
                   help="also start an S3 gateway (implies -filer)")
    s.add_argument("-s3.port", dest="s3Port", type=int, default=8333)
    s.set_defaults(fn=cmd_server)

    f = sub.add_parser("filer")
    f.add_argument("-master", default="127.0.0.1:9333")
    f.add_argument("-ip", default="127.0.0.1")
    f.add_argument("-port", type=int, default=8888)
    f.add_argument("-store", default="./filer.db")
    f.add_argument("-maxMB", type=int, default=4)
    f.add_argument("-metricsPort", type=int, default=0)
    f.add_argument("-peers", default="",
                   help="comma-separated peer filer http addresses for "
                        "metadata federation")
    f.add_argument("-encryptVolumeData", dest="cipher",
                   action="store_true",
                   help="AES-GCM encrypt chunk data before it reaches "
                        "volume servers")
    f.add_argument("-clusterId", type=int, default=0,
                   help="geo replication: this cluster's nonzero id "
                        "(the LWW tiebreak; enables HLC stamping and "
                        "the /.geo/* surface)")
    f.add_argument("-geoPeers", default="",
                   help="comma-separated REMOTE-cluster filer http "
                        "addresses to replicate to (active-active; one "
                        "journaled link per address)")
    f.add_argument("-geoRateMBps", type=float, default=None,
                   help="per-link replication budget (None = env "
                        "SEAWEEDFS_TPU_GEO_RATE_MBPS, 0 = unthrottled)")
    f.add_argument("-metaLogDir", default="",
                   help="durable metadata event log dir (default: "
                        "<store path>.metalog for disk stores)")
    f.set_defaults(fn=cmd_filer)

    mnt = sub.add_parser("mount")
    mnt.add_argument("-filer", default="127.0.0.1:8888")
    mnt.add_argument("-dir", required=True)
    mnt.add_argument("-collection", default="")
    mnt.add_argument("-replication", default="")
    mnt.add_argument("-chunkSizeLimitMB", type=int, default=4)
    mnt.add_argument("-cacheDir", default="")
    mnt.add_argument("-cacheCapacityMB", type=int, default=32)
    mnt.add_argument("-allowOthers", action="store_true")
    mnt.set_defaults(fn=cmd_mount)

    mb = sub.add_parser("msgBroker")
    mb.add_argument("-filer", default="127.0.0.1:8888")
    mb.add_argument("-ip", default="127.0.0.1")
    mb.add_argument("-port", type=int, default=17777)
    mb.add_argument("-peers", default="",
                    help="comma-separated peer broker grpc addresses")
    mb.set_defaults(fn=cmd_msg_broker)

    fr = sub.add_parser("filer.replicate")
    fr.add_argument("-filer", default=None,
                    help="source filer ip:port (omitted -> "
                         "replication.toml, then 127.0.0.1:8888)")
    fr.add_argument("-filerPath", default=None,
                    help="source path (omitted -> replication.toml, "
                         "then /)")
    fr.add_argument("-sink.type", dest="sink_type", default="",
                    choices=["", "local", "filer", "s3"],
                    help="with -sink; defaults to local")
    fr.add_argument("-sink", default="",
                    help="local dir, target filer ip:port, or s3 "
                         "endpoint/bucket; empty = use replication.toml")
    fr.set_defaults(fn=cmd_filer_replicate)

    fb = sub.add_parser("filer.backup")
    fb.add_argument("-filer", default="127.0.0.1:8888")
    fb.add_argument("-filerPath", default="/")
    fb.add_argument("-dir", required=True)
    fb.set_defaults(fn=cmd_filer_backup)

    fmt = sub.add_parser("filer.meta.tail")
    fmt.add_argument("-filer", default="127.0.0.1:8888")
    fmt.add_argument("-pathPrefix", default="/")
    fmt.set_defaults(fn=cmd_filer_meta_tail)

    fmb = sub.add_parser("filer.meta.backup")
    fmb.add_argument("-filer", default="127.0.0.1:8888")
    fmb.add_argument("-filerDir", default="/",
                     help="only back up this folder of the filer")
    fmb.add_argument("-restart", action="store_true",
                     help="copy the full metadata before the async "
                          "incremental backup")
    fmb.add_argument("-store", default="./meta_backup.db",
                     help="backup sqlite db path")
    fmb.set_defaults(fn=cmd_filer_meta_backup)

    fsy = sub.add_parser("filer.sync")
    fsy.add_argument("-a", required=True, help="filer A ip:port")
    fsy.add_argument("-b", required=True, help="filer B ip:port")
    fsy.add_argument("-filerPath", default="/")
    fsy.set_defaults(fn=cmd_filer_sync)

    s3p = sub.add_parser("s3")
    s3p.add_argument("-filer", default="127.0.0.1:8888",
                     help="filer http address(es), comma-separated; a "
                          "list pins a static fleet ring")
    s3p.add_argument("-master", default="",
                     help="comma-separated master http addresses: "
                          "discover the filer fleet from the master's "
                          "registrations and route by consistent hash "
                          "(the stateless-gateway mode)")
    s3p.add_argument("-port", type=int, default=8333)
    s3p.add_argument("-config", default="",
                     help="s3 identities json (empty = auth disabled)")
    s3p.add_argument("-domainName", default="")
    s3p.add_argument("-iam.config", dest="iam_config",
                     default="/etc/iam/identity.json",
                     help="filer path of the IAM-managed identity json "
                          "('' disables the live-reload loop)")
    s3p.add_argument("-geoMaster", default="",
                     help="comma-separated REMOTE-cluster master http "
                          "addresses: when the local filer fleet is "
                          "entirely unreachable, reads/writes fail over "
                          "to the remote cluster (geo failover)")
    s3p.set_defaults(fn=cmd_s3)

    iamp = sub.add_parser("iam")
    iamp.add_argument("-filer", default="127.0.0.1:8888")
    iamp.add_argument("-port", type=int, default=8111)
    iamp.set_defaults(fn=cmd_iam)

    gwp = sub.add_parser("gateway")
    gwp.add_argument("-master", default="127.0.0.1:9333",
                     help="comma-separated master http addresses")
    gwp.add_argument("-filer", default="",
                     help="comma-separated filer http addresses")
    gwp.add_argument("-port", type=int, default=5647)
    gwp.set_defaults(fn=cmd_gateway)

    wd = sub.add_parser("webdav")
    wd.add_argument("-filer", default="127.0.0.1:8888")
    wd.add_argument("-port", type=int, default=7333)
    wd.set_defaults(fn=cmd_webdav)

    fp = sub.add_parser("ftp")
    fp.add_argument("-filer", default="127.0.0.1:8888")
    fp.add_argument("-ip", default="127.0.0.1")
    fp.add_argument("-port", type=int, default=8021)
    fp.add_argument("-users", default="",
                    help='JSON file {"user": "password"}; empty = anonymous')
    fp.set_defaults(fn=cmd_ftp)

    bk = sub.add_parser("backup")
    bk.add_argument("-server", default="127.0.0.1:9333",
                    help="master http address")
    bk.add_argument("-volumeId", type=int, required=True)
    bk.add_argument("-dir", default=".")
    bk.add_argument("-collection", default="")
    bk.set_defaults(fn=cmd_backup)

    up = sub.add_parser("upload")
    up.add_argument("-master", default="127.0.0.1:9333")
    up.add_argument("-collection", default="")
    up.add_argument("-replication", default="")
    up.add_argument("-ttl", default="")
    up.add_argument("files", nargs="+")
    up.set_defaults(fn=cmd_upload)

    dl = sub.add_parser("download")
    dl.add_argument("-server", default="127.0.0.1:9333",
                    help="master http address")
    dl.add_argument("-dir", default=".")
    dl.add_argument("fids", nargs="+")
    dl.set_defaults(fn=cmd_download)

    fcat = sub.add_parser("filer.cat")
    fcat.add_argument("-filer", default="127.0.0.1:8888")
    fcat.add_argument("path")
    fcat.set_defaults(fn=cmd_filer_cat)

    fcp = sub.add_parser("filer.copy")
    fcp.add_argument("-filer", default="127.0.0.1:8888")
    fcp.add_argument("sources", nargs="+")
    fcp.add_argument("dest")
    fcp.set_defaults(fn=cmd_filer_copy)

    sh = sub.add_parser("shell")
    sh.add_argument("-master", default=None,
                    help="master ip:port (omitted -> shell.toml, then "
                         "127.0.0.1:9333)")
    sh.add_argument("-filer", default="",
                    help="filer http address for fs.*/s3.* commands")
    sh.add_argument("-c", dest="command", default="")
    sh.set_defaults(fn=cmd_shell)

    b = sub.add_parser("benchmark")
    b.add_argument("-master", default="127.0.0.1:9333")
    b.add_argument("-n", type=int, default=1024)
    b.add_argument("-size", type=int, default=1024)
    b.add_argument("-c", type=int, default=16)
    b.add_argument("--write-only", action="store_true")
    b.set_defaults(fn=cmd_bench)

    fx = sub.add_parser("fix")
    fx.add_argument("-dir", default=".")
    fx.add_argument("-volumeId", type=int, required=True)
    fx.add_argument("-collection", default="")
    fx.set_defaults(fn=cmd_fix)

    cp = sub.add_parser("compact")
    cp.add_argument("-dir", default=".")
    cp.add_argument("-volumeId", type=int, required=True)
    cp.add_argument("-collection", default="")
    cp.set_defaults(fn=cmd_compact)

    ex = sub.add_parser("export")
    ex.add_argument("-dir", default=".")
    ex.add_argument("-volumeId", type=int, required=True)
    ex.add_argument("-collection", default="")
    ex.add_argument("-o", dest="output", default="export.tar")
    ex.set_defaults(fn=cmd_export)

    ver = sub.add_parser("version")
    ver.set_defaults(fn=lambda a: print("seaweedfs_tpu 0.1.0"))

    sc = sub.add_parser("scaffold")
    sc.add_argument("-config", default="security",
                    choices=("security", "master", "filer",
                             "notification", "replication", "shell"))
    sc.add_argument("-output", default=".",
                    help="output directory, or - for stdout")
    sc.set_defaults(fn=cmd_scaffold)

    args = p.parse_args(argv)
    _setup_profiling(args)
    if args.cmd != "scaffold":
        _configure_security(args.cmd)
    args.fn(args)


if __name__ == "__main__":
    main()
