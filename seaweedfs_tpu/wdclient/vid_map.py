"""vid -> locations cache with a round-robin read cursor.

Reference: weed/wdclient/vid_map.go:30-43 — a map of volume id to server
locations plus an atomic cursor so concurrent readers spread load across
replicas.
"""

from __future__ import annotations

import itertools
import threading
from dataclasses import dataclass


@dataclass(frozen=True)
class Location:
    url: str
    public_url: str = ""


class VidMap:
    def __init__(self):
        self._locations: dict[int, list[Location]] = {}
        self._lock = threading.RLock()
        self._cursor = itertools.count()

    def lookup(self, vid: int) -> list[Location]:
        with self._lock:
            return list(self._locations.get(vid, ()))

    def pick(self, vid: int) -> Location | None:
        """Round-robin one location for a read."""
        with self._lock:
            locs = self._locations.get(vid)
            if not locs:
                return None
            return locs[next(self._cursor) % len(locs)]

    def add_location(self, vid: int, loc: Location) -> None:
        with self._lock:
            locs = self._locations.setdefault(vid, [])
            if all(l.url != loc.url for l in locs):
                locs.append(loc)

    def delete_location(self, vid: int, url: str) -> None:
        with self._lock:
            locs = self._locations.get(vid)
            if not locs:
                return
            locs[:] = [l for l in locs if l.url != url]
            if not locs:
                del self._locations[vid]

    def delete_volume(self, vid: int) -> None:
        with self._lock:
            self._locations.pop(vid, None)

    def delete_server(self, url: str) -> None:
        with self._lock:
            for vid in list(self._locations):
                self.delete_location(vid, url)

    def vids(self) -> list[int]:
        with self._lock:
            return list(self._locations)
