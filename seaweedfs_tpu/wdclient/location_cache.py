"""Tiered TTL cache for EC shard locations.

Reference: weed/storage/store_ec.go:223-264 (cachedLookupEcShardLocations)
keeps shard locations fresh on a tiered schedule instead of one flat TTL:
recently-confirmed locations are trusted for a while, EMPTY lookup results
are negative-cached only briefly (the shards may be mounting right now),
and a FAILED lookup serves stale data rather than silently returning
nothing — a dead master must degrade reads to "possibly stale", not
"volume vanished".
"""

from __future__ import annotations

import threading
import time
from typing import Callable

# lookup() -> {shard_id: [urls]}; raises on transport failure
LookupFn = Callable[[], "dict[int, list[str]]"]


class TieredLocationCache:
    """One instance caches the shard->locations map of a single EC volume.

    Tiers (seconds):
      found_ttl    — a lookup that returned locations is trusted this long
      empty_ttl    — a lookup that returned {} is negative-cached this long
      error_retry  — after a failed lookup, wait this long before retrying
                     (stale locations keep being served meanwhile)
    """

    def __init__(
        self,
        lookup: LookupFn,
        found_ttl: float = 300.0,
        empty_ttl: float = 11.0,
        error_retry: float = 2.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self._lookup = lookup
        self.found_ttl = found_ttl
        self.empty_ttl = empty_ttl
        self.error_retry = error_retry
        self._clock = clock
        self._lock = threading.Lock()
        self._locations: dict[int, list[str]] = {}
        self._fetched_at = float("-inf")  # last SUCCESSFUL lookup
        self._errored_at = float("-inf")  # last FAILED lookup
        self.lookups = 0  # successful upstream lookups (for tests/metrics)
        self.errors = 0

    def get(self) -> dict[int, list[str]]:
        with self._lock:
            now = self._clock()
            age = now - self._fetched_at
            ttl = self.found_ttl if self._locations else self.empty_ttl
            if age < ttl:
                return self._locations
            if now - self._errored_at < self.error_retry:
                return self._locations  # stale (or empty) until retry time
            try:
                fresh = self._lookup()
            except Exception:
                self.errors += 1
                self._errored_at = now
                return self._locations  # serve stale over nothing
            self.lookups += 1
            self._locations = fresh
            self._fetched_at = now
            return self._locations

    def invalidate(self) -> None:
        """Force the next get() to hit the upstream (e.g. after a fetch
        from a cached location failed — it may have moved)."""
        with self._lock:
            self._fetched_at = float("-inf")
            self._errored_at = float("-inf")
