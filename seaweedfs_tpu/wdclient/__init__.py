"""Cluster client library: MasterClient + vidMap location cache.

Reference surface: weed/wdclient (masterclient.go, vid_map.go).
"""

from .masterclient import MasterClient
from .vid_map import Location, VidMap

__all__ = ["MasterClient", "VidMap", "Location"]
