"""MasterClient: long-lived client keeping a streamed vid->location cache.

Reference: weed/wdclient/masterclient.go:38-154 — a reconnecting
KeepConnected stream against the current leader feeds VolumeLocation deltas
into the vidMap; lookups that miss the cache fall back to a LookupVolume
rpc.  Clients chase the leader hint carried on each VolumeLocation.
"""

from __future__ import annotations

import queue
import threading
import time

import grpc

from ..pb import master_pb2
from ..pb import rpc as rpclib
from ..util import failsafe
from .vid_map import Location, VidMap

# how often a registered client (filer) refreshes its stats snapshot on
# the KeepConnected stream — the master's federation fallback data
STATS_INTERVAL_S = 10.0

# typed NOT_LEADER rejection detail emitted by the master's grpc layer —
# the suffix is the leader's grpc address, so a client can re-resolve in
# one hop instead of rotating through the seed list on backoff
NOT_LEADER_PREFIX = "not the leader; leader is "


def parse_leader_hint(err: Exception) -> str:
    """leader grpc address out of a NOT_LEADER grpc error, or ''."""
    details = getattr(err, "details", None)
    detail = details() if callable(details) else str(err)
    if detail and NOT_LEADER_PREFIX in detail:
        hint = detail.split(NOT_LEADER_PREFIX, 1)[1].strip()
        # "None" = the deposed master does not know the new leader yet
        if hint and hint != "None":
            return hint
    return ""


class MasterClient:
    def __init__(self, name: str, master_grpc_addresses: list[str],
                 grpc_port: int = 0, client_type: str = "",
                 http_address: str = ""):
        self.name = name
        self.masters = list(master_grpc_addresses)
        self.grpc_port = grpc_port
        # federation registration: a non-empty client_type announces this
        # process (e.g. a filer) to the master's observability plane with
        # a scrapeable HTTP address + periodic stats snapshots
        self.client_type = client_type
        self.http_address = http_address
        self.vid_map = VidMap()
        self.current_master = ""
        self._leader_hint = ""
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._connected = threading.Event()

    # -- lifecycle --------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._keep_connected_loop, daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def wait_until_connected(self, timeout: float = 10.0) -> bool:
        return self._connected.wait(timeout)

    # -- the KeepConnected loop ------------------------------------------

    def _keep_connected_loop(self) -> None:
        i = 0
        backoff = failsafe.Backoff(failsafe.RetryPolicy(
            max_attempts=1 << 30, base_delay=0.25, max_delay=5.0))
        while not self._stop.is_set():
            if self._leader_hint and self._leader_hint in self.masters:
                master = self._leader_hint
                self._leader_hint = ""
            else:
                master = self.masters[i % len(self.masters)]
                i += 1
            try:
                self._stream_from(master)
            except grpc.RpcError as e:
                hint = parse_leader_hint(e)
                if hint and hint in self.masters:
                    self._leader_hint = hint
            if self._connected.is_set():
                backoff.reset()  # the stream was live; reconnect fast
            self._connected.clear()
            if self._leader_hint:
                # deposed leader handed us its successor: reconnect NOW
                # — a fixed backoff here leaves lookups pointed at a
                # follower for a whole rotation cycle after failover
                continue
            self._stop.wait(backoff.next())

    def _registration(self) -> master_pb2.KeepConnectedRequest:
        req = master_pb2.KeepConnectedRequest(
            name=self.name, grpc_port=self.grpc_port,
            client_type=self.client_type, http_address=self.http_address,
        )
        if self.client_type:
            from ..stats.metrics import REGISTRY

            req.stats.captured_at_ms = int(time.time() * 1000)
            for sname, value in REGISTRY.snapshot_samples():
                req.stats.samples.add(name=sname, value=value)
        return req

    def _stream_from(self, master: str) -> None:
        stub = rpclib.master_stub(master)

        def requests():
            yield self._registration()
            # keep the stream open until stopped; registered clients
            # refresh their stats snapshot so the master's federation
            # fallback stays at most STATS_INTERVAL_S stale
            last_stats = time.monotonic()
            while not self._stop.wait(1.0):
                if (self.client_type
                        and time.monotonic() - last_stats
                        >= STATS_INTERVAL_S):
                    last_stats = time.monotonic()
                    yield self._registration()

        for loc in stub.KeepConnected(requests()):
            if self._stop.is_set():
                return
            self.current_master = master
            self._connected.set()
            self._apply(loc)
            if loc.leader:
                # leader hints carry the HTTP address; grpc = port + 10000
                host, port = loc.leader.rsplit(":", 1)
                leader_grpc = f"{host}:{int(port) + 10000}"
                if leader_grpc != master and leader_grpc in self.masters:
                    # leader moved: break the stream and reconnect there
                    self._leader_hint = leader_grpc
                    return

    def _apply(self, loc: master_pb2.VolumeLocation) -> None:
        location = Location(url=loc.url, public_url=loc.public_url or loc.url)
        for vid in loc.new_vids:
            self.vid_map.add_location(vid, location)
        for vid in loc.deleted_vids:
            self.vid_map.delete_location(vid, loc.url)

    # -- lookups ----------------------------------------------------------

    def lookup_volume(self, vid: int, refresh: bool = False) -> list[Location]:
        """Locations serving vid; `refresh=True` bypasses the cache (used
        after a cached location turned out dead — the volume may have
        moved or been EC-encoded, and the master's answer reflects that).

        A LookupVolume failure rotates to the next master under the
        shared failover policy instead of failing the request."""
        if not refresh:
            locs = self.vid_map.lookup(vid)
            if locs:
                return locs

        def ask(master: str) -> master_pb2.LookupVolumeResponse:
            req = master_pb2.LookupVolumeRequest(
                volume_or_file_ids=[str(vid)])
            try:
                return rpclib.master_stub(
                    master, timeout=10).LookupVolume(req)
            except grpc.RpcError as e:
                hint = parse_leader_hint(e)
                if hint and hint in self.masters and hint != master:
                    # follower named the leader: one extra hop beats
                    # burning a failover round on the rest of the seeds
                    self.current_master = hint
                    self._leader_hint = hint
                    return rpclib.master_stub(
                        hint, timeout=10).LookupVolume(req)
                raise

        try:
            resp = failsafe.call_with_failover(
                lambda _round: self._master_order(), ask,
                op="lookup_volume", retry_type="masterClient",
                policy=failsafe.RPC_POLICY, idempotent=True,
            )
        except (grpc.RpcError, failsafe.CircuitOpenError, OSError):
            # every master refused/errored: a stale cached answer (even
            # the one we bypassed) beats none at all
            return self.vid_map.lookup(vid)
        if refresh:
            self.vid_map.delete_volume(vid)
        for vl in resp.volume_id_locations:
            for l in vl.locations:
                self.vid_map.add_location(
                    vid, Location(l.url, l.public_url or l.url)
                )
        return self.vid_map.lookup(vid)

    def lookup_file_id(self, fid: str, refresh: bool = False) -> list[str]:
        """-> public urls serving this file id."""
        vid = int(fid.split(",", 1)[0])
        return [
            f"http://{l.public_url or l.url}/{fid}"
            for l in self.lookup_volume(vid, refresh=refresh)
        ]

    def invalidate_location(self, vid: int, url_or_netloc: str) -> None:
        """Evict one cached location of vid — called when a connection to
        that server was REFUSED (the process is gone; waiting out a TTL
        would keep routing reads into a dead peer)."""
        from ..util.http_util import netloc

        server = netloc(url_or_netloc)
        for loc in list(self.vid_map.lookup(vid)):
            if server in (loc.url, loc.public_url):
                self.vid_map.delete_location(vid, loc.url)

    def _master_order(self) -> list[str]:
        if self.current_master:
            rest = [m for m in self.masters if m != self.current_master]
            return [self.current_master, *rest]
        return list(self.masters)
