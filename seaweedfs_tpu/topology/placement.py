"""Replica placement as pure functions over node snapshots.

Reference: weed/topology/volume_growth.go (pick main rack/DC then replicas)
and node_list.go.  Pure and deterministic given the candidate list and a
seed — the SURVEY.md §4 tier-3 test pattern.
"""

from __future__ import annotations

import random
from dataclasses import dataclass

from ..storage.replica_placement import ReplicaPlacement


@dataclass(frozen=True)
class Candidate:
    node_id: str
    data_center: str
    rack: str
    free_slots: int


def pick_nodes_for_write(
    candidates: list[Candidate],
    rp: ReplicaPlacement,
    data_center: str = "",
    rack: str = "",
    rng: random.Random | None = None,
) -> list[Candidate]:
    """Choose copy_count() nodes satisfying the XYZ placement policy.

    Raises ValueError when the topology can't satisfy the policy.
    """
    rng = rng or random.Random(0)
    usable = [c for c in candidates if c.free_slots > 0]
    if data_center:
        main_pool = [c for c in usable if c.data_center == data_center]
    else:
        main_pool = usable
    if rack:
        main_pool = [c for c in main_pool if c.rack == rack]
    if not main_pool:
        raise ValueError("no writable node in requested dc/rack")

    # group by dc -> rack
    by_dc: dict[str, dict[str, list[Candidate]]] = {}
    for c in usable:
        by_dc.setdefault(c.data_center, {}).setdefault(c.rack, []).append(c)

    # main dc must supply 1 + same_rack + diff_rack nodes
    def dc_ok(dc: str) -> bool:
        racks = by_dc[dc]
        sizes = sorted((len(v) for v in racks.values()), reverse=True)
        return (
            len(racks) >= 1 + rp.diff_rack
            and sum(sizes) >= 1 + rp.same_rack + rp.diff_rack
            and sizes[0] >= 1 + rp.same_rack
        )

    main_dcs = [c.data_center for c in main_pool]
    viable_dcs = [dc for dc in dict.fromkeys(main_dcs) if dc_ok(dc)]
    other_dcs = [dc for dc in by_dc if dc not in viable_dcs]
    if not viable_dcs:
        raise ValueError("replica placement unsatisfiable: no viable main dc")
    if len(by_dc) < 1 + rp.diff_dc:
        raise ValueError("replica placement unsatisfiable: not enough dcs")

    main_dc = rng.choice(viable_dcs)
    racks = by_dc[main_dc]
    viable_racks = [r for r, nodes in racks.items() if len(nodes) >= 1 + rp.same_rack]
    if rack and rack in viable_racks:
        viable_racks = [rack]
    if not viable_racks:
        raise ValueError("replica placement unsatisfiable: no rack with room")
    main_rack = rng.choice(viable_racks)

    picked: list[Candidate] = []
    # main node + same-rack copies
    rack_nodes = list(racks[main_rack])
    rng.shuffle(rack_nodes)
    need = 1 + rp.same_rack
    picked.extend(rack_nodes[:need])
    if len(picked) < need:
        raise ValueError("not enough nodes in main rack")
    # different racks in the same dc
    other_racks = [r for r in racks if r != main_rack]
    rng.shuffle(other_racks)
    if len(other_racks) < rp.diff_rack:
        raise ValueError("not enough racks for diff-rack copies")
    for r in other_racks[: rp.diff_rack]:
        picked.append(rng.choice(racks[r]))
    # different data centers
    dcs = [dc for dc in by_dc if dc != main_dc]
    rng.shuffle(dcs)
    if len(dcs) < rp.diff_dc:
        raise ValueError("not enough data centers for diff-dc copies")
    for dc in dcs[: rp.diff_dc]:
        all_nodes = [c for nodes in by_dc[dc].values() for c in nodes]
        picked.append(rng.choice(all_nodes))
    return picked


def ec_source_locality(rack: str, data_center: str,
                       my_rack: str, my_dc: str) -> str:
    """Locality label of a remote EC repair source relative to the
    rebuilder: `rack` = same rack (and DC), `dc` = anything beyond the
    rack boundary.  `local` (same node) never reaches here — local
    shards are read from disk, not fetched."""
    if rack and rack == my_rack and (not my_dc or data_center == my_dc):
        return "rack"
    return "dc"


def best_ec_holder(
    candidates: "list[tuple[str, str, str]]",
    my_rack: str = "",
    my_dc: str = "",
) -> "tuple[str, str, str]":
    """Best holder of one shard from its (address, rack, dc) candidate
    list: same-rack wins, address as tiebreak — the ONE rule shared by
    the rebuilder's client and the shell's `ec.rebuild -plan`, so the
    dry run can never diverge from what the rebuilder actually does."""
    return min(candidates, key=lambda h: (
        0 if ec_source_locality(h[1], h[2], my_rack, my_dc) == "rack"
        else 1, h[0]))


def order_ec_sources(
    holders: "dict[int, tuple[str, str, str]]",
    my_rack: str = "",
    my_dc: str = "",
) -> list[int]:
    """Rack/DC-aware remote source selection: order candidate source
    shard ids so same-rack holders are drawn first, then same-DC, then
    the rest — repair traffic prefers the cheap links (arXiv:1309.0186).
    `holders` maps shard id -> (address, rack, dc) of its best holder.
    Shard id breaks ties so the order is deterministic."""
    def rank(sid: int) -> tuple:
        _addr, rack, dc = holders[sid]
        same_rack = rack == my_rack and (not my_dc or dc == my_dc)
        same_dc = dc == my_dc
        return (0 if same_rack else 1 if same_dc else 2, sid)

    return sorted(holders, key=rank)


def group_partial_sources(
    holders: "dict[int, tuple[str, str, str]]",
) -> list[dict]:
    """Group chosen remote sources into one partial-sum request per
    rack: every member server computes its local coefficient-weighted
    sum, the group's aggregator folds them, and exactly ONE combined
    partial crosses the rack boundary per group.

    The aggregator is the member holding the most source shards (fewest
    delegate hops for the bulk of the bytes), address as tiebreak.
    Returns [{"rack", "dc", "aggregator", "members": {addr: [sids]}}]
    sorted by (dc, rack) for determinism."""
    by_rack: dict[tuple[str, str], dict[str, list[int]]] = {}
    for sid, (addr, rack, dc) in sorted(holders.items()):
        by_rack.setdefault((dc, rack), {}).setdefault(addr, []).append(sid)
    groups = []
    for (dc, rack), members in sorted(by_rack.items()):
        aggregator = max(members, key=lambda a: (len(members[a]), a))
        groups.append({
            "rack": rack,
            "dc": dc,
            "aggregator": aggregator,
            "members": members,
        })
    return groups


def spread_rebuild_targets(
    volumes: "list[dict]",
    candidates: "dict[str, int]",
) -> "dict[int, str]":
    """Assign one rebuild-target node per volume of a mass-repair batch
    so no single node becomes the write bottleneck: a hard cap of
    ceil(N / alive_nodes) + 1 assignments per node.

    ``volumes`` come pre-ranked (exposure order — the assignment keeps
    that order so the most exposed volumes get first pick of targets);
    each entry carries ``volume_id`` and ``holders`` (node -> count of
    surviving shards it holds).  ``candidates`` maps alive node ids to
    free EC slots.  Within the cap the node already holding the most
    surviving shards of the volume wins (its plan columns apply locally,
    off the wire), then most free slots, id as tiebreak."""
    import math

    if not candidates:
        return {}
    cap = math.ceil(len(volumes) / len(candidates)) + 1
    load = {n: 0 for n in candidates}
    out: dict[int, str] = {}
    for v in volumes:
        under_cap = [n for n in candidates if load[n] < cap]
        # a full node (no free EC slots left after its assignments so
        # far) cannot STORE the rebuilt shards — preferring it for its
        # local sources would park the job on no-space retries while
        # capacity sits idle elsewhere; only when EVERY node is full is
        # it allowed back in (the rebuild itself surfaces the no-space)
        eligible = [n for n in under_cap if candidates[n] - load[n] > 0]
        if not eligible:
            eligible = under_cap
        holders = v.get("holders", {})
        best = max(eligible, key=lambda n: (
            holders.get(n, 0), candidates[n] - load[n], n))
        out[v["volume_id"]] = best
        load[best] += 1
    return out


def balanced_ec_distribution(
    free_slots_by_node: dict[str, int], total_shards: int = 14
) -> dict[str, list[int]]:
    """Spread shard ids across nodes, most-free-first, round-robin.

    Mirrors balancedEcDistribution (command_ec_encode.go:248-264): each
    allocation goes to the node with the most remaining free EC slots.
    """
    remaining = dict(free_slots_by_node)
    out: dict[str, list[int]] = {n: [] for n in free_slots_by_node}
    for sid in range(total_shards):
        best = max(remaining, key=lambda n: (remaining[n], -len(out[n])))
        if remaining[best] <= 0:
            raise ValueError("not enough free EC slots for all shards")
        out[best].append(sid)
        remaining[best] -= 1
    return {n: sids for n, sids in out.items() if sids}
