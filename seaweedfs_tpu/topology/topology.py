"""Cluster topology: DC -> rack -> data node tree with volume/EC bookkeeping.

Reference: weed/topology/ (node tree, topology.go, topology_ec.go).  The
tree is kept as flat dicts keyed by node id ("ip:port") with dc/rack
attributes — placement logic consumes snapshots, not the tree itself, so
the Go pointer-tree shape isn't load-bearing and is not reproduced.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

from ..pb import master_pb2
from ..storage.ec.shard_bits import ShardBits


@dataclass
class VolumeInfo:
    volume_id: int
    size: int = 0
    collection: str = ""
    file_count: int = 0
    delete_count: int = 0
    deleted_byte_count: int = 0
    read_only: bool = False
    replica_placement: int = 0
    version: int = 3
    ttl: int = 0
    compact_revision: int = 0
    modified_at_second: int = 0
    disk_type: str = ""  # normalized: "" == hdd

    @classmethod
    def from_pb(cls, m: master_pb2.VolumeInformationMessage) -> "VolumeInfo":
        return cls(
            volume_id=m.id,
            size=m.size,
            collection=m.collection,
            file_count=m.file_count,
            modified_at_second=m.modified_at_second,
            delete_count=m.delete_count,
            deleted_byte_count=m.deleted_byte_count,
            read_only=m.read_only,
            replica_placement=m.replica_placement,
            version=m.version,
            ttl=m.ttl,
            compact_revision=m.compact_revision,
            disk_type=m.disk_type,
        )


@dataclass
class DataNode:
    id: str  # "ip:port" (HTTP url)
    public_url: str
    grpc_address: str
    data_center: str = "DefaultDataCenter"
    rack: str = "DefaultRack"
    max_volumes: int = 7
    volumes: dict = field(default_factory=dict)  # vid -> VolumeInfo
    ec_shards: dict = field(default_factory=dict)  # vid -> ShardBits
    ec_collections: dict = field(default_factory=dict)  # vid -> collection
    ec_shard_sizes: dict = field(default_factory=dict)  # vid -> bytes/shard
    last_seen: float = field(default_factory=time.monotonic)
    # per-disk-type capacity from the heartbeat's max_volume_counts map
    # (reference: Disk nodes under DataNode); empty -> one default tier
    max_volume_counts: dict = field(default_factory=dict)
    # disk-fault plane: dir -> {"state", "free_bytes", "total_bytes"}
    # from the heartbeat's DiskHealthMessage list; empty = unknown
    # (legacy node), treated as healthy
    disk_health: dict = field(default_factory=dict)

    def worst_disk_state(self) -> str:
        """The most degraded state across this node's data dirs
        ("healthy" when the node reports nothing)."""
        order = {"healthy": 0, "low_space": 1, "full": 2, "failing": 3}
        worst = "healthy"
        for d in self.disk_health.values():
            s = d.get("state", "healthy")
            if order.get(s, 0) > order[worst]:
                worst = s
        return worst

    def has_writable_disk(self) -> bool:
        """False when EVERY reported disk is full or failing: growth and
        rebuild placement must not target this node."""
        if not self.disk_health:
            return True
        return any(d.get("state") in ("healthy", "low_space", None)
                   for d in self.disk_health.values())

    def free_slots(self) -> int:
        if not self.has_writable_disk():
            return 0
        return self.max_volumes - len(self.volumes) - (len(self.ec_shards) + 9) // 10

    def disk_types(self) -> list[str]:
        return sorted(self.max_volume_counts) if self.max_volume_counts \
            else [""]

    def free_slots_for(self, disk_type: str) -> int:
        """Free volume slots on one disk tier (capacityByFreeVolumeCount,
        command_ec_common.go / command_volume_tier_move.go).  A node
        whose disks are all full/failing has no free slots on ANY tier —
        the watermark gates placement before ENOSPC can."""
        if not self.has_writable_disk():
            return 0
        cap = self.max_volume_counts.get(disk_type)
        if cap is None:
            if disk_type == "" and not self.max_volume_counts:
                cap = self.max_volumes  # legacy node: one default tier
            else:
                return 0
        used = sum(1 for v in self.volumes.values()
                   if v.disk_type == disk_type)
        return cap - used

    def free_ec_slots(self) -> int:
        if not self.has_writable_disk():
            return 0
        used = sum(ShardBits(b).count() for b in self.ec_shards.values())
        return (self.max_volumes - len(self.volumes)) * 10 - used


class Topology:
    def __init__(self, volume_size_limit: int = 30 * 1024**3,
                 pulse_seconds: float = 5.0):
        self.nodes: dict[str, DataNode] = {}
        self.volume_size_limit = volume_size_limit
        self.pulse_seconds = pulse_seconds
        self.lock = threading.RLock()
        self.max_volume_id = 0

    # -- membership -------------------------------------------------------

    def register_node(self, node: DataNode) -> "tuple[DataNode, bool]":
        """-> (node, was_new).  `was_new` is decided under the SAME lock
        acquisition that registers, so two concurrent streams for one
        node id can never both observe a join."""
        with self.lock:
            existing = self.nodes.get(node.id)
            if existing is None:
                self.nodes[node.id] = node
                return node, True
            existing.last_seen = time.monotonic()
            existing.public_url = node.public_url
            existing.grpc_address = node.grpc_address
            if node.data_center:
                existing.data_center = node.data_center
            if node.rack:
                existing.rack = node.rack
            if node.max_volumes:
                existing.max_volumes = node.max_volumes
            if node.max_volume_counts:
                existing.max_volume_counts = dict(node.max_volume_counts)
            return existing, False

    def unregister_node(self, node_id: str) -> list[int]:
        """Remove a node; returns vids whose locations changed."""
        with self.lock:
            node = self.nodes.pop(node_id, None)
            if node is None:
                return []
            return list(node.volumes) + list(node.ec_shards)

    def collect_dead_nodes(self) -> list[str]:
        """Nodes silent for 3 missed pulses (topology_event_handling.go:17)."""
        cutoff = time.monotonic() - 3 * self.pulse_seconds
        with self.lock:
            return [nid for nid, n in self.nodes.items() if n.last_seen < cutoff]

    # -- volume bookkeeping ----------------------------------------------

    def sync_volumes(self, node: DataNode,
                     volumes: list[master_pb2.VolumeInformationMessage]) -> None:
        with self.lock:
            node.volumes = {m.id: VolumeInfo.from_pb(m) for m in volumes}
            for m in volumes:
                self.max_volume_id = max(self.max_volume_id, m.id)
            node.last_seen = time.monotonic()

    def sync_ec_shards(self, node: DataNode,
                       shards: list[master_pb2.VolumeEcShardInformationMessage]) -> None:
        with self.lock:
            node.ec_shards = {m.id: ShardBits(m.ec_index_bits) for m in shards}
            node.ec_collections = {m.id: m.collection for m in shards}
            node.ec_shard_sizes = {m.id: m.shard_size for m in shards
                                   if m.shard_size}
            node.last_seen = time.monotonic()

    def apply_incremental(self, node: DataNode, hb: master_pb2.Heartbeat) -> None:
        with self.lock:
            for m in hb.new_volumes:
                node.volumes[m.id] = VolumeInfo(
                    volume_id=m.id, collection=m.collection,
                    replica_placement=m.replica_placement, version=m.version,
                    ttl=m.ttl,
                )
                self.max_volume_id = max(self.max_volume_id, m.id)
            for m in hb.deleted_volumes:
                node.volumes.pop(m.id, None)
            for m in hb.new_ec_shards:
                bits = node.ec_shards.get(m.id, ShardBits(0))
                node.ec_shards[m.id] = bits.plus(m.ec_index_bits)
                node.ec_collections[m.id] = m.collection
                if m.shard_size:
                    node.ec_shard_sizes[m.id] = m.shard_size
            for m in hb.deleted_ec_shards:
                bits = node.ec_shards.get(m.id, ShardBits(0))
                left = bits.minus(m.ec_index_bits)
                if left:
                    node.ec_shards[m.id] = left
                else:
                    node.ec_shards.pop(m.id, None)
            node.last_seen = time.monotonic()

    # -- lookups ----------------------------------------------------------

    def lookup_volume(self, vid: int) -> list[DataNode]:
        with self.lock:
            return [n for n in self.nodes.values() if vid in n.volumes]

    def lookup_ec_shards(self, vid: int) -> dict[int, list[DataNode]]:
        """shard id -> nodes holding it."""
        out: dict[int, list[DataNode]] = {}
        with self.lock:
            for n in self.nodes.values():
                bits = n.ec_shards.get(vid)
                if bits is None:
                    continue
                for sid in ShardBits(bits).shard_ids():
                    out.setdefault(sid, []).append(n)
        return out

    def next_volume_id(self) -> int:
        with self.lock:
            self.max_volume_id += 1
            return self.max_volume_id

    def collections(self) -> set[str]:
        with self.lock:
            names = set()
            for n in self.nodes.values():
                for v in n.volumes.values():
                    names.add(v.collection)
                for c in n.ec_collections.values():
                    names.add(c)
            return names

    def to_topology_info(self) -> master_pb2.TopologyInfo:
        """Snapshot for VolumeList / shell placement logic."""
        info = master_pb2.TopologyInfo(id="topo")
        with self.lock:
            dcs: dict[str, master_pb2.DataCenterInfo] = {}
            racks: dict[tuple[str, str], master_pb2.RackInfo] = {}
            for n in self.nodes.values():
                dc = dcs.get(n.data_center)
                if dc is None:
                    dc = info.data_center_infos.add(id=n.data_center)
                    dcs[n.data_center] = dc
                rack_key = (n.data_center, n.rack)
                rack = racks.get(rack_key)
                if rack is None:
                    rack = dc.rack_infos.add(id=n.rack)
                    racks[rack_key] = rack
                dn = rack.data_node_infos.add(id=n.id)
                # one DiskInfo per disk type (reference DataNodeInfo
                # diskInfos map; "" == hdd default tier); the union with
                # volume-reported types keeps a volume visible even if the
                # node's capacity map doesn't advertise its tier
                types = sorted(set(n.disk_types())
                               | {v.disk_type for v in n.volumes.values()})
                for dt in types:
                    disk = dn.disk_infos[dt]
                    vols = [v for v in n.volumes.values()
                            if v.disk_type == dt]
                    disk.volume_count = len(vols)
                    disk.max_volume_count = (
                        n.max_volume_counts.get(dt, n.max_volumes))
                    disk.free_volume_count = n.free_slots_for(dt)
                    disk.active_volume_count = len(vols)
                    for v in vols:
                        disk.volume_infos.add(
                            id=v.volume_id,
                            size=v.size,
                            collection=v.collection,
                            file_count=v.file_count,
                            delete_count=v.delete_count,
                            deleted_byte_count=v.deleted_byte_count,
                            read_only=v.read_only,
                            replica_placement=v.replica_placement,
                            version=v.version,
                            ttl=v.ttl,
                            modified_at_second=v.modified_at_second,
                            disk_type=v.disk_type,
                        )
                # EC shards stay on the default tier's DiskInfo
                disk = dn.disk_infos[n.disk_types()[0]]
                for vid, bits in n.ec_shards.items():
                    disk.ec_shard_infos.add(
                        id=vid,
                        collection=n.ec_collections.get(vid, ""),
                        ec_index_bits=int(bits),
                    )
        return info
