from .topology import DataNode, Topology, VolumeInfo  # noqa: F401
from .volume_layout import VolumeLayout  # noqa: F401
