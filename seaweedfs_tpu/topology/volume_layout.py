"""VolumeLayout: writable-volume tracking per (collection, rp, ttl).

Reference: weed/topology/volume_layout.go — tracks which vids are writable
(enough replicas, not oversized, not read-only) and where they live.
"""

from __future__ import annotations

import random
import threading

from ..storage.replica_placement import ReplicaPlacement


class VolumeLayout:
    def __init__(self, rp: ReplicaPlacement, ttl: str,
                 volume_size_limit: int):
        self.rp = rp
        self.ttl = ttl
        self.volume_size_limit = volume_size_limit
        self.locations: dict[int, list[str]] = {}  # vid -> node ids
        self.writable: set[int] = set()
        self.readonly: set[int] = set()
        self.oversized: set[int] = set()
        self._lock = threading.RLock()
        self._rng = random.Random(0)

    def register(self, vid: int, node_id: str, size: int,
                 read_only: bool) -> None:
        with self._lock:
            locs = self.locations.setdefault(vid, [])
            if node_id not in locs:
                locs.append(node_id)
            if read_only:
                self.readonly.add(vid)
            else:
                self.readonly.discard(vid)
            if size >= self.volume_size_limit:
                self.oversized.add(vid)
            self._update_writable(vid)

    def unregister(self, vid: int, node_id: str) -> None:
        with self._lock:
            locs = self.locations.get(vid, [])
            if node_id in locs:
                locs.remove(node_id)
            if not locs:
                self.locations.pop(vid, None)
                self.writable.discard(vid)
            else:
                self._update_writable(vid)

    def _update_writable(self, vid: int) -> None:
        locs = self.locations.get(vid, [])
        ok = (
            len(locs) >= self.rp.copy_count()
            and vid not in self.readonly
            and vid not in self.oversized
        )
        if ok:
            self.writable.add(vid)
        else:
            self.writable.discard(vid)

    def pick_for_write(self) -> tuple[int, list[str]]:
        with self._lock:
            if not self.writable:
                raise LookupError("no writable volume")
            vid = self._rng.choice(sorted(self.writable))
            return vid, list(self.locations[vid])

    def set_oversized(self, vid: int, size: int) -> None:
        with self._lock:
            if size >= self.volume_size_limit:
                self.oversized.add(vid)
                self._update_writable(vid)

    def active_writable_count(self) -> int:
        with self._lock:
            return len(self.writable)
