"""Cross-process trace stitching.

Every process keeps its own span ring; before this module, following one
request across the cluster meant querying each server's /debug/traces
and joining on traceId by hand (METRICS.md used to say exactly that).
The master's /cluster/traces fans the per-trace query out to every
registered node and this module merges the per-node span lists into one
parent-linked timeline.

Clock skew: span `start` values are wall-clock stamps from different
machines.  Each node's /debug/traces response carries `now` (its wall
clock at render time); comparing that against the master's clock midway
through the scrape (send time + RTT/2, the classic NTP estimate) yields
a per-node skew that is annotated on the result AND applied to a
`startAdjusted` field per span, so the merged timeline sorts sanely even
across machines that disagree by more than a span duration.  The
estimate is RTT-bounded, not exact — it is labeled, never silently
folded into `start`.
"""

from __future__ import annotations


def stitch_trace(trace_id: str, node_results: list[dict]) -> dict:
    """Merge per-node span lists for one trace id.

    `node_results` items: {
        "instance": "ip:port", "type": "volume" | "filer" | "master",
        "spans": [span dicts from /debug/traces],
        "skew_s": estimated node_clock - master_clock (0.0 for self),
        "rtt_s": scrape round trip (0.0 for self),
    }

    -> {"traceId", "spans": [...], "nodes": {...}, "startS", "durationMs"}
    with spans sorted by skew-adjusted start, each span annotated with
    `instance` and `startAdjusted`, and parent links marked `orphan` when
    the parent span id was not found anywhere in the merged set (its
    process died, or the ring evicted it).
    """
    spans: list[dict] = []
    nodes: dict[str, dict] = {}
    for res in node_results:
        instance = res["instance"]
        node_spans = res.get("spans", [])
        nodes[instance] = {
            "type": res.get("type", ""),
            "spanCount": len(node_spans),
            "clockSkewMs": round(res.get("skew_s", 0.0) * 1e3, 3),
            "scrapeRttMs": round(res.get("rtt_s", 0.0) * 1e3, 3),
        }
        for s in node_spans:
            s = dict(s)
            s["instance"] = instance
            s["startAdjusted"] = s["start"] - res.get("skew_s", 0.0)
            spans.append(s)
    known_ids = {s["spanId"] for s in spans}
    for s in spans:
        s["orphan"] = bool(s["parentId"]) and s["parentId"] not in known_ids
    spans.sort(key=lambda s: s["startAdjusted"])
    out = {"traceId": trace_id, "nodes": nodes, "spans": spans}
    if spans:
        t0 = spans[0]["startAdjusted"]
        t1 = max(s["startAdjusted"] + s["durationMs"] / 1e3 for s in spans)
        out["startS"] = round(t0, 6)
        out["durationMs"] = round((t1 - t0) * 1e3, 3)
    return out


def estimate_skew(node_now: float, sent_at: float, rtt_s: float) -> float:
    """node_clock - local_clock, assuming a symmetric network path."""
    return node_now - (sent_at + rtt_s / 2.0)
