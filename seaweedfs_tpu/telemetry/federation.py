"""Cluster metrics federation: merge per-node Prometheus expositions.

The master's /cluster/metrics scrapes every known node's /metrics over
the keep-alive pool (bounded per-node deadline, concurrent fan-out) and
re-serves one exposition with `instance="ip:port"` and `type="volume" |
"filer" | "master"` labels injected into every sample — the shape
Prometheus's own /federate endpoint produces, so one scrape config
covers a whole cluster.  Nodes a live scrape cannot reach fall back to
the compact gauge/counter snapshot their last heartbeat carried, marked
with `seaweedfs_federation_stale{instance} 1` and a snapshot-age sample
so dashboards can grey them out instead of silently flat-lining.

The merge is family-grouped (the text format requires all samples of a
family contiguous): each node's exposition is parsed into families +
samples, HELP/TYPE are deduplicated (first node wins; identical
codebase, so they agree), and samples append under their family with the
extra labels injected ahead of the node's own.
"""

from __future__ import annotations

from ..stats.metrics import REGISTRY, escape_label_value

# synthesized federation meta-families (rendered here, not registered in
# the process registry: they describe the scrape, not this process)
FED_UP = "seaweedfs_federation_up"
FED_STALE = "seaweedfs_federation_stale"
FED_AGE = "seaweedfs_federation_snapshot_age_seconds"
FED_SCRAPE_SECONDS = "seaweedfs_federation_scrape_seconds"

_META_FAMILIES = {
    FED_UP: ("gauge", "live federation scrape succeeded for this node"),
    FED_STALE: ("gauge",
                "serving a heartbeat snapshot because the live scrape "
                "failed"),
    FED_AGE: ("gauge", "age of the heartbeat snapshot being served"),
    FED_SCRAPE_SECONDS: ("gauge", "wall time of the live scrape"),
}


def inject_labels(sample_name: str, extra: dict) -> str:
    """`name{a="b"}` + {instance: i, type: t} -> `name{instance="i",...}`.

    Extra labels go FIRST so a node-side label can never mask them; the
    node's own label text is preserved verbatim (it is already escaped).
    """
    pairs = ",".join(
        f'{k}="{escape_label_value(v)}"' for k, v in extra.items())
    if not pairs:
        return sample_name
    brace = sample_name.find("{")
    if brace < 0:
        return f"{sample_name}{{{pairs}}}"
    inner = sample_name[brace + 1:-1]
    if inner:
        return f"{sample_name[:brace]}{{{pairs},{inner}}}"
    return f"{sample_name[:brace]}{{{pairs}}}"


def parse_exposition(text: str):
    """-> (families, samples): families[name] = (kind, help);
    samples = [(family, sample_name_with_labels, value_text)].

    A sample whose family has no TYPE line files under its own name with
    kind "untyped".  Histogram samples (`_bucket`/`_sum`/`_count`) file
    under their base family so regrouping keeps them contiguous."""
    families: dict[str, tuple[str, str]] = {}
    helps: dict[str, str] = {}
    samples: list[tuple[str, str, str]] = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("# HELP "):
            name, _, help_ = line[len("# HELP "):].partition(" ")
            helps[name] = help_
            continue
        if line.startswith("# TYPE "):
            name, _, kind = line[len("# TYPE "):].partition(" ")
            families[name] = (kind.strip(), helps.get(name, ""))
            continue
        if line.startswith("#"):
            continue
        brace = line.find("{")
        if brace >= 0:
            close = line.rfind("}")
            if close < 0:
                continue  # malformed; drop rather than corrupt the merge
            name, sample_name = line[:brace], line[: close + 1]
            value = line[close + 1:].strip().split(" ")[0]
        else:
            space = line.find(" ")
            if space < 0:
                continue
            name = sample_name = line[:space]
            value = line[space + 1:].strip().split(" ")[0]
        family = _family_of(name, families)
        samples.append((family, sample_name, value))
    return families, samples


def _family_of(sample_name: str, families: dict) -> str:
    if sample_name in families:
        return sample_name
    for suffix in ("_bucket", "_sum", "_count"):
        if sample_name.endswith(suffix):
            base = sample_name[: -len(suffix)]
            if families.get(base, ("",))[0] == "histogram":
                return base
    return sample_name


class FederatedExposition:
    """Accumulates per-node expositions/snapshots into one rendering.

    `family_prefixes` (the /cluster/metrics ?family= filter) drops
    non-matching families at merge time; the federation meta-families
    (up/stale/age/scrape) always render, so a filtered scrape still
    shows which nodes answered."""

    def __init__(self, family_prefixes: "list[str] | None" = None):
        self._families: dict[str, tuple[str, str]] = dict(_META_FAMILIES)
        self._prefixes = family_prefixes
        # family -> [rendered sample line]; insertion order = output order
        self._samples: dict[str, list[str]] = {}

    def _wanted(self, family: str) -> bool:
        if self._prefixes is None or family in _META_FAMILIES:
            return True
        return any(family.startswith(p) for p in self._prefixes)

    def _add_sample(self, family: str, line: str) -> None:
        if not self._wanted(family):
            return
        self._samples.setdefault(family, []).append(line)

    def _meta(self, name: str, node: dict, value) -> None:
        labels = {"instance": node["instance"], "type": node["type"]}
        self._add_sample(name, f"{inject_labels(name, labels)} {value}")

    def add_live(self, node: dict, text: str, scrape_seconds: float) -> None:
        """One successfully scraped node: `node` has instance + type."""
        extra = {"instance": node["instance"], "type": node["type"]}
        families, samples = parse_exposition(text)
        for name, info in families.items():
            self._families.setdefault(name, info)
        for family, sample_name, value in samples:
            self._add_sample(
                family, f"{inject_labels(sample_name, extra)} {value}")
        self._meta(FED_UP, node, 1)
        self._meta(FED_STALE, node, 0)
        self._meta(FED_SCRAPE_SECONDS, node, round(scrape_seconds, 6))

    def add_snapshot(self, node: dict, samples, age_seconds: float) -> None:
        """One unreachable node, served from its heartbeat snapshot:
        `samples` = [(sample_name_with_labels, value)].  Family kinds
        come from this process's registry (same codebase => same
        families); unknown names render as untyped."""
        extra = {"instance": node["instance"], "type": node["type"]}
        for sample_name, value in samples:
            name = sample_name.partition("{")[0]
            family = name
            m = REGISTRY.family(name)
            if m is not None:
                family = m.name
                self._families.setdefault(family, (m.kind, m.help))
            else:
                self._families.setdefault(family, ("untyped", ""))
            self._add_sample(
                family, f"{inject_labels(sample_name, extra)} {value}")
        self._meta(FED_UP, node, 0)
        self._meta(FED_STALE, node, 1)
        self._meta(FED_AGE, node, round(age_seconds, 3))

    def add_down(self, node: dict) -> None:
        """Unreachable and no snapshot either — still visible as down."""
        self._meta(FED_UP, node, 0)
        self._meta(FED_STALE, node, 0)

    def render(self) -> str:
        out: list[str] = []
        for family, lines in self._samples.items():
            kind, help_ = self._families.get(family, ("untyped", ""))
            out.append(f"# HELP {family} {help_}")
            out.append(f"# TYPE {family} {kind}")
            out.extend(lines)
        return "\n".join(out) + "\n"
