"""Synthetic canary plane: black-box probes that feed the SLO engine.

Passive metrics say a process is up; they cannot say it is serving the
right bytes at the right speed.  The canary prober (one per master,
probe bytes charged to the shared background-I/O bucket) continuously
runs end-to-end probes and emits the `seaweedfs_canary_*` SLIs the SLO
engine's availability and staleness specs judge — so "process up but
serving garbage or slow" pages:

* ``volume_rt``    — write/read/delete round trip against every volume
  server, byte identity checked (the write path, the read path and the
  delete tombstone per node, per tick);
* ``ec_degraded``  — a drop-shard read through an EC volume's
  reconstruct path via /debug/canary/ec (CRC-gated byte identity), so
  decode-path rot is found by a probe, not by the next real shard loss
  (arXiv:1709.05365's degraded-read tail is exactly the blind spot);
* ``metadata_rt``  — a routed PUT/GET/DELETE through the S3 gateway
  when one is configured, else straight through a registered filer
  (exercises fleet routing + the filer store);
* ``geo_sentinel`` — when the master has `-peerClusters`, a sentinel
  object written through the local filer and read back from a REMOTE
  cluster's filer; the observed payload age is the end-to-end geo lag
  (`seaweedfs_canary_staleness_seconds{probe="geo_sentinel"}`).

Every probe runs under `record_op("canary", probe)`, so its span lands
in the tracer and its latency histogram carries exemplar trace ids —
the availability alert's one-hop link to a stitched timeline.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time

from ..stats.metrics import (
    CANARY_PROBE_SECONDS,
    CANARY_PROBE_TOTAL,
    CANARY_STALENESS,
)
from ..util import connpool, glog
from .middleware import record_op

PAYLOAD_BYTES = int(os.environ.get("SEAWEEDFS_TPU_CANARY_PAYLOAD", "1024"))
TIMEOUT_S = float(os.environ.get("SEAWEEDFS_TPU_CANARY_TIMEOUT_S", "2.0"))

PROBES = ("volume_rt", "ec_degraded", "metadata_rt", "geo_sentinel")


class ProbeSkipped(Exception):
    """Probe target exists but holds nothing to judge (e.g. an empty EC
    volume) — counted `skipped`, never `error`."""


class CanaryProber:
    """Master-resident black-box prober.  `run_once()` is synchronous
    (tests drive it directly); `start()` runs it on `interval_s`."""

    def __init__(self, master, interval_s: float = 0.0,
                 s3_address: str = "", timeout_s: float = TIMEOUT_S):
        self.master = master
        self.interval_s = interval_s
        self.s3_address = s3_address.rstrip("/")
        self.timeout_s = timeout_s
        self._stop = threading.Event()
        self._thread: "threading.Thread | None" = None
        self._rng = random.Random()
        self._tick = 0
        self._lock = threading.Lock()
        # probe -> {"result", "error", "targets": {target: detail}}
        self._results: dict[str, dict] = {}
        self._last_ok: dict[str, float] = {}
        self._byte_mismatches = 0
        # geo: newest sentinel timestamp observed ON the remote side
        self._geo_seen_ts = 0.0
        self._geo_first_write = 0.0

    # -- lifecycle --------------------------------------------------------

    def start(self) -> None:
        if self.interval_s <= 0 or self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="canary")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.run_once()
            except Exception as e:  # noqa: BLE001 — the prober must survive
                glog.warning("canary tick failed: %s", e)

    # -- probe plumbing ---------------------------------------------------

    def _charge(self, nbytes: int) -> None:
        """Probe traffic drains the same cluster background-I/O bucket
        as scrub and lifecycle jobs (the PR 9 shared budget) — canaries
        must never compete with clients for foreground bandwidth."""
        lc = getattr(self.master, "lifecycle", None)
        if lc is not None:
            lc.bucket.consume(nbytes, stop=self._stop)

    def _observe(self, probe: str, target: str, fn) -> bool:
        """Run one probe body under a span; count + time it; -> ok.

        One in-probe retry (fresh attempt after a short pause): real
        clients ride the failsafe retry layer, so "available" means
        available WITH a retry — a transient race (a volume sealed
        between topology snapshot and write, a holder cache gone stale
        after a shard move) is not an outage, while a dead node fails
        both attempts and still pages."""
        span = None
        err = ""
        skipped: "ProbeSkipped | None" = None
        try:
            with record_op("canary", probe, target=target) as sp:
                span = sp
                try:
                    fn()
                except ProbeSkipped as e:
                    # swallowed INSIDE the span: a skip is not an error
                    # status (it must not occupy the tracer's bounded
                    # important ring) and not a latency sample (a ~0s
                    # observation would drag the probe p50 toward zero)
                    skipped = e
                except Exception:  # noqa: BLE001 — retry once, fresh
                    if self._stop.wait(0.15):
                        raise
                    try:
                        fn()
                    except ProbeSkipped as e:
                        # the retry's fresh pick found nothing left to
                        # probe (volume sealed away mid-probe): still a
                        # skip, never an error
                        skipped = e
            result = "skipped" if skipped is not None else "ok"
            if skipped is not None:
                err = str(skipped)[:200]
        except Exception as e:  # noqa: BLE001 — a failed probe is data
            result = "error"
            err = f"{type(e).__name__}: {e}"[:200]
        CANARY_PROBE_TOTAL.labels(probe, result).inc()
        if span is not None and skipped is None:
            CANARY_PROBE_SECONDS.labels(probe).observe(
                span.duration, trace_id=span.trace_id)
        with self._lock:
            entry = self._results.setdefault(
                probe, {"targets": {}})
            entry.pop("skipped", None)
            entry["targets"][target or "-"] = {
                "result": result, "error": err,
                "at": round(time.time(), 3),
                "traceId": span.trace_id if span is not None else "",
            }
        return result == "ok"

    def _skip(self, probe: str, reason: str) -> None:
        CANARY_PROBE_TOTAL.labels(probe, "skipped").inc()
        with self._lock:
            self._results[probe] = {
                "targets": {}, "skipped": reason}

    def _prune_targets(self, probe: str, valid: set) -> None:
        """Drop retained per-target results whose target left the
        cluster — a dead node's last error must not read as a live
        failure forever."""
        with self._lock:
            entry = self._results.get(probe)
            if entry is None:
                return
            entry["targets"] = {
                k: v for k, v in entry["targets"].items() if k in valid}

    def _http(self, method: str, url: str, body: bytes = b"",
              headers: "dict | None" = None) -> bytes:
        with connpool.request(method, url, body=body or None,
                              headers=headers or {},
                              timeout=self.timeout_s) as r:
            data = r.read()
            if r.status >= 300:
                raise IOError(f"{method} {url} -> {r.status}")
            return data

    def _payload(self) -> bytes:
        return os.urandom(PAYLOAD_BYTES)

    # -- the probes -------------------------------------------------------

    def _volume_targets(self) -> list[tuple[str, int]]:
        """[(node_id, writable_vid)] — one writable volume per node."""
        out = []
        with self.master.topo.lock:
            for n in self.master.topo.nodes.values():
                vids = sorted(vid for vid, v in n.volumes.items()
                              if not v.read_only)
                if vids:
                    out.append((n.id, vids[self._tick % len(vids)]))
        return out

    def _pick_writable(self, node_id: str) -> "int | None":
        """Fresh writable volume id for ONE node — one short lock, no
        full-topology rescan per attempt."""
        with self.master.topo.lock:
            n = self.master.topo.nodes.get(node_id)
            if n is None:
                return None
            vids = sorted(vid for vid, v in n.volumes.items()
                          if not v.read_only)
        return vids[self._tick % len(vids)] if vids else None

    def probe_volume_rt(self) -> None:
        targets = self._volume_targets()
        if not targets:
            return self._skip("volume_rt", "no node with a writable volume")
        self._prune_targets("volume_rt", {n for n, _v in targets})
        for node_id, _vid in targets:

            def round_trip(node_id=node_id):
                # fresh pick per attempt: the retry must not re-POST to
                # a volume that was sealed/EC-encoded since the first try
                vid = self._pick_writable(node_id)
                if vid is None:
                    raise ProbeSkipped("no writable volume on node")
                payload = self._payload()
                key = self.master.sequencer.next_file_id(1)
                cookie = self._rng.randrange(0, 2 ** 32)
                fid = f"{vid},{key:x}{cookie:08x}"
                auth = self.master.sign_fid(fid)
                headers = {"Content-Type": "application/octet-stream"}
                if auth:
                    headers["Authorization"] = f"BEARER {auth}"
                url = f"http://{node_id}/{fid}"
                self._charge(2 * len(payload))
                self._http("POST", url, body=payload, headers=headers)
                try:
                    got = self._http("GET", url)
                    if got != payload:
                        with self._lock:
                            self._byte_mismatches += 1
                        raise IOError(
                            f"byte identity broken: wrote "
                            f"{len(payload)}B read {len(got)}B")
                finally:
                    # best-effort cleanup even when the read leg failed —
                    # canary objects must not accumulate
                    try:
                        self._http("DELETE", url, headers=headers)
                    except Exception:  # noqa: BLE001
                        pass

            self._observe("volume_rt", node_id, round_trip)

    def _ec_targets(self) -> list[tuple[str, int]]:
        out = []
        with self.master.topo.lock:
            for n in self.master.topo.nodes.values():
                for vid in sorted(n.ec_shards):
                    out.append((n.id, vid))
        return out

    def probe_ec_degraded(self) -> None:
        targets = self._ec_targets()
        if not targets:
            return self._skip("ec_degraded", "no EC volumes in topology")
        node_id, vid = targets[self._tick % len(targets)]

        def drop_shard_read():
            doc = json.loads(self._http(
                "GET", f"http://{node_id}/debug/canary/ec?volume={vid}"))
            if doc.get("empty"):
                raise ProbeSkipped("ec volume holds no live needle")
            if not doc.get("ok"):
                raise IOError(doc.get("error", "canary read failed"))

        self._prune_targets(
            "ec_degraded", {f"{n}/vol{v}" for n, v in targets})
        self._observe("ec_degraded", f"{node_id}/vol{vid}", drop_shard_read)

    def _filer_addresses(self) -> list[str]:
        out = []
        for _name, info in sorted(self.master.clients_snapshot().items()):
            if info.get("type") == "filer" and info.get("http_address"):
                out.append(info["http_address"])
        return out

    def probe_metadata_rt(self) -> None:
        payload = self._payload()
        self._prune_targets(
            "metadata_rt",
            {self.s3_address} if self.s3_address
            else set(self._filer_addresses()))
        if self.s3_address:
            bucket = "seaweedfs-canary"
            obj = f"{bucket}/probe-{self.master.port}"
            base = (self.s3_address if "://" in self.s3_address
                    else f"http://{self.s3_address}")
            self._charge(2 * len(payload))

            def s3_round_trip():
                # bucket create is idempotent on the filer-backed gateway
                try:
                    self._http("PUT", f"{base}/{bucket}")
                except Exception:  # noqa: BLE001 — may already exist
                    pass
                self._http("PUT", f"{base}/{obj}", body=payload)
                try:
                    got = self._http("GET", f"{base}/{obj}")
                    if got != payload:
                        with self._lock:
                            self._byte_mismatches += 1
                        raise IOError("s3 byte identity broken")
                finally:
                    try:
                        self._http("DELETE", f"{base}/{obj}")
                    except Exception:  # noqa: BLE001
                        pass

            self._observe("metadata_rt", self.s3_address, s3_round_trip)
            return
        filers = self._filer_addresses()
        if not filers:
            return self._skip(
                "metadata_rt", "no S3 gateway configured, no filer "
                               "registered")
        filer = filers[self._tick % len(filers)]
        path = f"/.canary/probe-{self.master.port}"
        self._charge(2 * len(payload))

        def filer_round_trip():
            self._http("PUT", f"http://{filer}{path}", body=payload)
            try:
                got = self._http("GET", f"http://{filer}{path}")
                if got != payload:
                    with self._lock:
                        self._byte_mismatches += 1
                    raise IOError("filer byte identity broken")
            finally:
                try:
                    self._http("DELETE", f"http://{filer}{path}")
                except Exception:  # noqa: BLE001
                    pass

        self._observe("metadata_rt", filer, filer_round_trip)

    SENTINEL_PATH = "/.canary/geo-sentinel"

    def probe_geo_sentinel(self) -> None:
        peers = getattr(self.master, "peer_clusters", None) or []
        if not peers:
            return self._skip("geo_sentinel", "no -peerClusters configured")
        filers = self._filer_addresses()
        if not filers:
            return self._skip("geo_sentinel", "no local filer registered")
        now = time.time()
        body = json.dumps({"ts": now, "from": f"{self.master.ip}:"
                                              f"{self.master.port}"}).encode()
        self._charge(len(body))
        try:
            self._http("PUT", f"http://{filers[0]}{self.SENTINEL_PATH}",
                       body=body)
            if self._geo_first_write == 0.0:
                self._geo_first_write = now
        except Exception as e:  # noqa: BLE001
            glog.warning("geo sentinel write failed: %s", e)

        def read_remote(peer):
            doc = json.loads(self._http(
                "GET", f"http://{peer}/cluster/status"))
            remote_filers = [
                f.get("httpAddress") for f in
                (doc.get("Filers") or {}).values() if f.get("httpAddress")]
            if not remote_filers:
                raise IOError(f"peer {peer} reports no filers")
            sent = json.loads(self._http(
                "GET",
                f"http://{remote_filers[0]}{self.SENTINEL_PATH}"))
            ts = float(sent["ts"])
            with self._lock:
                self._geo_seen_ts = max(self._geo_seen_ts, ts)

        for peer in peers:
            self._observe("geo_sentinel", peer,
                          lambda peer=peer: read_remote(peer))
        # staleness = age of the newest sentinel payload the remote side
        # served; before the first successful remote read it grows from
        # the first local write (replication never confirmed)
        anchor = self._geo_seen_ts or self._geo_first_write
        if anchor:
            CANARY_STALENESS.labels("geo_sentinel").set(
                max(0.0, time.time() - anchor))

    # -- tick + surfaces --------------------------------------------------

    def run_once(self) -> dict:
        """One full probe round; returns the status document."""
        self._tick += 1
        for probe, fn in (
            ("volume_rt", self.probe_volume_rt),
            ("ec_degraded", self.probe_ec_degraded),
            ("metadata_rt", self.probe_metadata_rt),
            ("geo_sentinel", self.probe_geo_sentinel),
        ):
            try:
                fn()
            except Exception as e:  # noqa: BLE001 — per-probe isolation
                glog.warning("canary probe %s crashed: %s", probe, e)
            self._refresh_staleness(probe)
        return self.status()

    def _refresh_staleness(self, probe: str) -> None:
        """seaweedfs_canary_staleness_seconds{probe}: seconds since the
        probe last FULLY succeeded (every target ok).  geo_sentinel owns
        its gauge (payload age) inside the probe."""
        if probe == "geo_sentinel":
            return
        with self._lock:
            entry = self._results.get(probe)
            if entry is None or entry.get("skipped"):
                return
            targets = entry.get("targets", {})
            # skipped targets are neutral: the probe is "fully ok" when
            # nothing it could reach errored
            all_ok = bool(targets) and all(
                t["result"] != "error" for t in targets.values())
            now = time.monotonic()
            self._last_ok.setdefault(f"{probe}:first", now)
            if all_ok:
                self._last_ok[probe] = now
            # before any success, staleness grows from the first attempt
            last = self._last_ok.get(probe,
                                     self._last_ok[f"{probe}:first"])
        CANARY_STALENESS.labels(probe).set(round(now - last, 3))

    def status(self) -> dict:
        with self._lock:
            # deep-copy per-target entries: the returned doc is read and
            # json-serialized by HTTP handler threads with no lock, and
            # a live inner dict mutating mid-iteration would 500 the
            # /cluster/alerts an operator is polling mid-incident
            probes = {
                k: {**{kk: vv for kk, vv in v.items() if kk != "targets"},
                    "targets": {t: dict(r)
                                for t, r in v.get("targets", {}).items()}}
                for k, v in self._results.items()
            }
            return {
                "interval_s": self.interval_s,
                "running": self._thread is not None,
                "tick": self._tick,
                "byteMismatches": self._byte_mismatches,
                "probes": probes,
            }
