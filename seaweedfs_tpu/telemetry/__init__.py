"""Request tracing + telemetry: spans, traceparent propagation, middleware.

See trace.py (span recorder + W3C context) and middleware.py (the shared
HTTP request instrumentation used by master/volume/filer/S3).
"""

from . import trace  # noqa: F401
from .middleware import (  # noqa: F401
    DEBUG_FAULTS_PATH,
    DEBUG_HOT_PATH,
    DEBUG_PROFILE_HISTORY_PATH,
    DEBUG_PROFILE_PATH,
    DEBUG_TRACES_PATH,
    METRICS_PATH,
    SLOW_REQUEST_SECONDS,
    debug_traces_body,
    http_request,
    parse_trace_query,
    record_op,
    serve_debug_http,
)
from .trace import (  # noqa: F401
    TRACER,
    Span,
    Tracer,
    current_trace_id,
    inject_headers,
    parse_traceparent,
    remote_context,
    start_span,
    traceparent_header,
    wrap_context,
)

__all__ = [
    "TRACER", "Span", "Tracer", "current_trace_id", "inject_headers",
    "parse_traceparent", "remote_context", "start_span",
    "traceparent_header", "wrap_context", "http_request", "record_op",
    "debug_traces_body", "serve_debug_http", "parse_trace_query",
    "DEBUG_FAULTS_PATH", "DEBUG_HOT_PATH", "DEBUG_PROFILE_HISTORY_PATH",
    "DEBUG_PROFILE_PATH", "DEBUG_TRACES_PATH",
    "METRICS_PATH", "SLOW_REQUEST_SECONDS",
]
