"""In-process distributed tracing: spans, W3C traceparent, a bounded ring.

Reference shape: OpenTelemetry's SDK, cut down to what a blob store's
request path needs — a thread-local context stack, wall-clock spans, and
a fixed-size ring buffer of finished spans that /debug/traces serves as
JSON.  No exporter, no sampler: every request is recorded until the ring
evicts it, which is the right trade for a debug surface (the Facebook
warehouse study's lesson is that you need per-hop latency for the tail
*after* the fact, not a 1% head sample).

Propagation uses the W3C trace-context `traceparent` header
(`00-<32 hex trace id>-<16 hex span id>-<2 hex flags>`) on HTTP and the
same string as gRPC metadata, so one client write yields one connected
trace across filer -> master assign -> volume POST -> replication.

Usage:
    from seaweedfs_tpu.telemetry import trace
    with trace.start_span("volumeServer.post", path="/3,0123"):
        ...
    hdr = trace.traceparent_header()        # inject into outgoing calls
    with trace.remote_context(incoming_hdr):  # adopt a caller's context
        ...
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field

from ..util import glog

# ring capacity: finished spans kept in memory per process
MAX_SPANS = int(os.environ.get("SEAWEEDFS_TPU_TRACE_BUFFER", "2048"))

# separate bounded ring for spans an alert will want: error-status and
# slow spans.  Without it a burst of healthy traffic evicts the one
# trace a firing alert's exemplar points at before anyone looks — the
# page would link to an empty timeline.
MAX_IMPORTANT_SPANS = int(
    os.environ.get("SEAWEEDFS_TPU_TRACE_IMPORTANT_BUFFER", "512"))

# slow-span retention threshold; same knob the middleware's slow-request
# log uses (middleware imports this binding — one source of truth)
SLOW_SPAN_SECONDS = float(
    os.environ.get("SEAWEEDFS_TPU_SLOW_REQUEST_S", "1.0"))

_ctx = threading.local()  # _ctx.stack: list[(trace_id, span_id)]

# ids need uniqueness, not unpredictability: os.urandom costs a syscall
# per call and every request opens a span (two ids) — a urandom-seeded
# PRNG is plenty (getrandbits is a single atomic C call, thread-safe
# under the GIL)
_id_rng = random.Random(os.urandom(16))


def _rand_hex(nbytes: int) -> str:
    return f"{_id_rng.getrandbits(8 * nbytes):0{2 * nbytes}x}"


@dataclass
class Span:
    trace_id: str
    span_id: str
    parent_id: str
    name: str
    start: float  # wall-clock seconds (time.time)
    duration: float = 0.0
    attrs: dict = field(default_factory=dict)
    status: str = "ok"

    def to_dict(self) -> dict:
        return {
            "traceId": self.trace_id,
            "spanId": self.span_id,
            "parentId": self.parent_id,
            "name": self.name,
            "start": self.start,
            "durationMs": round(self.duration * 1e3, 3),
            "attrs": self.attrs,
            "status": self.status,
        }


class Tracer:
    """Bounded recorder of finished spans, grouped on read by trace id.

    Two rings: the main ring holds everything; error-status and slow
    spans are ALSO retained in a separate bounded ring, so a burst of
    healthy traffic cannot evict the trace an alert needs before an
    operator follows the exemplar link."""

    def __init__(self, max_spans: int = MAX_SPANS,
                 max_important: int = MAX_IMPORTANT_SPANS):
        self._spans: deque[Span] = deque(maxlen=max_spans)
        self._important: deque[Span] = deque(maxlen=max_important)
        self._lock = threading.Lock()

    def record(self, span: Span) -> None:
        with self._lock:
            self._spans.append(span)
            if span.status != "ok" or span.duration >= SLOW_SPAN_SECONDS:
                self._important.append(span)

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self._important.clear()

    def spans(self) -> list[Span]:
        """Main + important rings, deduplicated (a span recent enough to
        still sit in the main ring appears once)."""
        with self._lock:
            main = list(self._spans)
            important = list(self._important)
        seen = {(s.trace_id, s.span_id) for s in main}
        merged = [s for s in important
                  if (s.trace_id, s.span_id) not in seen]
        merged.extend(main)
        return merged

    def recent_traces(self, limit: int = 50,
                      trace_id: str | None = None) -> list[dict]:
        """Most-recent traces first, each with its spans in start order.
        `trace_id` filters the ring down to one trace (the cluster
        stitcher's per-trace query; a full dump per node would make the
        fan-out O(ring size x nodes))."""
        by_trace: dict[str, list[Span]] = {}
        for s in self.spans():
            if trace_id is not None and s.trace_id != trace_id:
                continue
            by_trace.setdefault(s.trace_id, []).append(s)
        # order traces by the latest span end they contain, newest first
        ordered = sorted(
            by_trace.items(),
            key=lambda kv: max(s.start + s.duration for s in kv[1]),
            reverse=True,
        )[:limit]
        return [
            {
                "traceId": tid,
                "spans": [s.to_dict()
                          for s in sorted(spans, key=lambda s: s.start)],
            }
            for tid, spans in ordered
        ]

    def traces_json(self, limit: int = 50,
                    trace_id: str | None = None) -> bytes:
        # "now" = this process's wall clock at render time: the stitcher
        # compares it against its own clock (minus half the scrape RTT)
        # to annotate per-node clock skew on merged timelines
        return json.dumps({
            "now": time.time(),
            "traces": self.recent_traces(limit, trace_id=trace_id),
        }).encode()


TRACER = Tracer()


# -- thread-local context ----------------------------------------------------


def _stack() -> list:
    stack = getattr(_ctx, "stack", None)
    if stack is None:
        stack = _ctx.stack = []
    return stack


def current_context() -> tuple[str, str] | None:
    """(trace_id, span_id) of the active span, or None."""
    stack = _stack()
    return stack[-1] if stack else None


def current_trace_id() -> str | None:
    ctx = current_context()
    return ctx[0] if ctx else None


@contextmanager
def start_span(name: str, tracer: Tracer = TRACER, **attrs):
    """Open a span under the current context (new trace when none)."""
    stack = _stack()
    if stack:
        trace_id, parent_id = stack[-1]
    else:
        trace_id, parent_id = _rand_hex(16), ""
    span = Span(
        trace_id=trace_id,
        span_id=_rand_hex(8),
        parent_id=parent_id,
        name=name,
        start=time.time(),
        attrs=dict(attrs),
    )
    stack.append((trace_id, span.span_id))
    t0 = time.perf_counter()
    try:
        yield span
    except BaseException as e:
        span.status = f"error: {type(e).__name__}"
        raise
    finally:
        span.duration = time.perf_counter() - t0
        stack.pop()
        tracer.record(span)


@contextmanager
def child_span(name: str, tracer: Tracer = TRACER, **attrs):
    """`start_span` only when already inside a trace; no-op otherwise.

    For instrumentation on paths that also run outside any request
    (codec calls from bulk encodes, client hops from background loops):
    a root span per call would flood the ring with single-span traces
    and evict the request traces /debug/traces exists to serve."""
    if current_context() is None:
        yield None
        return
    with start_span(name, tracer=tracer, **attrs) as span:
        yield span


# -- W3C traceparent ---------------------------------------------------------

TRACEPARENT = "traceparent"


def format_traceparent(trace_id: str, span_id: str) -> str:
    return f"00-{trace_id}-{span_id}-01"


def traceparent_header() -> str | None:
    """Header value for the active context, or None outside any span."""
    ctx = current_context()
    if ctx is None:
        return None
    return format_traceparent(*ctx)


_HEX = frozenset("0123456789abcdef")


def _is_hex(s: str) -> bool:
    # strict per-character check: int(s, 16) would admit '+', '-' and
    # '_' separators and re-propagate a spec-invalid id downstream
    return bool(s) and set(s) <= _HEX


def parse_traceparent(value: str | None) -> tuple[str, str] | None:
    """-> (trace_id, span_id) or None on anything malformed."""
    if not value:
        return None
    parts = value.strip().lower().split("-")
    if len(parts) < 4 or len(parts[0]) != 2 or len(parts[1]) != 32 \
            or len(parts[2]) != 16:
        return None
    version, trace_id, span_id = parts[0], parts[1], parts[2]
    if not (_is_hex(version) and _is_hex(trace_id) and _is_hex(span_id)):
        return None
    if version == "ff":  # forbidden version per spec
        return None
    if set(trace_id) == {"0"} or set(span_id) == {"0"}:
        return None  # all-zero ids are invalid per spec
    return trace_id, span_id


@contextmanager
def remote_context(traceparent: str | None):
    """Adopt a remote caller's context for the duration of the block.

    With a malformed/absent header this is a no-op: spans opened inside
    start a fresh trace, exactly like an edge request."""
    parsed = parse_traceparent(traceparent)
    if parsed is None:
        yield None
        return
    stack = _stack()
    stack.append(parsed)
    try:
        yield parsed
    finally:
        stack.pop()


def inject_headers(headers: dict) -> dict:
    """Add traceparent to an outgoing-request header dict (mutates + returns)."""
    hdr = traceparent_header()
    if hdr is not None:
        headers[TRACEPARENT] = hdr
    return headers


def wrap_context(fn):
    """Carry the caller's trace context into a thread-pool worker.

    The filer fans chunk uploads and chunk reads out to an executor;
    without this the volume-server hops would each start orphan traces."""
    ctx = current_context()
    if ctx is None:
        return fn

    def bound(*args, **kwargs):
        stack = _stack()
        stack.append(ctx)
        try:
            return fn(*args, **kwargs)
        finally:
            stack.pop()

    return bound


# log correlation: every glog line emitted under an active span carries
# the trace id (the slow-request log's join key back to /debug/traces)
glog.set_context_provider(current_trace_id)
