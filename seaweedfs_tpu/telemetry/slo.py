"""SLO engine: multi-window multi-burn-rate judgment over cluster SLIs.

The cluster emits rich passive signals (federated /cluster/metrics,
heartbeat snapshots) and the canary plane emits active ones, but nothing
*judged* them: an operator had no answer to "is the cluster meeting its
SLOs right now, and if not, which trace shows why".  This module is the
master-resident answer — declarative SLO specs evaluated as burn-rate
rules over windowed counter deltas, an alert state machine with bounded
history, and pluggable sinks.

Burn rate is the SRE-workbook quantity: (observed error rate) / (error
budget rate).  Burning at 1.0 spends exactly the budget over the SLO
period; the page tier fires when BOTH a fast short window and a longer
confirmation window burn above a factor (default 5m/1h at 14.4x — the
classic "2% of a 30-day budget in one hour" rule), so a blip can't page
but a real incident pages within the short window.  The warn tier runs
slow windows (6h/3d at 1.0x) for budget-trending problems.  Windows
scale uniformly via SEAWEEDFS_TPU_SLO_WINDOW_SCALE (or the engine's
`window_scale` argument) so tests and small clusters can evaluate the
same rules at second-scale.

Three SLI kinds:

* ``ratio``   — bad/total counter deltas (canary probe failures,
  request errors); burn = (bad/total) / (1 - objective).
* ``latency`` — histogram bucket deltas: bad = requests above the
  threshold bucket; same burn arithmetic.  Firing latency alerts embed
  the exemplar trace ids the histograms recorded, so a page is one hop
  from `/cluster/alerts` to `/cluster/traces?trace=<id>`.
* ``gauge``   — a level signal (geo lag, queue depth): pending the
  moment the threshold is crossed, firing once it has held for
  ``for_s``, resolved when it drops back.
* ``event``   — a counter delta over the SHORT window (volumes newly
  dropped below redundancy): fires the moment ``threshold`` events land
  in the window, resolves when the window rolls past them.  A gauge
  would miss a spike a fast repair drains between two evaluation ticks;
  the counter cannot un-happen.

Grounding: arXiv:1309.0186 measures the operational cost of discovering
degraded redundancy late (~98 lost-block events/day at warehouse scale);
arXiv:1709.05365 shows online-EC tail latency diverging from medians
exactly when passive averages look healthy — both argue for burn-rate
evaluation plus active probing over more raw gauges.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from collections import deque
from dataclasses import dataclass, field

from ..stats.metrics import (
    SLO_ALERT_STATE,
    SLO_BURN_RATE,
    SLO_EVAL_SECONDS,
    SLO_TRANSITIONS,
)
from ..util import glog
from .federation import parse_exposition

WINDOW_SCALE_ENV = "SEAWEEDFS_TPU_SLO_WINDOW_SCALE"

# alert states, also the seaweedfs_slo_alert_state gauge encoding
OK, PENDING, FIRING = "ok", "pending", "firing"
_STATE_VALUE = {OK: 0, PENDING: 1, FIRING: 2}


@dataclass(frozen=True)
class BurnWindow:
    """One multi-window burn-rate rule: fire when burn exceeds `factor`
    in BOTH the short and the long window (pending on short-only)."""

    short_s: float
    long_s: float
    factor: float


# page tier: 5m/1h at 14.4x (2% of a 30d budget in 1h); warn tier:
# 6h/3d at 1.0x (burning at budget pace for days)
PAGE_WINDOW = BurnWindow(300.0, 3600.0, 14.4)
WARN_WINDOW = BurnWindow(21600.0, 259200.0, 1.0)

_LABEL_RE = re.compile(r'([A-Za-z_][A-Za-z0-9_]*)="((?:[^"\\]|\\.)*)"')


def sample_labels(sample_name: str) -> tuple[str, dict]:
    """`name{a="b",c="d"}` -> ("name", {"a": "b", "c": "d"})."""
    brace = sample_name.find("{")
    if brace < 0:
        return sample_name, {}
    name = sample_name[:brace]
    labels = {
        k: v.replace('\\"', '"').replace("\\\\", "\\").replace("\\n", "\n")
        for k, v in _LABEL_RE.findall(sample_name[brace:])
    }
    return name, labels


def _matches(labels: dict, want: "dict | None") -> bool:
    """`want` values are a string or a tuple of accepted strings."""
    if not want:
        return True
    for k, v in want.items():
        got = labels.get(k)
        if isinstance(v, (tuple, list, set)):
            if got not in v:
                return False
        elif got != v:
            return False
    return True


@dataclass
class SloSpec:
    """One declarative SLO.  `kind` selects which fields apply:

    ratio:   bad_family/bad_labels over total_family/total_labels
    latency: family/labels histogram, threshold_s, objective
    gauge:   family/labels level >= threshold for for_s seconds
    """

    name: str
    severity: str  # "page" | "warn"
    kind: str  # "ratio" | "latency" | "gauge" | "event"
    description: str = ""
    # ratio
    bad_family: str = ""
    bad_labels: dict = field(default_factory=dict)
    total_family: str = ""
    total_labels: dict = field(default_factory=dict)
    objective: float = 0.999
    # latency (reuses objective)
    family: str = ""
    labels: dict = field(default_factory=dict)
    threshold_s: float = 0.5
    # gauge
    threshold: float = 1.0
    for_s: float = 0.0
    # overrides / linking
    window: "BurnWindow | None" = None
    exemplar_family: str = ""

    def burn_window(self) -> BurnWindow:
        if self.window is not None:
            return self.window
        return PAGE_WINDOW if self.severity == "page" else WARN_WINDOW

    def families(self) -> list[str]:
        """Exposition family prefixes this spec's evaluation needs."""
        out = []
        for f in (self.bad_family, self.total_family, self.family):
            if f and f not in out:
                out.append(f)
        return out

    def to_dict(self) -> dict:
        w = self.burn_window()
        d = {
            "name": self.name, "severity": self.severity,
            "kind": self.kind, "description": self.description,
            "windowShortS": w.short_s, "windowLongS": w.long_s,
            "burnFactor": w.factor,
        }
        if self.kind in ("ratio", "latency"):
            d["objective"] = self.objective
        if self.kind == "latency":
            d["thresholdS"] = self.threshold_s
            d["family"] = self.family
        if self.kind in ("gauge", "event"):
            d["threshold"] = self.threshold
            d["forS"] = self.for_s
            d["family"] = self.family
        return d


def spec_from_dict(d: dict) -> SloSpec:
    """Declarative JSON -> SloSpec (the -sloSpecs file loader).  Window
    override: {"window": {"shortS":, "longS":, "factor":}}."""
    d = dict(d)
    w = d.pop("window", None)
    spec = SloSpec(**d)
    if w is not None:
        spec.window = BurnWindow(float(w["shortS"]), float(w["longS"]),
                                 float(w.get("factor", 1.0)))
    return spec


def specs_from_json(path: str) -> list[SloSpec]:
    with open(path) as f:
        return [spec_from_dict(d) for d in json.load(f)]


def default_specs() -> list[SloSpec]:
    """The stock judgment suite.  Thresholds are env-tunable where a
    deployment's hardware moves them."""
    read_p99 = float(os.environ.get("SEAWEEDFS_TPU_SLO_READ_P99_S", "0.5"))
    write_p99 = float(os.environ.get("SEAWEEDFS_TPU_SLO_WRITE_P99_S", "1.0"))
    geo_lag = float(os.environ.get("SEAWEEDFS_TPU_SLO_GEO_LAG_S", "60"))
    backlog = float(os.environ.get("SEAWEEDFS_TPU_SLO_BACKLOG_JOBS", "256"))
    return [
        SloSpec(
            name="availability", severity="page", kind="ratio",
            description="black-box canary round trips succeeding "
                        "(write/read/delete, EC degraded read, routed "
                        "metadata PUT/GET)",
            bad_family="seaweedfs_canary_probe_total",
            bad_labels={"result": "error"},
            total_family="seaweedfs_canary_probe_total",
            total_labels={"result": ("ok", "error")},
            # three nines on the synthetic signal: one stray probe error
            # cannot page (long-window dilution), a dead node's sustained
            # failures page within the short window
            objective=0.999,
            exemplar_family="seaweedfs_canary_probe_seconds",
        ),
        SloSpec(
            name="read-latency-p99", severity="page", kind="latency",
            description="volume-server GET latency under the p99 bound",
            family="seaweedfs_request_seconds",
            labels={"type": "volumeServer", "op": "get"},
            threshold_s=read_p99, objective=0.99,
            exemplar_family="seaweedfs_request_seconds",
        ),
        SloSpec(
            name="write-latency-p99", severity="page", kind="latency",
            description="volume-server POST latency under the p99 bound",
            family="seaweedfs_request_seconds",
            labels={"type": "volumeServer", "op": "post"},
            threshold_s=write_p99, objective=0.99,
            exemplar_family="seaweedfs_request_seconds",
        ),
        SloSpec(
            name="ec-exposure", severity="page", kind="event",
            description="EC volumes newly planned into dead-node mass "
                        "repair in the fast window (shards below full "
                        "redundancy — the lost-block events "
                        "arXiv:1309.0186 measures the cost of "
                        "discovering late)",
            family="seaweedfs_repair_batch_volumes_total",
            threshold=1.0, for_s=0.0,
        ),
        SloSpec(
            name="leader-flapping", severity="page", kind="event",
            description="raft leader changes in the fast window — more "
                        "than a couple means elections are churning "
                        "(partitioned quorum, clock trouble, or an "
                        "overloaded master losing its heartbeats) and "
                        "every flap re-runs the control-plane warm-up "
                        "barrier",
            family="seaweedfs_raft_leader_changes_total",
            threshold=3.0, for_s=0.0,
        ),
        SloSpec(
            name="repair-backlog", severity="warn", kind="gauge",
            description="mass-repair jobs journaled but unfinished — "
                        "sustained depth means repair is not keeping up "
                        "with exposure",
            family="seaweedfs_repair_batch_queue_depth",
            threshold=1.0, for_s=120.0,
        ),
        SloSpec(
            name="under-replication", severity="warn", kind="gauge",
            description="volumes with fewer live replicas than their "
                        "placement requires",
            family="seaweedfs_volume_underreplicated",
            threshold=1.0, for_s=30.0,
        ),
        SloSpec(
            name="geo-lag", severity="warn", kind="gauge",
            description="geo replication link lag",
            family="seaweedfs_geo_lag_seconds",
            threshold=geo_lag, for_s=0.0,
        ),
        SloSpec(
            name="geo-staleness", severity="warn", kind="gauge",
            description="age of the geo sentinel object observed on the "
                        "remote cluster (canary-measured end-to-end lag)",
            family="seaweedfs_canary_staleness_seconds",
            labels={"probe": "geo_sentinel"},
            threshold=2 * geo_lag, for_s=0.0,
        ),
        SloSpec(
            name="maintenance-backlog", severity="warn", kind="gauge",
            description="lifecycle + scrub/repair background jobs "
                        "journaled but unfinished",
            family="seaweedfs_lifecycle_queue_depth",
            threshold=backlog, for_s=60.0,
        ),
    ]


# -- sinks -------------------------------------------------------------------


def log_sink(alert: dict) -> None:
    """Default sink: one glog line per transition (warning for firing,
    info otherwise) — greppable next to the slow-request log."""
    line = ("slo alert %(slo)s [%(severity)s] -> %(state)s "
            "burn=%(burnShort).2f/%(burnLong).2f" % {
                "slo": alert["slo"], "severity": alert["severity"],
                "state": alert["state"],
                "burnShort": alert.get("burnShort", 0.0),
                "burnLong": alert.get("burnLong", 0.0)})
    if alert.get("exemplars"):
        line += " exemplar=" + alert["exemplars"][0]["traceId"]
    (glog.warning if alert["state"] == FIRING else glog.info)(line)


class WebhookSink:
    """POST each alert transition as JSON to a webhook URL.  Failures
    log and drop — the judgment plane must never block on its sink."""

    def __init__(self, url: str, timeout_s: float = 3.0):
        self.url = url
        self.timeout_s = timeout_s

    def __call__(self, alert: dict) -> None:
        from ..util import connpool

        try:
            with connpool.request(
                    "POST", self.url, body=json.dumps(alert).encode(),
                    headers={"Content-Type": "application/json"},
                    timeout=self.timeout_s) as r:
                r.read()
        except Exception as e:  # noqa: BLE001 — sink failure is non-fatal
            glog.warning("alert webhook %s failed: %s", self.url, e)


# -- engine ------------------------------------------------------------------


class SloEngine:
    """Evaluates SLO specs over a scrape function's counter samples.

    `scrape(family_prefixes) -> exposition text` is normally the
    master's federated /cluster/metrics render (with the ?family=
    subset filter, so a tick never pulls the full exposition);
    `exemplars(family_prefix) -> [exemplar dict]` is normally
    REGISTRY.exemplars.  Both are injectable for tests.
    """

    MAX_HISTORY_ENTRIES = 4096

    def __init__(
        self,
        scrape,
        specs: "list[SloSpec] | None" = None,
        sinks=None,
        interval_s: float = 0.0,
        exemplars=None,
        window_scale: "float | None" = None,
        now=time.time,
        max_history: int = 256,
    ):
        self._scrape = scrape
        self.specs = list(specs) if specs is not None else default_specs()
        self.interval_s = interval_s
        self._sinks = list(sinks) if sinks is not None else [log_sink]
        self._exemplars = exemplars
        if window_scale is None:
            window_scale = float(os.environ.get(WINDOW_SCALE_ENV, "1.0"))
        self.window_scale = max(float(window_scale), 1e-6)
        self._now = now
        # (t, {sample_name: value}) ring covering the longest long window
        self._history: deque = deque()
        self._states: dict[str, dict] = {}
        self.alert_history: deque = deque(maxlen=max_history)
        self._lock = threading.RLock()
        # serializes whole evaluations; the state lock above is held
        # only for the cheap history-append + rule pass, so a scrape
        # that eats its full federation budget never blocks
        # /cluster/alerts or /cluster/status reads
        self._eval_mutex = threading.Lock()
        self._stop = threading.Event()
        self._thread: "threading.Thread | None" = None
        self._last_eval = 0.0

    # -- lifecycle --------------------------------------------------------

    def start(self) -> None:
        if self.interval_s <= 0 or self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="slo-engine")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.evaluate()
            except Exception as e:  # noqa: BLE001 — the judge must survive
                glog.warning("slo evaluation failed: %s", e)

    # -- evaluation -------------------------------------------------------

    def families(self) -> list[str]:
        out: list[str] = []
        for spec in self.specs:
            for f in spec.families():
                if f not in out:
                    out.append(f)
        return out

    def _collect(self) -> dict:
        """Scrape + parse, NO locks held: the federation fan-out can
        take seconds when nodes are unreachable."""
        text = self._scrape(self.families())
        _families, samples = parse_exposition(text)
        merged: dict[str, float] = {}
        for _family, sample_name, value in samples:
            try:
                v = float(value)
            except ValueError:
                continue
            # distinct nodes carry distinct instance labels, so samples
            # never truly collide; last write wins on a duplicate
            merged[sample_name] = v
        return merged

    def _ingest(self, t: float, merged: dict) -> None:
        self._history.append((t, merged))
        horizon = max(
            (s.burn_window().long_s for s in self.specs), default=3600.0
        ) * self.window_scale * 1.25
        while (len(self._history) > 2
               and (t - self._history[1][0] > horizon
                    or len(self._history) > self.MAX_HISTORY_ENTRIES)):
            self._history.popleft()

    def _baseline(self, t: float, window_s: float) -> "tuple[float, dict]":
        """Newest history entry at least `window_s` old; with less
        history than the window, the oldest entry (partial window)."""
        base_t, base = self._history[0]
        for et, entry in self._history:
            if t - et >= window_s:
                base_t, base = et, entry
            else:
                break
        return base_t, base

    def _sum_delta(self, cur: dict, base: dict, family: str,
                   want: "dict | None") -> float:
        total = 0.0
        prefix_b = family + "{"
        for name, v in cur.items():
            if name != family and not name.startswith(prefix_b):
                continue
            _f, labels = sample_labels(name)
            if not _matches(labels, want):
                continue
            # clamp per-sample: a restarted node's counter reset must
            # not produce a negative delta that cancels real errors
            total += max(0.0, v - base.get(name, 0.0))
        return total

    def _latency_deltas(self, cur: dict, base: dict,
                        spec: SloSpec) -> tuple[float, float]:
        """-> (bad, total) request deltas for a latency spec: total from
        `_count`, good from the cumulative bucket at the smallest bound
        >= threshold_s."""
        count_f = spec.family + "_count"
        bucket_f = spec.family + "_bucket"
        total = self._sum_delta(cur, base, count_f, spec.labels)
        # choose the snap bound from the le values actually present
        bounds = set()
        prefix = bucket_f + "{"
        for name in cur:
            if name.startswith(prefix):
                _f, labels = sample_labels(name)
                if not _matches(labels, spec.labels):
                    continue
                le = labels.get("le", "")
                if le and le != "+Inf":
                    try:
                        bounds.add(float(le))
                    except ValueError:
                        pass
        snap = min((b for b in bounds if b >= spec.threshold_s),
                   default=None)
        if snap is None:
            return 0.0, total
        want = dict(spec.labels)
        want["le"] = (repr(float(snap)), str(snap), f"{snap:g}")
        good = self._sum_delta(cur, base, bucket_f, want)
        return max(0.0, total - good), total

    def _gauge_value(self, cur: dict, spec: SloSpec) -> float:
        best = 0.0
        prefix_b = spec.family + "{"
        for name, v in cur.items():
            if name != spec.family and not name.startswith(prefix_b):
                continue
            _f, labels = sample_labels(name)
            if _matches(labels, spec.labels):
                best = max(best, v)
        return best

    def evaluate(self) -> list[dict]:
        """One tick: scrape, compute burn rates, run every spec's state
        machine.  Returns the transitions that happened this tick."""
        with self._eval_mutex:
            t0 = time.perf_counter()
            cur = self._collect()  # seconds-long worst case; no locks
            with self._lock:
                t = self._now()
                self._ingest(t, cur)
                transitions: list[dict] = []
                for spec in self.specs:
                    transitions.extend(self._eval_spec(spec, t, cur))
                self._last_eval = t
            SLO_EVAL_SECONDS.observe(time.perf_counter() - t0)
        for alert in transitions:
            for sink in self._sinks:
                try:
                    sink(alert)
                except Exception as e:  # noqa: BLE001
                    glog.warning("alert sink failed: %s", e)
        return transitions

    def _eval_spec(self, spec: SloSpec, t: float, cur: dict) -> list[dict]:
        w = spec.burn_window()
        short_s = w.short_s * self.window_scale
        long_s = w.long_s * self.window_scale
        st = self._states.setdefault(spec.name, {
            "state": OK, "since": t, "above_since": None})
        burn_short = burn_long = 0.0
        value = None
        if spec.kind in ("gauge", "event"):
            if spec.kind == "event":
                # events over the SHORT window: a spike a fast repair
                # drains between ticks still counts — the counter delta
                # cannot un-happen the way a gauge reading can
                _bt, base = self._baseline(t, short_s)
                value = self._sum_delta(cur, base, spec.family,
                                        spec.labels)
            else:
                value = self._gauge_value(cur, spec)
            above = value >= spec.threshold
            if above and st["above_since"] is None:
                st["above_since"] = t
            if not above:
                st["above_since"] = None
            for_s = spec.for_s * self.window_scale
            if above and t - st["above_since"] >= for_s:
                new_state = FIRING
            elif above:
                new_state = PENDING
            else:
                new_state = OK
            # a level signal reads naturally as a burn of 0/ceiling
            burn_short = burn_long = (
                value / spec.threshold if spec.threshold > 0 else value)
        else:
            budget = max(1e-9, 1.0 - spec.objective)
            for window_s, slot in ((short_s, "short"), (long_s, "long")):
                _bt, base = self._baseline(t, window_s)
                if spec.kind == "latency":
                    bad, total = self._latency_deltas(cur, base, spec)
                else:
                    bad = self._sum_delta(
                        cur, base, spec.bad_family, spec.bad_labels)
                    total = self._sum_delta(
                        cur, base, spec.total_family, spec.total_labels)
                burn = (bad / total / budget) if total > 0 else 0.0
                if slot == "short":
                    burn_short = burn
                else:
                    burn_long = burn
            if burn_short > w.factor and burn_long > w.factor:
                new_state = FIRING
            elif burn_short > w.factor:
                new_state = PENDING
            else:
                new_state = OK
        SLO_BURN_RATE.labels(spec.name, "short").set(burn_short)
        SLO_BURN_RATE.labels(spec.name, "long").set(burn_long)
        SLO_ALERT_STATE.labels(spec.name, spec.severity).set(
            _STATE_VALUE[new_state])
        old_state = st["state"]
        alert = {
            "slo": spec.name, "severity": spec.severity,
            "state": new_state, "since": round(st["since"], 3),
            "at": round(t, 3), "description": spec.description,
            "burnShort": round(burn_short, 4),
            "burnLong": round(burn_long, 4),
            "windowShortS": round(short_s, 3),
            "windowLongS": round(long_s, 3),
        }
        if value is not None:
            alert["value"] = round(value, 4)
        if new_state == FIRING and old_state == FIRING:
            # keep the transition tick's exemplars on the ACTIVE alert:
            # an operator opening /cluster/alerts minutes into the page
            # still gets the one-hop trace link
            prev = st.get("alert") or {}
            for key in ("exemplars", "from"):
                if key in prev:
                    alert[key] = prev[key]
        st["alert"] = alert
        if new_state == old_state:
            return []
        st["state"] = new_state
        st["since"] = t
        alert["since"] = round(t, 3)
        alert["from"] = old_state
        if new_state == FIRING:
            self._attach_exemplars(spec, alert)
        to = new_state if new_state != OK else "resolved"
        SLO_TRANSITIONS.labels(spec.name, to).inc()
        self.alert_history.append(dict(alert))
        return [alert]

    def _attach_exemplars(self, spec: SloSpec, alert: dict) -> None:
        """Embed the slowest recent exemplar trace ids so the alert is
        one hop from page to stitched timeline.

        Exemplars come from the LOCAL process registry (histograms on
        remote nodes keep their own); candidates are filtered by the
        spec's label selector so a write-latency page can never link a
        slow GET's trace.  A spec judging purely remote SLIs simply
        attaches none — honest absence beats an irrelevant link."""
        if not spec.exemplar_family or self._exemplars is None:
            return
        try:
            ex = self._exemplars(spec.exemplar_family)
        except Exception:  # noqa: BLE001 — exemplars are best-effort
            return
        want = spec.labels or None
        picked = [{
            "traceId": e["traceId"], "seconds": e["value"], "le": e["le"],
            "traceQuery": f"/cluster/traces?trace={e['traceId']}",
        } for e in ex if _matches(e.get("labels", {}), want)][:3]
        if picked:
            alert["exemplars"] = picked

    # -- surfaces ---------------------------------------------------------

    def status(self, evaluate_if_idle: bool = True) -> dict:
        """The /cluster/alerts document.  With no evaluation loop
        running (interval 0), serve a fresh evaluation so the endpoint
        is usable on a manually driven master."""
        if evaluate_if_idle and self._thread is None:
            try:
                self.evaluate()
            except Exception as e:  # noqa: BLE001
                glog.warning("on-demand slo evaluation failed: %s", e)
        with self._lock:
            active = []
            states = {}
            for spec in self.specs:
                st = self._states.get(spec.name)
                if st is None:
                    continue
                states[spec.name] = {
                    "state": st["state"],
                    "sinceS": round(self._now() - st["since"], 3),
                    "severity": spec.severity,
                }
                if st["state"] != OK and "alert" in st:
                    active.append(st["alert"])
            return {
                "specs": [s.to_dict() for s in self.specs],
                "states": states,
                "alerts": active,
                "history": list(self.alert_history),
                "windowScale": self.window_scale,
                "intervalS": self.interval_s,
                "evaluatedAt": round(self._last_eval, 3),
            }

    def health_summary(self) -> dict:
        """Compact block for /cluster/status: counts + firing names."""
        with self._lock:
            firing = [n for n, st in self._states.items()
                      if st["state"] == FIRING]
            pending = [n for n, st in self._states.items()
                       if st["state"] == PENDING]
        return {
            "firing": sorted(firing),
            "pending": sorted(pending),
            "specs": len(self.specs),
            "evaluating": self._thread is not None,
        }
