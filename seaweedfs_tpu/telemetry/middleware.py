"""Shared request instrumentation for the four HTTP server types.

One code path replaces the previous ad-hoc `REQUEST_COUNTER.labels(...)`
call sites: every request through `http_request` / `record_op` gets,
uniformly,

  * seaweedfs_request_total{type,op}        (counter)
  * seaweedfs_request_seconds{type,op}      (latency histogram)
  * an active span (joined to the caller's trace via `traceparent`)
  * a slow-request glog line carrying the trace id when the request
    exceeds SLOW_REQUEST_SECONDS

so the master, volume, filer and S3 gateways cannot drift apart in what
they measure (the pre-refactor state: master assign counted but never
timed, filer counted but never timed, volume did both by hand).
"""

from __future__ import annotations

import os
from contextlib import contextmanager

from ..stats.metrics import REQUEST_COUNTER, REQUEST_HISTOGRAM
from ..util import glog
from . import trace

SLOW_REQUEST_SECONDS = float(
    os.environ.get("SEAWEEDFS_TPU_SLOW_REQUEST_S", "1.0"))

DEBUG_TRACES_PATH = "/debug/traces"
DEBUG_FAULTS_PATH = "/debug/faults"
METRICS_PATH = "/metrics"


@contextmanager
def record_op(server_type: str, op: str, **attrs):
    """Instrument one logical operation: counter + histogram + span."""
    REQUEST_COUNTER.labels(server_type, op).inc()
    hist = REQUEST_HISTOGRAM.labels(server_type, op)
    span = None
    try:
        with trace.start_span(f"{server_type}.{op}", **attrs) as span:
            yield span
    finally:
        if span is not None:
            hist.observe(span.duration)
            if span.duration >= SLOW_REQUEST_SECONDS:
                glog.warning(
                    "slow request %s.%s took %.3fs trace=%s",
                    server_type, op, span.duration, span.trace_id,
                )


@contextmanager
def http_request(handler, server_type: str, op: str):
    """`record_op` for a BaseHTTPRequestHandler request: adopts the
    caller's `traceparent` (if any) so the span joins their trace."""
    incoming = handler.headers.get(trace.TRACEPARENT)
    with trace.remote_context(incoming):
        with record_op(
            server_type, op,
            method=handler.command, path=handler.path.split("?")[0],
        ) as span:
            yield span


def debug_traces_body(limit: int = 50) -> bytes:
    """JSON body for GET /debug/traces on any server."""
    return trace.TRACER.traces_json(limit)


def serve_debug_http(handler, path: str) -> bool:
    """Answer /metrics, /debug/traces or /debug/faults on a
    BaseHTTPRequestHandler.

    The one implementation of the observability surface every server
    type mounts on its main HTTP port; returns True when `path` was one
    of the endpoints (response fully written), False otherwise."""
    if path == DEBUG_TRACES_PATH:
        body, ctype = debug_traces_body(), "application/json"
    elif path == METRICS_PATH:
        from ..stats.metrics import REGISTRY

        body, ctype = REGISTRY.render().encode(), "text/plain; version=0.0.4"
    elif path == DEBUG_FAULTS_PATH:
        import json
        import urllib.parse

        from ..util import faultpoint

        query = urllib.parse.parse_qs(
            urllib.parse.urlparse(handler.path).query)
        try:
            state = faultpoint.handle_debug_request(query)
        except (ValueError, PermissionError) as e:
            body = json.dumps({"error": str(e)}).encode()
            handler.send_response(403 if isinstance(e, PermissionError)
                                  else 400)
            handler.send_header("Content-Type", "application/json")
            handler.send_header("Content-Length", str(len(body)))
            handler.end_headers()
            if handler.command != "HEAD":
                handler.wfile.write(body)
            return True
        body, ctype = json.dumps(state).encode(), "application/json"
    else:
        return False
    handler.send_response(200)
    handler.send_header("Content-Type", ctype)
    handler.send_header("Content-Length", str(len(body)))
    handler.end_headers()
    if handler.command != "HEAD":
        handler.wfile.write(body)
    return True
