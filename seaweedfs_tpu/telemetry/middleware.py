"""Shared request instrumentation for the four HTTP server types.

One code path replaces the previous ad-hoc `REQUEST_COUNTER.labels(...)`
call sites: every request through `http_request` / `record_op` gets,
uniformly,

  * seaweedfs_request_total{type,op}        (counter)
  * seaweedfs_request_seconds{type,op}      (latency histogram)
  * an active span (joined to the caller's trace via `traceparent`)
  * a slow-request glog line carrying the trace id when the request
    exceeds SLOW_REQUEST_SECONDS

so the master, volume, filer and S3 gateways cannot drift apart in what
they measure (the pre-refactor state: master assign counted but never
timed, filer counted but never timed, volume did both by hand).
"""

from __future__ import annotations

from contextlib import contextmanager

from ..stats.metrics import REQUEST_COUNTER, REQUEST_HISTOGRAM
from ..util import glog
from . import trace

# one threshold for the slow-request log AND the tracer's important-span
# retention ring (defined in trace.py so the tracer needs no import from
# here)
SLOW_REQUEST_SECONDS = trace.SLOW_SPAN_SECONDS

DEBUG_TRACES_PATH = "/debug/traces"
DEBUG_FAULTS_PATH = "/debug/faults"
DEBUG_PROFILE_PATH = "/debug/profile"
DEBUG_PROFILE_HISTORY_PATH = "/debug/profile/history"
DEBUG_HOT_PATH = "/debug/hot"
METRICS_PATH = "/metrics"

TRACE_LIMIT_MAX = 1000


@contextmanager
def record_op(server_type: str, op: str, **attrs):
    """Instrument one logical operation: counter + histogram + span."""
    REQUEST_COUNTER.labels(server_type, op).inc()
    hist = REQUEST_HISTOGRAM.labels(server_type, op)
    span = None
    try:
        with trace.start_span(f"{server_type}.{op}", **attrs) as span:
            yield span
    finally:
        if span is not None:
            # the span's trace id rides along as the histogram exemplar:
            # the slowest sample per bucket window keeps its trace id, so
            # a firing latency alert links straight to a timeline
            hist.observe(span.duration, trace_id=span.trace_id)
            if span.duration >= SLOW_REQUEST_SECONDS:
                glog.warning(
                    "slow request %s.%s took %.3fs trace=%s",
                    server_type, op, span.duration, span.trace_id,
                )


@contextmanager
def http_request(handler, server_type: str, op: str):
    """`record_op` for a BaseHTTPRequestHandler request: adopts the
    caller's `traceparent` (if any) so the span joins their trace."""
    incoming = handler.headers.get(trace.TRACEPARENT)
    # heavy-hitter attribution: every HTTP request feeds the peer-IP
    # sketch, so "which client is hammering us" is answerable on any
    # server type without per-handler wiring
    addr = getattr(handler, "client_address", None)
    if addr:
        from . import hotkeys

        hotkeys.record("peer", addr[0])
    with trace.remote_context(incoming):
        with record_op(
            server_type, op,
            method=handler.command, path=handler.path.split("?")[0],
        ) as span:
            yield span


def debug_traces_body(limit: int = 50, trace_id: str | None = None) -> bytes:
    """JSON body for GET /debug/traces on any server."""
    return trace.TRACER.traces_json(limit, trace_id=trace_id)


def parse_trace_query(query: dict) -> tuple[str | None, int]:
    """Validated (?trace=<32-hex id>, ?limit=N) from a parse_qs dict.

    Raises ValueError with an operator-readable message — the shared
    input validation for every server's /debug/traces and the master's
    /cluster/traces (which forwards the same parameters)."""
    trace_id: str | None = None
    raw = query.get("trace", [""])[0].strip().lower()
    if raw:
        if len(raw) != 32 or not trace._is_hex(raw):
            raise ValueError("trace must be a 32-hex-char trace id")
        trace_id = raw
    raw_limit = query.get("limit", [""])[0].strip()
    limit = 50
    if raw_limit:
        try:
            limit = int(raw_limit)
        except ValueError:
            raise ValueError("limit must be an integer") from None
        if not 1 <= limit <= TRACE_LIMIT_MAX:
            raise ValueError(f"limit must be in [1, {TRACE_LIMIT_MAX}]")
    return trace_id, limit


def _send(handler, code: int, body: bytes, ctype: str) -> None:
    handler.send_response(code)
    handler.send_header("Content-Type", ctype)
    handler.send_header("Content-Length", str(len(body)))
    handler.end_headers()
    if handler.command != "HEAD":
        handler.wfile.write(body)


def _send_error(handler, code: int, message: str) -> None:
    import json

    _send(handler, code, json.dumps({"error": message}).encode(),
          "application/json")


def serve_debug_http(handler, path: str) -> bool:
    """Answer /metrics, /debug/traces, /debug/faults or /debug/profile on
    a BaseHTTPRequestHandler.

    The one implementation of the observability surface every server
    type mounts on its main HTTP port; returns True when `path` was one
    of the endpoints (response fully written), False otherwise."""
    import json
    import urllib.parse

    if path == DEBUG_TRACES_PATH:
        query = urllib.parse.parse_qs(
            urllib.parse.urlparse(handler.path).query)
        try:
            trace_id, limit = parse_trace_query(query)
        except ValueError as e:
            _send_error(handler, 400, str(e))
            return True
        body, ctype = debug_traces_body(limit, trace_id), "application/json"
    elif path == METRICS_PATH:
        from ..stats.metrics import REGISTRY, parse_family_prefixes

        query = urllib.parse.parse_qs(
            urllib.parse.urlparse(handler.path).query)
        try:
            prefixes = parse_family_prefixes(query.get("family", [""])[0])
        except ValueError as e:
            _send_error(handler, 400, str(e))
            return True
        body, ctype = (REGISTRY.render(prefixes).encode(),
                       "text/plain; version=0.0.4")
    elif path == DEBUG_PROFILE_HISTORY_PATH:
        from ..util import profiler

        if not profiler.enabled():
            _send_error(handler, 403,
                        f"profiler disabled ({profiler.DISABLE_VAR}=1)")
            return True
        body, ctype = (json.dumps(profiler.continuous_history()).encode(),
                       "application/json")
    elif path == DEBUG_HOT_PATH:
        from . import hotkeys

        query = urllib.parse.parse_qs(
            urllib.parse.urlparse(handler.path).query)
        try:
            n = int(query.get("n", [""])[0] or 32)
            if not 1 <= n <= 1024:
                raise ValueError("n must be in [1, 1024]")
        except ValueError as e:
            _send_error(handler, 400, str(e))
            return True
        body, ctype = (json.dumps(hotkeys.snapshot(n)).encode(),
                       "application/json")
    elif path == DEBUG_PROFILE_PATH:
        from ..util import profiler
        from ..util.grace import profile_status

        query = urllib.parse.parse_qs(
            urllib.parse.urlparse(handler.path).query)
        if query.get("status", [""])[0]:
            # the pre-sampler status stub, kept for cheap liveness checks
            body, ctype = (json.dumps(profile_status()).encode(),
                           "application/json")
        elif not profiler.enabled():
            _send_error(handler, 403,
                        f"profiler disabled ({profiler.DISABLE_VAR}=1)")
            return True
        else:
            try:
                seconds = float(query.get("seconds", [""])[0]
                                or profiler.DEFAULT_DURATION_S)
                hz = int(query.get("hz", [""])[0] or profiler.DEFAULT_HZ)
                text = profiler.profile_collapsed(seconds, hz)
            except (ValueError, TypeError) as e:
                _send_error(handler, 400, str(e))
                return True
            except profiler.ProfilerBusy as e:
                _send_error(handler, 409, str(e))
                return True
            body, ctype = text.encode(), "text/plain; charset=utf-8"
    elif path == DEBUG_FAULTS_PATH:
        from ..util import faultpoint

        query = urllib.parse.parse_qs(
            urllib.parse.urlparse(handler.path).query)
        try:
            state = faultpoint.handle_debug_request(query)
        except (ValueError, PermissionError) as e:
            _send_error(handler,
                        403 if isinstance(e, PermissionError) else 400,
                        str(e))
            return True
        body, ctype = json.dumps(state).encode(), "application/json"
    else:
        return False
    _send(handler, 200, body, ctype)
    return True
