"""Heavy-hitter attribution: which keys are hot RIGHT NOW.

Every real incident on a blob store starts with the same question —
*which needle / bucket / tenant / client is doing this to us* — and
counters can't answer it without unbounded per-key label cardinality.
The space-saving sketch (Metwally et al., "Efficient Computation of
Frequent and Top-k Elements in Data Streams") answers it in O(k)
memory: track at most k counters; on a miss, evict the minimum counter
and inherit its count as the new key's overestimation error.  Any key
whose true frequency exceeds N/k is guaranteed to be present, and every
reported count is exact to within its per-key `error`.

One `HotKeyRecorder` per process holds a sketch per dimension over a
rolling window (current + previous, so a reader always sees one fully
closed window).  Feeds are one call per request from the existing
handler paths:

    needle — volume server GET/POST/DELETE fid
    bucket — S3 gateway request routing
    tenant — filer admission (tenant_for_path)
    peer   — request middleware (client address, every server type)

Surfaces: `/debug/hot` per node, `GET /cluster/hot` federated on the
master, `seaweedfs_hotkey_*` metric families, and the hot-key section
of flight-recorder debug bundles.
"""

from __future__ import annotations

import heapq
import os
import threading
import time

from ..stats.metrics import HOTKEY_EVENTS, HOTKEY_TOP, HOTKEY_TRACKED

DIMENSIONS = ("needle", "bucket", "tenant", "peer")

# kill-switch mirrors the profiler's polarity: attribution only costs a
# little CPU, so it is on by default and =0 closes it fleet-wide
DISABLE_VAR = "SEAWEEDFS_TPU_HOTKEYS"
K_VAR = "SEAWEEDFS_TPU_HOTKEYS_K"
WINDOW_VAR = "SEAWEEDFS_TPU_HOTKEYS_WINDOW_S"
DEFAULT_K = 64
DEFAULT_WINDOW_S = 60.0
# per-key gauge children published per dimension per window — the hard
# cardinality bound on the seaweedfs_hotkey_top_count family
TOP_GAUGE_KEYS = 10


def enabled() -> bool:
    return os.environ.get(DISABLE_VAR, "") != "0"


def _env_num(var: str, default: float) -> float:
    try:
        return float(os.environ.get(var, "") or default)
    except ValueError:
        return default


class SpaceSaving:
    """Bounded top-k frequency sketch.  Not thread-safe; the recorder
    serializes access.

    Eviction uses a lazy min-heap: every count update pushes a fresh
    (count, key) entry and leaves the old one stale; a miss pops until
    the top entry matches the live count — that key is the true minimum
    (every live count has an entry, smaller stale ones are skipped).
    Misses cost O(log k) amortized instead of an O(k) scan, which is
    what keeps the all-miss feed (distinct needle ids on every request)
    inside the flight recorder's <3% overhead budget."""

    __slots__ = ("k", "_counts", "_errors", "_heap")

    def __init__(self, k: int):
        self.k = max(1, int(k))
        self._counts: dict[str, int] = {}
        self._errors: dict[str, int] = {}
        self._heap: list[tuple[int, str]] = []

    def __len__(self) -> int:
        return len(self._counts)

    def record(self, key: str, n: int = 1) -> None:
        counts = self._counts
        cur = counts.get(key)
        if cur is not None:
            counts[key] = cur + n
            heapq.heappush(self._heap, (cur + n, key))
        elif len(counts) < self.k:
            counts[key] = n
            self._errors[key] = 0
            heapq.heappush(self._heap, (n, key))
        else:
            # evict the minimum; the newcomer inherits its count as error
            heap = self._heap
            while True:
                c, victim = heap[0]
                if counts.get(victim) == c:
                    break
                heapq.heappop(heap)  # stale entry
            floor = counts.pop(victim)
            self._errors.pop(victim, None)
            heapq.heapreplace(heap, (floor + n, key))
            counts[key] = floor + n
            self._errors[key] = floor
        # bound the stale backlog: rebuild from live counts when the
        # heap outgrows the sketch by a constant factor
        if len(self._heap) > 8 * self.k:
            self._heap = [(c, k) for k, c in counts.items()]
            heapq.heapify(self._heap)

    def top(self, n: int | None = None) -> list[dict]:
        items = sorted(self._counts.items(), key=lambda kv: (-kv[1], kv[0]))
        if n is not None:
            items = items[:n]
        return [{"key": k, "count": c, "error": self._errors.get(k, 0)}
                for k, c in items]


class HotKeyRecorder:
    """Per-dimension rolling-window sketches behind one cheap lock."""

    def __init__(self, k: int | None = None,
                 window_s: float | None = None):
        self.k = int(_env_num(K_VAR, DEFAULT_K)) if k is None else int(k)
        self.window_s = (_env_num(WINDOW_VAR, DEFAULT_WINDOW_S)
                         if window_s is None else float(window_s))
        self.window_s = max(0.05, self.window_s)
        self._lock = threading.Lock()
        self._cur = {d: SpaceSaving(self.k) for d in DIMENSIONS}
        self._prev = {d: SpaceSaving(self.k) for d in DIMENSIONS}
        self._window_start = time.time()
        # resolved counter children: skips the labels() lookup on the
        # per-request hot path
        self._events = {d: HOTKEY_EVENTS.labels(d) for d in DIMENSIONS}

    def record(self, dim: str, key: str, n: int = 1) -> None:
        if not key or dim not in self._cur:
            return
        with self._lock:
            now = time.time()
            if now - self._window_start >= self.window_s:
                self._rotate_locked(now)
            self._cur[dim].record(str(key), n)
        self._events[dim].inc(n)

    def _rotate_locked(self, now: float) -> None:
        # the closing window becomes the readable "previous"; its top
        # keys replace the per-key gauge children wholesale, so the
        # family's cardinality stays <= dims * TOP_GAUGE_KEYS forever
        self._prev = self._cur
        self._cur = {d: SpaceSaving(self.k) for d in DIMENSIONS}
        self._window_start = now
        with HOTKEY_TOP._lock:
            HOTKEY_TOP._children.clear()
        for dim, sketch in self._prev.items():
            HOTKEY_TRACKED.labels(dim).set(len(sketch))
            for entry in sketch.top(TOP_GAUGE_KEYS):
                HOTKEY_TOP.labels(dim, entry["key"]).set(entry["count"])

    def snapshot(self, n: int = 32) -> dict:
        """JSON doc for /debug/hot: current (in-progress) and previous
        (closed) window top keys per dimension."""
        with self._lock:
            now = time.time()
            if now - self._window_start >= self.window_s:
                self._rotate_locked(now)
            doc = {
                "enabled": enabled(),
                "k": self.k,
                "windowS": self.window_s,
                "windowAgeS": now - self._window_start,
                "dims": {
                    d: {
                        "current": self._cur[d].top(n),
                        "previous": self._prev[d].top(n),
                    }
                    for d in DIMENSIONS
                },
            }
        return doc


_RECORDER: HotKeyRecorder | None = None
_RECORDER_LOCK = threading.Lock()


def recorder() -> HotKeyRecorder:
    global _RECORDER
    r = _RECORDER
    if r is None:
        with _RECORDER_LOCK:
            if _RECORDER is None:
                _RECORDER = HotKeyRecorder()
            r = _RECORDER
    return r


def reset() -> None:
    """Drop the process singleton (tests / bench A-B re-read the env)."""
    global _RECORDER
    with _RECORDER_LOCK:
        _RECORDER = None


def record(dim: str, key: str, n: int = 1) -> None:
    """The hot-path feed: no-op when the kill-switch is set."""
    if not enabled():
        return
    recorder().record(dim, key, n)


def snapshot(n: int = 32) -> dict:
    return recorder().snapshot(n)
