"""seaweedfs_tpu — a TPU-native distributed blob store (SeaweedFS-class).

Master / volume-server / filer architecture with needle-log volumes and
RS(10,4) erasure coding, where the GF(2^8) codec is a JAX/XLA program on TPU
instead of CPU SIMD assembly.  See SURVEY.md for the reference analysis this
framework is built against.
"""

__version__ = "0.1.0"
