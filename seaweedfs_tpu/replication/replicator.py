"""Replicator: map a filer metadata event onto sink operations.

Reference: weed/replication/replicator.go:18,36 — the create/delete/
update/rename decision tree over (old_entry, new_entry, new_parent_path).
"""

from __future__ import annotations

import threading

from ..pb import filer_pb2
from ..util import glog
from .sink import Sink, SinkPermanentError
from .source import FilerSource, subscribe_metadata


class Replicator:
    def __init__(self, source: FilerSource, sink: Sink,
                 path_prefix: str = "/", signature: int = 0):
        """``signature`` is passed to the metadata subscription so events
        this replicator's own sink caused (carrying the same signature)
        are filtered out — required for loop-free bidirectional sync."""
        self.source = source
        self.sink = sink
        self.path_prefix = path_prefix
        self.signature = signature
        self.replicated = 0

    def process_event(self, directory: str,
                      event: filer_pb2.EventNotification) -> None:
        """One event -> sink ops (replicator.go Replicate)."""
        has_old = bool(event.old_entry.name)
        has_new = bool(event.new_entry.name)
        if not has_old and not has_new:
            return
        if has_old and not has_new:
            self.sink.delete_entry(
                directory, event.old_entry.name, event.old_entry.is_directory
            )
        elif has_new and not has_old:
            data = self.source.read_entry_data(directory, event.new_entry)
            self.sink.create_entry(directory, event.new_entry, data)
        else:  # update or rename
            new_dir = event.new_parent_path or directory
            if (event.new_parent_path
                    and event.new_parent_path != directory) or (
                    event.old_entry.name != event.new_entry.name):
                self.sink.delete_entry(
                    directory, event.old_entry.name,
                    event.old_entry.is_directory,
                )
            data = self.source.read_entry_data(new_dir, event.new_entry)
            self.sink.create_entry(new_dir, event.new_entry, data)
        self.replicated += 1

    def run(self, stop_event: threading.Event | None = None,
            since_ns: int = 0) -> None:
        """Consume the source filer's metadata stream until stopped.

        A source that was NEVER reachable raises (an unreachable filer
        must not look like a successful zero-event replication).  A
        stream dropped after traffic — source restart, network blip —
        RESUBSCRIBES from the last applied event timestamp with the
        shared capped-jitter backoff (util/failsafe.py), the reference's
        filer.sync reconnect discipline."""
        import time as _time

        import grpc

        from ..telemetry import trace
        from ..util import failsafe

        backoff = failsafe.Backoff(failsafe.RetryPolicy(
            max_attempts=1 << 30, base_delay=0.5, max_delay=15.0))
        resume_ns = since_ns
        source_seen = False
        while True:
            if not source_seen:
                # prove the source is REACHABLE with a cheap unary rpc
                # before trusting the subscription loop: a quiet stream
                # and a blackholed address are otherwise indistinguishable
                from ..pb import rpc as rpclib

                host, _, port = self.source.filer_http.partition(":")
                stub = rpclib.filer_stub(f"{host}:{int(port) + 10000}",
                                         timeout=20)
                stub.GetFilerConfiguration(
                    filer_pb2.GetFilerConfigurationRequest())  # raises
                source_seen = True
            try:
                for resp in subscribe_metadata(
                    self.source.filer_http, self.path_prefix, resume_ns,
                    signature=self.signature,
                ):
                    if stop_event is not None and stop_event.is_set():
                        return
                    while True:
                        try:
                            self.process_event(resp.directory,
                                               resp.event_notification)
                        except SinkPermanentError as e:
                            # the target rejected this event for good
                            # (4xx): re-applying can never succeed —
                            # count it, skip it, keep the stream moving
                            from ..stats.metrics import REPLICATION_ERROR

                            REPLICATION_ERROR.labels("apply").inc()
                            glog.warning("replicate %s rejected "
                                         "permanently: %s; skipping "
                                         "event", resp.directory, e)
                        except Exception as e:  # noqa: BLE001 — transient
                            # transport/5xx after the sink's own retries:
                            # retry THIS event in place.  Resubscribing
                            # from the last applied ts would SKIP it when
                            # it arrived late with an older ts than
                            # resume_ns (the aggregated stream is
                            # arrival-ordered but the subscription resume
                            # is ts-filtered) — the event would never be
                            # re-delivered.  Sink applies are idempotent
                            # upserts, so in-place repeats are safe
                            delay = backoff.next()
                            failsafe.RETRY_COUNTER.labels(
                                "replicator", "apply", "transient").inc()
                            glog.warning(
                                "replicate %s failed (%s); retrying the "
                                "event in %.2fs", resp.directory, e,
                                delay)
                            if stop_event is not None:
                                if stop_event.wait(delay):
                                    return
                            else:
                                _time.sleep(delay)
                            continue
                        break
                    # reset only AFTER an event actually applied: a
                    # redelivered poison event would otherwise see the
                    # base delay forever (reset at stream-top) instead
                    # of escalating toward the policy cap
                    backoff.reset()
                    resume_ns = max(resume_ns, resp.ts_ns)
                return  # server closed the stream cleanly
            except grpc.RpcError as e:
                if e.code() == grpc.StatusCode.CANCELLED:
                    return
                if stop_event is not None and stop_event.is_set():
                    return
                delay = backoff.next()
                failsafe.RETRY_COUNTER.labels(
                    "replicator", "subscribe", "stream_drop").inc()
                glog.warning(
                    "replicate stream from %s dropped (%s); resuming "
                    "from ts=%d in %.2fs trace=%s",
                    self.source.filer_http, e.code(), resume_ns, delay,
                    trace.current_trace_id() or "-")
                if stop_event is not None:
                    if stop_event.wait(delay):
                        return
                else:
                    _time.sleep(delay)
