"""Cross-cluster active-active replication (ISSUE 12).

Two pieces, both hosted inside the filer process:

* ``GeoReplicator`` — one per remote cluster link.  Tails the local
  filer's DURABLE metadata event log (filer/meta_log.py) from a
  journaled checkpoint (the PR 9 crash-safe JSONL journal), ships every
  event PLUS the referenced object bytes to the remote cluster's filer
  over the connpool, and paces itself with a per-link token bucket — the
  same background-budget discipline as scrub/lifecycle traffic
  (arXiv:1309.0186): async batched shipping, never synchronous dual
  writes (arXiv:1709.05365's cold-path economics).

  Crash safety: the checkpoint advances only after the remote
  acknowledged the event, and re-shipping after a crash is deduplicated
  remotely by the per-link watermark — together, exactly-once apply.
  Sequence numbers are contiguous by construction; a checkpoint that
  fell behind the log's retention raises ``MetaLogGap`` and the link
  RESYNCS from a full namespace walk (LWW makes the overlap safe).

* ``GeoApplier`` — the receiving side, behind the filer's
  ``POST /.geo/apply`` endpoint.  Resolves ACTIVE-ACTIVE conflicts by
  last-writer-wins on the hybrid logical clock every event carries
  (ts_ns stamped by the origin's meta log, origin cluster id as the
  tiebreak), consults delete tombstones so an older create cannot
  resurrect a deleted object, folds the remote clock into the local one
  (``meta_log.observe``), and counts every LWW rejection in
  ``seaweedfs_geo_conflicts_total`` — conflicts are surfaced, never
  silent.  Applied mutations re-enter the local write path carrying the
  ORIGIN's signature, which is what keeps a bidirectional link loop-free
  (the replicator skips events signed by its own remote).
"""

from __future__ import annotations

import json
import os
import struct
import threading
import time
import urllib.error
import urllib.parse
from collections import deque

from ..filer.filer import join_path, split_path
from ..filer.meta_log import (
    GEO_HLC_KEY,
    MetaLogGap,
    decode_hlc,
    encode_hlc,
    entry_hlc,
    tombstone_key,
)
from ..maintenance.journal import JobJournal
from ..stats.metrics import (
    GEO_APPLIED,
    GEO_BYTES,
    GEO_CONFLICTS,
    GEO_EVENTS,
    GEO_LAG,
)
from ..util import connpool, failsafe, faultpoint, glog
from .sink import FP_REPLICATION_APPLY

RATE_ENV = "SEAWEEDFS_TPU_GEO_RATE_MBPS"
DEFAULT_RATE_MBPS = 8.0

# per-event wire overhead charged to the link budget on top of the body
EVENT_OVERHEAD_BYTES = 256

# checkpoint cadence: re-shipping the window after a crash is dedup'd by
# the remote watermark, so a per-event fsync'd journal write would buy
# nothing but write amplification
CHECKPOINT_EVERY = 20
CHECKPOINT_INTERVAL_S = 1.0

# tombstones older than this are garbage-collected when next read — any
# create they could have fenced off is long since shipped both ways
TOMBSTONE_RETAIN_S = float(os.environ.get(
    "SEAWEEDFS_TPU_GEO_TOMBSTONE_RETAIN_S", str(7 * 86400)))

# one geo event materializes its whole object body in RAM on BOTH
# filers (sender _read_data, applier body buffer); beyond this size the
# sender skips the event (counted as an error) and the applier refuses
# with 413 — an unbounded Content-Length on /.geo/apply must not be an
# OOM lever
MAX_BODY_BYTES = int(os.environ.get(
    "SEAWEEDFS_TPU_GEO_MAX_BODY_MB", "256")) << 20

# events stamped further ahead of the local clock than this are REFUSED
# (400, permanent): folding a corrupt/forged far-future hlc into the
# local clock would poison BOTH clusters' HLCs persistently and fence
# the path with an unbeatable tombstone
MAX_SKEW_S = float(os.environ.get("SEAWEEDFS_TPU_GEO_MAX_SKEW_S", "3600"))

# namespaces that never cross clusters: each cluster owns its own config
# (filer.conf, IAM identities) and broker internals
SKIP_PREFIXES = ("/etc/", "/topics/")

_WM_PREFIX = b"GeoSeq"


def _wm_key(source_signature: int) -> bytes:
    return _WM_PREFIX + struct.pack(">i", source_signature)


class GeoSkewError(ValueError):
    """Event hlc too far ahead of the local clock: REMOTE-state
    rejection (the sender's clock is broken), not a poison event — the
    HTTP layer marks it so the sender holds the link instead of
    skipping the event past its checkpoint forever."""


def _iter_dir(store, directory: str):
    """Paginated listing of one directory — shared by the applier's
    subtree walks and the resync shipper so the resume/termination
    logic cannot drift between copies."""
    start = ""
    while True:
        batch = list(store.list_entries(directory, start_from=start,
                                        limit=1024))
        if not batch:
            return
        yield from batch
        start = batch[-1].name


class GeoApplier:
    """LWW apply of remote cluster events into the local filer.

    Idempotency key = (source store signature, source LOG identity,
    source log seq): the per-source watermark persisted in the store KV
    drops re-shipped events, so a replicator crash-resuming behind its
    checkpoint applies each event exactly once.  The log identity scopes
    the seq comparison to ONE meta-log incarnation — a source whose log
    dir was wiped restarts at seq 1 with a new log id, and its events
    must not be swallowed as "duplicates" of the OLD log's higher
    watermark.  seq==0 events (namespace resync walks) skip the
    watermark and rely on LWW alone."""

    PERSIST_EVERY = 64
    PERSIST_INTERVAL_S = 2.0

    def __init__(self, fs):
        self.fs = fs  # FilerServer
        self._lock = threading.Lock()
        self._watermarks: dict[int, tuple[int, str]] = {}  # src->(seq,log)
        self._dirty = 0
        self._last_persist = time.monotonic()

    # -- watermarks --------------------------------------------------------

    def watermark(self, source: int) -> tuple[int, str]:
        """-> (seq, log_id) high-water mark for one source; log_id ""
        for pre-log-identity senders/records (seq compared unscoped)."""
        with self._lock:
            wm = self._watermarks.get(source)
            if wm is not None:
                return wm
            raw = self.fs.filer.store.kv_get(_wm_key(source))
            if raw and len(raw) >= 8:
                wm = (struct.unpack(">q", raw[:8])[0],
                      raw[8:].decode("ascii", "replace"))
            else:
                wm = (0, "")
            self._watermarks[source] = wm
            return wm

    def _advance(self, source: int, seq: int, log: str) -> None:
        with self._lock:
            cur_seq, cur_log = self._watermarks.get(source, (0, ""))
            if seq <= cur_seq and log == cur_log:
                return
            # a CHANGED log id rebinds the watermark to the new
            # incarnation (seq restarts); same-log marks only advance
            self._watermarks[source] = (max(seq, cur_seq)
                                        if log == cur_log else seq, log)
            self._dirty += 1
            now = time.monotonic()
            if (self._dirty >= self.PERSIST_EVERY
                    or now - self._last_persist > self.PERSIST_INTERVAL_S):
                self._persist_locked()

    def _persist_locked(self) -> None:
        for source, (seq, log) in self._watermarks.items():
            self.fs.filer.store.kv_put(
                _wm_key(source),
                struct.pack(">q", seq) + log.encode("ascii", "replace"))
        self._dirty = 0
        self._last_persist = time.monotonic()

    def flush(self) -> None:
        with self._lock:
            self._persist_locked()

    def status(self) -> dict:
        with self._lock:
            return {"watermarks": {src: seq for src, (seq, _log)
                                   in self._watermarks.items()}}

    # -- LWW core ----------------------------------------------------------

    def _local_stamp(self, path: str):
        """-> (best local (hlc, cluster) stamp or None, current entry).
        The stamp is the max of the live entry's stamp, any delete
        tombstone at the path, and any ANCESTOR tombstone — a recursive
        directory delete fences the whole subtree with ONE tombstone at
        the directory (children get none), so a backlogged older write
        inside the subtree must compare against the ancestors too or it
        resurrects the deleted tree on this cluster only.  Tombstones
        past TOMBSTONE_RETAIN_S are GC'd here lazily (there is no store
        KV scan to sweep them eagerly; unrevisited paths keep their row
        until the next remote touch)."""
        filer = self.fs.filer
        entry = filer.find_entry(path)
        stamps = []
        s = entry_hlc(entry) if entry is not None and entry.name else None
        if s is not None:
            stamps.append(s)
        probe = path
        while probe and probe != "/":
            tomb = decode_hlc(filer.store.kv_get(tombstone_key(probe)))
            if tomb is not None:
                if (time.time_ns() - tomb[0]) / 1e9 > TOMBSTONE_RETAIN_S:
                    filer.store.kv_delete(tombstone_key(probe))
                else:
                    stamps.append(tomb)
            probe = probe.rsplit("/", 1)[0]
        return (max(stamps) if stamps else None), entry

    def apply(self, origin: int, source: int, seq: int, hlc: int, op: str,
              path: str, data: bytes = b"", mime: str = "",
              log: str = "") -> dict:
        """Apply one remote event; returns {"result": ...}.

        result ∈ ok | dup | conflict — all three mean "processed, sender
        may advance".  Errors raise (the sender retries transients)."""
        faultpoint.inject(FP_REPLICATION_APPLY, ctx=f"geo {path}")
        origin_l = str(origin)
        if hlc and hlc > time.time_ns() + MAX_SKEW_S * 1e9:
            # a sane peer's clock is within MAX_SKEW_S of ours; beyond
            # that the stamp is corrupt or forged and must not enter
            # the clock, the store, or a tombstone
            raise GeoSkewError(
                f"event hlc is {(hlc - time.time_ns()) / 1e9:.0f}s ahead "
                f"of this cluster's clock (max skew {MAX_SKEW_S:.0f}s)")
        if seq and source:
            wm_seq, wm_log = self.watermark(source)
            # the seq comparison only means "already applied" within ONE
            # log incarnation; a changed id means the source's log was
            # wiped/repointed and ITS seqs restarted — not duplicates.
            # A log-less sender (pre-identity) can only compare unscoped;
            # against a mismatched/legacy record we RE-APPLY instead —
            # safe, every apply is LWW-guarded — and rebind the mark
            if seq <= wm_seq and (not log or log == wm_log):
                GEO_APPLIED.labels(origin_l, "dup").inc()
                return {"result": "dup"}
        if hlc:
            # HLC merge rule: later local writes must stamp past every
            # remote write already applied here
            self.fs.filer.meta_log.observe(hlc)
        if op == "mkdir":
            result = self._apply_mkdir(origin, hlc, path)
        elif op == "put":
            result = self._apply_put(origin, hlc, path, data, mime)
        elif op == "delete":
            result = self._apply_delete(origin, hlc, path)
        else:
            raise ValueError(f"unknown geo op {op!r}")
        if seq and source:
            self._advance(source, seq, log)
        GEO_APPLIED.labels(origin_l, result).inc()
        return {"result": result}

    def _apply_mkdir(self, origin: int, hlc: int, path: str) -> str:
        # directories carry no payload and merge trivially when they
        # exist — but a missing dir must still pass the tombstone fence:
        # an older remote mkdir must not resurrect a newer local delete
        # (divergence: the delete wins on the origin, the resurrect here)
        incoming = (hlc, origin) if hlc else None
        with self.fs.filer.path_mutation_lock(path):
            local, entry = self._local_stamp(path)
            if entry is not None and entry.name:
                return "dup"  # already present: idempotent merge
            if incoming is not None and local is not None \
                    and incoming < local:
                GEO_CONFLICTS.labels(str(origin), "local").inc()
                return "conflict"
            # the origin stamp rides along so a later backlog delete of
            # the dir (older hlc than our apply time) still wins LWW
            self.fs.filer._ensure_parents(
                path, signatures=[origin],
                stamp=encode_hlc(hlc, origin) if hlc else None)
        return "ok"

    def _apply_put(self, origin: int, hlc: int, path: str, data: bytes,
                   mime: str) -> str:
        incoming = (hlc, origin)
        # the stripe serializes the stamp check + write-through against
        # concurrent local mutations of the same path: without it a
        # newer local write landing in the window would be silently
        # overwritten by this older remote event (reentrant: write_file
        # -> create_entry re-acquires it).  The hold spans the chunk
        # upload — acceptable because MAX_BODY_BYTES bounds it; writing
        # outside the stripe would need a re-check + orphan-chunk
        # cleanup on abort for a window that LWW already closes
        with self.fs.filer.path_mutation_lock(path):
            local, _entry = self._local_stamp(path)
            if local is not None:
                if incoming == local:
                    return "dup"  # same event, re-delivered
                if incoming < local:
                    # a strictly-newer local mutation already landed:
                    # the remote write was concurrent and loses (LWW)
                    GEO_CONFLICTS.labels(str(origin), "local").inc()
                    return "conflict"
            # winner: write through the normal path (chunks assigned in
            # THIS cluster, quotas accounted here, within-cluster peers
            # replicate it) carrying the ORIGIN's stamp + signature
            self.fs.write_file(
                path, data, mime=mime, signatures=[origin],
                extended={GEO_HLC_KEY: encode_hlc(hlc, origin)})
        return "ok"

    def _apply_delete(self, origin: int, hlc: int, path: str) -> str:
        incoming = (hlc, origin)
        with self.fs.filer.path_mutation_lock(path):
            local, entry = self._local_stamp(path)
            exists = entry is not None and bool(entry.name)
            if local is not None:
                if incoming == local and not exists:
                    return "dup"
                if incoming < local:
                    GEO_CONFLICTS.labels(str(origin), "local").inc()
                    return "conflict"
            directory, name = split_path(path)
            # the tombstone must carry the ORIGIN's stamp so every
            # cluster fences with the same clock value — and it must be
            # in the KV BEFORE delete_entry appends the meta-log event
            # (tombstone=), or a tailing replicator relaying the delete
            # onward could read a fresh local stamp in the window and
            # inflate the fence around a 3+-cluster mesh
            tomb = encode_hlc(hlc, origin)
            if not exists:
                self.fs.filer.store.kv_put(tombstone_key(path), tomb)
                return "ok"
            if not entry.is_directory:
                try:
                    self.fs.filer.delete_entry(
                        directory, name, is_recursive=True,
                        ignore_recursive_error=True, signatures=[origin],
                        tombstone=tomb)
                except FileNotFoundError:
                    self.fs.filer.store.kv_put(tombstone_key(path), tomb)
                return "ok"
            # directory: fence the subtree FIRST (under the root
            # stripe) so older writes can't slip in mid-walk
            self.fs.filer.store.kv_put(tombstone_key(path), tomb)
        # a recursive delete is LWW per CHILD, not per root: children
        # stamped newer than the delete are concurrent writes it must
        # lose to — on the origin they beat the ancestor tombstone and
        # get re-created, so destroying them here would diverge the
        # clusters forever.  Walk OUTSIDE the root stripe, taking each
        # child's OWN stripe one at a time: the per-child stamp check
        # then serializes against concurrent local writes (a newer
        # write landing mid-walk survives), and holding at most one
        # stripe can never deadlock ABBA against a concurrent
        # recursive apply rooted on one of our child stripes
        kept = self._delete_older_subtree(path, incoming, tomb, origin)
        if kept:
            return "conflict"
        with self.fs.filer.path_mutation_lock(path):
            try:
                # non-recursive: a child created since the walk makes
                # this fail loudly instead of being silently destroyed
                self.fs.filer._delete_entry_locked(
                    directory, name, is_recursive=False,
                    signatures=[origin], tombstone=tomb)
            except FileNotFoundError:
                pass
            except IsADirectoryError:
                GEO_CONFLICTS.labels(str(origin), "local").inc()
                return "conflict"
        return "ok"

    def _delete_older_subtree(self, path: str, incoming: tuple,
                              tomb: bytes, origin: int) -> int:
        """Depth-first delete of every entry under ``path`` stamped at
        or before the incoming delete; returns how many newer entries
        survived (each counted as a conflict).  A directory survives
        when it keeps survivors below it, or its own stamp is newer.
        Caller must NOT hold any path stripe (each child is re-checked
        and deleted under its own)."""
        filer = self.fs.filer
        kept = 0
        for e in list(_iter_dir(filer.store, path)):
            p = join_path(path, e.name)
            sub_kept = 0
            if e.is_directory:
                sub_kept = self._delete_older_subtree(p, incoming, tomb,
                                                      origin)
            with filer.path_mutation_lock(p):
                cur = filer.store.find_entry(path, e.name)
                if cur is None or not cur.name:
                    kept += sub_kept
                    continue  # already gone (racing delete)
                stamp = entry_hlc(cur)
                newer = stamp is not None and stamp > incoming
                if sub_kept or newer:
                    kept += sub_kept
                    if newer:
                        kept += 1
                        GEO_CONFLICTS.labels(str(origin), "local").inc()
                    continue
                try:
                    # child tombstones carry the origin stamp so a
                    # relay of these per-child delete events stays
                    # mesh-safe.  Non-recursive: a directory that
                    # gained a child since the sub-walk fails the
                    # delete loudly instead of destroying it
                    filer._delete_entry_locked(
                        path, e.name, is_recursive=False,
                        signatures=[origin], tombstone=tomb)
                except FileNotFoundError:
                    pass
                except IsADirectoryError:
                    kept += 1  # gained a child mid-walk: a newer write
                    GEO_CONFLICTS.labels(str(origin), "local").inc()
        return kept


class GeoReplicator:
    """One replication direction: this cluster's filer -> one remote
    cluster's filer.  Runs as a daemon thread inside the filer process."""

    def __init__(self, fs, remote_http: str, journal_dir: str | None = None,
                 rate_mbps: float | None = None, path_prefix: str = "/"):
        self.fs = fs
        self.remote_http = remote_http
        self.path_prefix = path_prefix
        self.link = f"c{fs.filer.cluster_id}->{remote_http}"
        if rate_mbps is None:
            rate_mbps = float(os.environ.get(RATE_ENV, DEFAULT_RATE_MBPS))
        self.bucket = None
        if rate_mbps > 0:
            from ..storage.scrub import TokenBucket

            self.bucket = TokenBucket(rate_mbps * (1 << 20))
        path = None
        if journal_dir:
            os.makedirs(journal_dir, exist_ok=True)
            safe = remote_http.replace(":", "_").replace("/", "_")
            path = os.path.join(journal_dir, f"geo.{safe}.journal.jsonl")
        self.journal = JobJournal(path)
        self._key = f"geo:{remote_http}"
        self._remote_cid: int | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._unsaved = 0
        self._last_save = time.monotonic()
        self._last_seq = 0  # newest source-log seq fully processed
        self.shipped = 0
        self.resyncs = 0
        self.last_shipped_ts = 0

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self.run, name=f"geo-{self.remote_http}", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        if self._last_seq:
            self._save_checkpoint(self._last_seq, force=True)

    def status(self) -> dict:
        ckpt = self.checkpoint()
        log_seq = self.fs.filer.meta_log.last_seq()
        # _last_seq runs ahead of the batched journal save; either one
        # reaching the log head means the link is drained — a drained
        # idle link is 0s behind, not "age of the last event"
        if max(ckpt, self._last_seq) >= log_seq:
            lag = 0.0
        elif self.last_shipped_ts:
            lag = max(0.0, (time.time_ns() - self.last_shipped_ts) / 1e9)
        else:
            lag = None
        return {
            "link": self.link,
            "remote": self.remote_http,
            "checkpoint": ckpt,
            "logSeq": log_seq,
            "shipped": self.shipped,
            "resyncs": self.resyncs,
            "rateMBps": (self.bucket.rate / (1 << 20)
                         if self.bucket else 0.0),
            "lagSeconds": lag,
        }

    # -- checkpoint --------------------------------------------------------

    def checkpoint(self) -> int:
        rec = self.journal.get(self._key)
        return int(rec.get("seq", 0)) if rec else 0

    def _save_checkpoint(self, seq: int, force: bool = False) -> None:
        self._unsaved += 1
        now = time.monotonic()
        if not force and self._unsaved < CHECKPOINT_EVERY and \
                now - self._last_save < CHECKPOINT_INTERVAL_S:
            return
        if seq > self.checkpoint() or force:
            # state "checkpoint" is outside the journal's ACTIVE_STATES,
            # so replay treats it as a plain latest-record-wins fact (no
            # spurious "resuming in-flight job" demotion); log_id pins
            # the checkpoint to ONE log incarnation
            self.journal.put({"key": self._key, "seq": seq,
                              "state": "checkpoint",
                              "log_id": self.fs.filer.meta_log.log_id,
                              "remote": self.remote_http})
        self._unsaved = 0
        self._last_save = now

    # -- main loop ---------------------------------------------------------

    def run(self) -> None:
        backoff = failsafe.Backoff(failsafe.RetryPolicy(
            max_attempts=1 << 30, base_delay=0.5, max_delay=15.0))
        while not self._stop.is_set():
            try:
                if self._remote_cid is None:
                    self._remote_cid = self._handshake()
                    backoff.reset()
                self._sync()
                return  # stop was set
            except MetaLogGap as e:
                glog.warning("geo link %s: %s — full namespace resync",
                             self.link, e)
                try:
                    self._resync()
                    backoff.reset()
                except Exception as re:  # noqa: BLE001 — retry the link
                    glog.warning("geo resync to %s failed: %s",
                                 self.remote_http, re)
                    GEO_EVENTS.labels(self.link, "error").inc()
                    if self._stop.wait(backoff.next()):
                        return
            except Exception as e:  # noqa: BLE001 — the link must survive
                GEO_EVENTS.labels(self.link, "error").inc()
                delay = backoff.next()
                glog.warning("geo link %s interrupted (%s); retrying "
                             "in %.1fs", self.link, e, delay)
                if self._stop.wait(delay):
                    return

    def _handshake(self) -> int:
        """The remote's cluster id — required for loop prevention (events
        it already signed are skipped) and sanity (replicating a cluster
        into itself would loop on the first event)."""
        with connpool.request(
                "GET", f"http://{self.remote_http}/.geo/status",
                timeout=10) as r:
            doc = json.loads(r.read())
        cid = int(doc.get("clusterId", 0))
        if cid and cid == self.fs.filer.cluster_id:
            raise ValueError(
                f"remote {self.remote_http} reports THIS cluster id "
                f"({cid}); geo links must cross clusters")
        return cid

    def _sync(self) -> None:
        log = self.fs.filer.meta_log
        rec = self.journal.get(self._key) or {}
        after = int(rec.get("seq", 0))
        if after and rec.get("log_id") not in (None, log.log_id):
            # the checkpoint was taken against a DIFFERENT log
            # incarnation (wiped/repointed store dir restarting at seq
            # 1): its bare seqs mean nothing against this history, and
            # resuming by them would silently skip the new log's first
            # `after` events once last_seq catches up — resync instead
            # (the post-resync checkpoint records the current log_id)
            glog.warning(
                "geo link %s: checkpoint belongs to log %s, local log "
                "is %s — discarding it", self.link, rec.get("log_id"),
                log.log_id)
            raise MetaLogGap(after, log.first_retained_seq)
        if after > log.last_seq():
            # the log restarted below our checkpoint (memory-mode log, or
            # a wiped store dir): unknown history was lost — resync
            raise MetaLogGap(after, log.last_seq() + 1)
        for seq, ev in log.tail(after, stop_event=self._stop):
            if not self._process(seq, ev):
                # stopped before the remote acknowledged: do NOT
                # advance — a restart re-delivers the event (the
                # applier's (src, log, seq) watermark dedups any half
                # that DID land)
                return
            self._last_seq = seq
            self._save_checkpoint(seq)

    # -- one event ---------------------------------------------------------

    def _skip(self, path: str) -> bool:
        if any(path.startswith(p) for p in SKIP_PREFIXES):
            return True
        if self.path_prefix and self.path_prefix != "/":
            return not path.startswith(self.path_prefix)
        return False

    def _process(self, seq: int, ev) -> bool:
        """Ship one tailed event; returns False when the link stopped
        before every ship was acknowledged (checkpoint must not move)."""
        n = ev.event_notification
        if self._remote_cid and self._remote_cid in n.signatures:
            # this mutation IS a geo apply from the remote: shipping it
            # back would loop
            GEO_EVENTS.labels(self.link, "skipped").inc()
            return True
        directory = ev.directory
        old_name, new_name = n.old_entry.name, n.new_entry.name
        moved = bool(old_name and new_name and (
            n.new_parent_path not in ("", directory)
            or old_name != new_name))
        if old_name and (not new_name or moved):
            old_path = join_path(directory, old_name)
            if not self._skip(old_path):
                # ship the TOMBSTONE's stamp, not the event's: a relayed
                # delete (mesh of 3+ clusters) logs a fresh monotonic
                # event ts, but the tombstone keeps the ORIGIN's
                # (hlc, cluster) — shipping relay time would inflate the
                # fence at every hop and wrongly beat concurrent writes
                # the origin delete properly lost to.
                # The delete half of a move shares the event's seq with
                # the put half — ship it watermark-free (seq=0, fenced
                # by the tombstone's LWW stamp) so advancing the remote
                # watermark here cannot drop the put half as a duplicate
                tomb = decode_hlc(self.fs.filer.store.kv_get(
                    tombstone_key(old_path)))
                hlc, origin = (tomb if tomb is not None
                               else (ev.ts_ns, None))
                if not self._ship(0 if moved else seq, "delete",
                                  old_path, hlc, origin=origin):
                    return False
        if new_name:
            target_dir = (n.new_parent_path or directory) if moved \
                else directory
            path = join_path(target_dir, new_name)
            # ship the ENTRY's stamp, not the event's: a relayed apply
            # (mesh of 3+ clusters) logs a fresh monotonic event ts but
            # the entry keeps the ORIGIN's (hlc, cluster) — re-shipping
            # with relay time/identity would inflate stamps around the
            # mesh and every hop would re-win LWW over the original
            stamp = decode_hlc(
                bytes(n.new_entry.extended.get(GEO_HLC_KEY, b"")))
            hlc, origin = stamp if stamp is not None else (ev.ts_ns,
                                                           None)
            if self._skip(path):
                GEO_EVENTS.labels(self.link, "skipped").inc()
            elif n.new_entry.is_directory:
                if not self._ship(seq, "mkdir", path, hlc,
                                  origin=origin):
                    return False
                if moved:
                    # a renamed directory moved its children with raw
                    # store ops (no per-child events): the remote just
                    # recursively deleted the old subtree, so re-ship
                    # the children from the store under the new path
                    if not self._walk_ship(path):
                        return False
            elif self._entry_size(n.new_entry) > MAX_BODY_BYTES:
                glog.warning("geo %s: %s is %d bytes, over the %d "
                             "replication cap; skipping event seq=%d",
                             self.link, path,
                             self._entry_size(n.new_entry),
                             MAX_BODY_BYTES, seq)
                GEO_EVENTS.labels(self.link, "error").inc()
            else:
                try:
                    data = self._read_data(n.new_entry)
                except Exception as e:  # noqa: BLE001 — chunks may be
                    # gone already (overwritten + vacuumed); the newer
                    # event in the stream carries the live bytes
                    glog.warning("geo %s: source bytes for %s unreadable "
                                 "(%s); skipping event seq=%d", self.link,
                                 path, e, seq)
                    GEO_EVENTS.labels(self.link, "error").inc()
                    return True
                if not self._ship(seq, "put", path, hlc, data=data,
                                  mime=n.new_entry.attributes.mime,
                                  origin=origin):
                    return False
        elif not old_name:
            GEO_EVENTS.labels(self.link, "skipped").inc()
        return True

    @staticmethod
    def _entry_size(entry) -> int:
        if entry.content:
            return len(entry.content)
        if not entry.chunks:
            return 0
        from ..filer import filechunks

        return filechunks.total_size(entry.chunks)

    def _read_data(self, entry) -> bytes:
        if entry.content:
            return bytes(entry.content)
        if not entry.chunks:
            return b""
        from ..filer import filechunks

        return self.fs.read_entry_range(
            entry, 0, filechunks.total_size(entry.chunks))

    def _ship(self, seq: int, op: str, path: str, hlc: int,
              data: bytes = b"", mime: str = "",
              origin: int | None = None) -> bool:
        """POST one event to the remote applier; blocks (with backoff)
        until the remote processed it or the link is stopped.  Permanent
        rejections (4xx: malformed, oversized) are counted and skipped —
        one poison event must not dam the stream.

        Returns True when the event was ACKNOWLEDGED by the remote (or
        intentionally skipped as poison); False when the link stopped
        before that — the caller must NOT advance its checkpoint past an
        unacknowledged event, or a restart would silently lose it."""
        if self.bucket is not None:
            self.bucket.consume(len(data) + EVENT_OVERHEAD_BYTES,
                                stop=self._stop)
            if self._stop.is_set():
                return False
        q = urllib.parse.urlencode({
            "origin": (origin if origin is not None
                       else self.fs.filer.cluster_id),
            "src": self.fs.signature,
            # scopes the remote's (src, seq) watermark to THIS log
            # incarnation — after a wiped log restarts seq at 1, the
            # new events must not be swallowed by the old high-water
            "log": self.fs.filer.meta_log.log_id,
            "seq": seq,
            "hlc": hlc,
            "op": op,
            "path": path,
            "mime": mime or "",
        })
        url = f"http://{self.remote_http}/.geo/apply?{q}"
        backoff = failsafe.Backoff(failsafe.RetryPolicy(
            max_attempts=1 << 30, base_delay=0.3, max_delay=10.0))
        while not self._stop.is_set():
            try:
                with connpool.request("POST", url, body=data,
                                      timeout=120) as r:
                    doc = json.loads(r.read())
            except urllib.error.HTTPError as e:
                reason, retryable = failsafe.classify(e, idempotent=True)
                skew = (e.code == 400 and e.headers is not None
                        and e.headers.get("X-Seaweed-Reject") == "skew")
                if e.code in (403, 404) or skew:
                    # remote-STATE rejections, not poison events: 403 =
                    # remote tenant quota full, 404 = remote geo
                    # disabled (config rollback — /.geo/apply only 404s
                    # when the applier is absent; apply errors map to
                    # 400/403/500), 400+skew marker = OUR clock too far
                    # ahead of the remote's.  All clear over OPERATOR
                    # time; skipping would advance the checkpoint past
                    # the event and silently break byte-identity with
                    # no resync trigger (MetaLogGap never fires).  Hold
                    # the link — the growing seaweedfs_geo_lag_seconds
                    # is the operator signal
                    reason = {403: "quota", 404: "geo_disabled"}.get(
                        e.code, "skew")
                    retryable = True
                if not retryable:
                    glog.warning("geo %s: %s %s rejected (%s); skipping",
                                 self.link, op, path, reason)
                    GEO_EVENTS.labels(self.link, "error").inc()
                    return True
                failsafe.RETRY_COUNTER.labels("geo", "ship", reason).inc()
                if self._stop.wait(backoff.next()):
                    return False
                continue
            except Exception as e:  # noqa: BLE001 — transport: retry
                reason, _ = failsafe.classify(e, idempotent=True)
                failsafe.RETRY_COUNTER.labels("geo", "ship", reason).inc()
                glog.warning("geo %s unreachable (%s: %s); retrying",
                             self.remote_http, reason, e)
                if self._stop.wait(backoff.next()):
                    return False
                continue
            result = doc.get("result", "ok")
            GEO_EVENTS.labels(
                self.link,
                {"ok": "shipped", "dup": "dup",
                 "conflict": "conflict"}.get(result, "error")).inc()
            GEO_BYTES.labels(self.link).inc(
                len(data) + EVENT_OVERHEAD_BYTES)
            self.shipped += 1
            if seq:
                # resync walks (seq=0) re-ship OLD entries whose stamps
                # (or the unstamped placeholder hlc=1) say nothing about
                # replication lag — only live tailed events do
                self.last_shipped_ts = hlc
                GEO_LAG.labels(self.link).set(
                    max(0.0, (time.time_ns() - hlc) / 1e9))
            return True
        return False  # stopped before the remote acknowledged

    # -- divergence reconciliation ----------------------------------------

    def _resync(self) -> None:
        """Full namespace walk shipped as seq=0 LWW puts: the remote
        applies only what it does not already have newer — the rejoin
        reconciliation path when the event log cannot bridge the gap."""
        self.resyncs += 1
        log = self.fs.filer.meta_log
        base = log.last_seq()
        if not self._walk_ship("/"):
            # stopped mid-walk: leave the checkpoint where it was — a
            # restart re-enters through the same MetaLogGap and walks
            # the namespace again (LWW/watermark-safe to repeat)
            return
        # writes during the walk have seq > base and re-ship from the
        # tail; the overlap is LWW/watermark-safe
        self._last_seq = max(self._last_seq, base)
        self._save_checkpoint(base, force=True)

    def _walk_ship(self, root: str) -> bool:
        """Ship every entry under ``root`` as seq=0 LWW events, carrying
        each entry's TRUE origin stamp — an entry the remote itself
        originated must compare equal there (dup), not as a phantom
        conflict between cluster ids at the same timestamp.  Returns
        False when stopped before the walk completed."""
        store = self.fs.filer.store
        queue = deque([root])
        while queue:
            if self._stop.is_set():
                return False
            d = queue.popleft()
            for e in _iter_dir(store, d):
                path = join_path(d, e.name)
                if e.is_directory:
                    queue.append(path)
                    if not self._skip(path):
                        if not self._ship(0, "mkdir", path,
                                          self._entry_ts(e),
                                          origin=self._entry_origin(e)):
                            return False
                    continue
                if self._skip(path):
                    continue
                if self._entry_size(e) > MAX_BODY_BYTES:
                    glog.warning("geo resync: %s over the %d-byte "
                                 "replication cap; skipping", path,
                                 MAX_BODY_BYTES)
                    GEO_EVENTS.labels(self.link, "error").inc()
                    continue
                try:
                    data = self._read_data(e)
                except Exception as ex:  # noqa: BLE001
                    glog.warning("geo resync: %s unreadable (%s)",
                                 path, ex)
                    GEO_EVENTS.labels(self.link, "error").inc()
                    continue
                if not self._ship(0, "put", path, self._entry_ts(e),
                                  data=data, mime=e.attributes.mime,
                                  origin=self._entry_origin(e)):
                    return False
        return True

    @staticmethod
    def _entry_ts(entry) -> int:
        stamp = entry_hlc(entry)
        return stamp[0] if stamp else 1

    def _entry_origin(self, entry) -> int:
        """The cluster id of an entry's stored stamp (who WROTE it), for
        re-shipping pre-existing state; entries with no stamp (or the
        pre-geo cid 0) are claimed by this cluster."""
        stamp = entry_hlc(entry)
        if stamp is not None and stamp[1]:
            return stamp[1]
        return self.fs.filer.cluster_id
