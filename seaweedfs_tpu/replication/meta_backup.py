"""`filer.meta.backup`: continuous filer-metadata backup into a local store.

Reference: weed/command/filer_meta_backup.go — a full BFS copy of the
namespace on `-restart` (or when no previous backup offset exists), then
the SubscribeMetadata event stream applied incrementally to the backup
FilerStore, with the resume offset persisted in that store's own KV under
``metaBackup`` so a later run continues where this one stopped.

Design differences from the reference: the backup store is any registered
framework FilerStore (``filer.stores.make_store``) rather than a
viper-toml plugin scan, and the streaming loop is a plain generator the
CLI runs in the foreground (tests drive ``apply_event`` directly and run
``stream`` in a thread).
"""

from __future__ import annotations

import time

import grpc

from ..filer.filerstore import make_store
from ..pb import filer_pb2
from ..s3api.filer_client import FilerClient

OFFSET_KEY = b"metaBackup"


def _child(directory: str, name: str) -> tuple[str, str]:
    return (directory.rstrip("/") or "/"), name


class MetaBackup:
    """Mirror one filer's namespace into a local FilerStore."""

    def __init__(self, filer_http: str, store, filer_dir: str = "/"):
        self.filer_http = filer_http
        self.store = store
        self.filer_dir = filer_dir.rstrip("/") or "/"
        self.client = FilerClient(filer_http)

    @classmethod
    def with_store(cls, filer_http: str, store: str, store_path: str = "",
                   filer_dir: str = "/", **options) -> "MetaBackup":
        return cls(filer_http, make_store(store, path=store_path, **options),
                   filer_dir=filer_dir)

    # -- offset ------------------------------------------------------------

    def get_offset(self) -> int | None:
        raw = self.store.kv_get(OFFSET_KEY)
        if not raw:
            return None
        return int.from_bytes(raw, "big")

    def set_offset(self, ts_ns: int) -> None:
        self.store.kv_put(OFFSET_KEY, ts_ns.to_bytes(8, "big"))

    # -- full copy ---------------------------------------------------------

    def traverse(self) -> int:
        """BFS the live namespace into the store; returns entries copied."""
        copied = 0
        for directory, entry in self.client.walk(self.filer_dir):
            self.store.insert_entry(directory, entry)
            copied += 1
        return copied

    # -- incremental stream ------------------------------------------------

    def apply_event(self, resp: filer_pb2.SubscribeMetadataResponse) -> None:
        """One metadata event -> backup store mutation (create / delete /
        in-place update / cross-directory rename as delete+insert)."""
        n = resp.event_notification
        old_name = n.old_entry.name
        new_name = n.new_entry.name
        if not old_name and not new_name:
            return
        if not old_name:  # create
            self.store.insert_entry(n.new_parent_path or resp.directory,
                                    n.new_entry)
        elif not new_name:  # delete
            d, name = _child(resp.directory, old_name)
            self.store.delete_entry(d, name)
        elif (resp.directory == (n.new_parent_path or resp.directory)
              and old_name == new_name):  # in-place update
            self.store.update_entry(resp.directory, n.new_entry)
        else:  # rename
            d, name = _child(resp.directory, old_name)
            self.store.delete_entry(d, name)
            self.store.insert_entry(n.new_parent_path or resp.directory,
                                    n.new_entry)

    def stream(self, stop=None, offset_every_s: float = 3.0) -> None:
        """Apply the live event stream from the saved offset onward.

        The resume offset is persisted on a ~3s cadence (the reference uses
        a 3s ticker), not per event — a per-event kv_put would serialize a
        high-churn stream on one store commit per mutation.  Crash window:
        up to 3s of events replay on restart, which is safe because every
        apply is idempotent (insert-or-replace / delete-if-present).
        `stop` (an Event-like with is_set) makes the loop exit for tests.
        """
        from ..pb import rpc as rpclib

        since = self.get_offset() or 0
        last_ns = 0
        last_save = time.monotonic()
        host, _, port = self.filer_http.partition(":")
        stub = rpclib.filer_stub(f"{host}:{int(port) + 10000}")
        # keep the streaming call handle: cancel() is the only way to
        # interrupt an IDLE subscription (no events -> the iterator never
        # returns control, so a stop flag alone could not be observed)
        self._call = stub.SubscribeMetadata(
            filer_pb2.SubscribeMetadataRequest(
                client_name="meta.backup",
                path_prefix=self.filer_dir,
                since_ns=since,
            )
        )
        try:
            for resp in self._call:
                self.apply_event(resp)
                last_ns = resp.ts_ns
                now = time.monotonic()
                if now - last_save >= offset_every_s:
                    self.set_offset(last_ns)
                    last_save = now
                if stop is not None and stop.is_set():
                    return
        except grpc.RpcError as e:
            if e.code() != grpc.StatusCode.CANCELLED:  # cancel() = clean stop
                raise
        finally:
            self.cancel()
            if last_ns:
                self.set_offset(last_ns)

    def cancel(self) -> None:
        """Tear down the in-flight subscription (safe to call anytime)."""
        call = getattr(self, "_call", None)
        if call is not None:
            try:
                call.cancel()
            except Exception:  # noqa: BLE001 — best-effort teardown
                pass

    def run(self, restart: bool = False) -> None:
        """The CLI entry loop (runFilerMetaBackup)."""
        if restart or self.get_offset() is None:
            started_ns = time.time_ns()
            n = self.traverse()
            print(f"meta.backup: copied {n} entries")
            self.set_offset(started_ns)
        while True:
            try:
                self.stream()
            except Exception as e:  # noqa: BLE001 — reconnect loop
                print(f"meta.backup: stream interrupted: {e}; retrying")
                time.sleep(1.747)
