"""Replication source: a filer's metadata event stream + chunk reader.

Reference: weed/replication/source/filer_source.go (lookup + read chunk
data from the source cluster) and the SubscribeMetadata consumption loop
in weed/command/filer_replicate.go.
"""

from __future__ import annotations

import urllib.parse

from ..pb import filer_pb2
from ..pb import rpc as rpclib
from ..util import connpool

GRPC_PORT_OFFSET = 10000


def _grpc_addr(http_addr: str) -> str:
    host, _, port = http_addr.partition(":")
    return f"{host}:{int(port) + GRPC_PORT_OFFSET}"


def subscribe_metadata(filer_http: str, path_prefix: str = "/",
                       since_ns: int = 0, client_name: str = "replicate",
                       signature: int = 0):
    """Yield SubscribeMetadataResponse events from a filer (filer.proto:20).

    Blocking generator; the caller runs it in its own thread and stops by
    closing the underlying channel / killing the thread.
    """
    stub = rpclib.filer_stub(_grpc_addr(filer_http))
    yield from stub.SubscribeMetadata(
        filer_pb2.SubscribeMetadataRequest(
            client_name=client_name,
            path_prefix=path_prefix,
            since_ns=since_ns,
            signature=signature,
        )
    )


class FilerSource:
    """Reads file content for replicated entries from the source filer."""

    def __init__(self, filer_http: str):
        self.filer_http = filer_http

    def read_entry_data(self, directory: str, entry: filer_pb2.Entry) -> bytes:
        if entry.content:
            return bytes(entry.content)
        if not entry.chunks:
            return b""
        path = f"{directory.rstrip('/')}/{entry.name}"
        url = f"http://{self.filer_http}{urllib.parse.quote(path)}"
        with connpool.request("GET", url, timeout=60) as r:
            return r.read()
