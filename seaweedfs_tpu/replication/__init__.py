"""Cross-cluster replication: replay filer metadata events into sinks.

Reference: weed/replication/replicator.go:18 (event -> sink op mapping),
sink/{filersink,localsink,...}, source/filer_source.go, driven by
`weed filer.replicate` / `filer.sync` / `filer.backup`
(weed/command/filer_replicate.go, filer_sync.go, filer_backup.go).
"""

from .replicator import Replicator
from .sink import FilerSink, LocalSink
from .source import FilerSource, subscribe_metadata

__all__ = [
    "Replicator",
    "FilerSink",
    "LocalSink",
    "FilerSource",
    "subscribe_metadata",
]
